"""Device execution service: cross-partition dynamic batch coalescing.

BENCH_r05 showed the device starved on exactly the workload the framework
serves — the featurize/transform path: MFU 0.09 (EfficientNetB0), 0.20
(DenseNet121), 0.28 (InceptionV3). The cause is structural: every engine
partition task (``engine/dataframe.py`` pool → transformer op →
``ModelFunction.apply_batch``) stages its own ≤ ``batch_size`` chunk and
issues its own device launch, so an 8-way partitioned DataFrame runs 8
small serial launches instead of one full bucket, and dispatch overhead
dominates for cheap models.

This module is the process-wide fix: transformers enter the device through
ONE choke point, :func:`execute`, and concurrent small requests against
the same compiled function are **coalesced** into one padded
bucket-ladder launch:

- worker threads submit ``(compiled-fn, rows)`` requests to a
  per-compiled-fn queue;
- a coalescer thread drains the queue under a bounded wait window
  (``EngineConfig.coalesce_window_ms``; default an adaptive fraction of
  the observed request latency) and a max-bucket cap, concatenates the
  requests into one padded launch, dispatches it async, slices each
  request's output rows back **on device**, and completes the requesters'
  futures in submission order — each requester then pays its own single
  device→host fetch for exactly its rows;
- a **solo request under no contention takes the existing inline path**
  (``apply_batch`` on the caller's thread) with zero added latency — the
  service only changes behavior when there is someone to coalesce with.

Composition with the existing layers (the invariants tests pin down):

- **bit-identical, order-preserving**: a coalesced launch computes the
  same per-row values as per-request launches (row-wise models are
  bucket-size invariant — the same invariance the OOM re-chunk path has
  always relied on), and every requester gets its rows back in its own
  submission order;
- **resilience**: classification applies per super-batch — ANY failure
  (transient, OOM, FATAL) splits the launch back into per-request
  sub-launches via ``apply_batch`` on the requesters' own threads, so a
  transient's classified retry/backoff runs per request (never a sleep
  on the coalescer thread, which would stall every queued sibling), an
  OOM re-chunks exactly as the non-coalesced path would, and a poisoned
  request fails alone instead of taking its coalesced siblings down
  with it (ops are pure by the engine's contract, so the replay is
  safe);
- **supervision**: the supervisor's deadline watchdog and hedging bound
  each *task* as before (the window is bounded, so a blocked requester
  always unblocks); a hedged duplicate attempt carries its task's token
  (:func:`task_scope`, set by ``engine/supervisor.py``) and **dedups
  before coalescing** — while its sibling's request is still queued the
  attempts share one future instead of launching the same rows twice,
  and once the sibling has launched the hedge re-runs independently so
  speculation can still win past a stalled launch;
- **telemetry**: coalesce-size and queue-wait histograms, a launch
  histogram and an executor occupancy gauge (docs/OBSERVABILITY.md);
- **training never coalesces**: ``Trainer.fit`` owns its own step program
  (donated state threading, deferred sync) and never routes through this
  module — coalescing across training steps would interleave state
  updates from unrelated streams.

Shutdown never leaks a future: :func:`shutdown` (and interpreter exit)
fails every queued request with :class:`ExecutorShutdown`, so a worker
blocked mid-window always completes or raises. Shutdown and
:func:`reset` are idempotent and safe to race with concurrent submits —
a submit that loses the race gets :class:`ExecutorShutdown`, never a
hang or a leaked future.

Overload protection (ISSUE 6, docs/RESILIENCE.md "Overload & graceful
degradation") — every knob defaults to today's unbounded behavior:

- **admission control**: ``EngineConfig.executor_max_queued_requests`` /
  ``executor_max_queued_rows`` bound each compiled fn's queue. A submit
  over the bound either *blocks* with backpressure (the default,
  bounded by the caller's deadline) or — with
  ``executor_overload_mode="shed"`` — fails immediately with
  :class:`~sparkdl_tpu.core.resilience.ExecutorOverloaded`, which
  classifies RETRYABLE so the engine's task retry absorbs the spike;
- **deadline propagation**: the supervisor's per-task ``Deadline``
  rides in ambiently (:class:`deadline_scope`); the coalescer drops
  already-expired requests at drain time — before paying for a launch —
  failing them with ``DeadlineExceeded`` (the same deadline-marked
  taxonomy the watchdog uses, so the failure never quarantines and
  never retries past the budget);
- **priority lanes**: requests carry ``"interactive"`` or ``"bulk"``
  (default bulk); the coalescer drains interactive first and — in shed
  mode — an interactive arrival displaces the newest queued bulk
  request rather than being shed itself, so batch featurize can never
  starve online traffic;
- **per-model circuit breaker**: ``executor_breaker_threshold`` terminal
  launch failures within ``executor_breaker_window_s`` trip the
  breaker; while open, submits fail fast with
  :class:`~sparkdl_tpu.core.resilience.ExecutorCircuitOpen` (RETRYABLE
  — backoff rides past ``executor_breaker_cooldown_s``, then a single
  half-open probe re-tests the model and recovery reopens traffic).
  Trip/probe/recover are health events + telemetry counters, and
  queue-depth/shed-rate gauges join the executor metrics.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from sparkdl_tpu.core import batching, health, resilience, telemetry
from sparkdl_tpu.core.resilience import (  # noqa: F401 - re-exported API
    ExecutorCircuitOpen,
    ExecutorOverloaded,
)

logger = logging.getLogger(__name__)

# Adaptive window bounds (seconds) when EngineConfig.coalesce_window_ms is
# None: a fraction of the observed end-to-end request latency, clamped so
# the window neither busy-spins on microsecond models nor adds visible
# latency to slow ones.
_WINDOW_FRACTION = 0.25
_WINDOW_MIN_S = 0.0005
_WINDOW_MAX_S = 0.02
_WINDOW_DEFAULT_S = 0.002
# Idle coalescer threads exit after this long with an empty queue (and
# restart on the next queued request), so tests and long-lived processes
# don't accumulate one parked thread per model ever served. The live
# value is the EngineConfig.executor_idle_retire_s knob (the serving
# residency manager shortens it to make eviction prompt); this constant
# is only the fallback when the engine layer isn't importable.
_IDLE_EXIT_S = 5.0


def _idle_exit_s() -> float:
    """The idle-retirement interval, read from EngineConfig per use so a
    knob flip (tests, residency manager) takes effect on parked threads
    at their next wakeup — no service restart needed. Core must stay
    importable without the engine, hence the lazy import."""
    try:
        from sparkdl_tpu.engine.dataframe import EngineConfig
    except ImportError:  # pragma: no cover - engine always ships
        return _IDLE_EXIT_S
    value = getattr(EngineConfig, "executor_idle_retire_s", _IDLE_EXIT_S)
    try:
        value = float(value)
    except (TypeError, ValueError):
        return _IDLE_EXIT_S
    return value if value > 0 else _IDLE_EXIT_S


class ExecutorShutdown(RuntimeError):
    """The execution service was shut down with this request still queued."""


# Priority lanes: interactive drains first and is shed last. Bulk is the
# default — batch featurize must OPT OUT of being sheddable, never the
# other way around.
PRIORITY_INTERACTIVE = "interactive"
PRIORITY_BULK = "bulk"
PRIORITIES = (PRIORITY_INTERACTIVE, PRIORITY_BULK)

# Tick for the blocking-admission wait: short enough that a caller whose
# deadline expires mid-wait notices promptly, long enough not to spin.
_ADMIT_WAIT_TICK_S = 0.05


@dataclass(frozen=True)
class OverloadPolicy:
    """Per-submit snapshot of the EngineConfig overload knobs (read once
    in :func:`execute`, so a knob flip mid-run can't tear one request's
    admission decision). All defaults mean "today's behavior": unbounded
    queue, no shedding, breaker disabled."""

    max_queued_requests: Optional[int] = None
    max_queued_rows: Optional[int] = None
    shed: bool = False          # False = block with backpressure
    breaker_threshold: int = 0  # 0 disables the circuit breaker
    breaker_window_s: float = 30.0
    breaker_cooldown_s: float = 1.0

    @property
    def bounded(self) -> bool:
        return (self.max_queued_requests is not None
                or self.max_queued_rows is not None)


_NO_OVERLOAD = OverloadPolicy()


# ---------------------------------------------------------------------------
# Task tokens (hedge dedup)
# ---------------------------------------------------------------------------

_tls = threading.local()


def current_task_token() -> Optional[Tuple]:
    """The ambient dedup identity for THIS executor call: the task token
    set by :func:`task_scope` extended with the attempt's call sequence
    number. Ops are pure and deterministic (the engine contract), so the
    N-th device call of a task's hedge attempt computes the same rows as
    the N-th call of its primary — the sequence number keeps a task whose
    op chain enters the device several times (e.g. two chained
    transformers sharing one model) from dedup'ing call N onto call M.
    Each read advances the sequence. None outside a scope."""
    token = getattr(_tls, "token", None)
    if token is None:
        return None
    seq = _tls.seq
    _tls.seq = seq + 1
    return token + (seq,)


def reset_call_sequence() -> None:
    """Restart the ambient token's device-call sequence. The supervisor
    calls this at the start of EVERY retry-loop attempt inside a pool
    attempt's :class:`task_scope` (``run_partition_task``'s classified
    retries re-run the op chain from the top, so their device calls
    restart at call 0) — without the reset a retried primary's call 0
    would sit at seq N while a fresh hedge's call 0 sits at seq 0, and
    the hedge's call N could dedup onto the WRONG device call's output.
    No-op outside a scope."""
    if getattr(_tls, "token", None) is not None:
        _tls.seq = 0


class task_scope:
    """Mark device requests from this thread as belonging to one logical
    task attempt. The supervisor wraps every pool attempt (primary,
    retry, hedge) of a task in the SAME token (each attempt — including
    each retry-loop attempt inside a pool attempt, via
    :func:`reset_call_sequence` — restarting the call-sequence counter),
    so a hedged duplicate submitting the same rows while its sibling's
    request is still pending shares that request's future instead of
    coalescing the rows twice."""

    def __init__(self, token: Tuple) -> None:
        self._token = token
        self._prev: Optional[Tuple] = None
        self._prev_seq = 0

    def __enter__(self) -> "task_scope":
        self._prev = getattr(_tls, "token", None)
        self._prev_seq = getattr(_tls, "seq", 0)
        _tls.token = self._token
        _tls.seq = 0
        return self

    def __exit__(self, *exc: Any) -> None:
        _tls.token = self._prev
        _tls.seq = self._prev_seq


def current_deadline() -> Optional[resilience.Deadline]:
    """The ambient task deadline for THIS thread's executor calls (set by
    :class:`deadline_scope`; the supervisor enters one per task attempt).
    None outside a scope."""
    return getattr(_tls, "deadline", None)


class deadline_scope:
    """Thread the caller's :class:`~sparkdl_tpu.core.resilience.Deadline`
    into every executor call made on this thread. ``run_partition_task``
    wraps each task in one, so a queued request knows its budget: the
    blocking admission wait is bounded by it, and the coalescer drops a
    request whose deadline already expired at drain time — before paying
    for a launch — instead of turning one slow window into a convoy of
    doomed launches. A ``Deadline(None)`` (no budget) is not threaded:
    the unloaded hot path stays free of per-request expiry checks."""

    def __init__(self, deadline: Optional[resilience.Deadline]) -> None:
        self._deadline = (deadline if deadline is not None
                          and deadline.timeout_s is not None else None)
        self._prev: Optional[resilience.Deadline] = None

    def __enter__(self) -> "deadline_scope":
        self._prev = getattr(_tls, "deadline", None)
        _tls.deadline = self._deadline
        return self

    def __exit__(self, *exc: Any) -> None:
        _tls.deadline = self._prev


#: Tenant tag for requests that carry none and run outside any scope.
DEFAULT_TENANT = "default"


def current_tenant() -> Optional[str]:
    """The ambient tenant tag for THIS thread's executor calls (set by
    :class:`tenant_scope`; cluster workers enter one per dispatched
    partition so worker-side metrics stay tenant-attributed). None
    outside a scope."""
    return getattr(_tls, "tenant", None)


class tenant_scope:
    """Tag every executor call made on this thread with one tenant.
    The fair-queueing coalescer schedules lanes per tenant
    (deficit-round-robin within priority), so the tag decides whose
    quota a request burns. Explicit ``execute(tenant=...)`` beats the
    scope; the scope beats ``EngineConfig.executor_default_tenant``.
    ``tenant_scope(None)`` is a no-op layer (ambient tag unchanged)."""

    def __init__(self, tenant: Optional[str]) -> None:
        self._tenant = tenant
        self._prev: Optional[str] = None

    def __enter__(self) -> "tenant_scope":
        self._prev = getattr(_tls, "tenant", None)
        if self._tenant is not None:
            _tls.tenant = self._tenant
        return self

    def __exit__(self, *exc: Any) -> None:
        _tls.tenant = self._prev


# ---------------------------------------------------------------------------
# Requests and per-compiled-fn state
# ---------------------------------------------------------------------------


class _ReplayInline:
    """Sentinel future result: the coalescer handed the request back for
    the REQUESTER'S OWN thread to run via ``apply_batch`` (solo drained
    window, or a member of a terminally-failed super-batch). Executing
    these on the coalescer thread would serialize device work that pool
    threads previously overlapped — and block every queued sibling
    behind one request's fetch and retry-backoff sleeps."""

    __slots__ = ()


_REPLAY_INLINE = _ReplayInline()


class _Request:
    """One queued submission: host-staged rows + the future that will carry
    the ON-DEVICE output slices back to the requester."""

    __slots__ = ("tree", "rows", "future", "token", "policy", "ctx",
                 "t_enqueue", "launched", "priority", "deadline",
                 "tenant", "is_probe", "breaker_noted")

    def __init__(self, tree: Any, rows: int, token: Optional[Tuple],
                 policy: resilience.RetryPolicy,
                 priority: str = PRIORITY_BULK,
                 deadline: Optional[resilience.Deadline] = None,
                 tenant: str = DEFAULT_TENANT) -> None:
        self.tree = tree
        self.rows = rows
        self.future: "Future[Any]" = Future()
        self.token = token
        self.policy = policy
        self.priority = priority
        self.deadline = deadline
        self.tenant = tenant
        # True when this request is the breaker's half-open probe: its
        # outcome decides reopen-vs-close, and a probe that dies WITHOUT
        # reaching the device must release the probe slot (never wedge
        # the breaker half-open)
        self.is_probe = False
        # set-exception failures are breaker-counted ONCE per request —
        # a plumbing failure fanned out to a whole window, or two hedged
        # waiters sharing one dedup'd future, must not multiply one
        # launch failure into several breaker counts
        self.breaker_noted = False
        self.ctx = telemetry.current_context()
        self.t_enqueue = time.monotonic()
        # set when the coalescer drains this request: dedup only shares
        # PRE-launch requests, so a hedge arriving later re-executes
        # independently and speculation can still win past a launch that
        # stalled on the device
        self.launched = False


class _FnState:
    """Coalescing state for one compiled fn (one bucket ladder).

    Keyed by the jitted callable's identity — a strong reference is held
    here, so the id can never be recycled while the state exists. All
    fields are guarded by ``cond``'s lock except the immutable config.
    """

    def __init__(self, key: Tuple, fn: Any, model: Any, batch_size: int,
                 mesh: Any, multiple: int) -> None:
        self.key = key
        self.fn = fn
        self.model = model
        self.batch_size = batch_size  # caller's batch_size (pre mesh pad)
        self.mesh = mesh
        self.multiple = multiple
        self.cond = threading.Condition()
        self.pending: "deque[_Request]" = deque()
        self.pending_rows = 0       # incremental sum(r.rows for pending)
        self.pending_deadlines = 0  # queued requests carrying a deadline
        # Deficit-round-robin credit per tenant (guarded by cond): rows
        # each tenant may still release this scheduling round. Cleared
        # for a tenant once it has nothing queued, so an idle tenant
        # cannot bank unbounded credit.
        self.tenant_deficit: Dict[str, float] = {}
        self.dedup: Dict[Tuple, _Request] = {}
        self.inflight = 0           # launches running (inline + coalesced)
        self.window_s: Optional[float] = None  # None = adaptive
        self.cap = batch_size
        self.overload: OverloadPolicy = _NO_OVERLOAD
        # DRR weight per tenant (None = every tenant weight 1); snapshot
        # of EngineConfig.executor_tenant_weights, refreshed per submit
        # like the overload policy.
        self.tenant_weights: Optional[Dict[str, int]] = None
        self.donate = False  # staged batches donated to their launches
        self.planner: Optional[batching.BucketPlanner] = None
        self.latency_ewma: Optional[float] = None
        self.thread: Optional[threading.Thread] = None
        self.last_used = time.monotonic()
        self.retired = False  # set by retire_model: exit at next wakeup
        # Circuit breaker (closed -> open -> half_open -> closed); all
        # guarded by cond. breaker_failures holds terminal-failure
        # timestamps inside the rolling window.
        self.breaker_state = "closed"
        self.breaker_failures: "deque[float]" = deque()
        self.breaker_opened_at = 0.0
        self.breaker_probe_inflight = False

    def effective_window(self) -> float:
        if self.window_s is not None:
            return self.window_s
        if self.latency_ewma is None:
            return _WINDOW_DEFAULT_S
        return min(max(self.latency_ewma * _WINDOW_FRACTION,
                       _WINDOW_MIN_S), _WINDOW_MAX_S)

    def note_latency(self, seconds: float) -> None:
        prev = self.latency_ewma
        self.latency_ewma = (seconds if prev is None
                             else 0.8 * prev + 0.2 * seconds)


class DeviceExecutor:
    """The process-wide coalescing service (one instance per process; the
    module-level :func:`execute` routes through :func:`service`)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._states: Dict[Tuple, _FnState] = {}
        self._closed = False
        self._shutdown_complete = False  # idempotent-shutdown fast path
        self._thread_seq = 0
        self._inflight_total = 0  # O(1) occupancy counter (gauge source)
        self._queued_total = 0    # O(1) queue-depth counter (gauge source)
        self._admitted = 0        # bounded-admission accounting
        self._shed = 0            # (shed-rate gauge = shed/(shed+admitted))

    # -- submission ----------------------------------------------------------

    def submit(self, model: Any, tree: Any, rows: int, batch_size: int,
               mesh: Any, multiple: int, policy: resilience.RetryPolicy,
               window_s: Optional[float], cap: int,
               prefetch: int, *, priority: str = PRIORITY_BULK,
               deadline: Optional[resilience.Deadline] = None,
               tenant: str = DEFAULT_TENANT,
               tenant_weights: Optional[Dict[str, int]] = None,
               overload: OverloadPolicy = _NO_OVERLOAD,
               donate: bool = False,
               planner: Optional[batching.BucketPlanner] = None) -> Any:
        """Run ``rows`` staged rows through the model, coalescing with any
        concurrent sibling requests against the same compiled fn. Returns
        host numpy (structure mirrors the model output). Blocking.

        ``priority`` picks the lane (interactive drains first, bulk sheds
        first); ``deadline`` bounds the blocking-admission wait and lets
        the coalescer drop this request unlaunched once expired;
        ``tenant`` is the fair-queueing tag — within a priority lane the
        coalescer releases queued rows per tenant by deficit-round-robin
        (weights from ``EngineConfig.executor_tenant_weights``);
        ``overload`` carries the admission/breaker knob snapshot;
        ``donate`` donates staged batches to their launches (its jitted
        variant is a distinct compiled fn, hence a distinct coalescing
        state); ``planner`` is the telemetry-tuned bucket ladder for the
        coalescer's pad choice and the replay paths."""
        if priority not in PRIORITIES:
            # a typo'd lane would queue into a lane the coalescer never
            # drains — the caller would hang forever, not error
            raise ValueError(
                f"priority must be one of {PRIORITIES}, got {priority!r}")
        fn = model.jitted(mesh=mesh, donate_batch=donate)
        state = self._state(fn, model, batch_size, mesh, multiple)
        token = current_task_token()
        t0 = time.monotonic()
        request: Optional[_Request] = None
        inline = False
        is_probe = False
        with state.cond:
            if self._closed:
                raise ExecutorShutdown("device execution service is shut "
                                       "down")
            state.window_s = window_s
            state.cap = cap
            state.overload = overload
            state.tenant_weights = tenant_weights
            state.donate = donate
            state.planner = planner
            is_probe = self._breaker_admit_locked(state)
            try:
                if deadline is not None and deadline.expired():
                    # never queue work that is already doomed; the
                    # caller's cooperative deadline handling classifies
                    # this exactly like an in-op expiry. Recorded under
                    # the same event as a drain-time drop so the overload
                    # accounting closes: every executor-raised
                    # DeadlineExceeded is one EXECUTOR_DEADLINE_SHED.
                    health.record(health.EXECUTOR_DEADLINE_SHED,
                                  rows=rows, priority=priority,
                                  at="admission")
                    raise resilience.DeadlineExceeded(
                        f"request expired before admission (deadline "
                        f"{deadline.timeout_s}s)")
                if token is not None:
                    dup = state.dedup.get(token)
                    if (dup is not None and dup.rows == rows
                            and not dup.launched and not dup.future.done()):
                        # hedged duplicate of a sibling attempt whose
                        # request is still QUEUED: share its future — the
                        # rows coalesce exactly once. An already-launched
                        # (or inline) sibling is NOT shared: the hedge
                        # re-runs the pure ops independently, so
                        # speculation can still win past a launch stalled
                        # on the device.
                        request = dup
                        if is_probe:
                            # the shared request's outcome decides the
                            # probe — mark it so _await releases the
                            # probe slot on a never-launched death
                            request.is_probe = True
                        # the shared request lives as long as the LATEST
                        # deadline among its waiters: a fresh hedge must
                        # not be killed at drain time by the primary's
                        # nearly-expired budget (hedging exists to rescue
                        # exactly that straggler)
                        if dup.deadline is not None:
                            if deadline is None:
                                dup.deadline = None
                                state.pending_deadlines -= 1
                            elif (deadline.remaining()
                                    > dup.deadline.remaining()):
                                dup.deadline = deadline
                        telemetry.count(telemetry.M_COALESCE_DEDUP)
                if request is None:
                    if state.inflight == 0 and not state.pending:
                        # solo under no contention: the existing inline
                        # path on the caller's thread — zero added
                        # latency. inflight is bumped first so siblings
                        # arriving meanwhile queue up for the coalescer
                        # instead of serializing behind us.
                        state.inflight += 1
                        self._note_inflight(1)
                        if overload.bounded:
                            self._note_admitted()
                        inline = True
                    else:
                        if overload.bounded:
                            self._admit_locked(state, rows, priority,
                                               deadline, tenant)
                            self._note_admitted()
                        request = _Request(tree, rows, token, policy,
                                           priority=priority,
                                           deadline=deadline,
                                           tenant=tenant)
                        request.is_probe = is_probe
                        state.pending.append(request)
                        state.pending_rows += rows
                        if deadline is not None:
                            state.pending_deadlines += 1
                        self._note_queued(1)
                        if token is not None:
                            state.dedup[token] = request
                        self._ensure_thread(state)
                        state.cond.notify_all()
            except BaseException:
                # a probe that never reached the device (shed, expired,
                # shutdown) must not wedge the breaker half-open: return
                # it to half_open-with-no-probe so the next arrival
                # probes instead of failing fast forever
                if is_probe:
                    state.breaker_probe_inflight = False
                raise
        if not inline:
            return self._await(state, request, t0)
        try:
            with self._breaker_observe(state, is_probe=is_probe):
                return model.apply_batch(tree, batch_size=batch_size,
                                         mesh=mesh, retry_policy=policy,
                                         prefetch=prefetch, donate=donate,
                                         planner=planner)
        finally:
            with state.cond:
                state.inflight -= 1
                state.note_latency(time.monotonic() - t0)
                self._note_inflight(-1)

    def _await(self, state: _FnState, request: _Request, t0: float) -> Any:
        """Block on the request's future and pay the requester's single
        device→host fetch per output leaf (slices arrive device-resident
        with the pad rows already cut off).

        Dispatch is async, so a launch that failed at EXECUTION time (a
        real device OOM the dispatch-side classification never saw)
        surfaces here, at the fetch. That path re-runs THIS request alone
        through ``apply_batch`` — its classified retry and OOM
        bucket-halving apply, and a poisoned sibling cannot take this
        request down with it. Errors delivered via ``set_exception``
        already went through per-request isolation and propagate as-is.
        """
        import jax

        try:
            out = request.future.result()  # isolated failures raise here
        except BaseException as e:  # sparkdl: allow(broad-retry): breaker accounting only — re-raised below, never retried here
            # once per REQUEST, not per waiter: two hedged waiters share
            # one dedup'd future, and a launch-plumbing failure already
            # noted (and marked) every window member in the coalescer
            with state.cond:
                noted, request.breaker_noted = request.breaker_noted, True
            if not noted:
                self._breaker_note(state, e, is_probe=request.is_probe)
            raise
        if isinstance(out, _ReplayInline):
            # handed back by the coalescer (solo drained window, or a
            # terminal super-batch failure split): run the model's own
            # chunked path HERE, on the requester's thread — classified
            # retry and OOM bucket-halving apply per request, and the
            # coalescer thread stays free to drain siblings
            try:
                with self._breaker_observe(state,
                                           is_probe=request.is_probe):
                    return state.model.apply_batch(
                        request.tree, batch_size=state.batch_size,
                        mesh=state.mesh, retry_policy=request.policy,
                        prefetch=0, donate=state.donate,
                        planner=state.planner)
            finally:
                with state.cond:
                    state.note_latency(time.monotonic() - t0)
        try:
            host = jax.tree_util.tree_map(np.asarray, out)
        except Exception as e:  # noqa: BLE001 - classified, then replayed
            kind = resilience.classify(e)
            if kind == resilience.OOM:
                health.record(health.OOM_RECHUNK, rows=request.rows,
                              at="fetch")
            logger.warning(
                "coalesced result fetch failed (%s: %s; classified %s); "
                "re-running the %d-row request alone", type(e).__name__,
                e, kind, request.rows)
            with self._breaker_observe(state, is_probe=request.is_probe,
                                       note_success=False):
                host = state.model.apply_batch(
                    request.tree, batch_size=state.batch_size,
                    mesh=state.mesh, retry_policy=request.policy,
                    prefetch=0, donate=state.donate,
                    planner=state.planner)
        self._breaker_note(state, None, is_probe=request.is_probe)
        with state.cond:
            state.note_latency(time.monotonic() - t0)
        return host

    # -- state / thread management -------------------------------------------

    def _state(self, fn: Any, model: Any, batch_size: int, mesh: Any,
               multiple: int) -> _FnState:
        key = (id(fn), batch_size, multiple)
        with self._lock:
            state = self._states.get(key)
            if state is None or state.fn is not fn:
                self._sweep_stale_locked()
                state = _FnState(key, fn, model, batch_size, mesh,
                                 multiple)
                self._states[key] = state
            state.last_used = time.monotonic()
            return state

    def _retire_locked(self, state: _FnState, now: float) -> None:
        """Drop a fully-quiesced idle state from the registry — the ONE
        definition of the retirement invariant, shared by the coalescer's
        idle exit and the opportunistic new-state sweep. BOTH state.cond
        and self._lock must be held."""
        if (not state.pending and state.inflight == 0
                and state.thread is None
                and now - state.last_used >= _idle_exit_s()
                and self._states.get(state.key) is state):
            del self._states[state.key]

    def _sweep_stale_locked(self) -> None:
        """Drop idle states so the service never pins a discarded model's
        weights for the process lifetime (model churn: CrossValidator,
        notebooks). Called with self._lock held, on the rare new-state
        path; a state's cond is only probed non-blocking — the canonical
        lock order is cond→lock, so blocking here could deadlock."""
        now = time.monotonic()
        for state in list(self._states.values()):
            if now - state.last_used < _idle_exit_s():
                continue
            if not state.cond.acquire(blocking=False):
                continue  # busy: next sweep gets it
            try:
                self._retire_locked(state, now)
            finally:
                state.cond.release()

    def retire_model(self, model: Any, variants: Optional[list] = None
                     ) -> int:
        """Eviction hook for the serving residency manager: drop every
        idle coalescing state whose strong reference pins ``model`` (or
        one of its memoized ``variants`` — precision/donation wrappers
        are distinct compiled fns with their own states). Busy states
        (queued or in-flight work) are skipped — their requests complete
        normally and the idle sweep retires them afterwards; eviction
        never tears live work. Returns the number of states dropped."""
        idents = {id(m) for m in (variants or [model])}
        idents.add(id(model))
        with self._lock:
            victims = [s for s in self._states.values()
                       if id(s.model) in idents]
        dropped = 0
        for state in victims:
            with state.cond:  # canonical lock order: cond -> lock
                if state.pending or state.inflight:
                    continue
                with self._lock:
                    if self._states.get(state.key) is state:
                        del self._states[state.key]
                        dropped += 1
                state.retired = True
                state.cond.notify_all()  # parked coalescer exits promptly
        return dropped

    def _ensure_thread(self, state: _FnState) -> None:
        # caller holds state.cond
        if state.thread is not None and state.thread.is_alive():
            return
        with self._lock:
            self._thread_seq += 1
            seq = self._thread_seq
        state.thread = threading.Thread(
            target=self._coalesce_loop, args=(state,),
            name=f"sparkdl-exec-{seq}", daemon=True)
        state.thread.start()

    def _note_inflight(self, delta: int) -> None:
        """O(1) process-wide in-flight accounting feeding the occupancy
        gauge (no cross-state sums on the per-request hot path)."""
        with self._lock:
            self._inflight_total += delta
            total = self._inflight_total
        if telemetry.active() is not None:
            telemetry.gauge_set(telemetry.M_EXECUTOR_OCCUPANCY, total)

    def _note_queued(self, delta: int) -> None:
        """O(1) process-wide queued-request accounting (queue-depth gauge)."""
        with self._lock:
            self._queued_total += delta
            total = self._queued_total
        if telemetry.active() is not None:
            telemetry.gauge_set(telemetry.M_EXECUTOR_QUEUE_DEPTH, total)

    def _note_admitted(self) -> None:
        with self._lock:
            self._admitted += 1
        self._note_shed_rate()

    def _note_shed(self, rows: int, priority: str, reason: str,
                   tenant: str = DEFAULT_TENANT) -> None:
        with self._lock:
            self._shed += 1
        health.record(health.EXECUTOR_SHED, rows=rows, priority=priority,
                      reason=reason, tenant=tenant)
        self._note_shed_rate()

    def _note_shed_rate(self) -> None:
        if telemetry.active() is None:
            return
        with self._lock:
            admitted, shed = self._admitted, self._shed
        if admitted + shed:
            telemetry.gauge_set(telemetry.M_EXECUTOR_SHED_RATE,
                                shed / (admitted + shed))

    # -- admission control ----------------------------------------------------

    def _admit_locked(self, state: _FnState, rows: int, priority: str,
                      deadline: Optional[resilience.Deadline],
                      tenant: str = DEFAULT_TENANT) -> None:
        """Enforce the per-fn queue bound (caller holds state.cond).

        Over the bound, shed mode fails fast (interactive first displaces
        the newest queued bulk request — bulk sheds before interactive);
        block mode waits with backpressure, bounded by the caller's
        deadline and woken by every coalescer drain. An empty queue
        always admits: a bound smaller than one request must not wedge."""
        ov = state.overload

        def over() -> bool:
            if not state.pending:
                return False
            if (ov.max_queued_requests is not None
                    and len(state.pending) >= ov.max_queued_requests):
                return True
            return (ov.max_queued_rows is not None
                    and state.pending_rows + rows > ov.max_queued_rows)

        while over():
            if ov.shed:
                if (priority == PRIORITY_INTERACTIVE
                        and self._evict_bulk_locked(state)):
                    continue  # re-check: the eviction may have made room
                self._note_shed(rows, priority, reason="admission",
                                tenant=tenant)
                raise ExecutorOverloaded(
                    f"executor queue for {getattr(state.model, 'name', '?')} "
                    f"is full ({len(state.pending)} request(s), "
                    f"{state.pending_rows} row(s) queued); {rows}-row "
                    f"{priority} request shed")
            # block with backpressure: bounded by the caller's deadline
            if self._closed:
                raise ExecutorShutdown(
                    "device execution service shut down while this "
                    "request waited for admission")
            timeout = _ADMIT_WAIT_TICK_S
            if deadline is not None:
                remaining = deadline.remaining()
                if remaining <= 0:
                    health.record(health.EXECUTOR_DEADLINE_SHED,
                                  rows=rows, priority=priority,
                                  at="backpressure")
                    raise resilience.DeadlineExceeded(
                        f"request deadline ({deadline.timeout_s}s) expired "
                        "while blocked on executor admission")
                timeout = min(timeout, remaining)
            state.cond.wait(timeout=timeout)
            if self._closed:
                raise ExecutorShutdown(
                    "device execution service shut down while this "
                    "request waited for admission")

    def _evict_bulk_locked(self, state: _FnState) -> bool:
        """Shed the NEWEST queued bulk request to make room for an
        interactive arrival (caller holds state.cond). Newest-first keeps
        the displaced work's retry cheapest: it waited least, so the
        least queue progress is thrown away. Returns True if one was
        evicted."""
        for r in reversed(state.pending):
            if r.priority != PRIORITY_BULK or r.future.done():
                continue
            state.pending.remove(r)
            state.pending_rows -= r.rows
            if r.deadline is not None:
                state.pending_deadlines -= 1
            if r.token is not None and state.dedup.get(r.token) is r:
                del state.dedup[r.token]
            self._note_queued(-1)
            self._note_shed(r.rows, r.priority, reason="displaced",
                            tenant=r.tenant)
            r.future.set_exception(ExecutorOverloaded(
                f"{r.rows}-row bulk request displaced from the full "
                f"executor queue by an interactive arrival"))
            return True
        return False

    # -- per-model circuit breaker --------------------------------------------

    def _breaker_admit_locked(self, state: _FnState) -> bool:
        """Gate a submit on the breaker state (caller holds state.cond).
        Returns True when THIS request is the half-open probe. Raises
        :class:`ExecutorCircuitOpen` (RETRYABLE) while open or while a
        probe is already in flight."""
        ov = state.overload
        if ov.breaker_threshold <= 0 or state.breaker_state == "closed":
            return False
        name = getattr(state.model, "name", "?")
        if state.breaker_state == "open":
            if (time.monotonic() - state.breaker_opened_at
                    < ov.breaker_cooldown_s):
                raise ExecutorCircuitOpen(
                    f"circuit breaker for model {name!r} is open "
                    f"({ov.breaker_threshold} terminal launch failure(s) "
                    f"within {ov.breaker_window_s}s); failing fast for "
                    f"{ov.breaker_cooldown_s}s")
            state.breaker_state = "half_open"
            state.breaker_probe_inflight = True
            health.record(health.BREAKER_PROBE, model=name)
            logger.warning(
                "circuit breaker for model %r half-open after %.2fs "
                "cooldown; admitting one probe request", name,
                ov.breaker_cooldown_s)
            return True
        # half_open: exactly one probe at a time
        if state.breaker_probe_inflight:
            raise ExecutorCircuitOpen(
                f"circuit breaker for model {name!r} is half-open with a "
                "probe in flight; failing fast")
        state.breaker_probe_inflight = True
        health.record(health.BREAKER_PROBE, model=name)
        return True

    @contextmanager
    def _breaker_observe(self, state: _FnState, *, is_probe: bool = False,
                         note_success: bool = True):
        """The single home for launch-outcome breaker accounting: feed
        the wrapped block's exception (re-raised) or success into
        :meth:`_breaker_note`. ``note_success=False`` for blocks whose
        success is noted later on a shared exit path (``_await``'s fetch
        chain ends in one success note)."""
        try:
            yield
        except BaseException as e:  # sparkdl: allow(broad-retry): breaker accounting only — re-raised, never retried here
            self._breaker_note(state, e, is_probe=is_probe)
            raise
        else:
            if note_success:
                self._breaker_note(state, None, is_probe=is_probe)

    def _breaker_note(self, state: _FnState,
                      error: Optional[BaseException], *,
                      is_probe: bool = False) -> None:
        """Feed one terminal launch outcome into the breaker. Failures
        that never reached the device (shed, shutdown, fast-fail,
        deadline — slowness, not poison) do not count — but a PROBE that
        dies that way must still release the probe slot (back to
        half-open-with-no-probe, so the next arrival probes), or the
        breaker would wedge half-open and fail fast forever."""
        if state.overload.breaker_threshold <= 0 and not is_probe:
            return  # breaker disabled: no lock on the hot path
        if isinstance(error, (ExecutorShutdown, ExecutorOverloaded,
                              ExecutorCircuitOpen,
                              resilience.DeadlineExceeded)):
            if is_probe:
                with state.cond:
                    if state.breaker_state == "half_open":
                        state.breaker_probe_inflight = False
            return
        with state.cond:
            ov = state.overload
            if ov.breaker_threshold <= 0:
                # knobs flipped to disabled mid-flight: still release a
                # probe slot so a later re-enable can't find it wedged
                if is_probe and state.breaker_state == "half_open":
                    state.breaker_probe_inflight = False
                return
            name = getattr(state.model, "name", "?")
            now = time.monotonic()
            if state.breaker_state == "half_open":
                if not is_probe:
                    # a stale pre-trip launch resolving late must not
                    # decide the probe's verdict ("exactly one probe; ITS
                    # outcome decides"): a stale failure joins the
                    # rolling window (cleared on recovery), a stale
                    # success is ignored
                    if error is not None:
                        state.breaker_failures.append(now)
                    return
                state.breaker_probe_inflight = False
                if error is None:
                    state.breaker_state = "closed"
                    state.breaker_failures.clear()
                    health.record(health.BREAKER_CLOSED, model=name)
                    logger.warning(
                        "circuit breaker for model %r closed: probe "
                        "launch succeeded", name)
                else:
                    state.breaker_state = "open"
                    state.breaker_opened_at = now
                    health.record(health.BREAKER_OPEN, model=name,
                                  probe=True, error=type(error).__name__)
                    logger.warning(
                        "circuit breaker for model %r re-opened: probe "
                        "failed (%s: %s)", name, type(error).__name__,
                        error)
                return
            if error is None or state.breaker_state == "open":
                return
            # closed + terminal failure: count within the rolling window
            state.breaker_failures.append(now)
            cutoff = now - ov.breaker_window_s
            while (state.breaker_failures
                    and state.breaker_failures[0] < cutoff):
                state.breaker_failures.popleft()
            if len(state.breaker_failures) >= ov.breaker_threshold:
                state.breaker_state = "open"
                state.breaker_opened_at = now
                state.breaker_probe_inflight = False
                health.record(health.BREAKER_OPEN, model=name,
                              failures=len(state.breaker_failures),
                              error=type(error).__name__)
                logger.error(
                    "circuit breaker for model %r OPEN: %d terminal "
                    "launch failure(s) within %.1fs (last: %s: %s); "
                    "failing fast for %.2fs", name,
                    len(state.breaker_failures), ov.breaker_window_s,
                    type(error).__name__, error, ov.breaker_cooldown_s)

    # -- the coalescer -------------------------------------------------------

    def _coalesce_loop(self, state: _FnState) -> None:
        # `crashed` guards the terminal fail-pending: an IDLE exit hands
        # the (empty) queue back cleanly — failing in that window could
        # race a fresh submit that already started a successor thread.
        crashed = True
        try:
            while True:
                with state.cond:
                    idle_since = time.monotonic()
                    while (not state.pending and not self._closed
                           and not state.retired):
                        state.cond.wait(timeout=_idle_exit_s())
                        if state.retired:
                            break
                        if (not state.pending and not self._closed
                                and time.monotonic() - idle_since
                                >= _idle_exit_s()):
                            state.thread = None
                            crashed = False
                            # retire the whole state with the thread so
                            # an abandoned model's weights don't stay
                            # pinned — unless the inline fast path is
                            # still using it (fresh last_used)
                            with self._lock:
                                self._retire_locked(state,
                                                    time.monotonic())
                            return
                    if self._closed:
                        crashed = False
                        return
                    if state.retired and not state.pending:
                        # evicted via retire_model with nothing queued:
                        # exit NOW instead of waiting out the idle
                        # timeout, so the state's strong model reference
                        # dies with the thread. A submit that raced the
                        # eviction and queued anyway is drained first
                        # (the branch above requires an empty queue).
                        state.thread = None
                        crashed = False
                        return
                    # bounded wait window, anchored at the head request's
                    # arrival: late siblings join until the window closes
                    # or the bucket cap is reached
                    deadline = (state.pending[0].t_enqueue
                                + state.effective_window())
                    while not self._closed:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0 or state.pending_rows >= state.cap:
                            break
                        if state.pending_deadlines:
                            # the earliest queued request deadline caps
                            # the wait: a doomed request triggers a drain
                            # (which drops it) the moment it expires,
                            # instead of blocking its caller for the
                            # remainder of a possibly much longer window
                            for r in state.pending:
                                if r.deadline is not None:
                                    remaining = min(remaining,
                                                    r.deadline.remaining())
                            if remaining <= 0:
                                break
                        state.cond.wait(timeout=remaining)
                    if self._closed:
                        crashed = False
                        return
                    batch: List[_Request] = []
                    expired: List[_Request] = []
                    total = 0
                    # ONE O(n) pass: drop already-expired requests BEFORE
                    # paying for a launch (an overloaded queue must not
                    # turn one slow window into a convoy of doomed
                    # launches) and partition survivors into lanes —
                    # never per-item deque.remove(), which would make a
                    # deep drain O(n^2) exactly when the queue is deep
                    lanes: Dict[str, Dict[str, List[_Request]]] = \
                        {p: {} for p in PRIORITIES}
                    for r in state.pending:
                        if r.deadline is not None and r.deadline.expired():
                            if (r.token is not None
                                    and state.dedup.get(r.token) is r):
                                del state.dedup[r.token]
                            expired.append(r)
                        else:
                            lanes[r.priority].setdefault(
                                r.tenant, []).append(r)
                    # interactive lane drains first; within a lane, one
                    # tenant is plain FIFO (the pre-fairness fast path,
                    # byte-identical release order) and several tenants
                    # release by deficit-round-robin — a flooding tenant
                    # saturates only its weighted share of the cap
                    overflow = False
                    throttled: List[str] = []
                    for lane in PRIORITIES:
                        if overflow:
                            break
                        queues = lanes[lane]
                        if not queues:
                            continue
                        if len(queues) == 1:
                            (reqs,) = queues.values()
                            for r in reqs:
                                if batch and total + r.rows > state.cap:
                                    overflow = True
                                    break
                                r.launched = True  # past dedup sharing
                                batch.append(r)
                                total += r.rows
                            continue
                        total, overflow = self._drr_release_locked(
                            state, queues, batch, total, throttled)
                    if throttled and batch:
                        for tenant in sorted(set(throttled)):
                            health.record(
                                health.TENANT_THROTTLED, tenant=tenant,
                                released_rows=total)
                    if batch or expired:
                        dropped = {id(r) for r in batch}
                        dropped.update(id(r) for r in expired)
                        # rebuild preserves arrival order for leftovers
                        state.pending = deque(
                            r for r in state.pending
                            if id(r) not in dropped)
                        state.pending_rows -= (
                            total + sum(r.rows for r in expired))
                        state.pending_deadlines = sum(
                            1 for r in state.pending
                            if r.deadline is not None)
                        self._note_queued(-(len(batch) + len(expired)))
                        # blocked admission waiters: room just freed
                        state.cond.notify_all()
                    if batch:
                        state.inflight += 1
                        self._note_inflight(1)
                if expired:
                    self._fail_expired(expired)
                if not batch:
                    continue  # the whole window expired unlaunched
                try:
                    self._launch(state, batch, total)
                except BaseException as e:  # sparkdl: allow(broad-retry): not a retry — the error is delivered to every drained future
                    # a failure in the launch plumbing itself (concat,
                    # slicing) must still complete every drained future —
                    # the batch already left `pending`, so the terminal
                    # fail-pending sweep would miss it
                    logger.exception(
                        "coalescer launch plumbing failed; delivering the "
                        "error to all %d drained request(s)", len(batch))
                    # ONE failed launch = ONE breaker count, however many
                    # requests the window held; mark every member so the
                    # waiters' fetch-side accounting doesn't re-count it
                    with state.cond:
                        for r in batch:
                            r.breaker_noted = True
                    self._breaker_note(
                        state, e,
                        is_probe=any(r.is_probe for r in batch))
                    for r in batch:
                        if not r.future.done():
                            r.future.set_exception(e)
                finally:
                    with state.cond:
                        state.inflight -= 1
                        for r in batch:
                            if (r.token is not None
                                    and state.dedup.get(r.token) is r):
                                del state.dedup[r.token]
                        self._note_inflight(-1)
        finally:
            if crashed or self._closed:
                self._fail_pending(state,
                                   ExecutorShutdown(
                                       "device execution service shut "
                                       "down with this request still "
                                       "queued"))

    def _drr_release_locked(self, state: _FnState,
                            queues: Dict[str, List[_Request]],
                            batch: List[_Request], total: int,
                            throttled: List[str]) -> Tuple[int, bool]:
        """Release one lane's queued requests by deficit-round-robin
        (caller holds ``state.cond``). Each round credits every tenant
        ``weight * quantum`` rows (quantum = the largest head-of-line
        request, so every tenant frees at least its head per round — the
        loop is O(requests) releases, never stuck), then releases that
        tenant's FIFO while the credit covers it. The first over-cap
        head stops the whole drain (same overflow contract as the FIFO
        path); credit persists across drains for tenants left queued —
        that deficit IS the fairness memory — and resets once a tenant
        drains dry, so idle tenants never bank unbounded credit.
        Tenants left holding requests while the batch launched are
        appended to ``throttled``. Returns ``(total, overflow)``."""
        weights = state.tenant_weights or {}
        deficit = state.tenant_deficit
        order = sorted(queues)
        overflow = False
        while not overflow and any(queues[t] for t in order):
            quantum = max(float(queues[t][0].rows)
                          for t in order if queues[t])
            for tenant in order:
                fifo = queues[tenant]
                if not fifo:
                    continue
                deficit[tenant] = (deficit.get(tenant, 0.0)
                                   + max(1, weights.get(tenant, 1))
                                   * quantum)
                while fifo and deficit[tenant] >= fifo[0].rows:
                    r = fifo[0]
                    if batch and total + r.rows > state.cap:
                        overflow = True
                        break
                    fifo.pop(0)
                    deficit[tenant] -= r.rows
                    r.launched = True  # past the dedup sharing window
                    batch.append(r)
                    total += r.rows
                if overflow:
                    break
        for tenant in order:
            if not queues[tenant]:
                deficit.pop(tenant, None)
            else:
                throttled.append(tenant)
        return total, overflow

    def _fail_expired(self, expired: List[_Request]) -> None:
        """Deliver the deadline-shed outcome: the same deadline-marked
        taxonomy the supervisor's watchdog uses (``DeadlineExceeded`` →
        FATAL, never retried past the budget, never quarantined)."""
        for r in expired:
            health.record(health.EXECUTOR_DEADLINE_SHED, rows=r.rows,
                          priority=r.priority, tenant=r.tenant,
                          queued_s=round(time.monotonic() - r.t_enqueue, 4))
            if not r.future.done():
                r.future.set_exception(resilience.DeadlineExceeded(
                    f"{r.rows}-row request expired in the executor queue "
                    f"(deadline {r.deadline.timeout_s}s); dropped before "
                    "launch"))

    def _fail_pending(self, state: _FnState, error: BaseException) -> None:
        with state.cond:
            pending = list(state.pending)
            state.pending.clear()
            state.pending_rows = 0
            state.pending_deadlines = 0
            state.dedup.clear()
            if state.thread is threading.current_thread():
                state.thread = None
            state.cond.notify_all()  # blocked admission waiters re-check
        if pending:
            self._note_queued(-len(pending))
        for r in pending:
            if not r.future.done():
                r.future.set_exception(error)

    def _launch(self, state: _FnState, batch: List[_Request],
                total_rows: int) -> None:
        """Dispatch one drained window. Requests are grouped by element
        signature first — one jitted fn can legally serve several shapes
        (e.g. uniform image batches of different sizes), and rows only
        concatenate within a shape. A group of one is handed back to run
        inline on its requester's thread; larger groups concatenate into
        one padded launch whose outputs are sliced back per request ON
        DEVICE."""
        t0 = time.monotonic()
        now = t0
        for r in batch:
            # the request's submit-time span context rides as the tail
            # exemplar: a breached queue-wait p99 names the exact trace
            # that waited, not the coalescer thread's ambient context
            telemetry.observe(telemetry.M_QUEUE_WAIT_S, now - r.t_enqueue,
                              exemplar=r.ctx)
            if r.tenant != DEFAULT_TENANT:
                # per-tenant fairness series (per-tenant NAMES — metrics
                # carry no labels); the default tenant stays on the
                # aggregate only, so single-tenant jobs add no series
                telemetry.observe(
                    telemetry.declare_metric(
                        telemetry.tenant_queue_wait_metric(r.tenant),
                        "histogram"),
                    now - r.t_enqueue, exemplar=r.ctx)
        groups: Dict[Tuple, List[_Request]] = {}
        for r in batch:
            groups.setdefault(batching.element_signature(r.tree),
                              []).append(r)
        for group in groups.values():
            rows = sum(r.rows for r in group)
            telemetry.observe(telemetry.M_COALESCE_REQUESTS, len(group),
                              bounds=telemetry.POW2_BOUNDS)
            telemetry.observe(telemetry.M_COALESCE_ROWS, rows,
                              bounds=telemetry.POW2_BOUNDS)
            if len(group) == 1:
                self._hand_back(group[0])
            else:
                self._run_coalesced(state, group, rows)
        telemetry.observe(telemetry.M_LAUNCH_S, time.monotonic() - t0,
                          exemplar=batch[0].ctx if batch else None)

    @staticmethod
    def _hand_back(r: _Request) -> None:
        """Per-request sub-launch: deliver the replay sentinel so the
        REQUESTER'S thread runs the model's own chunked path in `_await`
        (its classified retry and OOM bucket-halving apply unchanged).
        Requests of a split window replay concurrently on their own pool
        threads instead of serializing through the coalescer."""
        if not r.future.done():
            r.future.set_result(_REPLAY_INLINE)

    def _run_coalesced(self, state: _FnState, batch: List[_Request],
                       total_rows: int) -> None:
        import jax

        failure: Optional[Exception] = None
        slices: List[Any] = []
        # The span closes BEFORE any future is delivered: a requester that
        # tears its telemetry scope down the moment its result arrives
        # still finds the launch span recorded.
        with telemetry.span(telemetry.SPAN_COALESCED_LAUNCH,
                            parent=batch[0].ctx,
                            requests=len(batch), rows=total_rows):
            flat = [jax.tree_util.tree_flatten(r.tree) for r in batch]
            treedef = flat[0][1]
            cat_leaves = [np.concatenate([f[0][j] for f in flat], axis=0)
                          for j in range(len(flat[0][0]))]
            planner = state.planner
            if planner is not None:
                # the coalesced launch stream feeds the same learned
                # ladder as the chunked path; a cap tighter than the
                # planner's batch_size falls back to pow2 inside
                planner.observe(total_rows)
                bucket = planner.bucket_for(total_rows, cap=state.cap)
            else:
                bucket = batching.bucket_size(total_rows, state.cap,
                                              state.multiple)
            padded = treedef.unflatten(
                [batching.pad_batch(leaf, bucket)[0]
                 for leaf in cat_leaves])
            fn = state.fn
            # the HEAD request's policy decides whether a transient
            # counts as a retry for accounting; the actual retries run
            # per request under each request's OWN policy (the hand-back
            # below) — never as a backoff sleep on the coalescer thread,
            # which would stall every queued sibling for the duration
            policy = batch[0].policy
            try:
                resilience.inject("device_oom", rows=bucket,
                                  valid=total_rows)
                resilience.inject("transfer_stall", rows=bucket,
                                  valid=total_rows)
                out = fn(padded)  # dispatched async; no block here
            except Exception as e:  # noqa: BLE001 - classified below
                kind = resilience.classify(e)
                if kind == resilience.OOM:
                    health.record(health.OOM_RECHUNK, bucket=bucket,
                                  requests=len(batch))
                elif (kind == resilience.RETRYABLE
                        and policy.max_retries > 0):
                    # CHUNK_RETRY parity with the chunk path: the failed
                    # super-batch IS retried — per request, on the
                    # requesters' own threads via the replay sentinel
                    health.record(health.CHUNK_RETRY, bucket=bucket,
                                  attempt=1, error=type(e).__name__)
                failure = e
            else:
                out_leaves, out_treedef = jax.tree_util.tree_flatten(out)
                off = 0
                for r in batch:
                    slices.append(out_treedef.unflatten(
                        [leaf[off:off + r.rows] for leaf in out_leaves]))
                    off += r.rows
        if failure is not None:
            # ANY super-batch failure splits back into per-request
            # sub-launches on the requesters' own threads. A transient
            # retries there under each request's policy (backoff sleeps
            # never park the coalescer); an OOM re-chunks exactly as the
            # non-coalesced path would (apply_batch's bucket-halving per
            # request); a FATAL poisons only its own request instead of
            # the whole window. Ops are pure (engine contract), so the
            # replay is safe and bit-identical.
            logger.warning(
                "coalesced launch of %d request(s) failed (%s: %s); "
                "splitting back to per-request sub-launches",
                len(batch), type(failure).__name__, failure)
            for r in batch:
                self._hand_back(r)
            return
        for r, sliced in zip(batch, slices):
            if not r.future.done():
                r.future.set_result(sliced)

    # -- introspection -------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """Instantaneous queue/breaker state for the telemetry
        exporter's periodic snapshots (docs/OBSERVABILITY.md "Live
        metrics & SLOs"). Lock order honored: ``self._lock`` is released
        before any state's cond is taken (canonical order is
        cond→lock)."""
        with self._lock:
            states = list(self._states.values())
            out: Dict[str, Any] = {
                "closed": self._closed,
                "queued_requests": self._queued_total,
                "inflight": self._inflight_total,
                "admitted": self._admitted,
                "shed": self._shed,
            }
        models = []
        for state in states:
            with state.cond:
                models.append({
                    "model": getattr(state.model, "name", "?"),
                    "pending_requests": len(state.pending),
                    "pending_rows": state.pending_rows,
                    "inflight": state.inflight,
                    "breaker_state": state.breaker_state,
                })
        out["models"] = models
        return out

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self) -> None:
        """Stop every coalescer thread; fail every queued request with
        :class:`ExecutorShutdown`. In-flight launches complete. No future
        is ever left pending.

        Idempotent and safe to race with concurrent :meth:`submit` calls:
        a second shutdown is a no-op, and a submit that loses the race
        observes ``_closed`` under its state's cond (``_closed`` is
        published under ``self._lock``, which every state lookup also
        takes) and raises — a request can never be queued after its
        state's pending sweep ran without the sweep seeing it."""
        with self._lock:
            if self._shutdown_complete:
                return  # double-shutdown: a no-op
            self._closed = True
            states = list(self._states.values())
        err = ExecutorShutdown("device execution service shut down with "
                               "this request still queued")
        for state in states:
            with state.cond:
                state.cond.notify_all()
                thread = state.thread
            if thread is not None and thread is not threading.current_thread():
                thread.join(timeout=5.0)
            self._fail_pending(state, err)
        with self._lock:
            self._shutdown_complete = True


# ---------------------------------------------------------------------------
# Module-level service + the choke point
# ---------------------------------------------------------------------------

_service = DeviceExecutor()
_service_lock = threading.Lock()


def service() -> DeviceExecutor:
    return _service


def shutdown() -> None:
    """Shut the process-wide service down (fails queued requests)."""
    _service.shutdown()


def status() -> Dict[str, Any]:
    """Queue/breaker state of the process-wide service (the telemetry
    exporter embeds this in every periodic snapshot)."""
    return _service.status()


def reset() -> DeviceExecutor:
    """Shut down and replace the process-wide service (test isolation)."""
    global _service
    with _service_lock:
        old = _service
        _service = DeviceExecutor()
    old.shutdown()
    return _service


def _tree_leaves(obj: Any) -> list:
    """Flatten a staged payload (array / tuple / list / dict pytree)
    without importing jax on the counting path."""
    if isinstance(obj, dict):
        out = []
        for v in obj.values():
            out.extend(_tree_leaves(v))
        return out
    if isinstance(obj, (tuple, list)):
        out = []
        for v in obj:
            out.extend(_tree_leaves(v))
        return out
    return [obj]


def execute(model: Any, array: Any, *, batch_size: int = 64,
            mesh: Any = None,
            retry_policy: Optional[resilience.RetryPolicy] = None,
            prefetch: int = 2, coalesce: Optional[bool] = None,
            priority: Optional[str] = None,
            deadline: Optional[resilience.Deadline] = None,
            tenant: Optional[str] = None,
            coalesce_window_ms: Optional[float] = None) -> Any:
    """THE device entry point for the inference data plane.

    Transformers call this instead of ``model.apply_batch`` (enforced by
    the choke-point lint in ``tests/test_taxonomy_lint.py``): with
    ``EngineConfig.coalesce`` on (the default), eligible requests —
    non-empty, at most one bucket's worth of rows — route through the
    coalescing service; everything else (and ``coalesce=False``) takes
    the existing ``apply_batch`` path unchanged. ``coalesce=None`` reads
    ``EngineConfig.coalesce``.

    ``priority`` (``"interactive"``/``"bulk"``; ``None`` reads
    ``EngineConfig.executor_default_priority``) picks the service lane;
    ``deadline`` (``None`` adopts the ambient :class:`deadline_scope`
    one, which the engine supervisor threads per task) bounds queue wait
    and backpressure blocking. ``tenant`` tags the request for the
    fair-queueing coalescer (``None`` adopts the ambient
    :class:`tenant_scope` tag, falling back to
    ``EngineConfig.executor_default_tenant``). The admission/breaker
    knobs are read from ``EngineConfig`` per call — see the module
    docstring.

    ``coalesce_window_ms`` overrides ``EngineConfig.coalesce_window_ms``
    for THIS call: the serving plane's per-model SLO targets drive the
    adaptive window through it (a tight latency target caps how long a
    row-level request may wait for coalescing siblings). ``None`` keeps
    the config/adaptive behavior.
    """
    # Lazy layering: core must stay importable without the engine, but the
    # coalescing knobs live with the other engine-wide knobs on
    # EngineConfig (the class tests already snapshot/restore).
    from sparkdl_tpu.engine.dataframe import EngineConfig

    EngineConfig.validate()  # read-time knob validation (clear ValueError)
    if telemetry.active() is not None:
        # bytes as staged by the HOST: on the columnar plane this is raw
        # uint8 pixels — the counter is the observable that "host ships
        # uint8 only" (docs/PERF.md "Columnar data plane"); a float32
        # staging regression shows up as a 4x jump per image.
        try:
            payload = sum(int(getattr(leaf, "nbytes", 0))
                          for leaf in _tree_leaves(array))
        except Exception:  # exotic payloads never break the data plane
            payload = 0
        if payload:
            telemetry.count(telemetry.M_STAGED_BYTES, payload)
    # Precision and donation are decided HERE, once, from EngineConfig —
    # never per call site (the choke-point lint flags transformers that
    # try). "float32" leaves the model untouched: bit-identical escape
    # hatch. with_dtype memoizes per precision, so the jit caches behind
    # each variant are shared across calls.
    if (EngineConfig.inference_precision != "float32"
            and hasattr(model, "with_dtype")):
        model = model.with_dtype(EngineConfig.inference_precision)
    donate = EngineConfig.inference_donate_buffers
    eff_batch, multiple = model.bucket_params(batch_size, mesh)
    planner = batching.default_planner(
        getattr(model, "name", "model"), eff_batch, multiple)
    if coalesce is None:
        coalesce = EngineConfig.coalesce
    if not coalesce:
        return model.apply_batch(array, batch_size=batch_size, mesh=mesh,
                                 retry_policy=retry_policy,
                                 prefetch=prefetch, donate=donate,
                                 planner=planner)
    import jax

    array = model.stage_inputs(array)
    cap = eff_batch
    if EngineConfig.coalesce_max_rows is not None:
        cap = min(cap, int(EngineConfig.coalesce_max_rows))
    rows = jax.tree_util.tree_leaves(array)[0].shape[0]
    if rows == 0 or rows > cap:
        # nothing to coalesce (empty partitions hit the memoized empty
        # template) / already a full bucket or more: chunked path
        return model.apply_batch(array, batch_size=batch_size, mesh=mesh,
                                 retry_policy=retry_policy,
                                 prefetch=prefetch, donate=donate,
                                 planner=planner)
    window_ms = (coalesce_window_ms if coalesce_window_ms is not None
                 else EngineConfig.coalesce_window_ms)
    window_s = None if window_ms is None else max(0.0, window_ms / 1e3)
    policy = (retry_policy if retry_policy is not None
              else resilience.DEFAULT_INFERENCE_POLICY)
    if (EngineConfig.executor_max_queued_requests is None
            and EngineConfig.executor_max_queued_rows is None
            and EngineConfig.executor_breaker_threshold <= 0):
        overload = _NO_OVERLOAD  # defaults: no per-call allocation
    else:
        overload = OverloadPolicy(
            max_queued_requests=EngineConfig.executor_max_queued_requests,
            max_queued_rows=EngineConfig.executor_max_queued_rows,
            shed=EngineConfig.executor_overload_mode == "shed",
            breaker_threshold=EngineConfig.executor_breaker_threshold,
            breaker_window_s=EngineConfig.executor_breaker_window_s,
            breaker_cooldown_s=EngineConfig.executor_breaker_cooldown_s)
    if priority is None:
        priority = EngineConfig.executor_default_priority
    if deadline is None:
        deadline = current_deadline()
    if tenant is None:
        tenant = current_tenant()
        if tenant is None:
            tenant = EngineConfig.executor_default_tenant
    return _service.submit(model, array, rows, batch_size, mesh, multiple,
                           policy, window_s, cap, prefetch,
                           priority=priority, deadline=deadline,
                           tenant=tenant,
                           tenant_weights=EngineConfig.executor_tenant_weights,
                           overload=overload, donate=donate,
                           planner=planner)
