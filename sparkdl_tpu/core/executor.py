"""Device execution service: cross-partition dynamic batch coalescing.

BENCH_r05 showed the device starved on exactly the workload the framework
serves — the featurize/transform path: MFU 0.09 (EfficientNetB0), 0.20
(DenseNet121), 0.28 (InceptionV3). The cause is structural: every engine
partition task (``engine/dataframe.py`` pool → transformer op →
``ModelFunction.apply_batch``) stages its own ≤ ``batch_size`` chunk and
issues its own device launch, so an 8-way partitioned DataFrame runs 8
small serial launches instead of one full bucket, and dispatch overhead
dominates for cheap models.

This module is the process-wide fix: transformers enter the device through
ONE choke point, :func:`execute`, and concurrent small requests against
the same compiled function are **coalesced** into one padded
bucket-ladder launch:

- worker threads submit ``(compiled-fn, rows)`` requests to a
  per-compiled-fn queue;
- a coalescer thread drains the queue under a bounded wait window
  (``EngineConfig.coalesce_window_ms``; default an adaptive fraction of
  the observed request latency) and a max-bucket cap, concatenates the
  requests into one padded launch, dispatches it async, slices each
  request's output rows back **on device**, and completes the requesters'
  futures in submission order — each requester then pays its own single
  device→host fetch for exactly its rows;
- a **solo request under no contention takes the existing inline path**
  (``apply_batch`` on the caller's thread) with zero added latency — the
  service only changes behavior when there is someone to coalesce with.

Composition with the existing layers (the invariants tests pin down):

- **bit-identical, order-preserving**: a coalesced launch computes the
  same per-row values as per-request launches (row-wise models are
  bucket-size invariant — the same invariance the OOM re-chunk path has
  always relied on), and every requester gets its rows back in its own
  submission order;
- **resilience**: classification applies per super-batch — ANY failure
  (transient, OOM, FATAL) splits the launch back into per-request
  sub-launches via ``apply_batch`` on the requesters' own threads, so a
  transient's classified retry/backoff runs per request (never a sleep
  on the coalescer thread, which would stall every queued sibling), an
  OOM re-chunks exactly as the non-coalesced path would, and a poisoned
  request fails alone instead of taking its coalesced siblings down
  with it (ops are pure by the engine's contract, so the replay is
  safe);
- **supervision**: the supervisor's deadline watchdog and hedging bound
  each *task* as before (the window is bounded, so a blocked requester
  always unblocks); a hedged duplicate attempt carries its task's token
  (:func:`task_scope`, set by ``engine/supervisor.py``) and **dedups
  before coalescing** — while its sibling's request is still queued the
  attempts share one future instead of launching the same rows twice,
  and once the sibling has launched the hedge re-runs independently so
  speculation can still win past a stalled launch;
- **telemetry**: coalesce-size and queue-wait histograms, a launch
  histogram and an executor occupancy gauge (docs/OBSERVABILITY.md);
- **training never coalesces**: ``Trainer.fit`` owns its own step program
  (donated state threading, deferred sync) and never routes through this
  module — coalescing across training steps would interleave state
  updates from unrelated streams.

Shutdown never leaks a future: :func:`shutdown` (and interpreter exit)
fails every queued request with :class:`ExecutorShutdown`, so a worker
blocked mid-window always completes or raises.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from sparkdl_tpu.core import batching, health, resilience, telemetry

logger = logging.getLogger(__name__)

# Adaptive window bounds (seconds) when EngineConfig.coalesce_window_ms is
# None: a fraction of the observed end-to-end request latency, clamped so
# the window neither busy-spins on microsecond models nor adds visible
# latency to slow ones.
_WINDOW_FRACTION = 0.25
_WINDOW_MIN_S = 0.0005
_WINDOW_MAX_S = 0.02
_WINDOW_DEFAULT_S = 0.002
# Idle coalescer threads exit after this long with an empty queue (and
# restart on the next queued request), so tests and long-lived processes
# don't accumulate one parked thread per model ever served.
_IDLE_EXIT_S = 5.0


class ExecutorShutdown(RuntimeError):
    """The execution service was shut down with this request still queued."""


# ---------------------------------------------------------------------------
# Task tokens (hedge dedup)
# ---------------------------------------------------------------------------

_tls = threading.local()


def current_task_token() -> Optional[Tuple]:
    """The ambient dedup identity for THIS executor call: the task token
    set by :func:`task_scope` extended with the attempt's call sequence
    number. Ops are pure and deterministic (the engine contract), so the
    N-th device call of a task's hedge attempt computes the same rows as
    the N-th call of its primary — the sequence number keeps a task whose
    op chain enters the device several times (e.g. two chained
    transformers sharing one model) from dedup'ing call N onto call M.
    Each read advances the sequence. None outside a scope."""
    token = getattr(_tls, "token", None)
    if token is None:
        return None
    seq = _tls.seq
    _tls.seq = seq + 1
    return token + (seq,)


def reset_call_sequence() -> None:
    """Restart the ambient token's device-call sequence. The supervisor
    calls this at the start of EVERY retry-loop attempt inside a pool
    attempt's :class:`task_scope` (``run_partition_task``'s classified
    retries re-run the op chain from the top, so their device calls
    restart at call 0) — without the reset a retried primary's call 0
    would sit at seq N while a fresh hedge's call 0 sits at seq 0, and
    the hedge's call N could dedup onto the WRONG device call's output.
    No-op outside a scope."""
    if getattr(_tls, "token", None) is not None:
        _tls.seq = 0


class task_scope:
    """Mark device requests from this thread as belonging to one logical
    task attempt. The supervisor wraps every pool attempt (primary,
    retry, hedge) of a task in the SAME token (each attempt — including
    each retry-loop attempt inside a pool attempt, via
    :func:`reset_call_sequence` — restarting the call-sequence counter),
    so a hedged duplicate submitting the same rows while its sibling's
    request is still pending shares that request's future instead of
    coalescing the rows twice."""

    def __init__(self, token: Tuple) -> None:
        self._token = token
        self._prev: Optional[Tuple] = None
        self._prev_seq = 0

    def __enter__(self) -> "task_scope":
        self._prev = getattr(_tls, "token", None)
        self._prev_seq = getattr(_tls, "seq", 0)
        _tls.token = self._token
        _tls.seq = 0
        return self

    def __exit__(self, *exc: Any) -> None:
        _tls.token = self._prev
        _tls.seq = self._prev_seq


# ---------------------------------------------------------------------------
# Requests and per-compiled-fn state
# ---------------------------------------------------------------------------


class _ReplayInline:
    """Sentinel future result: the coalescer handed the request back for
    the REQUESTER'S OWN thread to run via ``apply_batch`` (solo drained
    window, or a member of a terminally-failed super-batch). Executing
    these on the coalescer thread would serialize device work that pool
    threads previously overlapped — and block every queued sibling
    behind one request's fetch and retry-backoff sleeps."""

    __slots__ = ()


_REPLAY_INLINE = _ReplayInline()


class _Request:
    """One queued submission: host-staged rows + the future that will carry
    the ON-DEVICE output slices back to the requester."""

    __slots__ = ("tree", "rows", "future", "token", "policy", "ctx",
                 "t_enqueue", "launched")

    def __init__(self, tree: Any, rows: int, token: Optional[Tuple],
                 policy: resilience.RetryPolicy) -> None:
        self.tree = tree
        self.rows = rows
        self.future: "Future[Any]" = Future()
        self.token = token
        self.policy = policy
        self.ctx = telemetry.current_context()
        self.t_enqueue = time.monotonic()
        # set when the coalescer drains this request: dedup only shares
        # PRE-launch requests, so a hedge arriving later re-executes
        # independently and speculation can still win past a launch that
        # stalled on the device
        self.launched = False


class _FnState:
    """Coalescing state for one compiled fn (one bucket ladder).

    Keyed by the jitted callable's identity — a strong reference is held
    here, so the id can never be recycled while the state exists. All
    fields are guarded by ``cond``'s lock except the immutable config.
    """

    def __init__(self, key: Tuple, fn: Any, model: Any, batch_size: int,
                 mesh: Any, multiple: int) -> None:
        self.key = key
        self.fn = fn
        self.model = model
        self.batch_size = batch_size  # caller's batch_size (pre mesh pad)
        self.mesh = mesh
        self.multiple = multiple
        self.cond = threading.Condition()
        self.pending: "deque[_Request]" = deque()
        self.dedup: Dict[Tuple, _Request] = {}
        self.inflight = 0           # launches running (inline + coalesced)
        self.window_s: Optional[float] = None  # None = adaptive
        self.cap = batch_size
        self.latency_ewma: Optional[float] = None
        self.thread: Optional[threading.Thread] = None
        self.last_used = time.monotonic()

    def effective_window(self) -> float:
        if self.window_s is not None:
            return self.window_s
        if self.latency_ewma is None:
            return _WINDOW_DEFAULT_S
        return min(max(self.latency_ewma * _WINDOW_FRACTION,
                       _WINDOW_MIN_S), _WINDOW_MAX_S)

    def note_latency(self, seconds: float) -> None:
        prev = self.latency_ewma
        self.latency_ewma = (seconds if prev is None
                             else 0.8 * prev + 0.2 * seconds)


class DeviceExecutor:
    """The process-wide coalescing service (one instance per process; the
    module-level :func:`execute` routes through :func:`service`)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._states: Dict[Tuple, _FnState] = {}
        self._closed = False
        self._thread_seq = 0
        self._inflight_total = 0  # O(1) occupancy counter (gauge source)

    # -- submission ----------------------------------------------------------

    def submit(self, model: Any, tree: Any, rows: int, batch_size: int,
               mesh: Any, multiple: int, policy: resilience.RetryPolicy,
               window_s: Optional[float], cap: int,
               prefetch: int) -> Any:
        """Run ``rows`` staged rows through the model, coalescing with any
        concurrent sibling requests against the same compiled fn. Returns
        host numpy (structure mirrors the model output). Blocking."""
        fn = model.jitted(mesh=mesh)
        state = self._state(fn, model, batch_size, mesh, multiple)
        token = current_task_token()
        t0 = time.monotonic()
        request: Optional[_Request] = None
        inline = False
        with state.cond:
            if self._closed:
                raise ExecutorShutdown("device execution service is shut "
                                       "down")
            state.window_s = window_s
            state.cap = cap
            if token is not None:
                dup = state.dedup.get(token)
                if (dup is not None and dup.rows == rows
                        and not dup.launched and not dup.future.done()):
                    # hedged duplicate of a sibling attempt whose request
                    # is still QUEUED: share its future — the rows
                    # coalesce exactly once. An already-launched (or
                    # inline) sibling is NOT shared: the hedge re-runs
                    # the pure ops independently, so speculation can
                    # still win past a launch stalled on the device.
                    request = dup
                    telemetry.count(telemetry.M_COALESCE_DEDUP)
            if request is None:
                if state.inflight == 0 and not state.pending:
                    # solo under no contention: the existing inline path
                    # on the caller's thread — zero added latency.
                    # inflight is bumped first so siblings arriving
                    # meanwhile queue up for the coalescer instead of
                    # serializing behind us.
                    state.inflight += 1
                    self._note_inflight(1)
                    inline = True
                else:
                    request = _Request(tree, rows, token, policy)
                    state.pending.append(request)
                    if token is not None:
                        state.dedup[token] = request
                    self._ensure_thread(state)
                    state.cond.notify_all()
        if not inline:
            return self._await(state, request, t0)
        try:
            return model.apply_batch(tree, batch_size=batch_size,
                                     mesh=mesh, retry_policy=policy,
                                     prefetch=prefetch)
        finally:
            with state.cond:
                state.inflight -= 1
                state.note_latency(time.monotonic() - t0)
                self._note_inflight(-1)

    def _await(self, state: _FnState, request: _Request, t0: float) -> Any:
        """Block on the request's future and pay the requester's single
        device→host fetch per output leaf (slices arrive device-resident
        with the pad rows already cut off).

        Dispatch is async, so a launch that failed at EXECUTION time (a
        real device OOM the dispatch-side classification never saw)
        surfaces here, at the fetch. That path re-runs THIS request alone
        through ``apply_batch`` — its classified retry and OOM
        bucket-halving apply, and a poisoned sibling cannot take this
        request down with it. Errors delivered via ``set_exception``
        already went through per-request isolation and propagate as-is.
        """
        import jax

        out = request.future.result()  # isolated failures raise here
        if isinstance(out, _ReplayInline):
            # handed back by the coalescer (solo drained window, or a
            # terminal super-batch failure split): run the model's own
            # chunked path HERE, on the requester's thread — classified
            # retry and OOM bucket-halving apply per request, and the
            # coalescer thread stays free to drain siblings
            try:
                return state.model.apply_batch(
                    request.tree, batch_size=state.batch_size,
                    mesh=state.mesh, retry_policy=request.policy,
                    prefetch=0)
            finally:
                with state.cond:
                    state.note_latency(time.monotonic() - t0)
        try:
            host = jax.tree_util.tree_map(np.asarray, out)
        except Exception as e:  # noqa: BLE001 - classified, then replayed
            kind = resilience.classify(e)
            if kind == resilience.OOM:
                health.record(health.OOM_RECHUNK, rows=request.rows,
                              at="fetch")
            logger.warning(
                "coalesced result fetch failed (%s: %s; classified %s); "
                "re-running the %d-row request alone", type(e).__name__,
                e, kind, request.rows)
            host = state.model.apply_batch(
                request.tree, batch_size=state.batch_size,
                mesh=state.mesh, retry_policy=request.policy, prefetch=0)
        with state.cond:
            state.note_latency(time.monotonic() - t0)
        return host

    # -- state / thread management -------------------------------------------

    def _state(self, fn: Any, model: Any, batch_size: int, mesh: Any,
               multiple: int) -> _FnState:
        key = (id(fn), batch_size, multiple)
        with self._lock:
            state = self._states.get(key)
            if state is None or state.fn is not fn:
                self._sweep_stale_locked()
                state = _FnState(key, fn, model, batch_size, mesh,
                                 multiple)
                self._states[key] = state
            state.last_used = time.monotonic()
            return state

    def _retire_locked(self, state: _FnState, now: float) -> None:
        """Drop a fully-quiesced idle state from the registry — the ONE
        definition of the retirement invariant, shared by the coalescer's
        idle exit and the opportunistic new-state sweep. BOTH state.cond
        and self._lock must be held."""
        if (not state.pending and state.inflight == 0
                and state.thread is None
                and now - state.last_used >= _IDLE_EXIT_S
                and self._states.get(state.key) is state):
            del self._states[state.key]

    def _sweep_stale_locked(self) -> None:
        """Drop idle states so the service never pins a discarded model's
        weights for the process lifetime (model churn: CrossValidator,
        notebooks). Called with self._lock held, on the rare new-state
        path; a state's cond is only probed non-blocking — the canonical
        lock order is cond→lock, so blocking here could deadlock."""
        now = time.monotonic()
        for state in list(self._states.values()):
            if now - state.last_used < _IDLE_EXIT_S:
                continue
            if not state.cond.acquire(blocking=False):
                continue  # busy: next sweep gets it
            try:
                self._retire_locked(state, now)
            finally:
                state.cond.release()

    def _ensure_thread(self, state: _FnState) -> None:
        # caller holds state.cond
        if state.thread is not None and state.thread.is_alive():
            return
        with self._lock:
            self._thread_seq += 1
            seq = self._thread_seq
        state.thread = threading.Thread(
            target=self._coalesce_loop, args=(state,),
            name=f"sparkdl-exec-{seq}", daemon=True)
        state.thread.start()

    def _note_inflight(self, delta: int) -> None:
        """O(1) process-wide in-flight accounting feeding the occupancy
        gauge (no cross-state sums on the per-request hot path)."""
        with self._lock:
            self._inflight_total += delta
            total = self._inflight_total
        if telemetry.active() is not None:
            telemetry.gauge_set(telemetry.M_EXECUTOR_OCCUPANCY, total)

    # -- the coalescer -------------------------------------------------------

    def _coalesce_loop(self, state: _FnState) -> None:
        # `crashed` guards the terminal fail-pending: an IDLE exit hands
        # the (empty) queue back cleanly — failing in that window could
        # race a fresh submit that already started a successor thread.
        crashed = True
        try:
            while True:
                with state.cond:
                    idle_since = time.monotonic()
                    while not state.pending and not self._closed:
                        state.cond.wait(timeout=_IDLE_EXIT_S)
                        if (not state.pending and not self._closed
                                and time.monotonic() - idle_since
                                >= _IDLE_EXIT_S):
                            state.thread = None
                            crashed = False
                            # retire the whole state with the thread so
                            # an abandoned model's weights don't stay
                            # pinned — unless the inline fast path is
                            # still using it (fresh last_used)
                            with self._lock:
                                self._retire_locked(state,
                                                    time.monotonic())
                            return
                    if self._closed:
                        crashed = False
                        return
                    # bounded wait window, anchored at the head request's
                    # arrival: late siblings join until the window closes
                    # or the bucket cap is reached
                    deadline = (state.pending[0].t_enqueue
                                + state.effective_window())
                    while not self._closed:
                        total = sum(r.rows for r in state.pending)
                        remaining = deadline - time.monotonic()
                        if remaining <= 0 or total >= state.cap:
                            break
                        state.cond.wait(timeout=remaining)
                    if self._closed:
                        crashed = False
                        return
                    batch: List[_Request] = []
                    total = 0
                    while state.pending:
                        nxt = state.pending[0]
                        if batch and total + nxt.rows > state.cap:
                            break  # leave the rest for the next round
                        nxt.launched = True  # past dedup's sharing window
                        batch.append(state.pending.popleft())
                        total += nxt.rows
                    state.inflight += 1
                    self._note_inflight(1)
                try:
                    self._launch(state, batch, total)
                except BaseException as e:  # taxonomy-ok: not a retry — the error is delivered to every drained future
                    # a failure in the launch plumbing itself (concat,
                    # slicing) must still complete every drained future —
                    # the batch already left `pending`, so the terminal
                    # fail-pending sweep would miss it
                    logger.exception(
                        "coalescer launch plumbing failed; delivering the "
                        "error to all %d drained request(s)", len(batch))
                    for r in batch:
                        if not r.future.done():
                            r.future.set_exception(e)
                finally:
                    with state.cond:
                        state.inflight -= 1
                        for r in batch:
                            if (r.token is not None
                                    and state.dedup.get(r.token) is r):
                                del state.dedup[r.token]
                        self._note_inflight(-1)
        finally:
            if crashed or self._closed:
                self._fail_pending(state,
                                   ExecutorShutdown(
                                       "device execution service shut "
                                       "down with this request still "
                                       "queued"))

    def _fail_pending(self, state: _FnState, error: BaseException) -> None:
        with state.cond:
            pending = list(state.pending)
            state.pending.clear()
            state.dedup.clear()
            if state.thread is threading.current_thread():
                state.thread = None
        for r in pending:
            if not r.future.done():
                r.future.set_exception(error)

    def _launch(self, state: _FnState, batch: List[_Request],
                total_rows: int) -> None:
        """Dispatch one drained window. Requests are grouped by element
        signature first — one jitted fn can legally serve several shapes
        (e.g. uniform image batches of different sizes), and rows only
        concatenate within a shape. A group of one is handed back to run
        inline on its requester's thread; larger groups concatenate into
        one padded launch whose outputs are sliced back per request ON
        DEVICE."""
        t0 = time.monotonic()
        now = t0
        for r in batch:
            telemetry.observe(telemetry.M_QUEUE_WAIT_S, now - r.t_enqueue)
        groups: Dict[Tuple, List[_Request]] = {}
        for r in batch:
            groups.setdefault(batching.element_signature(r.tree),
                              []).append(r)
        for group in groups.values():
            rows = sum(r.rows for r in group)
            telemetry.observe(telemetry.M_COALESCE_REQUESTS, len(group),
                              bounds=telemetry.POW2_BOUNDS)
            telemetry.observe(telemetry.M_COALESCE_ROWS, rows,
                              bounds=telemetry.POW2_BOUNDS)
            if len(group) == 1:
                self._hand_back(group[0])
            else:
                self._run_coalesced(state, group, rows)
        telemetry.observe(telemetry.M_LAUNCH_S, time.monotonic() - t0)

    @staticmethod
    def _hand_back(r: _Request) -> None:
        """Per-request sub-launch: deliver the replay sentinel so the
        REQUESTER'S thread runs the model's own chunked path in `_await`
        (its classified retry and OOM bucket-halving apply unchanged).
        Requests of a split window replay concurrently on their own pool
        threads instead of serializing through the coalescer."""
        if not r.future.done():
            r.future.set_result(_REPLAY_INLINE)

    def _run_coalesced(self, state: _FnState, batch: List[_Request],
                       total_rows: int) -> None:
        import jax

        failure: Optional[Exception] = None
        slices: List[Any] = []
        # The span closes BEFORE any future is delivered: a requester that
        # tears its telemetry scope down the moment its result arrives
        # still finds the launch span recorded.
        with telemetry.span(telemetry.SPAN_COALESCED_LAUNCH,
                            parent=batch[0].ctx,
                            requests=len(batch), rows=total_rows):
            flat = [jax.tree_util.tree_flatten(r.tree) for r in batch]
            treedef = flat[0][1]
            cat_leaves = [np.concatenate([f[0][j] for f in flat], axis=0)
                          for j in range(len(flat[0][0]))]
            bucket = batching.bucket_size(total_rows, state.cap,
                                          state.multiple)
            padded = treedef.unflatten(
                [batching.pad_batch(leaf, bucket)[0]
                 for leaf in cat_leaves])
            fn = state.fn
            # the HEAD request's policy decides whether a transient
            # counts as a retry for accounting; the actual retries run
            # per request under each request's OWN policy (the hand-back
            # below) — never as a backoff sleep on the coalescer thread,
            # which would stall every queued sibling for the duration
            policy = batch[0].policy
            try:
                resilience.inject("device_oom", rows=bucket,
                                  valid=total_rows)
                resilience.inject("transfer_stall", rows=bucket,
                                  valid=total_rows)
                out = fn(padded)  # dispatched async; no block here
            except Exception as e:  # noqa: BLE001 - classified below
                kind = resilience.classify(e)
                if kind == resilience.OOM:
                    health.record(health.OOM_RECHUNK, bucket=bucket,
                                  requests=len(batch))
                elif (kind == resilience.RETRYABLE
                        and policy.max_retries > 0):
                    # CHUNK_RETRY parity with the chunk path: the failed
                    # super-batch IS retried — per request, on the
                    # requesters' own threads via the replay sentinel
                    health.record(health.CHUNK_RETRY, bucket=bucket,
                                  attempt=1, error=type(e).__name__)
                failure = e
            else:
                out_leaves, out_treedef = jax.tree_util.tree_flatten(out)
                off = 0
                for r in batch:
                    slices.append(out_treedef.unflatten(
                        [leaf[off:off + r.rows] for leaf in out_leaves]))
                    off += r.rows
        if failure is not None:
            # ANY super-batch failure splits back into per-request
            # sub-launches on the requesters' own threads. A transient
            # retries there under each request's policy (backoff sleeps
            # never park the coalescer); an OOM re-chunks exactly as the
            # non-coalesced path would (apply_batch's bucket-halving per
            # request); a FATAL poisons only its own request instead of
            # the whole window. Ops are pure (engine contract), so the
            # replay is safe and bit-identical.
            logger.warning(
                "coalesced launch of %d request(s) failed (%s: %s); "
                "splitting back to per-request sub-launches",
                len(batch), type(failure).__name__, failure)
            for r in batch:
                self._hand_back(r)
            return
        for r, sliced in zip(batch, slices):
            if not r.future.done():
                r.future.set_result(sliced)

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self) -> None:
        """Stop every coalescer thread; fail every queued request with
        :class:`ExecutorShutdown`. In-flight launches complete. No future
        is ever left pending."""
        with self._lock:
            self._closed = True
            states = list(self._states.values())
        err = ExecutorShutdown("device execution service shut down with "
                               "this request still queued")
        for state in states:
            with state.cond:
                state.cond.notify_all()
                thread = state.thread
            if thread is not None and thread is not threading.current_thread():
                thread.join(timeout=5.0)
            self._fail_pending(state, err)


# ---------------------------------------------------------------------------
# Module-level service + the choke point
# ---------------------------------------------------------------------------

_service = DeviceExecutor()
_service_lock = threading.Lock()


def service() -> DeviceExecutor:
    return _service


def shutdown() -> None:
    """Shut the process-wide service down (fails queued requests)."""
    _service.shutdown()


def reset() -> DeviceExecutor:
    """Shut down and replace the process-wide service (test isolation)."""
    global _service
    with _service_lock:
        old = _service
        _service = DeviceExecutor()
    old.shutdown()
    return _service


def execute(model: Any, array: Any, *, batch_size: int = 64,
            mesh: Any = None,
            retry_policy: Optional[resilience.RetryPolicy] = None,
            prefetch: int = 2, coalesce: Optional[bool] = None) -> Any:
    """THE device entry point for the inference data plane.

    Transformers call this instead of ``model.apply_batch`` (enforced by
    the choke-point lint in ``tests/test_taxonomy_lint.py``): with
    ``EngineConfig.coalesce`` on (the default), eligible requests —
    non-empty, at most one bucket's worth of rows — route through the
    coalescing service; everything else (and ``coalesce=False``) takes
    the existing ``apply_batch`` path unchanged. ``coalesce=None`` reads
    ``EngineConfig.coalesce``.
    """
    # Lazy layering: core must stay importable without the engine, but the
    # coalescing knobs live with the other engine-wide knobs on
    # EngineConfig (the class tests already snapshot/restore).
    from sparkdl_tpu.engine.dataframe import EngineConfig

    if coalesce is None:
        coalesce = EngineConfig.coalesce
    if not coalesce:
        return model.apply_batch(array, batch_size=batch_size, mesh=mesh,
                                 retry_policy=retry_policy,
                                 prefetch=prefetch)
    import jax

    array = model.stage_inputs(array)
    eff_batch, multiple = model.bucket_params(batch_size, mesh)
    cap = eff_batch
    if EngineConfig.coalesce_max_rows is not None:
        cap = min(cap, int(EngineConfig.coalesce_max_rows))
    rows = jax.tree_util.tree_leaves(array)[0].shape[0]
    if rows == 0 or rows > cap:
        # nothing to coalesce (empty partitions hit the memoized empty
        # template) / already a full bucket or more: chunked path
        return model.apply_batch(array, batch_size=batch_size, mesh=mesh,
                                 retry_policy=retry_policy,
                                 prefetch=prefetch)
    window_ms = EngineConfig.coalesce_window_ms
    window_s = None if window_ms is None else max(0.0, window_ms / 1e3)
    policy = (retry_policy if retry_policy is not None
              else resilience.DEFAULT_INFERENCE_POLICY)
    return _service.submit(model, array, rows, batch_size, mesh, multiple,
                           policy, window_s, cap, prefetch)
