"""Core runtime: mesh/device setup, ModelFunction, batching, checkpointing.

The rebuild's L2 (the reference's graph toolkit, SURVEY.md §1) — except the
"graph" is a pure function and the "session" is jit+PJRT.

Exports are LAZY (PEP 562), mirroring the top-level package: importing
``sparkdl_tpu.core`` must not drag in jax. The decode pool's spawned
worker processes (``core/decode_pool.py``) import this package on their
way to the image codecs, and a jax import per worker would cost seconds
of startup and a device-runtime footprint per process; the stdlib-only
submodules (health, resilience, telemetry, profiling, pipeline) stay
importable for free. ``from sparkdl_tpu.core import executor`` still
works — Python falls back to the submodule import — and the re-exported
names (``ModelFunction``, ``Telemetry``, …) resolve on first attribute
access.
"""

_LAZY_EXPORTS = {
    # mesh / sharding surface
    "DATA_AXIS": ("sparkdl_tpu.core.mesh", "DATA_AXIS"),
    "MODEL_AXIS": ("sparkdl_tpu.core.mesh", "MODEL_AXIS"),
    "CONTEXT_AXIS": ("sparkdl_tpu.core.mesh", "CONTEXT_AXIS"),
    "EXPERT_AXIS": ("sparkdl_tpu.core.mesh", "EXPERT_AXIS"),
    "MeshConfig": ("sparkdl_tpu.core.mesh", "MeshConfig"),
    "make_mesh": ("sparkdl_tpu.core.mesh", "make_mesh"),
    "data_parallel_mesh": ("sparkdl_tpu.core.mesh", "data_parallel_mesh"),
    "batch_sharding": ("sparkdl_tpu.core.mesh", "batch_sharding"),
    "replicated": ("sparkdl_tpu.core.mesh", "replicated"),
    "shard_batch": ("sparkdl_tpu.core.mesh", "shard_batch"),
    # model function
    "ModelFunction": ("sparkdl_tpu.core.model_function", "ModelFunction"),
    "InputModel": ("sparkdl_tpu.core.model_function", "InputModel"),
    "TensorSpec": ("sparkdl_tpu.core.model_function", "TensorSpec"),
    # submodules re-exported as attributes (import still works without
    # these entries; they keep `sparkdl_tpu.core.batching`-style attribute
    # access alive for code that only imported the package)
    "batching": ("sparkdl_tpu.core", "batching"),
    "debug": ("sparkdl_tpu.core", "debug"),
    "decode_pool": ("sparkdl_tpu.core", "decode_pool"),
    "executor": ("sparkdl_tpu.core", "executor"),
    "health": ("sparkdl_tpu.core", "health"),
    "mesh": ("sparkdl_tpu.core", "mesh"),
    "model_function": ("sparkdl_tpu.core", "model_function"),
    "pipeline": ("sparkdl_tpu.core", "pipeline"),
    "profiling": ("sparkdl_tpu.core", "profiling"),
    "resilience": ("sparkdl_tpu.core", "resilience"),
    "slo": ("sparkdl_tpu.core", "slo"),
    "telemetry": ("sparkdl_tpu.core", "telemetry"),
    # resilience / health / telemetry names
    "Deadline": ("sparkdl_tpu.core.resilience", "Deadline"),
    "Fault": ("sparkdl_tpu.core.resilience", "Fault"),
    "FaultInjector": ("sparkdl_tpu.core.resilience", "FaultInjector"),
    "RetryPolicy": ("sparkdl_tpu.core.resilience", "RetryPolicy"),
    "classify": ("sparkdl_tpu.core.resilience", "classify"),
    "DeviceExecutor": ("sparkdl_tpu.core.executor", "DeviceExecutor"),
    "DevicePrefetcher": ("sparkdl_tpu.core.pipeline", "DevicePrefetcher"),
    "DecodePool": ("sparkdl_tpu.core.decode_pool", "DecodePool"),
    "HealthMonitor": ("sparkdl_tpu.core.health", "HealthMonitor"),
    "MetricsRegistry": ("sparkdl_tpu.core.telemetry", "MetricsRegistry"),
    "RunReport": ("sparkdl_tpu.core.telemetry", "RunReport"),
    "SLORule": ("sparkdl_tpu.core.slo", "SLORule"),
    "SLOWatchdog": ("sparkdl_tpu.core.slo", "SLOWatchdog"),
    "Telemetry": ("sparkdl_tpu.core.telemetry", "Telemetry"),
    "Tracer": ("sparkdl_tpu.core.telemetry", "Tracer"),
}

__all__ = sorted(_LAZY_EXPORTS)


def __getattr__(name):
    try:
        module_name, attr = _LAZY_EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module 'sparkdl_tpu.core' has no attribute {name!r}") from None
    import importlib

    if module_name == "sparkdl_tpu.core":
        value = importlib.import_module(f"sparkdl_tpu.core.{attr}")
    else:
        value = getattr(importlib.import_module(module_name), attr)
    globals()[name] = value
    return value
