"""Core runtime: mesh/device setup, ModelFunction, batching, checkpointing.

The rebuild's L2 (the reference's graph toolkit, SURVEY.md §1) — except the
"graph" is a pure function and the "session" is jit+PJRT.
"""

from sparkdl_tpu.core.mesh import (
    DATA_AXIS, MODEL_AXIS, CONTEXT_AXIS, EXPERT_AXIS,
    MeshConfig, make_mesh, data_parallel_mesh, batch_sharding, replicated,
    shard_batch,
)
from sparkdl_tpu.core.executor import DeviceExecutor
from sparkdl_tpu.core.model_function import ModelFunction, InputModel, TensorSpec
from sparkdl_tpu.core import batching
from sparkdl_tpu.core import executor
from sparkdl_tpu.core import health
from sparkdl_tpu.core import pipeline
from sparkdl_tpu.core import resilience
from sparkdl_tpu.core import slo
from sparkdl_tpu.core import telemetry
from sparkdl_tpu.core.slo import SLORule, SLOWatchdog
from sparkdl_tpu.core.pipeline import DevicePrefetcher
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.resilience import (
    Deadline, Fault, FaultInjector, RetryPolicy, classify,
)
from sparkdl_tpu.core.telemetry import (
    MetricsRegistry, RunReport, Telemetry, Tracer,
)

__all__ = [
    "DATA_AXIS", "MODEL_AXIS", "CONTEXT_AXIS", "EXPERT_AXIS",
    "MeshConfig", "make_mesh", "data_parallel_mesh", "batch_sharding",
    "replicated", "shard_batch",
    "ModelFunction", "InputModel", "TensorSpec",
    "batching", "executor", "health", "pipeline", "resilience",
    "slo", "telemetry",
    "Deadline", "DeviceExecutor", "DevicePrefetcher", "Fault",
    "FaultInjector",
    "HealthMonitor", "MetricsRegistry", "RetryPolicy", "RunReport",
    "SLORule", "SLOWatchdog", "Telemetry", "Tracer", "classify",
]
