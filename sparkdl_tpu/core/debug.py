"""Debug hardening (SURVEY.md §5.2).

The reference had no sanitizers of its own (JVM memory model + TF session
thread-safety); the TPU-native equivalents are JAX's numeric and tracer
sanitizers, packaged here:

- ``debug_mode()`` — context manager enabling ``jax_debug_nans`` (every
  primitive re-checked; a NaN raises ``FloatingPointError`` at the op that
  produced it instead of poisoning downstream metrics) and
  ``jax_check_tracer_leaks`` (escaped tracers raise at the leak site).
- ``SPARKDL_DEBUG=1`` — tests/conftest.py enables both suite-wide; off by
  default because op-by-op NaN re-checking disables fusion and slows whole
  models by orders of magnitude.

Use around a failing fit::

    from sparkdl_tpu.core.debug import debug_mode
    with debug_mode():
        estimator.fit(df)   # raises at the first NaN-producing op
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator

DEBUG_ENV = "SPARKDL_DEBUG"


@contextlib.contextmanager
def debug_mode(nans: bool = True, leaks: bool = True) -> Iterator[None]:
    """Enable NaN checking and tracer-leak checking within the scope."""
    import jax

    managers = []
    if nans:
        managers.append(("jax_debug_nans", True))
    if leaks:
        managers.append(("jax_check_tracer_leaks", True))
    with contextlib.ExitStack() as stack:
        for name, value in managers:
            # jax.config attributes are context-manager capable via
            # jax.config.update + restore; use the documented option CM.
            stack.enter_context(_option(name, value))
        yield


@contextlib.contextmanager
def _option(name: str, value) -> Iterator[None]:
    import jax

    old = getattr(jax.config, name)
    jax.config.update(name, value)
    try:
        yield
    finally:
        jax.config.update(name, old)


def debug_enabled() -> bool:
    return os.environ.get(DEBUG_ENV, "") not in ("", "0")
