"""Asynchronous host→device input pipeline (ISSUE 3 tentpole).

The classic tf.data/Horovod-era overlap the reference stack shipped by
default: host ETL (decode, resize, pad, ``device_put``) for batch ``k+1``
runs on a background staging thread while the device trains on batch
``k``. BENCH_r05 measured the e2e streaming fit spending ~24 s in
``sparkdl.decode`` and ~93 s in ``sparkdl.train_step`` strictly
serialized; a bounded prefetcher hides the host side behind device
compute with zero change in results (staging is pure — the batch values
and their order are identical, only the thread that prepares them moves).

Design constraints honored here:

- **Bounded**: at most ``depth`` staged items wait in the queue, plus the
  one the producer holds while blocked on ``put`` — memory stays
  O(depth) batches, never the epoch.
- **Order-preserving**: ONE staging thread consumes the source iterator
  in order and a FIFO queue delivers in order — a pipelined fit replays
  the exact batch sequence of the serial loop (bit-identical training,
  exact checkpoint resume).
- **Error propagation**: an exception anywhere in the source iterator or
  ``stage_fn`` is caught on the staging thread, delivered to the
  consumer at its next ``__next__``, and re-raised there with the
  staging thread fully joined — no leaked thread, no swallowed error.
- **Clean shutdown**: ``close()`` (or leaving the ``with`` block, or
  dropping the iterator early) wakes a producer blocked on a full queue,
  joins the thread, and drops staged items.
- **Observable**: per-stream counters (items staged, consumer stalls and
  stall seconds, producer-ahead hits, max queue depth) feed the
  ``sparkdl.host_wait`` phase timer (core.profiling) and one
  ``prefetch_report`` health event per stream, so "is the device starved
  by the host?" is answerable from phase stats alone.

``depth=0`` degrades to synchronous inline staging on the caller's
thread (no thread is created) — same iteration contract, zero overlap;
the knob every caller can use to fall back to the serial behavior.

Scope note (r8): this prefetcher overlaps *within* one multi-chunk
stream (a large partition, a training epoch). The complementary
*cross-stream* overlap — many partitions each holding less than one
bucket of rows — is the device execution service's coalescer
(``core/executor.py``): single-bucket requests skip the staging thread
(nothing to stage ahead) and merge with concurrent siblings instead.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Iterator, Optional

from sparkdl_tpu.core import health, profiling, telemetry


class _Done:
    """Sentinel: the source iterator is exhausted."""


class _Raised:
    """Sentinel wrapper: the staging thread raised; deliver to consumer."""

    def __init__(self, error: BaseException) -> None:
        self.error = error


@dataclass
class PrefetchStats:
    """Counters for one prefetch stream (all monotonic, thread-safe via
    the prefetcher's lock)."""

    staged: int = 0          # items produced by the staging thread
    consumed: int = 0        # items delivered to the consumer
    stalls: int = 0          # consumer waits on an empty queue (starvation)
    stall_s: float = 0.0     # total seconds the consumer waited
    ready_hits: int = 0      # items that were staged BEFORE being requested
    max_depth: int = 0       # high-water mark of staged-and-waiting items
    stage_s: float = 0.0     # total seconds spent in stage_fn + source pull

    def as_dict(self) -> dict:
        return {
            "staged": self.staged, "consumed": self.consumed,
            "stalls": self.stalls, "stall_s": round(self.stall_s, 6),
            "ready_hits": self.ready_hits, "max_depth": self.max_depth,
            "stage_s": round(self.stage_s, 6),
        }


# Wake-up granularity for a producer blocked on a full queue: close() is
# noticed within this bound without busy-waiting.
_PUT_POLL_S = 0.05


class DevicePrefetcher:
    """Bounded background staging stage over any ``(item, ...)`` iterable.

    ::

        with DevicePrefetcher(batches, stage_fn=stage, depth=2) as staged:
            for xd, yd in staged:
                state, metrics = train_step(state, xd, yd)   # async

    ``source``: any iterable — including generators that decode lazily
    (``streamPartitions`` output): the whole pull+decode+stage chain runs
    on the staging thread, overlapping device compute on the consumer's
    thread. ``stage_fn(item) -> staged`` runs on the staging thread too
    (``device_put`` / ``make_array_from_process_local_data``; identity
    when None). ``depth``: staged items buffered ahead (0 = inline
    synchronous staging, no thread). One stream is ONE pass — build a
    fresh prefetcher per epoch.

    Multi-host note: do NOT run collectives (lockstep allgathers, global
    array assembly) on the staging thread while the consumer thread also
    dispatches collective programs — the two threads' enqueue order is
    scheduler-dependent and can diverge across processes, hanging the
    gang. ``Trainer.fit`` therefore forces ``depth=0`` (inline staging,
    no thread) whenever ``jax.process_count() > 1``.
    """

    def __init__(self, source: Iterable[Any],
                 stage_fn: Optional[Callable[[Any], Any]] = None,
                 depth: int = 2, name: str = "prefetch",
                 report_health: bool = False) -> None:
        if depth < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")
        self.name = name
        self.depth = depth
        # Event-log hygiene: only long-lived named streams (one per fit
        # epoch) emit the prefetch_report health EVENT — a per-partition-
        # chunk event from every run_batched call would flood
        # HealthMonitor's bounded first-N event log and evict later
        # quarantine/retry entries. Stall time always feeds the global
        # sparkdl.host_wait phase timer regardless.
        self._report_health = report_health
        self.stats = PrefetchStats()
        self._stage_fn = stage_fn
        self._lock = threading.Lock()
        self._closed = False
        self._reported = False
        self._inline: Optional[Iterator[Any]] = None
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Cross-thread trace handoff (core.telemetry): spans opened on
        # the staging thread (stage_fn's annotate calls, the source's
        # decode phases) parent under the CONSUMER's span that built
        # this prefetcher, keeping one run trace across threads.
        self._trace_ctx = telemetry.current_context()
        if depth == 0:
            self._inline = iter(source)
            return
        self._queue = queue.Queue(maxsize=depth)
        self._thread = threading.Thread(
            target=self._produce, args=(iter(source),),
            name=f"sparkdl-prefetch-{name}", daemon=True)
        self._thread.start()

    # -- staging thread ------------------------------------------------------

    def _produce(self, it: Iterator[Any]) -> None:
        out: Any = _Done
        telemetry.attach(self._trace_ctx)  # fresh thread: safe to adopt
        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    break
                if self._stage_fn is not None:
                    item = self._stage_fn(item)
                dt = time.perf_counter() - t0
                with self._lock:
                    self.stats.staged += 1
                    self.stats.stage_s += dt
                    if self._queue.qsize() + 1 > self.stats.max_depth:
                        self.stats.max_depth = self._queue.qsize() + 1
                if telemetry.active() is not None:
                    telemetry.gauge_set(telemetry.M_PREFETCH_DEPTH,
                                        self._queue.qsize() + 1)
                if not self._put(item):
                    return  # closed while waiting for queue room
        except BaseException as e:  # noqa: BLE001 - delivered to consumer
            out = _Raised(e)
        # deliver the terminal sentinel (drop it if the consumer closed)
        self._put(out)

    def _put(self, item: Any) -> bool:
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=_PUT_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer ------------------------------------------------------------

    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self) -> Any:
        if self._inline is not None:  # depth == 0: synchronous staging
            if self._closed:
                raise StopIteration
            # the consumer waits out the ENTIRE host pull+stage inline —
            # serial staging is 100% starvation, so the whole duration
            # feeds HOST_WAIT (overlap_ratio → 0, the serial baseline;
            # the threaded path only records actual queue waits)
            t0 = time.perf_counter()
            try:
                item = next(self._inline)
            except StopIteration:
                self._finish()
                raise
            staged = (self._stage_fn(item)
                      if self._stage_fn is not None else item)
            dt = time.perf_counter() - t0
            with self._lock:
                self.stats.staged += 1
                self.stats.consumed += 1
                self.stats.stalls += 1
                self.stats.stall_s += dt
                self.stats.stage_s += dt
            profiling.add_phase_time(profiling.HOST_WAIT, dt)
            telemetry.observe(telemetry.M_PREFETCH_STALL_S, dt)
            return staged
        if self._closed:
            raise StopIteration
        try:
            item = self._queue.get_nowait()
            with self._lock:
                self.stats.ready_hits += 1
        except queue.Empty:
            # starvation: the device-driving thread is waiting on host ETL
            t0 = time.perf_counter()
            item = self._queue.get()
            dt = time.perf_counter() - t0
            with self._lock:
                self.stats.stalls += 1
                self.stats.stall_s += dt
            profiling.add_phase_time(profiling.HOST_WAIT, dt)
            telemetry.observe(telemetry.M_PREFETCH_STALL_S, dt)
        if item is _Done:
            self._finish()
            raise StopIteration
        if isinstance(item, _Raised):
            self._finish()
            raise item.error
        with self._lock:
            self.stats.consumed += 1
        return item

    # -- lifecycle -----------------------------------------------------------

    def _finish(self) -> None:
        """Normal end of stream: join the (already exiting) thread.
        Shares close()'s atomic check-and-set: a close() racing the
        consumer's end-of-stream (e.g. __del__ on the GC thread) must
        not null _thread between this method's check and its join."""
        with self._lock:
            if self._closed:
                return  # a racing close() already joined and reported
            self._closed = True
        if self._thread is not None:
            self._thread.join()
            with self._lock:
                self._thread = None
        self._report()

    def close(self) -> None:
        """Abort the stream: wake + join the staging thread, drop staged
        items. Idempotent; safe mid-stream and after exhaustion — the
        closed check-and-set is atomic under the lock, so two racing
        closers (consumer + __del__, or two threads sharing the
        prefetcher) can't both run the join/drain/report sequence."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self._thread is not None:
            self._stop.set()
            # drain so a producer blocked on put() can notice stop quickly
            while True:
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    break
            self._thread.join()
            with self._lock:
                self._thread = None
        self._report()

    def _report(self) -> None:
        with self._lock:
            if self._reported or not self._report_health:
                return
            self._reported = True
        health.record(health.PREFETCH_REPORT, name=self.name,
                      depth=self.depth, **self.stats.as_dict())

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def __del__(self) -> None:  # safety net only; callers use close()/with
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
