"""Declarative SLO watchdog over the sliding-window metric plane.

PR 6 made overload degradation *correct* (admission control, deadline
sheds, the per-model circuit breaker); this module makes it *stated*:
an operator declares objectives over recent time windows — "queue-wait
p99 under a second over the last 30 s", "shed rate near zero", "no
breaker trips" — and the telemetry scope's periodic exporter
(:class:`~sparkdl_tpu.core.telemetry.SnapshotExporter`) evaluates them
on every tick, emitting paired ``slo_breach`` / ``slo_recovered``
health events with structured-log alerts while the process is alive.
This is the substrate ROADMAP item 1's SLO-aware admission reads from:
a rule's breach state is exactly the control signal an adaptive
coalesce window or shed threshold needs.

Design points:

- **Rules are declarative and validated at construction.** An
  :class:`SLORule` names a *declared* metric (the
  ``core.telemetry.CANONICAL_METRIC_NAMES`` catalog, or a
  ``sparkdl.health.<event>`` mirror of a constant declared in
  ``core/health.py``) — a typo'd metric name raises ``ValueError``
  instead of silently never firing, and the AST lint in
  ``tests/test_taxonomy_lint.py`` enforces the same for every rule
  shipped in this module.
- **Windowed, not cumulative.** Observations come from
  ``MetricsRegistry.window_snapshot(rule.window_s)``: a 10-minute-old
  latency spike ages out of the verdict instead of polluting "current"
  p99 forever.
- **Hold-down, then exactly one pair per episode.** A rule must stay in
  breach for ``for_s`` continuous seconds (as seen by evaluation ticks)
  before ``slo_breach`` fires; the matching ``slo_recovered`` fires on
  the first in-budget evaluation afterwards. No flapping storms: one
  breach, one recovery, per violation episode.
- **Absence of data is not a breach.** A window with no samples
  observes ``None`` for histogram stats (and 0 for counter rates): a
  quiet executor never pages anyone about its p99.

Dependency-free (stdlib only); imports ``core.telemetry`` for the
metric catalog and ``core.health`` for the event choke point — the
telemetry scope imports THIS module lazily, so there is no cycle.
"""

from __future__ import annotations

import dataclasses
import logging
import operator
from typing import Any, Dict, Optional, Sequence, Tuple

from sparkdl_tpu.core import health, telemetry

logger = logging.getLogger(__name__)

_COMPARATORS = {
    ">": operator.gt,
    ">=": operator.ge,
    "<": operator.lt,
    "<=": operator.le,
}

#: Stats a rule may read, per instrument kind (see :meth:`SLORule.observe`).
_HISTOGRAM_STATS = ("p50", "p95", "p99", "count", "rate_per_s", "min",
                    "max")
_COUNTER_STATS = ("count", "rate_per_s")
_GAUGE_STATS = ("value",)
_STATS = tuple(dict.fromkeys(_HISTOGRAM_STATS + _COUNTER_STATS
                             + _GAUGE_STATS))


def _declared_health_metrics() -> frozenset:
    """Every valid ``sparkdl.health.<event>`` mirror name, derived from
    the UPPERCASE string constants declared in ``core/health.py`` — the
    same set the taxonomy lint trusts."""
    return frozenset(
        telemetry.HEALTH_METRIC_PREFIX + value
        for name, value in vars(health).items()
        if name.isupper() and isinstance(value, str))


@dataclasses.dataclass(frozen=True)
class SLORule:
    """One objective: ``<stat>(metric over window_s) <comparator>
    threshold`` must NOT hold (holding = breaching) for ``for_s``
    continuous seconds.

    ``metric`` must be a declared name — a ``CANONICAL_METRIC_NAMES``
    entry or a ``sparkdl.health.<declared event>`` mirror; anything else
    raises at construction (a typo'd rule must fail loudly, not watch
    nothing forever).
    """

    name: str
    metric: str
    window_s: float
    threshold: float
    comparator: str = ">"
    stat: str = "p99"
    for_s: float = 0.0

    def __post_init__(self) -> None:
        if not self.name:
            raise ValueError("SLORule.name must be non-empty")
        if self.comparator not in _COMPARATORS:
            raise ValueError(
                f"SLORule {self.name!r}: comparator must be one of "
                f"{tuple(_COMPARATORS)}, got {self.comparator!r}")
        if self.stat not in _STATS:
            raise ValueError(
                f"SLORule {self.name!r}: stat must be one of {_STATS}, "
                f"got {self.stat!r}")
        if self.window_s <= 0:
            raise ValueError(
                f"SLORule {self.name!r}: window_s must be > 0, got "
                f"{self.window_s!r}")
        if self.for_s < 0:
            raise ValueError(
                f"SLORule {self.name!r}: for_s must be >= 0, got "
                f"{self.for_s!r}")
        kind = telemetry.CANONICAL_METRIC_KINDS.get(self.metric)
        if kind is None:
            if self.metric in _declared_health_metrics():
                kind = "counter"  # health mirrors are always counters
            else:
                raise ValueError(
                    f"SLORule {self.name!r}: metric {self.metric!r} is "
                    "not a declared name — use a core.telemetry."
                    "CANONICAL_METRIC_NAMES entry or a sparkdl.health."
                    "<event> mirror of a constant declared in "
                    "core/health.py")
        allowed = {"histogram": _HISTOGRAM_STATS,
                   "counter": _COUNTER_STATS,
                   "gauge": _GAUGE_STATS}[kind]
        if self.stat not in allowed:
            # a stat the instrument kind can never produce would observe
            # None forever — watching nothing, silently
            raise ValueError(
                f"SLORule {self.name!r}: stat {self.stat!r} cannot be "
                f"observed on {self.metric!r} (a {kind}); valid stats: "
                f"{allowed}")

    def observe(self, windowed: Dict[str, Any]) -> Optional[float]:
        """Extract this rule's stat from one
        ``MetricsRegistry.window_snapshot`` result; ``None`` when the
        window holds no data for the metric."""
        hist = windowed["histograms"].get(self.metric)
        if hist is not None and self.stat in _HISTOGRAM_STATS:
            return hist.get(self.stat)
        ctr = windowed["counters"].get(self.metric)
        if ctr is not None and self.stat in _COUNTER_STATS:
            return ctr.get(self.stat)
        gauge = windowed["gauges"].get(self.metric)
        if gauge is not None and self.stat == "value":
            return gauge.get("last")
        return None

    def breaching(self, observed: Optional[float]) -> bool:
        if observed is None:
            return False  # no data is never a breach
        return _COMPARATORS[self.comparator](observed, self.threshold)


class _RuleState:
    __slots__ = ("breach_since", "active", "last_observed")

    def __init__(self) -> None:
        self.breach_since: Optional[float] = None
        self.active = False
        self.last_observed: Optional[float] = None


class SLOWatchdog:
    """Evaluates a rule set against a registry's windowed snapshots.

    One instance per telemetry scope (built by ``Telemetry.__enter__``
    when the exporter is on); :meth:`evaluate` is called on every
    exporter tick and at the final flush. Not thread-safe by design —
    only the exporter (one thread, plus the close-time flush under the
    exporter's tick lock) drives it.
    """

    def __init__(self, rules: Optional[Sequence[SLORule]] = None,
                 attribution: Optional[Any] = None) -> None:
        self.rules: Tuple[SLORule, ...] = tuple(
            DEFAULT_RULES if rules is None else rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO rule names: {names}")
        self._states = {r.name: _RuleState() for r in self.rules}
        self._capacity_warned: set = set()
        # optional per-worker attribution hook (the federated watchdog,
        # cluster/router.py): called as attribution(rule) when a breach
        # fires, returning {worker: observed} — the breach event then
        # names WHICH workers drove the cluster-wide verdict, not just
        # the merged number
        self.attribution = attribution

    def evaluate(self, registry: "telemetry.MetricsRegistry",
                 now: Optional[float] = None) -> Dict[str, Any]:
        """One evaluation pass: returns ``{rule: {observed, threshold,
        breached}}`` (the exporter embeds it in each snapshot line) and
        emits the breach/recovery events."""
        if now is None:
            now = telemetry._monotonic()
        snaps: Dict[float, Dict[str, Any]] = {}
        out: Dict[str, Any] = {}
        for rule in self.rules:
            windowed = snaps.get(rule.window_s)
            if windowed is None:
                windowed = snaps[rule.window_s] = \
                    registry.window_snapshot(rule.window_s)
            if (windowed["window_s"] is not None
                    and windowed["window_s"] + 1e-9 < rule.window_s
                    and rule.name not in self._capacity_warned):
                # the registry's ring can't answer the declared window;
                # Telemetry rejects this pairing at construction, but a
                # standalone watchdog must still say so (once), not
                # silently judge over less history than the rule states
                self._capacity_warned.add(rule.name)
                logger.warning(
                    "SLO rule %r window_s=%g exceeds the registry ring "
                    "capacity (%gs); evaluating over the capped window",
                    rule.name, rule.window_s, windowed["window_s"])
            state = self._states[rule.name]
            observed = rule.observe(windowed)
            state.last_observed = observed
            # the offending traces behind a histogram verdict: the
            # in-window tail exemplars (present only on scopes armed
            # with Telemetry(exemplar_k=...)) — a breach names the
            # concrete trace/span ids to chase, not just a number
            hist = windowed["histograms"].get(rule.metric)
            exemplars = (hist or {}).get("exemplars")
            if rule.breaching(observed):
                if state.breach_since is None:
                    state.breach_since = now
                if (not state.active
                        and now - state.breach_since >= rule.for_s):
                    state.active = True
                    extra = ({"exemplars": exemplars} if exemplars
                             else {})
                    if self.attribution is not None:
                        try:
                            extra["workers"] = self.attribution(rule)
                        # sparkdl: allow(broad-retry): not a retry — attribution is best-effort enrichment; the breach itself must fire regardless
                        except Exception:  # noqa: BLE001
                            logger.exception(
                                "SLO breach attribution hook failed "
                                "for rule %r", rule.name)
                    health.record(health.SLO_BREACH, rule=rule.name,
                                  metric=rule.metric, stat=rule.stat,
                                  observed=observed,
                                  threshold=rule.threshold,
                                  window_s=rule.window_s, **extra)
                    logger.warning(
                        "SLO breach %r: %s(%s over %gs) = %.6g %s %.6g "
                        "(held %.3gs)", rule.name, rule.stat, rule.metric,
                        rule.window_s, observed, rule.comparator,
                        rule.threshold, now - state.breach_since)
            else:
                state.breach_since = None
                if state.active:
                    state.active = False
                    health.record(health.SLO_RECOVERED, rule=rule.name,
                                  metric=rule.metric, stat=rule.stat,
                                  observed=observed,
                                  threshold=rule.threshold,
                                  window_s=rule.window_s)
                    logger.warning(
                        "SLO recovered %r: %s(%s over %gs) = %s, back "
                        "within %s %.6g", rule.name, rule.stat,
                        rule.metric, rule.window_s,
                        ("%.6g" % observed) if observed is not None
                        else "no data", rule.comparator, rule.threshold)
            out[rule.name] = {"observed": observed,
                              "threshold": rule.threshold,
                              "breached": state.active}
            if state.active and exemplars:
                out[rule.name]["exemplars"] = exemplars
        return out

    def state(self) -> Dict[str, Dict[str, Any]]:
        """Current per-rule verdicts (for tests and ad-hoc queries)."""
        return {r.name: {"breached": self._states[r.name].active,
                         "observed": self._states[r.name].last_observed}
                for r in self.rules}


# ---------------------------------------------------------------------------
# Default rules: make PR 6's degradation story observable out of the box
# ---------------------------------------------------------------------------

DEFAULT_WINDOW_S = 30.0
DEFAULT_QUEUE_WAIT_P99_S = 1.0   # executor queue wait must stay sub-second
DEFAULT_SHED_RATE_PER_S = 1.0    # sustained shedding, not a lone blip
DEFAULT_HOLD_S = 0.0


def default_rules(window_s: float = DEFAULT_WINDOW_S,
                  for_s: float = DEFAULT_HOLD_S,
                  queue_wait_p99_s: float = DEFAULT_QUEUE_WAIT_P99_S,
                  shed_rate_per_s: float = DEFAULT_SHED_RATE_PER_S,
                  ) -> Tuple[SLORule, ...]:
    """The shipped rule set, re-parameterized (tests and short-lived
    scopes want second-scale windows; the defaults suit serving)."""
    return (
        # the latency objective: queue-wait p99 over the window
        SLORule("executor_queue_wait_p99",
                metric=telemetry.M_QUEUE_WAIT_S,
                window_s=window_s, threshold=queue_wait_p99_s,
                comparator=">", stat="p99", for_s=for_s),
        # the loss objective: sustained admission shedding
        SLORule("executor_shed_rate",
                metric=telemetry.HEALTH_METRIC_PREFIX
                + health.EXECUTOR_SHED,
                window_s=window_s, threshold=shed_rate_per_s,
                comparator=">=", stat="rate_per_s", for_s=for_s),
        # the availability objective: any breaker trip in the window
        SLORule("executor_breaker_open",
                metric=telemetry.HEALTH_METRIC_PREFIX
                + health.BREAKER_OPEN,
                window_s=window_s, threshold=1.0,
                comparator=">=", stat="count", for_s=for_s),
    )


DEFAULT_RULES: Tuple[SLORule, ...] = default_rules()

# Serving-plane defaults (sparkdl_tpu/serving/, docs/SERVING.md): the
# ModelServer's aggregate request-latency objective and its admission
# shed rate. Per-model objectives are built from the deployment's
# latency target at declaration time.
DEFAULT_SERVING_P99_S = 0.5
DEFAULT_SERVING_SHED_RATE_PER_S = 1.0


def default_serving_rules(model_targets: Optional[Dict[str, float]] = None,
                          window_s: float = DEFAULT_WINDOW_S,
                          for_s: float = DEFAULT_HOLD_S,
                          request_p99_s: float = DEFAULT_SERVING_P99_S,
                          shed_rate_per_s: float =
                          DEFAULT_SERVING_SHED_RATE_PER_S,
                          ) -> Tuple[SLORule, ...]:
    """The serving plane's rule set: the aggregate request-latency p99
    and sustained admission shedding, plus ONE latency rule per entry of
    ``model_targets`` (model name -> p99 target in SECONDS). Per-model
    metrics have per-model names (metrics carry no labels), so each
    model rule watches ``sparkdl.serving.request_s.<model>`` — declared
    here via :func:`telemetry.declare_metric`, which is also what makes
    ``SLORule`` construction accept the dynamic name."""
    rules = [
        # the latency objective: end-to-end request p99 over the window
        SLORule("serving_request_p99",
                metric=telemetry.M_SERVING_REQUEST_S,
                window_s=window_s, threshold=request_p99_s,
                comparator=">", stat="p99", for_s=for_s),
        # the loss objective: sustained SLO-aware admission shedding
        SLORule("serving_shed_rate",
                metric=telemetry.HEALTH_METRIC_PREFIX
                + health.SERVING_SHED,
                window_s=window_s, threshold=shed_rate_per_s,
                comparator=">=", stat="rate_per_s", for_s=for_s),
    ]
    for model, target_s in sorted((model_targets or {}).items()):
        metric = telemetry.declare_metric(
            telemetry.serving_request_metric(model), "histogram")
        rules.append(
            SLORule(f"serving_request_p99_{model}", metric=metric,
                    window_s=window_s, threshold=float(target_s),
                    comparator=">", stat="p99", for_s=for_s))
    return tuple(rules)


# A failover is a worker death made invisible — one or two per window is
# the plane doing its job; a sustained rate means replicas are dying
# faster than they respawn and the survivors are absorbing everything.
DEFAULT_SERVING_FAILOVER_RATE_PER_S = 0.5


def cluster_serving_rules(model_targets: Optional[Dict[str, float]] = None,
                          window_s: float = DEFAULT_WINDOW_S,
                          for_s: float = DEFAULT_HOLD_S,
                          request_p99_s: float = DEFAULT_SERVING_P99_S,
                          shed_rate_per_s: float =
                          DEFAULT_SERVING_SHED_RATE_PER_S,
                          failover_rate_per_s: float =
                          DEFAULT_SERVING_FAILOVER_RATE_PER_S,
                          ) -> Tuple[SLORule, ...]:
    """The cluster serving plane's rule set: everything
    :func:`default_serving_rules` watches — in cluster mode every
    request is routed (and its latency observed) coordinator-side, so
    each per-model ``sparkdl.serving.request_s.<model>`` histogram IS
    the per-deployment windowed p99 **across all replicas** — plus a
    sustained-failover rule on the ``serving_failover`` health mirror
    (replicas dying faster than the plane can hide it)."""
    rules = list(default_serving_rules(
        model_targets, window_s=window_s, for_s=for_s,
        request_p99_s=request_p99_s, shed_rate_per_s=shed_rate_per_s))
    rules.append(
        SLORule("serving_failover_rate",
                metric=telemetry.HEALTH_METRIC_PREFIX
                + health.SERVING_FAILOVER,
                window_s=window_s, threshold=failover_rate_per_s,
                comparator=">=", stat="rate_per_s", for_s=for_s))
    return tuple(rules)


def tenant_queue_wait_rules(tenant_targets: Dict[str, float],
                            window_s: float = DEFAULT_WINDOW_S,
                            for_s: float = DEFAULT_HOLD_S,
                            ) -> Tuple[SLORule, ...]:
    """One queue-wait p99 rule per entry of ``tenant_targets`` (tenant
    tag -> p99 target in SECONDS) — the fairness objective of the
    elastic-capacity plane: under sustained overload from one tenant,
    the OTHER tenants' queue-wait p99 staying under target is what
    proves deficit-round-robin is doing its job. Per-tenant metrics have
    per-tenant names (``sparkdl.executor.queue_wait_s.<tenant>``,
    emitted by ``core/executor.py`` for every non-default tenant), so
    each rule watches its tenant's own series — declared here via
    :func:`telemetry.declare_metric`, same dynamic-name pattern as the
    per-model serving rules above."""
    rules = []
    for tenant, target_s in sorted(tenant_targets.items()):
        metric = telemetry.declare_metric(
            telemetry.tenant_queue_wait_metric(tenant), "histogram")
        rules.append(
            SLORule(f"tenant_queue_wait_p99_{tenant}", metric=metric,
                    window_s=window_s, threshold=float(target_s),
                    comparator=">", stat="p99", for_s=for_s))
    return tuple(rules)


# ---------------------------------------------------------------------------
# Federated variants (docs/OBSERVABILITY.md "Cluster metrics
# federation"): the SAME objectives evaluated against the coordinator's
# ClusterMetricsView fold instead of a single process's registry — a
# cluster p99 rule watches the MERGED percentile, a rate rule the SUMMED
# rate. Rule names get the cluster_ prefix so a coordinator can run its
# local watchdog and the federated one side by side without colliding
# episode state or event attribution.
# ---------------------------------------------------------------------------

FEDERATED_RULE_PREFIX = "cluster_"


def _federated(rules: Sequence[SLORule]) -> Tuple[SLORule, ...]:
    """Re-name a rule set for federated evaluation (same metrics, same
    thresholds — the VIEW they evaluate against is what changes)."""
    return tuple(
        dataclasses.replace(rule, name=FEDERATED_RULE_PREFIX + rule.name)
        for rule in rules)


def federated_default_rules(window_s: float = DEFAULT_WINDOW_S,
                            for_s: float = DEFAULT_HOLD_S,
                            queue_wait_p99_s: float =
                            DEFAULT_QUEUE_WAIT_P99_S,
                            shed_rate_per_s: float =
                            DEFAULT_SHED_RATE_PER_S,
                            ) -> Tuple[SLORule, ...]:
    """:func:`default_rules` against the federated view: the queue-wait
    objective becomes the CLUSTER-merged p99 (bucket counts summed
    across workers before the estimate), the shed/breaker objectives
    the cluster-summed counts."""
    return _federated(default_rules(
        window_s=window_s, for_s=for_s,
        queue_wait_p99_s=queue_wait_p99_s,
        shed_rate_per_s=shed_rate_per_s))


def federated_tenant_queue_wait_rules(tenant_targets: Dict[str, float],
                                      window_s: float = DEFAULT_WINDOW_S,
                                      for_s: float = DEFAULT_HOLD_S,
                                      ) -> Tuple[SLORule, ...]:
    """:func:`tenant_queue_wait_rules` against the federated view: each
    tenant's objective watches its MERGED cluster-wide p99 (per-tenant
    series federate like any histogram — same dynamic declare)."""
    return _federated(tenant_queue_wait_rules(
        tenant_targets, window_s=window_s, for_s=for_s))


def federated_cluster_serving_rules(model_targets:
                                    Optional[Dict[str, float]] = None,
                                    window_s: float = DEFAULT_WINDOW_S,
                                    for_s: float = DEFAULT_HOLD_S,
                                    request_p99_s: float =
                                    DEFAULT_SERVING_P99_S,
                                    shed_rate_per_s: float =
                                    DEFAULT_SERVING_SHED_RATE_PER_S,
                                    failover_rate_per_s: float =
                                    DEFAULT_SERVING_FAILOVER_RATE_PER_S,
                                    ) -> Tuple[SLORule, ...]:
    """:func:`cluster_serving_rules` against the federated view —
    worker-side serving series (replica-local latencies, shed/failover
    mirrors) fold in beside the coordinator's routed view."""
    return _federated(cluster_serving_rules(
        model_targets, window_s=window_s, for_s=for_s,
        request_p99_s=request_p99_s, shed_rate_per_s=shed_rate_per_s,
        failover_rate_per_s=failover_rate_per_s))
