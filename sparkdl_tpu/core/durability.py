"""Durable job recovery: write-ahead partition journal + atomic spill.

The reference pipeline inherited Spark's lineage-based fault tolerance —
a lost executor recomputes its partitions, a lost driver restarts the
job from durable state. Our in-process resilience (classified retries,
hedging, quarantine, decode-pool respawn) dies with the process; this
module extends it past the process boundary (docs/RESILIENCE.md,
"Durable recovery").

Design:

- **Job identity.** :func:`job_id` hashes the *plan*: the input
  partitions' Arrow IPC bytes, the schema, a best-effort fingerprint of
  the op chain (qualname + closure contents), and the quarantine config
  knobs. The same frame built the same way in a restarted process maps
  to the same journal directory; any change to inputs, ops, or
  semantics gets a fresh journal instead of a stale resume.
- **Write-ahead journal.** ``<durable_dir>/<job_id>/journal.jsonl``
  holds one record per *committed* partition: index, attempt count,
  spill filename, content hash, quarantine verdict. Every rewrite goes
  through tmp-file + fsync + ``os.replace`` + directory fsync, and each
  line carries its own digest — a torn or bit-rotted record is
  *detected and discarded*, never trusted.
- **Atomic spill/commit.** A completed partition's batch is serialized
  to Arrow IPC, spilled atomically to ``part-<i>.arrow``, and only then
  committed by its journal record (write-ahead order: spill before
  journal, so a journal record always points at a complete spill). On
  restart :meth:`PartitionJournal.resume` re-verifies every spill
  against its recorded hash; verified partitions are served from disk
  in original order, bit-identical, and only uncommitted ones re-run.
- **Exactly-once accounting.** Commits are idempotent (a hedge loser
  re-committing its partition is a no-op) and quarantine verdicts are
  persisted, so a poisoned partition stays quarantined across restarts
  instead of re-poisoning the gang.

The ``process_kill`` injection point fires *after* a record commits —
``kill -9``-ing the process at its most adversarial moment — and the
chaos suite proves the resumed run is bit-identical with zero
recomputed committed partitions.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import signal
import threading
from typing import Any, Dict, List, Optional, Sequence, Set

import pyarrow as pa

from sparkdl_tpu.core import health, resilience

logger = logging.getLogger(__name__)

_JOURNAL = "journal.jsonl"
_RUN_ID_FILE = "run_id"


# ---------------------------------------------------------------------------
# Atomic file helpers
# ---------------------------------------------------------------------------

def _fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives power loss."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platform without directory fds; rename is still atomic
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _atomic_write(path: str, payload: bytes) -> None:
    """Commit ``payload`` at ``path`` via tmp + fsync + ``os.replace``.

    The canonical durable-write shape (analyzer rule ``atomic-write``):
    readers never observe a torn file — they see the old content or the
    new content, and the fsync ordering makes the rename durable.
    """
    tmp = f"{path}.tmp.{os.getpid()}"
    # sparkdl: allow(blocking-under-lock): journal/spill publishes serialize on the per-job commit lock BY DESIGN — write-ahead ordering; two interleaved tmp+replace cycles would lose journal records
    with open(tmp, "wb") as f:
        # sparkdl: allow(blocking-under-lock): same serialized-publish contract as the open() above
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


# ---------------------------------------------------------------------------
# Plan fingerprinting
# ---------------------------------------------------------------------------

def _ipc_bytes(batch: pa.RecordBatch) -> bytes:
    """One-batch Arrow IPC stream — the spill format AND the content-hash
    input (hashing the exact bytes we spill makes verification trivial)."""
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, batch.schema) as writer:
        writer.write_batch(batch)
    return sink.getvalue()


def _batch_from_ipc(payload: bytes) -> pa.RecordBatch:
    with pa.ipc.open_stream(io.BytesIO(payload)) as reader:
        batches = [b for b in reader]
    if len(batches) != 1:
        raise IOError(
            f"durable spill holds {len(batches)} batches, expected 1")
    return batches[0]


def _stable_repr(v: Any) -> str:
    """Deterministic-ish repr for op closure contents.

    Covers the values engine ops actually close over (column names,
    callables, Arrow types, small config scalars). Objects whose repr
    embeds a memory address degrade to their type name — ambiguity there
    means two jobs differing only in such an object share a job id, which
    is why ``durable_dir`` should be scoped per logical job.
    """
    if isinstance(v, (str, int, float, bool, bytes, type(None))):
        return repr(v)
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_stable_repr(x) for x in v) + "]"
    if isinstance(v, (set, frozenset)):
        return "{" + ",".join(sorted(_stable_repr(x) for x in v)) + "}"
    if isinstance(v, dict):
        items = sorted(((str(k), _stable_repr(x)) for k, x in v.items()))
        return "{" + ",".join(f"{k}:{x}" for k, x in items) + "}"
    if callable(v):
        return getattr(v, "__qualname__", type(v).__qualname__)
    r = repr(v)
    return type(v).__qualname__ if " at 0x" in r else r


def _op_token(op: Any) -> str:
    """Fingerprint one engine op: qualname plus captured closure state,
    so ``select("a")`` and ``select("b")`` (same qualname, different
    captured column list) hash differently."""
    parts = [getattr(op, "__qualname__", type(op).__qualname__)]
    for cell in getattr(op, "__closure__", None) or ():
        try:
            parts.append(_stable_repr(cell.cell_contents))
        except ValueError:  # empty cell
            parts.append("<empty>")
    return "|".join(parts)


def ops_token(ops: Sequence[Any]) -> str:
    """Stable fingerprint of an op CHAIN alone (no input data): the
    ``_op_token`` canonicalization :func:`job_id` already applies, hashed.
    The cluster router keys shipped op-chain payloads on this, so a
    worker that has already received a chain (a streamed epoch, a retry)
    is not re-sent the pickled closures."""
    h = hashlib.sha256()
    for op in ops:
        h.update(_op_token(op).encode())
        h.update(b"\x00")
    return h.hexdigest()[:20]


def job_id(partitions: Sequence[pa.RecordBatch],
           schema: Optional[pa.Schema],
           ops: Sequence[Any]) -> str:
    """Stable job identity: hash of plan (inputs + schema + op chain)
    and the config knobs that change the committed output."""
    from sparkdl_tpu.engine.dataframe import EngineConfig

    h = hashlib.sha256()
    h.update(schema.serialize().to_pybytes() if schema is not None else b"")
    h.update(str(len(partitions)).encode())
    for batch in partitions:
        h.update(_ipc_bytes(batch))
    for op in ops:
        h.update(_op_token(op).encode())
        h.update(b"\x00")
    h.update(json.dumps({
        "quarantine": bool(EngineConfig.quarantine),
        "quarantine_max_fatal": int(EngineConfig.quarantine_max_fatal),
    }, sort_keys=True).encode())
    return h.hexdigest()[:20]


# ---------------------------------------------------------------------------
# Journal records
# ---------------------------------------------------------------------------

def _record_line(rec: Dict[str, Any]) -> str:
    """One journal line: the record plus its own content digest, so a
    torn tail (partial last line after a crash) is detectable."""
    body = json.dumps(rec, sort_keys=True)
    crc = hashlib.sha256(body.encode()).hexdigest()[:8]
    return json.dumps({"rec": rec, "crc": crc}, sort_keys=True) + "\n"


def _check_record(line: str) -> Optional[Dict[str, Any]]:
    """Parse + verify one journal line; None for torn/corrupt records."""
    try:
        obj = json.loads(line)
        rec, crc = obj["rec"], obj["crc"]
    except (ValueError, KeyError, TypeError):
        return None
    if not isinstance(rec, dict):
        return None
    body = json.dumps(rec, sort_keys=True)
    if hashlib.sha256(body.encode()).hexdigest()[:8] != crc:
        return None
    if (not isinstance(rec.get("partition"), int)
            or not isinstance(rec.get("sha256"), str)
            or not isinstance(rec.get("spill"), str)):
        return None
    return rec


class PartitionJournal:
    """Write-ahead journal + spill store for ONE durable engine job.

    Lifecycle: construct (loads any existing journal, dropping torn
    records), :meth:`resume` (verify spills, return the committed set),
    then :meth:`commit` each newly completed partition and :meth:`load`
    each restored one. Thread-safe: the supervisor commits from its
    worker threads.
    """

    def __init__(self, root: str, job: str, num_partitions: int) -> None:
        self.job_id = job
        self.dir = os.path.join(root, job)
        os.makedirs(self.dir, exist_ok=True)
        self._path = os.path.join(self.dir, _JOURNAL)
        self._lock = threading.Lock()
        self._records: Dict[int, Dict[str, Any]] = {}
        self._attempts: Dict[int, int] = {}
        self._num_partitions = num_partitions
        self._load()

    # -- restart path -------------------------------------------------------

    def _load(self) -> None:
        try:
            with open(self._path, encoding="utf-8") as f:
                lines = f.read().splitlines()
        except FileNotFoundError:
            return
        for line in lines:
            if not line:
                continue
            rec = _check_record(line)
            if rec is None:
                health.record(health.DURABLE_JOURNAL_TORN, job=self.job_id)
                logger.warning(
                    "durable journal %s: torn/corrupt record discarded",
                    self._path)
                continue
            self._records[rec["partition"]] = rec

    def resume(self) -> Set[int]:
        """Verify every journaled spill's content hash and return the
        committed partition set. A missing or corrupt spill DISCARDS its
        record (the partition recomputes) — never trusted."""
        with self._lock:
            good: Set[int] = set()
            bad: List[int] = []
            for i in sorted(self._records):
                if self._read_spill(self._records[i]) is None:
                    bad.append(i)
                else:
                    good.add(i)
            for i in bad:
                del self._records[i]
            if bad:
                self._rewrite_journal_locked()
        if good:
            health.record(health.DURABLE_RESUMED, job=self.job_id,
                          committed=len(good))
            logger.warning(
                "durable job %s: resuming with %d/%d partition(s) already "
                "committed", self.job_id, len(good), self._num_partitions)
        return good

    def _read_spill(self, rec: Dict[str, Any]) -> Optional[bytes]:
        path = os.path.join(self.dir, rec["spill"])
        try:
            # sparkdl: allow(blocking-under-lock): resume-time verification runs before any partition worker exists — nothing contends the journal lock yet
            with open(path, "rb") as f:
                payload = f.read()
        except OSError:
            return None
        if hashlib.sha256(payload).hexdigest() != rec["sha256"]:
            health.record(health.DURABLE_JOURNAL_TORN, job=self.job_id,
                          partition=rec["partition"])
            logger.warning(
                "durable job %s: spill %s failed content-hash verification; "
                "partition %d will recompute", self.job_id, rec["spill"],
                rec["partition"])
            return None
        return payload

    def load(self, index: int) -> pa.RecordBatch:
        """Load one committed partition from spill (verified at resume;
        vanishing mid-run is a real I/O failure and raises)."""
        with self._lock:
            rec = self._records[index]
        payload = self._read_spill(rec)
        if payload is None:
            raise IOError(
                f"durable job {self.job_id}: spill for committed partition "
                f"{index} disappeared or corrupted after resume verification")
        health.record(health.DURABLE_PARTITION_RESTORED, partition=index,
                      quarantined=bool(rec.get("quarantined")))
        return _batch_from_ipc(payload)

    # -- commit path --------------------------------------------------------

    def note_attempt(self, index: int) -> None:
        """Count one compute attempt (retries and hedges included) so the
        journal records how hard the partition fought before committing."""
        with self._lock:
            self._attempts[index] = self._attempts.get(index, 0) + 1

    def committed(self, index: int) -> bool:
        with self._lock:
            return index in self._records

    def records(self) -> List[Dict[str, Any]]:
        """Snapshot of committed records, partition-ordered (chaos suite
        proves zero-recompute from exactly this view)."""
        with self._lock:
            return [dict(self._records[i]) for i in sorted(self._records)]

    def commit(self, index: int, batch: pa.RecordBatch,
               quarantined: bool = False) -> pa.RecordBatch:
        """Spill + journal one completed partition; idempotent (a hedge
        loser finishing after the winner committed is a no-op).

        Write-ahead order: the spill lands atomically BEFORE the journal
        record that points at it, so every committed record references a
        complete, hashed spill — a crash between the two steps just
        recomputes the partition.
        """
        with self._lock:
            if index not in self._records:
                payload = _ipc_bytes(batch)
                spill = f"part-{index:05d}.arrow"
                # the commit lock serializes journal rewrites by design
                # (write-ahead ordering); partition compute threads
                # block here only for the O(partition-size) spill
                # write — see the suppression inside _atomic_write
                _atomic_write(os.path.join(self.dir, spill), payload)
                self._records[index] = {
                    "partition": index,
                    "attempts": self._attempts.get(index, 1),
                    "sha256": hashlib.sha256(payload).hexdigest(),
                    "spill": spill,
                    "quarantined": bool(quarantined),
                }
                self._rewrite_journal_locked()
        if resilience.should_fire("process_kill", partition=index):
            logger.warning(
                "FaultInjector: process_kill firing after commit of "
                "partition %d — SIGKILL self", index)
            os.kill(os.getpid(), signal.SIGKILL)
        return batch

    def _rewrite_journal_locked(self) -> None:
        payload = "".join(
            _record_line(self._records[i]) for i in sorted(self._records))
        # journal rewrites must serialize against concurrent commits or
        # two threads would interleave tmp+replace and lose records —
        # see the suppression inside _atomic_write
        _atomic_write(self._path, payload.encode())


def maybe_journal(partitions: Sequence[pa.RecordBatch],
                  schema: Optional[pa.Schema],
                  ops: Sequence[Any]) -> Optional[PartitionJournal]:
    """The job's journal when ``EngineConfig.durable_dir`` is set (and
    the frame actually computes something); None leaves every existing
    path untouched — durability is strictly opt-in."""
    from sparkdl_tpu.engine.dataframe import EngineConfig

    root = EngineConfig.durable_dir
    if not root or not ops:
        return None
    return PartitionJournal(root, job_id(partitions, schema, ops),
                            len(partitions))


# ---------------------------------------------------------------------------
# Run-id pinning
# ---------------------------------------------------------------------------

def pinned_run_id(durable_dir: str, name: str = "sparkdl") -> str:
    """The durable run id under ``durable_dir``: first caller mints and
    publishes it (atomic ``os.link`` — exactly one winner under racing
    restarts), every later process reads the same id. Telemetry pinned
    to this id appends to ONE snapshot timeline and ONE run report
    across crashes (``telemetry.Telemetry(..., run_id=...)``)."""
    os.makedirs(durable_dir, exist_ok=True)
    path = os.path.join(durable_dir, _RUN_ID_FILE)
    try:
        with open(path, encoding="utf-8") as f:
            return f.read().strip()
    except FileNotFoundError:
        pass
    minted = f"{name}-durable-{os.urandom(4).hex()}"
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(minted + "\n")
        f.flush()
        os.fsync(f.fileno())
    try:
        os.link(tmp, path)  # exclusive publish: fails iff someone else won
    except FileExistsError:
        pass
    finally:
        os.unlink(tmp)
    _fsync_dir(durable_dir)
    with open(path, encoding="utf-8") as f:
        return f.read().strip()
