"""Unified telemetry: cross-thread span tracing, metrics, one run report.

The reference library shipped no in-tree observability — operators
hand-instrumented the Spark UI and TF timelines (SURVEY.md §5.1). The
rebuild had fragments: global phase accumulators (`core/profiling.py`),
resilience counters (`core/health.py`), train metrics
(`train/metrics.py`) — none sharing identifiers, none exportable
together. With the data plane spanning four concurrent execution
contexts (driver, supervisor pool threads, the `DevicePrefetcher`
staging thread, the deferred-sync train loop), "where did step 412's
batch spend its time, and which partition task stalled it?" needs
correlated per-span records, not aggregate totals. This module is the
Dapper-style span model plus the Prometheus metric taxonomy for exactly
that, in three integrated parts:

1. **Span tracing** — a :class:`Tracer` producing per-span records
   (name, trace_id, span_id, parent_id, thread, start/end ns,
   attributes) into a bounded ring buffer, with explicit cross-thread
   context handoff (:func:`current_context` on the parent thread,
   ``span(parent=ctx)`` or :func:`attach` on the child) so engine
   partition tasks, prefetcher staging and `Trainer.fit` steps all
   parent correctly under one run trace. Exportable as Chrome-trace
   JSON (``chrome://tracing`` / Perfetto, one track per thread) with no
   ``jax.profiler`` dependency.
2. **Metrics registry** — named :class:`Counter` / :class:`Gauge` /
   :class:`Histogram` instruments (fixed log-scale buckets with
   p50/p95/p99 estimates), with a JSON :meth:`MetricsRegistry.snapshot`
   and a Prometheus text-exposition dump.
3. **Run report** — :class:`RunReport` merges the trace summary, the
   metric snapshot, ``profiling.phase_stats()``/``overlap_stats()`` and
   the active ``HealthMonitor`` report into one JSON artifact written
   at scope exit (opt-in via ``SPARKDL_TELEMETRY_DIR`` or an explicit
   ``Telemetry(out_dir=...)`` scope), plus a structured-logging adapter
   stamping ``run_id``/``trace_id`` onto framework log records.

Scoping mirrors :class:`~sparkdl_tpu.core.health.HealthMonitor`:
a :class:`Telemetry` scope activates process-wide (engine partition ops
run on pool threads where a ContextVar entered on the driver would be
invisible), nests, and restores the previous scope on exit. With no
active scope every entry point — :func:`span`, :func:`count`,
:func:`gauge_set`, :func:`observe` — is a single global read + ``None``
check returning a shared singleton: the hot paths allocate nothing and
never touch a device (telemetry must never introduce a device sync; the
step-loop AST lint in ``tests/test_taxonomy_lint.py`` stays satisfied).

Dependency-free by design (stdlib only): every layer may import it
without cycles. ``core.profiling`` imports this module; the run report
imports ``profiling``/``health`` lazily to break the cycle.
"""

from __future__ import annotations

import bisect
import itertools
import json
import logging
import math
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

TELEMETRY_DIR_ENV = "SPARKDL_TELEMETRY_DIR"

# ---------------------------------------------------------------------------
# Canonical names (docs/OBSERVABILITY.md is the human-readable catalog).
# The taxonomy lint (tests/test_taxonomy_lint.py) checks every annotate()/
# span() name used in sparkdl_tpu/ against CANONICAL_SPAN_NAMES — a typo'd
# phase name would otherwise silently fork a timer.
# ---------------------------------------------------------------------------

SPAN_RUN = "sparkdl.run"                      # telemetry scope root
SPAN_RUNNER_ATTEMPT = "sparkdl.runner_attempt"  # TPURunner gang attempt
SPAN_FIT = "sparkdl.fit"                      # one Trainer.fit call
SPAN_EPOCH = "sparkdl.epoch"                  # one epoch of the fit loop
SPAN_CHECKPOINT_SAVE = "sparkdl.checkpoint_save"
SPAN_ESTIMATOR_FIT = "sparkdl.estimator_fit"  # KerasImageFileEstimator._fit
SPAN_COLLECT = "sparkdl.collect"              # estimator collected decode
SPAN_MATERIALIZE = "sparkdl.materialize"      # DataFrame._materialize barrier
SPAN_TASK = "sparkdl.task"                    # one pool attempt (or hedge)
SPAN_TASK_ATTEMPT = "sparkdl.task_attempt"    # one retry-loop attempt
SPAN_COMPILE = "sparkdl.compile"              # first launch of a new shape
SPAN_COALESCED_LAUNCH = "sparkdl.coalesced_launch"  # core/executor.py

CANONICAL_SPAN_NAMES = frozenset({
    SPAN_RUN, SPAN_RUNNER_ATTEMPT, SPAN_FIT, SPAN_EPOCH,
    SPAN_CHECKPOINT_SAVE, SPAN_ESTIMATOR_FIT, SPAN_COLLECT,
    SPAN_MATERIALIZE, SPAN_TASK, SPAN_TASK_ATTEMPT,
    SPAN_COMPILE, SPAN_COALESCED_LAUNCH,
    # phase names (core/profiling.py constants + literal call sites)
    "sparkdl.decode", "sparkdl.stage", "sparkdl.stage_batch",
    "sparkdl.host_stage", "sparkdl.host_resize", "sparkdl.host_wait",
    "sparkdl.device_apply", "sparkdl.train_step", "sparkdl.device_sync",
})

# Metric catalog. Histograms in seconds use DEFAULT_TIME_BOUNDS; row-count
# histograms use POW2_BOUNDS. Health-event mirrors are dynamic:
# "sparkdl.health.<event>" per core/health.py event name, bumped in
# health.record so telemetry counters equal HealthMonitor counts exactly.
M_TASK_DURATION_S = "sparkdl.task.duration_s"          # histogram
M_STEP_TIME_S = "sparkdl.train.step_time_s"            # histogram (host)
M_STEPS_PER_SEC = "sparkdl.train.steps_per_sec"        # histogram
M_EXAMPLES_PER_SEC = "sparkdl.train.examples_per_sec"  # gauge
M_PREFETCH_DEPTH = "sparkdl.prefetch.queue_depth"      # gauge
M_PREFETCH_STALL_S = "sparkdl.prefetch.stall_s"        # histogram
M_BATCH_ROWS = "sparkdl.batching.rows"                 # counter (valid rows)
M_BATCH_PAD_ROWS = "sparkdl.batching.pad_rows"         # counter (pad rows)
M_BATCH_BUCKET_ROWS = "sparkdl.batching.bucket_rows"   # histogram
M_PADDING_WASTE = "sparkdl.batching.padding_waste"     # gauge (pad fraction)
M_ENGINE_ROWS_OUT = "sparkdl.engine.rows_out"          # counter
M_ENGINE_BYTES_OUT = "sparkdl.engine.bytes_out"        # counter
# Device execution service (core/executor.py, docs/PERF.md coalescing):
M_COALESCE_REQUESTS = "sparkdl.executor.coalesce_requests"  # histogram
M_COALESCE_ROWS = "sparkdl.executor.coalesce_rows"     # histogram
M_COALESCE_DEDUP = "sparkdl.executor.dedup_hits"       # counter (hedges)
M_QUEUE_WAIT_S = "sparkdl.executor.queue_wait_s"       # histogram
M_LAUNCH_S = "sparkdl.executor.launch_s"               # histogram (host)
M_EXECUTOR_OCCUPANCY = "sparkdl.executor.occupancy"    # gauge (in-flight)
# Overload protection (ISSUE 6): the shed/deadline/breaker COUNTS arrive
# for free as sparkdl.health.* mirrors of the core/health.py events; the
# gauges below are the executor's own instantaneous state.
M_EXECUTOR_QUEUE_DEPTH = "sparkdl.executor.queue_depth"  # gauge (queued reqs)
M_EXECUTOR_SHED_RATE = "sparkdl.executor.shed_rate"    # gauge (shed fraction)
HEALTH_METRIC_PREFIX = "sparkdl.health."

CANONICAL_METRIC_NAMES = frozenset({
    M_TASK_DURATION_S, M_STEP_TIME_S, M_STEPS_PER_SEC, M_EXAMPLES_PER_SEC,
    M_PREFETCH_DEPTH, M_PREFETCH_STALL_S, M_BATCH_ROWS, M_BATCH_PAD_ROWS,
    M_BATCH_BUCKET_ROWS, M_PADDING_WASTE, M_ENGINE_ROWS_OUT,
    M_ENGINE_BYTES_OUT, M_COALESCE_REQUESTS, M_COALESCE_ROWS,
    M_COALESCE_DEDUP, M_QUEUE_WAIT_S, M_LAUNCH_S, M_EXECUTOR_OCCUPANCY,
    M_EXECUTOR_QUEUE_DEPTH, M_EXECUTOR_SHED_RATE,
})

# ---------------------------------------------------------------------------
# Span tracing
# ---------------------------------------------------------------------------


class SpanContext(NamedTuple):
    """The cross-thread handoff token: enough to parent a remote span."""

    trace_id: str
    span_id: int


class _RootSentinel:
    """``Tracer.span(parent=ROOT)``: force a parentless root span (vs
    ``parent=None``, which adopts the ambient context)."""


ROOT = _RootSentinel()


_tls = threading.local()


def _span_stack() -> List["_Span"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class _NullSpan:
    """Shared no-op span: the inactive path returns THIS singleton —
    zero allocation, inert context manager."""

    __slots__ = ()
    context: Optional[SpanContext] = None

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set_attribute(self, key: str, value: Any) -> None:
        pass


NULL_SPAN = _NullSpan()


class _Span:
    """An open span; records into its tracer's ring buffer on exit."""

    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "attributes", "_start_ns", "_pushed")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: int, parent_id: Optional[int],
                 attributes: Dict[str, Any]) -> None:
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attributes = attributes
        self._start_ns = 0
        self._pushed = False

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def __enter__(self) -> "_Span":
        _span_stack().append(self)
        self._pushed = True
        self._start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        end_ns = time.perf_counter_ns()
        if self._pushed:
            stack = _span_stack()
            if stack and stack[-1] is self:
                stack.pop()
            elif self in stack:  # defensive: exited out of order
                stack.remove(self)
            self._pushed = False
        if exc_type is not None:
            self.attributes.setdefault("error", exc_type.__name__)
        self._tracer._record(self, self._start_ns, end_ns)
        return False


class Tracer:
    """Per-run span recorder: bounded ring buffer + Chrome-trace export.

    The ring keeps the most recent ``max_spans`` finished spans (the
    HealthMonitor event log keeps the FIRST n — traces want the tail: the
    end of a run is where failures live) and counts evictions in
    :attr:`dropped`. Thread-safe; spans may finish on any thread.
    """

    def __init__(self, trace_id: str, max_spans: int = 65536) -> None:
        self.trace_id = trace_id
        self.max_spans = max_spans
        self.dropped = 0
        self._lock = threading.Lock()
        self._spans: "deque[Dict[str, Any]]" = deque(maxlen=max_spans)
        self._ids = itertools.count(1)
        self._t0_ns = time.perf_counter_ns()

    # -- producing -----------------------------------------------------------

    def span(self, name: str, parent: Any = None,
             **attributes: Any) -> _Span:
        """An open span context manager. ``parent`` explicitly parents a
        cross-thread span (pass the creating thread's
        :func:`current_context`); otherwise the ambient context — this
        thread's innermost open span, its attached base, or the scope
        root — is the parent. ``parent=ROOT`` makes a parentless root
        span (the scope's own run span)."""
        if parent is ROOT:
            trace_id, parent_id = self.trace_id, None
        else:
            if parent is None:
                parent = current_context()
            if parent is None:
                trace_id, parent_id = self.trace_id, None
            else:
                trace_id, parent_id = parent.trace_id, parent.span_id
        return _Span(self, name, trace_id, next(self._ids), parent_id,
                     attributes)

    def _record(self, span: _Span, start_ns: int, end_ns: int) -> None:
        thread = threading.current_thread()
        rec = {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "thread_id": thread.ident,
            "thread_name": thread.name,
            "start_ns": start_ns - self._t0_ns,
            "end_ns": end_ns - self._t0_ns,
        }
        if span.attributes:
            rec["attributes"] = span.attributes
        with self._lock:
            if len(self._spans) == self.max_spans:
                self.dropped += 1
            self._spans.append(rec)

    # -- querying / export ---------------------------------------------------

    def spans(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s["name"] == name]
        return out

    def summary(self) -> Dict[str, Any]:
        """Aggregate per-name stats over ONE snapshot of the ring (the
        count and the aggregates must agree even while other threads
        keep recording)."""
        spans = self.spans()
        by_name: Dict[str, Dict[str, Any]] = {}
        threads = set()
        for s in spans:
            threads.add((s["thread_id"], s["thread_name"]))
            agg = by_name.setdefault(
                s["name"], {"count": 0, "total_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += (s["end_ns"] - s["start_ns"]) / 1e9
        for agg in by_name.values():
            agg["total_s"] = round(agg["total_s"], 6)
            agg["mean_s"] = round(agg["total_s"] / agg["count"], 6)
        return {
            "trace_id": self.trace_id,
            "spans_recorded": len(spans),
            "spans_dropped": self.dropped,
            "threads": sorted(t[1] for t in threads),
            "by_name": {k: by_name[k] for k in sorted(by_name)},
        }

    def chrome_trace(self) -> Dict[str, Any]:
        """Chrome-trace (Trace Event Format) document: complete ("X")
        events in microseconds on one track per thread, loadable by
        ``chrome://tracing`` and Perfetto. Timestamps are monotonic
        (``perf_counter_ns`` rebased to the tracer epoch), so parent
        spans always enclose their children."""
        events: List[Dict[str, Any]] = []
        pid = os.getpid()
        seen_threads: Dict[int, str] = {}
        for s in self.spans():
            seen_threads.setdefault(s["thread_id"], s["thread_name"])
            event = {
                "name": s["name"], "cat": "sparkdl", "ph": "X",
                "ts": s["start_ns"] / 1e3,
                "dur": (s["end_ns"] - s["start_ns"]) / 1e3,
                "pid": pid, "tid": s["thread_id"],
                "args": {"trace_id": s["trace_id"],
                         "span_id": s["span_id"],
                         "parent_id": s["parent_id"],
                         **s.get("attributes", {})},
            }
            events.append(event)
        for tid, tname in seen_threads.items():
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": tname}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

# Log-scale (factor-2) bucket upper bounds. Durations: 100 µs .. ~3.7 h.
DEFAULT_TIME_BOUNDS: Tuple[float, ...] = tuple(
    1e-4 * 2 ** i for i in range(27))
# Row counts / sizes: powers of two 1 .. 64Ki.
POW2_BOUNDS: Tuple[float, ...] = tuple(float(2 ** i) for i in range(17))


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._value: Optional[float] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            return self._value


class Histogram:
    """Fixed log-scale-bucket histogram with percentile estimates.

    Buckets are upper bounds (Prometheus ``le`` semantics) growing by a
    constant factor (default 2×), so the relative error of a percentile
    estimate is bounded by the factor. p50/p95/p99 are estimated at the
    geometric midpoint of the covering bucket, clamped to the observed
    [min, max].
    """

    __slots__ = ("name", "_lock", "bounds", "_counts", "count", "sum",
                 "min", "max")

    def __init__(self, name: str,
                 bounds: Sequence[float] = DEFAULT_TIME_BOUNDS) -> None:
        self.name = name
        self._lock = threading.Lock()
        self.bounds = tuple(float(b) for b in bounds)
        self._counts = [0] * (len(self.bounds) + 1)  # last = +Inf overflow
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self.count += 1
            self.sum += value
            if self.min is None or value < self.min:
                self.min = value
            if self.max is None or value > self.max:
                self.max = value

    def percentile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (q in [0, 1]) from the bucket counts."""
        with self._lock:
            if self.count == 0:
                return None
            target = max(1, math.ceil(q * self.count))
            cum = 0
            for i, c in enumerate(self._counts):
                cum += c
                if cum >= target:
                    lo = self.bounds[i - 1] if i > 0 else 0.0
                    hi = (self.bounds[i] if i < len(self.bounds)
                          else (self.max if self.max is not None else lo))
                    if lo > 0 and hi > 0:
                        est = math.sqrt(lo * hi)
                    else:
                        est = hi
                    return min(max(est, self.min), self.max)
            return self.max

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counts = list(self._counts)
            count, total = self.count, self.sum
            lo, hi = self.min, self.max
        buckets = {("+Inf" if i == len(self.bounds)
                    else repr(self.bounds[i])): c
                   for i, c in enumerate(counts) if c}
        return {
            "count": count, "sum": round(total, 9), "min": lo, "max": hi,
            "p50": self.percentile(0.50), "p95": self.percentile(0.95),
            "p99": self.percentile(0.99), "buckets": buckets,
        }


class MetricsRegistry:
    """Get-or-create registry of named instruments (one per name)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            inst = self._counters.get(name)
            if inst is None:
                inst = self._counters[name] = Counter(name)
            return inst

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            inst = self._gauges.get(name)
            if inst is None:
                inst = self._gauges[name] = Gauge(name)
            return inst

    def histogram(self, name: str,
                  bounds: Sequence[float] = DEFAULT_TIME_BOUNDS
                  ) -> Histogram:
        with self._lock:
            inst = self._histograms.get(name)
            if inst is None:
                inst = self._histograms[name] = Histogram(name, bounds)
            return inst

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able {counters, gauges, histograms} snapshot."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: counters[k].value for k in sorted(counters)},
            "gauges": {k: gauges[k].value for k in sorted(gauges)},
            "histograms": {k: histograms[k].snapshot()
                           for k in sorted(histograms)},
        }

    def prometheus_text(self) -> str:
        """Prometheus text exposition (0.0.4) dump of every instrument."""
        import re as _re

        def sane(name: str) -> str:
            return _re.sub(r"[^a-zA-Z0-9_:]", "_", name)

        lines: List[str] = []
        snap = self.snapshot()
        for name, value in snap["counters"].items():
            n = sane(name)
            lines += [f"# TYPE {n} counter", f"{n} {value}"]
        for name, value in snap["gauges"].items():
            if value is None:
                continue
            n = sane(name)
            lines += [f"# TYPE {n} gauge", f"{n} {value}"]
        with self._lock:
            hists = dict(self._histograms)
        for name in sorted(hists):
            h = hists[name]
            n = sane(name)
            lines.append(f"# TYPE {n} histogram")
            with h._lock:
                counts = list(h._counts)
                count, total = h.count, h.sum
            cum = 0
            for i, bound in enumerate(h.bounds):
                cum += counts[i]
                lines.append(f'{n}_bucket{{le="{bound}"}} {cum}')
            lines.append(f'{n}_bucket{{le="+Inf"}} {count}')
            lines.append(f"{n}_sum {total}")
            lines.append(f"{n}_count {count}")
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# The process-wide scope
# ---------------------------------------------------------------------------

_run_counter = itertools.count(1)


class _RunContextFilter(logging.Filter):
    """Stamps run_id/trace_id onto log records (via the record factory,
    so it reaches records regardless of which handler formats them)."""

    def __init__(self, run_id: str, trace_id: str) -> None:
        super().__init__()
        self.run_id = run_id
        self.trace_id = trace_id

    def filter(self, record: logging.LogRecord) -> bool:
        record.run_id = self.run_id
        record.trace_id = self.trace_id
        return True


class Telemetry:
    """One run's telemetry scope: tracer + metrics + end-of-run report.

    ::

        with Telemetry("nightly-fit", out_dir="/tmp/tel") as tel:
            pipeline.run()
        # exiting wrote sparkdl_run_report_<run_id>.json and
        # sparkdl_trace_<run_id>.json into out_dir

    ``out_dir`` defaults to ``$SPARKDL_TELEMETRY_DIR``; when neither is
    set no files are written and the scope is purely programmatic
    (``tel.tracer`` / ``tel.metrics`` / ``tel.report()``). While the
    scope is active, log records from the ``sparkdl_tpu`` namespace
    carry ``.run_id`` / ``.trace_id`` attributes (structured-logging
    adapter). To fold the active ``HealthMonitor``'s report into the
    run report, enter the monitor BEFORE (outside) the telemetry scope.
    """

    def __init__(self, name: str = "run", out_dir: Optional[str] = None,
                 max_spans: int = 65536) -> None:
        self.name = name
        self.out_dir = (out_dir if out_dir is not None
                        else os.environ.get(TELEMETRY_DIR_ENV))
        self.run_id = f"{name}-{os.getpid():x}-{next(_run_counter):04x}"
        self.tracer = Tracer(trace_id=self.run_id, max_spans=max_spans)
        self.metrics = MetricsRegistry()
        self._prev: Optional["Telemetry"] = None
        self._root: Optional[_Span] = None
        self._prev_factory: Any = None
        self._filter = _RunContextFilter(self.run_id, self.run_id)
        self.report_path: Optional[str] = None
        self.trace_path: Optional[str] = None

    # -- context -------------------------------------------------------------

    @property
    def root_context(self) -> Optional[SpanContext]:
        return self._root.context if self._root is not None else None

    def __enter__(self) -> "Telemetry":
        global _active
        with _activation_lock:
            self._prev = _active
            _active = self
            # structured-logging adapter: stamp run/trace ids at record
            # creation so they survive any handler (a Filter on the
            # package logger would miss records emitted via child
            # loggers — logging only runs logger-level filters on the
            # logger actually called)
            prev_factory = logging.getLogRecordFactory()
            self._prev_factory = prev_factory
            flt = self._filter

            def factory(*args: Any, **kwargs: Any) -> logging.LogRecord:
                record = prev_factory(*args, **kwargs)
                if record.name.startswith("sparkdl_tpu"):
                    flt.filter(record)
                return record

            logging.setLogRecordFactory(factory)
        self._root = self.tracer.span(SPAN_RUN, parent=ROOT,
                                      run=self.name)
        self._root.__enter__()
        return self

    def __exit__(self, *exc: Any) -> None:
        global _active
        if self._root is not None:
            # pass the unwinding exception through so the run root span
            # carries the error attribute like every interior span
            exc3 = exc if len(exc) == 3 else (None, None, None)
            self._root.__exit__(*exc3)
        with _activation_lock:
            _active = self._prev
            self._prev = None
            logging.setLogRecordFactory(self._prev_factory)
        if self.out_dir:
            try:
                self.write_report(self.out_dir)
            except OSError as e:
                logging.getLogger(__name__).error(
                    "could not write telemetry report to %r: %s",
                    self.out_dir, e)

    # -- reporting -----------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        return RunReport.build(self)

    def write_report(self, out_dir: str) -> str:
        """Write the run report + Chrome trace JSONs; returns the report
        path (also kept in :attr:`report_path` / :attr:`trace_path`)."""
        os.makedirs(out_dir, exist_ok=True)
        trace_path = os.path.join(
            out_dir, f"sparkdl_trace_{self.run_id}.json")
        with open(trace_path, "w") as f:
            json.dump(self.tracer.chrome_trace(), f)
        report = self.report()
        report["chrome_trace"] = trace_path
        report_path = os.path.join(
            out_dir, f"sparkdl_run_report_{self.run_id}.json")
        with open(report_path, "w") as f:
            json.dump(report, f, indent=2, default=str)
        self.report_path, self.trace_path = report_path, trace_path
        return report_path


_active: Optional[Telemetry] = None
_activation_lock = threading.Lock()


def active() -> Optional[Telemetry]:
    return _active


def current_context() -> Optional[SpanContext]:
    """The ambient span context on THIS thread: innermost open span,
    else the context attached via :func:`attach`, else the active
    scope's root span. ``None`` without an active scope."""
    tel = _active
    if tel is None:
        return None
    stack = getattr(_tls, "stack", None)
    if stack:
        return stack[-1].context
    base = getattr(_tls, "base", None)
    if base is not None:
        return base
    return tel.root_context


def attach(ctx: Optional[SpanContext]) -> None:
    """Adopt ``ctx`` as this thread's base context: ambient spans opened
    here parent under it. For FRESH worker threads (the prefetcher's
    staging thread); pool threads that outlive a task should pass
    ``parent=`` explicitly instead — an attached base would leak into
    the next task."""
    _tls.base = ctx


def span(name: str, parent: Optional[SpanContext] = None,
         **attributes: Any) -> Any:
    """An open span on the active scope's tracer; the shared
    :data:`NULL_SPAN` singleton (no allocation) when no scope is
    active."""
    tel = _active
    if tel is None:
        return NULL_SPAN
    return tel.tracer.span(name, parent=parent, **attributes)


def count(name: str, n: int = 1) -> None:
    """Bump a counter on the active registry (no-op — one global read —
    without a scope)."""
    tel = _active
    if tel is not None:
        tel.metrics.counter(name).inc(n)


def gauge_set(name: str, value: float) -> None:
    tel = _active
    if tel is not None:
        tel.metrics.gauge(name).set(value)


def observe(name: str, value: float,
            bounds: Sequence[float] = DEFAULT_TIME_BOUNDS) -> None:
    tel = _active
    if tel is not None:
        tel.metrics.histogram(name, bounds).observe(value)


# ---------------------------------------------------------------------------
# Run report
# ---------------------------------------------------------------------------


class RunReport:
    """Builder for the single end-of-run JSON artifact: trace summary +
    metric snapshot + phase/overlap stats + health report."""

    @staticmethod
    def build(tel: Telemetry,
              health_monitor: Any = None) -> Dict[str, Any]:
        # lazy imports: profiling imports this module at module level
        from sparkdl_tpu.core import health as _health
        from sparkdl_tpu.core import profiling as _profiling

        mon = (health_monitor if health_monitor is not None
               else _health.active_monitor())
        return {
            "run_id": tel.run_id,
            "run": tel.name,
            "created_unix_s": round(time.time(), 3),
            "trace": tel.tracer.summary(),
            "metrics": tel.metrics.snapshot(),
            "phases": _profiling.phase_stats(),
            "overlap": _profiling.overlap_stats(),
            "health": mon.report() if mon is not None else None,
        }
