"""Unified telemetry: cross-thread span tracing, metrics, one run report.

The reference library shipped no in-tree observability — operators
hand-instrumented the Spark UI and TF timelines (SURVEY.md §5.1). The
rebuild had fragments: global phase accumulators (`core/profiling.py`),
resilience counters (`core/health.py`), train metrics
(`train/metrics.py`) — none sharing identifiers, none exportable
together. With the data plane spanning four concurrent execution
contexts (driver, supervisor pool threads, the `DevicePrefetcher`
staging thread, the deferred-sync train loop), "where did step 412's
batch spend its time, and which partition task stalled it?" needs
correlated per-span records, not aggregate totals. This module is the
Dapper-style span model plus the Prometheus metric taxonomy for exactly
that, in three integrated parts:

1. **Span tracing** — a :class:`Tracer` producing per-span records
   (name, trace_id, span_id, parent_id, thread, start/end ns,
   attributes) into a bounded ring buffer, with explicit cross-thread
   context handoff (:func:`current_context` on the parent thread,
   ``span(parent=ctx)`` or :func:`attach` on the child) so engine
   partition tasks, prefetcher staging and `Trainer.fit` steps all
   parent correctly under one run trace. Exportable as Chrome-trace
   JSON (``chrome://tracing`` / Perfetto, one track per thread) with no
   ``jax.profiler`` dependency.
2. **Metrics registry** — named :class:`Counter` / :class:`Gauge` /
   :class:`Histogram` instruments (fixed log-scale buckets with
   p50/p95/p99 estimates), with a JSON :meth:`MetricsRegistry.snapshot`
   and a Prometheus text-exposition dump.
3. **Run report** — :class:`RunReport` merges the trace summary, the
   metric snapshot, ``profiling.phase_stats()``/``overlap_stats()`` and
   the active ``HealthMonitor`` report into one JSON artifact written
   at scope exit (opt-in via ``SPARKDL_TELEMETRY_DIR`` or an explicit
   ``Telemetry(out_dir=...)`` scope), plus a structured-logging adapter
   stamping ``run_id``/``trace_id`` onto framework log records.
4. **Live plane** (docs/OBSERVABILITY.md "Live metrics & SLOs") — every
   instrument a scope creates additionally feeds a fixed-size ring of
   time-bucketed sub-snapshots (monotonic-clock rotation, O(1) record
   path), so :meth:`MetricsRegistry.window_snapshot` answers "rate and
   p50/p95/p99 over the last N seconds" alongside the cumulative views
   — a 10-minute-old latency spike no longer pollutes "current" p99.
   A :class:`SnapshotExporter` daemon thread inside the scope writes a
   JSON-lines snapshot (windowed + cumulative + executor queue/breaker
   state) and an atomically-replaced Prometheus text file every
   ``export_interval_s``, evaluates the ``core.slo`` watchdog rules on
   each tick, and flushes one final snapshot at scope exit; the run
   report gains a ``timeline`` summary derived from the snapshots.

Scoping mirrors :class:`~sparkdl_tpu.core.health.HealthMonitor`:
a :class:`Telemetry` scope activates process-wide (engine partition ops
run on pool threads where a ContextVar entered on the driver would be
invisible), nests, and restores the previous scope on exit. With no
active scope every entry point — :func:`span`, :func:`count`,
:func:`gauge_set`, :func:`observe` — is a single global read + ``None``
check returning a shared singleton: the hot paths allocate nothing and
never touch a device (telemetry must never introduce a device sync; the
step-loop AST lint in ``tests/test_taxonomy_lint.py`` stays satisfied).

Dependency-free by design (stdlib only): every layer may import it
without cycles. ``core.profiling`` imports this module; the run report
imports ``profiling``/``health`` lazily to break the cycle.
"""

from __future__ import annotations

import bisect
import itertools
import json
import logging
import math
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

TELEMETRY_DIR_ENV = "SPARKDL_TELEMETRY_DIR"
# Opt-in periodic exporter cadence (seconds) for scopes that don't pass
# export_interval_s explicitly; requires TELEMETRY_DIR for file output.
EXPORT_INTERVAL_ENV = "SPARKDL_TELEMETRY_EXPORT_S"

# The window rings and the exporter read THIS clock (monotonic by
# default) so tests can drive rotation/cadence deterministically with a
# fake clock. The span hot path keeps calling perf_counter_ns directly.
_monotonic = time.monotonic

# ---------------------------------------------------------------------------
# Canonical names (docs/OBSERVABILITY.md is the human-readable catalog).
# The taxonomy lint (tests/test_taxonomy_lint.py) checks every annotate()/
# span() name used in sparkdl_tpu/ against CANONICAL_SPAN_NAMES — a typo'd
# phase name would otherwise silently fork a timer.
# ---------------------------------------------------------------------------

SPAN_RUN = "sparkdl.run"                      # telemetry scope root
SPAN_RUNNER_ATTEMPT = "sparkdl.runner_attempt"  # TPURunner gang attempt
SPAN_FIT = "sparkdl.fit"                      # one Trainer.fit call
SPAN_EPOCH = "sparkdl.epoch"                  # one epoch of the fit loop
SPAN_CHECKPOINT_SAVE = "sparkdl.checkpoint_save"
SPAN_ESTIMATOR_FIT = "sparkdl.estimator_fit"  # KerasImageFileEstimator._fit
SPAN_COLLECT = "sparkdl.collect"              # estimator collected decode
SPAN_MATERIALIZE = "sparkdl.materialize"      # DataFrame._materialize barrier
SPAN_TASK = "sparkdl.task"                    # one pool attempt (or hedge)
SPAN_TASK_ATTEMPT = "sparkdl.task_attempt"    # one retry-loop attempt
SPAN_COMPILE = "sparkdl.compile"              # first launch of a new shape
SPAN_COALESCED_LAUNCH = "sparkdl.coalesced_launch"  # core/executor.py
SPAN_DECODE_POOL = "sparkdl.decode_pool"      # one pooled decode fan-out
                                              # (core/decode_pool.py)
SPAN_MODEL_LOAD = "sparkdl.model_load"        # serving cold start: loader
                                              # run on a residency miss
                                              # (serving/residency.py)
SPAN_CLUSTER_DISPATCH = "sparkdl.cluster_dispatch"  # one partition's
                                              # round trip to a cluster
                                              # worker (cluster/router.py)
SPAN_CLUSTER_TASK = "sparkdl.cluster_task"    # worker-side execution of
                                              # one dispatched partition
                                              # (cluster/worker.py)
SPAN_DECODE_CHUNK = "sparkdl.decode_chunk"    # one chunk decoded inside
                                              # a pool worker process
                                              # (core/decode_pool.py)
SPAN_SERVING_SHADOW = "sparkdl.serving_shadow"  # shadow-lane replay of
                                              # one serving request
                                              # (serving/server.py)
SPAN_SERVING_PREDICT = "sparkdl.serving_predict"  # worker-side execution
                                              # of one cluster-routed
                                              # predict (serving/cluster.py)
SPAN_SERVING_WARMUP = "sparkdl.serving.warmup_s"  # AOT bucket-ladder
                                              # warmup of one deployment
                                              # (serving/registry.py)

CANONICAL_SPAN_NAMES = frozenset({
    SPAN_RUN, SPAN_RUNNER_ATTEMPT, SPAN_FIT, SPAN_EPOCH,
    SPAN_CHECKPOINT_SAVE, SPAN_ESTIMATOR_FIT, SPAN_COLLECT,
    SPAN_MATERIALIZE, SPAN_TASK, SPAN_TASK_ATTEMPT,
    SPAN_COMPILE, SPAN_COALESCED_LAUNCH, SPAN_DECODE_POOL,
    SPAN_MODEL_LOAD, SPAN_CLUSTER_DISPATCH, SPAN_CLUSTER_TASK,
    SPAN_DECODE_CHUNK, SPAN_SERVING_SHADOW, SPAN_SERVING_PREDICT,
    SPAN_SERVING_WARMUP,
    # phase names (core/profiling.py constants + literal call sites)
    "sparkdl.decode", "sparkdl.stage", "sparkdl.stage_batch",
    "sparkdl.host_stage", "sparkdl.host_resize", "sparkdl.host_wait",
    "sparkdl.device_apply", "sparkdl.train_step", "sparkdl.device_sync",
})

# Metric catalog. Histograms in seconds use DEFAULT_TIME_BOUNDS; row-count
# histograms use POW2_BOUNDS. Health-event mirrors are dynamic:
# "sparkdl.health.<event>" per core/health.py event name, bumped in
# health.record so telemetry counters equal HealthMonitor counts exactly.
M_TASK_DURATION_S = "sparkdl.task.duration_s"          # histogram
M_STEP_TIME_S = "sparkdl.train.step_time_s"            # histogram (host)
M_STEPS_PER_SEC = "sparkdl.train.steps_per_sec"        # histogram
M_EXAMPLES_PER_SEC = "sparkdl.train.examples_per_sec"  # gauge
M_PREFETCH_DEPTH = "sparkdl.prefetch.queue_depth"      # gauge
M_PREFETCH_STALL_S = "sparkdl.prefetch.stall_s"        # histogram
M_BATCH_ROWS = "sparkdl.batching.rows"                 # counter (valid rows)
M_BATCH_PAD_ROWS = "sparkdl.batching.pad_rows"         # counter (pad rows)
M_BATCH_BUCKET_ROWS = "sparkdl.batching.bucket_rows"   # histogram
M_PADDING_WASTE = "sparkdl.batching.padding_waste"     # gauge (pad fraction)
# Telemetry-tuned bucket ladder (core/batching.BucketPlanner, docs/PERF.md
# "Launch shaping & precision"): one counter bump per adopted ladder, and
# the planner's predicted pad fraction under the ladder it just adopted
# (the per-model padding waste AFTER tuning; the update counter's pace is
# bounded by the planner's hysteresis).
M_BUCKET_LADDER_UPDATE = "sparkdl.batching.bucket_ladder_update"  # counter
M_PLANNER_WASTE = "sparkdl.batching.planner_waste"     # gauge (pad fraction)
M_ENGINE_ROWS_OUT = "sparkdl.engine.rows_out"          # counter
M_ENGINE_BYTES_OUT = "sparkdl.engine.bytes_out"        # counter
# Device execution service (core/executor.py, docs/PERF.md coalescing):
M_COALESCE_REQUESTS = "sparkdl.executor.coalesce_requests"  # histogram
M_COALESCE_ROWS = "sparkdl.executor.coalesce_rows"     # histogram
M_COALESCE_DEDUP = "sparkdl.executor.dedup_hits"       # counter (hedges)
M_QUEUE_WAIT_S = "sparkdl.executor.queue_wait_s"       # histogram
M_LAUNCH_S = "sparkdl.executor.launch_s"               # histogram (host)
M_EXECUTOR_OCCUPANCY = "sparkdl.executor.occupancy"    # gauge (in-flight)
# Overload protection (ISSUE 6): the shed/deadline/breaker COUNTS arrive
# for free as sparkdl.health.* mirrors of the core/health.py events; the
# gauges below are the executor's own instantaneous state.
M_EXECUTOR_QUEUE_DEPTH = "sparkdl.executor.queue_depth"  # gauge (queued reqs)
M_EXECUTOR_SHED_RATE = "sparkdl.executor.shed_rate"    # gauge (shed fraction)
# Columnar data plane (docs/PERF.md "Columnar data plane"): bytes handed
# to the executor per execute() call, as staged on the host. On the
# columnar path this is raw uint8 pixels — the counter is how bench and
# tests assert "host ships uint8 only" (a f32 regression quadruples it).
M_STAGED_BYTES = "sparkdl.executor.staged_bytes"       # counter
# Parallel host decode pool (core/decode_pool.py, docs/PERF.md "Parallel
# host ingest"):
M_DECODE_POOL_DEPTH = "sparkdl.decode_pool.queue_depth"    # gauge (chunks)
M_DECODE_POOL_BUSY = "sparkdl.decode_pool.workers_busy"    # gauge
M_DECODE_POOL_DECODE_S = "sparkdl.decode_pool.decode_s"    # histogram
                                                           # (per blob)
# Online serving plane (sparkdl_tpu/serving/, docs/SERVING.md): row-level
# request path over the executor choke point. Per-model latency
# histograms are declared dynamically at deploy time as
# "sparkdl.serving.request_s.<model>" via declare_metric().
M_SERVING_REQUEST_S = "sparkdl.serving.request_s"      # histogram (e2e)
M_SERVING_QUEUE_DEPTH = "sparkdl.serving.queue_depth"  # gauge (in-flight
                                                       # predict calls)
M_SERVING_SHADOW_DIVERGENCE = "sparkdl.serving.shadow_divergence"
                                                       # histogram (max
                                                       # |active-shadow|)
M_SERVING_EVICTIONS = "sparkdl.serving.evictions"      # counter
# Cluster serving plane (serving/cluster.py, docs/SERVING.md "Cluster
# serving"): replicated deployments across cluster workers. The
# failover counter is the router's own canonical series (the
# serving_failover health mirror carries the same count — the merged
# report cross-checks them); the replicas gauge tracks the live replica
# set of the deployment most recently routed.
M_SERVING_FAILOVER = "sparkdl.serving.failover"        # counter (moved
                                                       # in-flight
                                                       # requests)
M_SERVING_REPLICAS = "sparkdl.serving.replicas"        # gauge (live
                                                       # replicas of the
                                                       # last-routed
                                                       # deployment)
# Cluster inference plane (sparkdl_tpu/cluster/, docs/DISTRIBUTED.md
# "Cluster inference"): the router's load/latency view. Worker-loss and
# re-dispatch COUNTS also arrive as sparkdl.health.* mirrors; the
# redispatch counter below is the router's own canonical series.
M_CLUSTER_OUTSTANDING_ROWS = "sparkdl.cluster.outstanding_rows"  # gauge
                                                       # (rows in flight
                                                       # across workers)
M_CLUSTER_DISPATCH_S = "sparkdl.cluster.dispatch_s"    # histogram (per
                                                       # partition round
                                                       # trip)
M_CLUSTER_REDISPATCH = "sparkdl.cluster.redispatch"    # counter
# Elastic capacity (autoscaler + graceful drain, docs/DISTRIBUTED.md
# "Elastic capacity"): the live worker-set size and how long a drain
# takes from preemption notice / scale-down order to clean exit.
M_CLUSTER_WORKERS = "sparkdl.cluster.workers"          # gauge (live,
                                                       # non-draining)
M_CLUSTER_DRAIN_S = "sparkdl.cluster.drain_s"          # histogram
# Pallas kernel autotune (core/kernels.py, docs/PERF.md "Fused kernels &
# AOT warmup"): one histogram observation per shootout (build + numeric
# check + timing of both candidates) and one adopted/rejected counter
# bump per settled verdict.
M_KERNEL_AUTOTUNE_S = "sparkdl.kernel.autotune_s"      # histogram
M_KERNEL_ADOPTED = "sparkdl.kernel.adopted"            # counter
M_KERNEL_REJECTED = "sparkdl.kernel.rejected"          # counter
# Per-tenant fair queueing (core/executor.py, docs/RESILIENCE.md): each
# tenant's queue-wait histogram gets a per-tenant NAME (metrics carry no
# labels), declared dynamically as "sparkdl.executor.queue_wait_s.<tenant>"
# via tenant_queue_wait_metric() + declare_metric().
HEALTH_METRIC_PREFIX = "sparkdl.health."

# Instrument kind per canonical metric — machine-readable so core/slo.py
# can reject a rule whose stat can never be observed on its metric (a
# p99 of a counter would silently watch nothing).
CANONICAL_METRIC_KINDS: Dict[str, str] = {
    M_TASK_DURATION_S: "histogram",
    M_STEP_TIME_S: "histogram",
    M_STEPS_PER_SEC: "histogram",
    M_EXAMPLES_PER_SEC: "gauge",
    M_PREFETCH_DEPTH: "gauge",
    M_PREFETCH_STALL_S: "histogram",
    M_BATCH_ROWS: "counter",
    M_BATCH_PAD_ROWS: "counter",
    M_BATCH_BUCKET_ROWS: "histogram",
    M_PADDING_WASTE: "gauge",
    M_BUCKET_LADDER_UPDATE: "counter",
    M_PLANNER_WASTE: "gauge",
    M_ENGINE_ROWS_OUT: "counter",
    M_ENGINE_BYTES_OUT: "counter",
    M_COALESCE_REQUESTS: "histogram",
    M_COALESCE_ROWS: "histogram",
    M_COALESCE_DEDUP: "counter",
    M_QUEUE_WAIT_S: "histogram",
    M_LAUNCH_S: "histogram",
    M_EXECUTOR_OCCUPANCY: "gauge",
    M_EXECUTOR_QUEUE_DEPTH: "gauge",
    M_EXECUTOR_SHED_RATE: "gauge",
    M_STAGED_BYTES: "counter",
    M_DECODE_POOL_DEPTH: "gauge",
    M_DECODE_POOL_BUSY: "gauge",
    M_DECODE_POOL_DECODE_S: "histogram",
    M_SERVING_REQUEST_S: "histogram",
    M_SERVING_QUEUE_DEPTH: "gauge",
    M_SERVING_SHADOW_DIVERGENCE: "histogram",
    M_SERVING_EVICTIONS: "counter",
    M_SERVING_FAILOVER: "counter",
    M_SERVING_REPLICAS: "gauge",
    M_CLUSTER_OUTSTANDING_ROWS: "gauge",
    M_CLUSTER_DISPATCH_S: "histogram",
    M_CLUSTER_REDISPATCH: "counter",
    M_CLUSTER_WORKERS: "gauge",
    M_CLUSTER_DRAIN_S: "histogram",
    M_KERNEL_AUTOTUNE_S: "histogram",
    M_KERNEL_ADOPTED: "counter",
    M_KERNEL_REJECTED: "counter",
}

CANONICAL_METRIC_NAMES = frozenset(CANONICAL_METRIC_KINDS)

_declare_lock = threading.Lock()


def declare_metric(name: str, kind: str) -> str:
    """Declare a DYNAMIC metric name (e.g. the per-model serving latency
    histogram ``sparkdl.serving.request_s.<model>``) into the catalog so
    ``core.slo.SLORule`` construction accepts it. Static call sites must
    use the ``M_*`` constants — this is for names that only exist at
    runtime (model deployments). Idempotent; re-declaring with a
    DIFFERENT kind raises (two writers disagreeing on the instrument
    would corrupt every rule watching it). Returns ``name``."""
    if kind not in ("histogram", "counter", "gauge"):
        raise ValueError(
            f"declare_metric kind must be 'histogram', 'counter' or "
            f"'gauge', got {kind!r}")
    global CANONICAL_METRIC_NAMES
    with _declare_lock:
        have = CANONICAL_METRIC_KINDS.get(name)
        if have is not None and have != kind:
            raise ValueError(
                f"metric {name!r} already declared as {have!r}, cannot "
                f"re-declare as {kind!r}")
        if have is None:
            CANONICAL_METRIC_KINDS[name] = kind
            CANONICAL_METRIC_NAMES = frozenset(CANONICAL_METRIC_KINDS)
    return name


def serving_request_metric(model: str) -> str:
    """The per-model serving latency histogram name. Metrics carry no
    labels, so per-model p99 objectives get per-model NAMES — declared
    at deploy time (``declare_metric``), observed by the ModelServer
    beside the aggregate ``M_SERVING_REQUEST_S``."""
    return M_SERVING_REQUEST_S + "." + model


def tenant_queue_wait_metric(tenant: str) -> str:
    """The per-tenant queue-wait histogram name. Like the per-model
    serving latency, per-tenant fairness objectives get per-tenant NAMES
    — declared on first use (``declare_metric``) by the executor's
    coalescer, observed beside the aggregate ``M_QUEUE_WAIT_S`` so a
    flooding tenant's self-inflicted wait is distinguishable from the
    wait it imposes on everyone else."""
    return M_QUEUE_WAIT_S + "." + tenant

# ---------------------------------------------------------------------------
# Span tracing
# ---------------------------------------------------------------------------


class SpanContext(NamedTuple):
    """The cross-thread handoff token: enough to parent a remote span."""

    trace_id: str
    span_id: int


class _RootSentinel:
    """``Tracer.span(parent=ROOT)``: force a parentless root span (vs
    ``parent=None``, which adopts the ambient context)."""


ROOT = _RootSentinel()


_tls = threading.local()


def _span_stack() -> List["_Span"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class _NullSpan:
    """Shared no-op span: the inactive path returns THIS singleton —
    zero allocation, inert context manager."""

    __slots__ = ()
    context: Optional[SpanContext] = None

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set_attribute(self, key: str, value: Any) -> None:
        pass


NULL_SPAN = _NullSpan()


class _Span:
    """An open span; records into its tracer's ring buffer on exit."""

    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "attributes", "_start_ns", "_pushed")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: int, parent_id: Optional[int],
                 attributes: Dict[str, Any]) -> None:
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attributes = attributes
        self._start_ns = 0
        self._pushed = False

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def __enter__(self) -> "_Span":
        _span_stack().append(self)
        self._pushed = True
        self._start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        end_ns = time.perf_counter_ns()
        if self._pushed:
            stack = _span_stack()
            if stack and stack[-1] is self:
                stack.pop()
            elif self in stack:  # defensive: exited out of order
                stack.remove(self)
            self._pushed = False
        if exc_type is not None:
            self.attributes.setdefault("error", exc_type.__name__)
        self._tracer._record(self, self._start_ns, end_ns)
        return False


class Tracer:
    """Per-run span recorder: bounded ring buffer + Chrome-trace export.

    The ring keeps the most recent ``max_spans`` finished spans (the
    HealthMonitor event log keeps the FIRST n — traces want the tail: the
    end of a run is where failures live) and counts evictions in
    :attr:`dropped`. Thread-safe; spans may finish on any thread.
    """

    def __init__(self, trace_id: str, max_spans: int = 65536) -> None:
        self.trace_id = trace_id
        self.max_spans = max_spans
        self.dropped = 0
        self.remote_adopted = 0
        self.remote_rejected = 0
        self._lock = threading.Lock()
        self._spans: "deque[Dict[str, Any]]" = deque(maxlen=max_spans)
        # span ids are pid-salted: a cluster/decode worker's spans merge
        # into the coordinator's ring, so ids allocated independently in
        # each process must never collide (Linux pids fit in 22 bits;
        # 40 low bits leave ~10^12 spans per process)
        self._ids = itertools.count((os.getpid() << 40) | 1)
        self._t0_ns = time.perf_counter_ns()

    # -- producing -----------------------------------------------------------

    def span(self, name: str, parent: Any = None,
             **attributes: Any) -> _Span:
        """An open span context manager. ``parent`` explicitly parents a
        cross-thread span (pass the creating thread's
        :func:`current_context`); otherwise the ambient context — this
        thread's innermost open span, its attached base, or the scope
        root — is the parent. ``parent=ROOT`` makes a parentless root
        span (the scope's own run span)."""
        if parent is ROOT:
            trace_id, parent_id = self.trace_id, None
        else:
            if parent is None:
                parent = current_context()
            if parent is None:
                trace_id, parent_id = self.trace_id, None
            else:
                trace_id, parent_id = parent.trace_id, parent.span_id
        return _Span(self, name, trace_id, next(self._ids), parent_id,
                     attributes)

    def _record(self, span: _Span, start_ns: int, end_ns: int) -> None:
        thread = threading.current_thread()
        rec = {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "thread_id": thread.ident,
            "thread_name": thread.name,
            "start_ns": start_ns - self._t0_ns,
            "end_ns": end_ns - self._t0_ns,
        }
        if span.attributes:
            rec["attributes"] = span.attributes
        with self._lock:
            if len(self._spans) == self.max_spans:
                self.dropped += 1
            self._spans.append(rec)

    # -- querying / export ---------------------------------------------------

    def spans(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s["name"] == name]
        return out

    def summary(self) -> Dict[str, Any]:
        """Aggregate per-name stats over ONE snapshot of the ring (the
        count and the aggregates must agree even while other threads
        keep recording)."""
        spans = self.spans()
        by_name: Dict[str, Dict[str, Any]] = {}
        threads = set()
        for s in spans:
            threads.add((s["thread_id"], s["thread_name"]))
            agg = by_name.setdefault(
                s["name"], {"count": 0, "total_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += (s["end_ns"] - s["start_ns"]) / 1e9
        for agg in by_name.values():
            agg["total_s"] = round(agg["total_s"], 6)
            agg["mean_s"] = round(agg["total_s"] / agg["count"], 6)
        return {
            "trace_id": self.trace_id,
            "spans_recorded": len(spans),
            "spans_dropped": self.dropped,
            "remote_adopted": self.remote_adopted,
            "remote_rejected": self.remote_rejected,
            "threads": sorted(t[1] for t in threads),
            "by_name": {k: by_name[k] for k in sorted(by_name)},
        }

    # -- cross-process merge (docs/OBSERVABILITY.md "Distributed
    # tracing"): a worker ships its ring rebased onto the parent's
    # clock; the parent adopts it into ONE merged trace -------------------

    def export_ring(self, *, clock_offset_ns: int = 0,
                    process: Optional[str] = None,
                    parent_remap: Optional[Dict[int, int]] = None,
                    limit: int = 4096) -> Dict[str, Any]:
        """The shippable view of this ring: every span rebased to the
        PARENT's monotonic clock (``abs_ns = rel + t0 + offset``, offset
        from the worker handshake) and stamped with this process's pid
        and ``process`` track label. ``parent_remap`` rewrites parent
        ids — the worker's still-open ``sparkdl.run`` root never ships,
        so spans under it re-parent onto the coordinator's root instead
        of dangling. Keeps the most recent ``limit`` spans; truncation
        adds to the shipped ``dropped`` count (never silent)."""
        spans = self.spans()
        shipped_dropped = self.dropped
        if len(spans) > limit:
            shipped_dropped += len(spans) - limit
            spans = spans[-limit:]
        pid = os.getpid()
        remap = parent_remap or {}
        out = []
        for s in spans:
            rec = dict(s)
            rec["start_ns"] = s["start_ns"] + self._t0_ns + clock_offset_ns
            rec["end_ns"] = s["end_ns"] + self._t0_ns + clock_offset_ns
            rec["pid"] = pid
            if process is not None:
                rec["process"] = process
            parent = rec.get("parent_id")
            if parent in remap:
                rec["parent_id"] = remap[parent]
            out.append(rec)
        return {"spans": out, "dropped": shipped_dropped,
                "clock_offset_ns": clock_offset_ns}

    def adopt_remote_spans(self, records: Sequence[Dict[str, Any]]
                           ) -> Tuple[int, int]:
        """Merge spans shipped by :meth:`export_ring` in another process
        into this ring: absolute parent-clock timestamps rebase onto
        this tracer's epoch so local and remote spans share one
        timeline. A record whose name is not canonical is REJECTED and
        counted (a worker must not invent an unmergeable name — the
        runtime half of the span-names lint); never raises. Returns
        ``(adopted, rejected)``."""
        adopted = rejected = 0
        for s in records:
            if s.get("name") not in CANONICAL_SPAN_NAMES:
                rejected += 1
                continue
            rec = dict(s)
            rec["start_ns"] = s["start_ns"] - self._t0_ns
            rec["end_ns"] = s["end_ns"] - self._t0_ns
            with self._lock:
                if len(self._spans) == self.max_spans:
                    self.dropped += 1
                self._spans.append(rec)
            adopted += 1
        with self._lock:
            self.remote_adopted += adopted
            self.remote_rejected += rejected
        return adopted, rejected

    def record_remote(self, name: str, parent: Optional[SpanContext],
                      start_abs_ns: int, end_abs_ns: int, *, pid: int,
                      process: Optional[str] = None,
                      **attributes: Any) -> bool:
        """Adopt ONE remote span measured in another process from a wire
        record (see :func:`remote_span`): the span id is allocated here
        (the remote process — e.g. a decode-pool worker with no tracer —
        never allocated one), timestamps arrive on this process's clock
        base already. Non-canonical names are rejected and counted, not
        raised. Returns True when recorded."""
        if name not in CANONICAL_SPAN_NAMES:
            with self._lock:
                self.remote_rejected += 1
            return False
        rec: Dict[str, Any] = {
            "name": name,
            "trace_id": parent.trace_id if parent else self.trace_id,
            "span_id": next(self._ids),
            "parent_id": parent.span_id if parent else None,
            "thread_id": 0,
            "thread_name": process or f"pid-{pid}",
            "start_ns": start_abs_ns - self._t0_ns,
            "end_ns": end_abs_ns - self._t0_ns,
            "pid": pid,
        }
        if process is not None:
            rec["process"] = process
        if attributes:
            rec["attributes"] = attributes
        with self._lock:
            if len(self._spans) == self.max_spans:
                self.dropped += 1
            self._spans.append(rec)
            self.remote_adopted += 1
        return True

    def chrome_trace(self) -> Dict[str, Any]:
        """Chrome-trace (Trace Event Format) document: complete ("X")
        events in microseconds on one track per thread, loadable by
        ``chrome://tracing`` and Perfetto. Timestamps are monotonic
        (``perf_counter_ns`` rebased to the tracer epoch), so parent
        spans always enclose their children. Adopted remote spans keep
        their origin pid, giving a merged cluster trace one labeled
        process group per worker beside the coordinator's."""
        events: List[Dict[str, Any]] = []
        own_pid = os.getpid()
        seen_threads: Dict[Tuple[int, int], str] = {}
        seen_procs: Dict[int, Optional[str]] = {}
        for s in self.spans():
            pid = s.get("pid", own_pid)
            seen_threads.setdefault((pid, s["thread_id"]),
                                    s["thread_name"])
            if s.get("process") is not None or pid not in seen_procs:
                seen_procs[pid] = s.get("process") or seen_procs.get(pid)
            event = {
                "name": s["name"], "cat": "sparkdl", "ph": "X",
                "ts": s["start_ns"] / 1e3,
                "dur": (s["end_ns"] - s["start_ns"]) / 1e3,
                "pid": pid, "tid": s["thread_id"],
                "args": {"trace_id": s["trace_id"],
                         "span_id": s["span_id"],
                         "parent_id": s["parent_id"],
                         **s.get("attributes", {})},
            }
            events.append(event)
        for (pid, tid), tname in seen_threads.items():
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": tname}})
        # pid-labeled process groups only once remote spans merged in —
        # a single-process trace keeps its pre-merge shape exactly
        if len(seen_procs) > 1 or any(seen_procs.values()):
            for pid, label in seen_procs.items():
                name = label or ("coordinator" if pid == own_pid
                                 else f"pid-{pid}")
                events.append({"name": "process_name", "ph": "M",
                               "pid": pid, "tid": 0,
                               "args": {"name": name}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def merged_chrome_trace(self, rings: Sequence[Dict[str, Any]]
                            ) -> Dict[str, Any]:
        """A Chrome-trace document merging this ring's CURRENT spans
        with remote :meth:`export_ring` payloads — WITHOUT mutating this
        ring. The flight recorder (``cluster/router.py``) dumps mid-run
        postmortems from on-demand ring pulls; adopting those pulled
        spans into the live ring would double them when the workers ship
        their final rings at close. A scratch tracer sharing this
        tracer's clock epoch does the merge instead (same canonical-name
        rejection as a real adoption), so the live ring stays
        untouched."""
        scratch = Tracer(self.trace_id, max_spans=self.max_spans)
        scratch._t0_ns = self._t0_ns
        with self._lock:
            scratch._spans.extend(dict(s) for s in self._spans)
        for ring in rings:
            scratch.adopt_remote_spans(ring.get("spans") or ())
        return scratch.chrome_trace()


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

# Log-scale (factor-2) bucket upper bounds. Durations: 100 µs .. ~3.7 h.
DEFAULT_TIME_BOUNDS: Tuple[float, ...] = tuple(
    1e-4 * 2 ** i for i in range(27))
# Row counts / sizes: powers of two 1 .. 64Ki.
POW2_BOUNDS: Tuple[float, ...] = tuple(float(2 ** i) for i in range(17))


def _estimate_percentile(q: float, counts: Sequence[int], count: int,
                         bounds: Sequence[float], vmin: Optional[float],
                         vmax: Optional[float]) -> Optional[float]:
    """Estimated q-quantile from ONE consistent copy of log-scale bucket
    counts: the geometric midpoint of the covering bucket, clamped to the
    observed [vmin, vmax]. Returns ``None`` (JSON null) for an empty
    histogram or window — never a bucket-midpoint guess over zero
    samples."""
    if count <= 0:
        return None
    target = max(1, math.ceil(q * count))
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = (bounds[i] if i < len(bounds)
                  else (vmax if vmax is not None else lo))
            est = math.sqrt(lo * hi) if lo > 0 and hi > 0 else hi
            if vmin is not None:
                est = max(est, vmin)
            if vmax is not None:
                est = min(est, vmax)
            return est
    return vmax


def _window_floor(span_s: float, slots: int, window_s: float) -> int:
    """Oldest slot epoch inside a trailing ``window_s`` window (clamped
    to the ring capacity). The current partial slot is always included,
    so the effective window is ``window_s`` ± one slot span."""
    k = min(slots, max(1, math.ceil(window_s / span_s)))
    return int(_monotonic() / span_s) - k + 1


class Counter:
    """Monotonic counter. With ``window=(span_s, slots)`` it also keeps a
    fixed ring of time-bucketed sub-counts (lazy monotonic-clock
    rotation, O(1) per inc) so :meth:`window_count` can answer "how many
    in the last N seconds" without a timer thread."""

    __slots__ = ("name", "_lock", "_value", "_w_span", "_w_epochs",
                 "_w_counts")

    def __init__(self, name: str,
                 window: Optional[Tuple[float, int]] = None) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._value = 0
        self._w_span: Optional[float] = None
        if window is not None:
            span_s, slots = window
            self._w_span = float(span_s)
            self._w_epochs = [-1] * slots
            self._w_counts = [0] * slots

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n
            if self._w_span is not None:
                epoch = int(_monotonic() / self._w_span)
                i = epoch % len(self._w_counts)
                if self._w_epochs[i] != epoch:  # lazy rotation
                    self._w_epochs[i] = epoch
                    self._w_counts[i] = 0
                self._w_counts[i] += n

    def window_count(self, window_s: float) -> int:
        """Occurrences within the trailing ``window_s`` (0 without a
        ring; resolution = one ring slot)."""
        if self._w_span is None:
            return 0
        with self._lock:
            floor_epoch = _window_floor(self._w_span, len(self._w_counts),
                                        window_s)
            return sum(c for e, c in zip(self._w_epochs, self._w_counts)
                       if e >= floor_epoch)

    def window_frame(self) -> Dict[int, int]:
        """Per-slot ``{epoch: count}`` export of the live ring (one
        consistent locked copy) — the metrics-federation wire format
        (docs/OBSERVABILITY.md "Cluster metrics federation"). Epochs are
        THIS process's monotonic slot indices; the coordinator rebases
        them onto its own clock with the handshake offset before
        folding. Empty without a ring."""
        if self._w_span is None:
            return {}
        with self._lock:
            floor_epoch = _window_floor(
                self._w_span, len(self._w_counts),
                self._w_span * len(self._w_counts))
            return {e: c for e, c in zip(self._w_epochs, self._w_counts)
                    if e >= floor_epoch and c}

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value. With ``window=`` it also
    remembers (last, min, max) per ring slot so the windowed view can
    report the envelope of the last N seconds, not just the final
    write."""

    __slots__ = ("name", "_lock", "_value", "_w_span", "_w_epochs",
                 "_w_vals")

    def __init__(self, name: str,
                 window: Optional[Tuple[float, int]] = None) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._value: Optional[float] = None
        self._w_span: Optional[float] = None
        if window is not None:
            span_s, slots = window
            self._w_span = float(span_s)
            self._w_epochs = [-1] * slots
            self._w_vals: List[Optional[Tuple[float, float, float]]] = \
                [None] * slots

    def set(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._value = value
            if self._w_span is not None:
                epoch = int(_monotonic() / self._w_span)
                i = epoch % len(self._w_vals)
                if self._w_epochs[i] != epoch:
                    self._w_epochs[i] = epoch
                    self._w_vals[i] = (value, value, value)
                else:
                    last, lo, hi = self._w_vals[i]  # type: ignore[misc]
                    self._w_vals[i] = (value, min(lo, value),
                                       max(hi, value))

    def window_values(self, window_s: float) -> Optional[Dict[str, float]]:
        """``{'last', 'min', 'max'}`` over the trailing window; ``None``
        when the window saw no :meth:`set` (or there is no ring)."""
        if self._w_span is None:
            return None
        with self._lock:
            floor_epoch = _window_floor(self._w_span, len(self._w_vals),
                                        window_s)
            seen = sorted((e, v) for e, v in zip(self._w_epochs,
                                                 self._w_vals)
                          if e >= floor_epoch and v is not None)
        if not seen:
            return None
        return {"last": seen[-1][1][0],
                "min": min(v[1] for _, v in seen),
                "max": max(v[2] for _, v in seen)}

    def window_frame(self) -> Dict[int, List[float]]:
        """Per-slot ``{epoch: [last, min, max]}`` envelope export of the
        live ring — the federation wire format for gauges (see
        :meth:`Counter.window_frame`). Empty without a ring."""
        if self._w_span is None:
            return {}
        with self._lock:
            floor_epoch = _window_floor(
                self._w_span, len(self._w_vals),
                self._w_span * len(self._w_vals))
            return {e: list(v) for e, v in zip(self._w_epochs,
                                               self._w_vals)
                    if e >= floor_epoch and v is not None}

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            return self._value


class Histogram:
    """Fixed log-scale-bucket histogram with percentile estimates.

    Buckets are upper bounds (Prometheus ``le`` semantics) growing by a
    constant factor (default 2×), so the relative error of a percentile
    estimate is bounded by the factor. p50/p95/p99 are estimated at the
    geometric midpoint of the covering bucket, clamped to the observed
    [min, max].
    """

    __slots__ = ("name", "_lock", "bounds", "_counts", "count", "sum",
                 "min", "max", "_w_span", "_w_epochs", "_w_slots",
                 "_ex_k", "_w_ex")

    def __init__(self, name: str,
                 bounds: Sequence[float] = DEFAULT_TIME_BOUNDS,
                 window: Optional[Tuple[float, int]] = None,
                 exemplar_k: int = 0) -> None:
        self.name = name
        self._lock = threading.Lock()
        self.bounds = tuple(float(b) for b in bounds)
        self._counts = [0] * (len(self.bounds) + 1)  # last = +Inf overflow
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._w_span: Optional[float] = None
        # opt-in tail-exemplar reservoir: the top-k observations per
        # window slot, each carrying the span context that produced it —
        # a breached p99 points at concrete traces, not just a number
        self._ex_k = int(exemplar_k) if window is not None else 0
        if window is not None:
            span_s, slots = window
            self._w_span = float(span_s)
            self._w_epochs = [-1] * slots
            # one sub-histogram per ring slot: [counts, count, sum, min,
            # max]; reset lazily when its slot's epoch rotates past
            self._w_slots: List[List[Any]] = [
                [[0] * (len(self.bounds) + 1), 0, 0.0, None, None]
                for _ in range(slots)]
            if self._ex_k:
                # per-slot exemplar list, ascending by value (min first
                # for O(1) eviction checks at tiny fixed k)
                self._w_ex: List[List[Tuple[float, str, int]]] = [
                    [] for _ in range(slots)]

    def observe(self, value: float,
                exemplar: Optional[SpanContext] = None) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self.count += 1
            self.sum += value
            if self.min is None or value < self.min:
                self.min = value
            if self.max is None or value > self.max:
                self.max = value
            if self._w_span is not None:
                epoch = int(_monotonic() / self._w_span)
                i = epoch % len(self._w_slots)
                slot = self._w_slots[i]
                if self._w_epochs[i] != epoch:  # lazy rotation
                    self._w_epochs[i] = epoch
                    slot[0] = [0] * (len(self.bounds) + 1)
                    slot[1], slot[2] = 0, 0.0
                    slot[3] = slot[4] = None
                    if self._ex_k:
                        self._w_ex[i] = []
                slot[0][idx] += 1
                slot[1] += 1
                slot[2] += value
                if slot[3] is None or value < slot[3]:
                    slot[3] = value
                if slot[4] is None or value > slot[4]:
                    slot[4] = value
                if self._ex_k and exemplar is not None:
                    ex = self._w_ex[i]
                    if len(ex) < self._ex_k:
                        bisect.insort(
                            ex, (value, exemplar.trace_id,
                                 exemplar.span_id))
                    elif value > ex[0][0]:  # beats the smallest kept
                        ex.pop(0)
                        bisect.insort(
                            ex, (value, exemplar.trace_id,
                                 exemplar.span_id))

    def percentile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (q in [0, 1]) from the bucket counts
        (``None`` on an empty histogram)."""
        with self._lock:
            return _estimate_percentile(q, self._counts, self.count,
                                        self.bounds, self.min, self.max)

    def _raw(self) -> Tuple[Tuple[float, ...], List[int], int, float]:
        """(bounds, counts, count, sum) as one consistent locked copy —
        the Prometheus exposition source."""
        with self._lock:
            return self.bounds, list(self._counts), self.count, self.sum

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counts = list(self._counts)
            count, total = self.count, self.sum
            lo, hi = self.min, self.max
        buckets = {("+Inf" if i == len(self.bounds)
                    else repr(self.bounds[i])): c
                   for i, c in enumerate(counts) if c}
        # percentiles from the SAME locked copy as the buckets (a
        # concurrent observe between the copy and the estimate cannot
        # skew them apart), None — not a midpoint guess — when empty
        return {
            "count": count, "sum": round(total, 9), "min": lo, "max": hi,
            "p50": _estimate_percentile(0.50, counts, count, self.bounds,
                                        lo, hi),
            "p95": _estimate_percentile(0.95, counts, count, self.bounds,
                                        lo, hi),
            "p99": _estimate_percentile(0.99, counts, count, self.bounds,
                                        lo, hi),
            "buckets": buckets,
        }

    def window_snapshot(self, window_s: float) -> Dict[str, Any]:
        """Merged ``{count, sum, rate_per_s, min, max, p50, p95, p99}``
        over the trailing ``window_s`` (resolution = one ring slot).
        Percentiles and min/max are ``None`` on an empty window; all
        zeros/None without a ring. With an armed exemplar reservoir the
        snapshot additionally carries ``exemplars``: the top-k in-window
        observations (descending), each
        ``{value, trace_id, span_id}`` — the key is absent entirely when
        exemplars are off, keeping the unarmed shape unchanged."""
        counts = [0] * (len(self.bounds) + 1)
        count, total = 0, 0.0
        vmin: Optional[float] = None
        vmax: Optional[float] = None
        exemplars: List[Tuple[float, str, int]] = []
        if self._w_span is not None:
            with self._lock:
                floor_epoch = _window_floor(self._w_span,
                                            len(self._w_slots), window_s)
                for i, (e, slot) in enumerate(zip(self._w_epochs,
                                                  self._w_slots)):
                    if e < floor_epoch or not slot[1]:
                        continue
                    for j, c in enumerate(slot[0]):
                        counts[j] += c
                    count += slot[1]
                    total += slot[2]
                    vmin = slot[3] if vmin is None else min(vmin, slot[3])
                    vmax = slot[4] if vmax is None else max(vmax, slot[4])
                    if self._ex_k:
                        exemplars.extend(self._w_ex[i])
        out = {
            "count": count, "sum": round(total, 9),
            "rate_per_s": round(count / window_s, 9) if window_s else 0.0,
            "min": vmin, "max": vmax,
            "p50": _estimate_percentile(0.50, counts, count, self.bounds,
                                        vmin, vmax),
            "p95": _estimate_percentile(0.95, counts, count, self.bounds,
                                        vmin, vmax),
            "p99": _estimate_percentile(0.99, counts, count, self.bounds,
                                        vmin, vmax),
        }
        if self._ex_k:
            exemplars.sort(reverse=True)
            out["exemplars"] = [
                {"value": v, "trace_id": t, "span_id": s}
                for v, t, s in exemplars[:self._ex_k]]
        return out

    def window_frame(self) -> Dict[int, List[Any]]:
        """Per-slot sub-histogram export of the live ring, keyed by slot
        epoch: ``{epoch: [bucket_counts, count, sum, min, max]}`` (with
        an armed exemplar reservoir each entry appends its slot's
        ``[(value, trace_id, span_id), ...]`` list). Mergeable by
        construction: the coordinator sums bucket counts across workers
        per rebased epoch, so a cluster percentile is estimated from ONE
        merged bucket array — not a worst-worker guess. Empty without a
        ring."""
        if self._w_span is None:
            return {}
        out: Dict[int, List[Any]] = {}
        with self._lock:
            floor_epoch = _window_floor(
                self._w_span, len(self._w_slots),
                self._w_span * len(self._w_slots))
            for i, (e, slot) in enumerate(zip(self._w_epochs,
                                              self._w_slots)):
                if e < floor_epoch or not slot[1]:
                    continue
                entry: List[Any] = [list(slot[0]), slot[1], slot[2],
                                    slot[3], slot[4]]
                if self._ex_k:
                    entry.append([list(ex) for ex in self._w_ex[i]])
                out[e] = entry
        return out


def escape_label_value(value: Any) -> str:
    """Prometheus text-exposition label-value escaping: backslash,
    double-quote and newline (in that order, per the 0.0.4 format)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """HELP-line escaping: backslash and newline only (quotes are legal
    in HELP text)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


class MetricsRegistry:
    """Get-or-create registry of named instruments (one per name).

    ``window_s``/``window_buckets`` arm the sliding-window rings on every
    instrument the registry creates: ``window_s`` is the largest
    queryable trailing window, bucketed into ``window_buckets`` ring
    slots (the window resolution). ``window_s=None`` (the bare-registry
    default) creates ring-free instruments — the pre-windowing record
    path, not even a clock read per record.

    ``exemplar_k`` (opt-in, default 0 = off) arms a per-slot tail
    exemplar reservoir on every histogram created here: callers passing
    a span context to :meth:`Histogram.observe` get their top-k
    observations per window surfaced with ``{value, trace_id, span_id}``
    in windowed snapshots."""

    def __init__(self, window_s: Optional[float] = None,
                 window_buckets: int = 12, exemplar_k: int = 0) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._window: Optional[Tuple[float, int]] = None
        if exemplar_k < 0:
            raise ValueError(f"exemplar_k must be >= 0, got {exemplar_k!r}")
        self.exemplar_k = int(exemplar_k)
        if window_s is not None:
            if window_s <= 0 or window_buckets <= 0:
                raise ValueError(
                    "window_s and window_buckets must be > 0, got "
                    f"{window_s!r}/{window_buckets!r}")
            self._window = (float(window_s) / int(window_buckets),
                            int(window_buckets))
        self.window_s = window_s

    def counter(self, name: str) -> Counter:
        with self._lock:
            inst = self._counters.get(name)
            if inst is None:
                inst = self._counters[name] = Counter(
                    name, window=self._window)
            return inst

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            inst = self._gauges.get(name)
            if inst is None:
                inst = self._gauges[name] = Gauge(name,
                                                  window=self._window)
            return inst

    def histogram(self, name: str,
                  bounds: Sequence[float] = DEFAULT_TIME_BOUNDS
                  ) -> Histogram:
        with self._lock:
            inst = self._histograms.get(name)
            if inst is None:
                inst = self._histograms[name] = Histogram(
                    name, bounds, window=self._window,
                    exemplar_k=self.exemplar_k)
            return inst

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able {counters, gauges, histograms} snapshot."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: counters[k].value for k in sorted(counters)},
            "gauges": {k: gauges[k].value for k in sorted(gauges)},
            "histograms": {k: histograms[k].snapshot()
                           for k in sorted(histograms)},
        }

    def window_snapshot(self, window_s: Optional[float] = None
                        ) -> Dict[str, Any]:
        """Sliding-window view over every instrument: counter counts and
        rates, gauge last/min/max envelopes, histogram percentiles —
        all over the trailing ``window_s`` seconds (default and cap: the
        ring capacity). Resolution is one ring slot, and the current
        partial slot is included, so the effective window is
        ``window_s`` ± one slot. Empty sections when the registry was
        built without windows."""
        if self._window is None:
            return {"window_s": None, "counters": {}, "gauges": {},
                    "histograms": {}}
        span, slots = self._window
        if window_s is None:
            window_s = span * slots
        window_s = min(float(window_s), span * slots)
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s!r}")
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        out_counters = {}
        for k in sorted(counters):
            c = counters[k].window_count(window_s)
            out_counters[k] = {"count": c,
                               "rate_per_s": round(c / window_s, 9)}
        out_gauges = {}
        for k in sorted(gauges):
            v = gauges[k].window_values(window_s)
            if v is not None:
                out_gauges[k] = v
        return {
            "window_s": window_s,
            "counters": out_counters,
            "gauges": out_gauges,
            "histograms": {k: histograms[k].window_snapshot(window_s)
                           for k in sorted(histograms)},
        }

    def export_frame(self) -> Optional[Dict[str, Any]]:
        """The bounded metrics-federation delta frame: every windowed
        instrument's live ring slots keyed by slot epoch, restricted to
        the canonical catalog plus the ``sparkdl.health.*`` mirrors (the
        restriction ``cluster/aggregate.py``'s counter fold already
        applies — a frame never ships a name the taxonomy lint would
        reject). ``None`` without windows: there is nothing windowed to
        federate. Frame size is bounded by construction — ring slots ×
        bucket counts per instrument, independent of traffic volume —
        and each frame is the full state-of-ring (idempotent
        merge-by-replace coordinator-side), so a dropped frame heals on
        the next cadence instead of leaving a permanent gap."""
        if self._window is None:
            return None
        span, slots = self._window
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)

        def declared(name: str) -> bool:
            return (name in CANONICAL_METRIC_NAMES
                    or name.startswith(HEALTH_METRIC_PREFIX))

        out_counters: Dict[str, Any] = {}
        for name in sorted(counters):
            if declared(name):
                frame = counters[name].window_frame()
                if frame:
                    out_counters[name] = frame
        out_gauges: Dict[str, Any] = {}
        for name in sorted(gauges):
            if declared(name):
                frame = gauges[name].window_frame()
                if frame:
                    out_gauges[name] = frame
        out_hists: Dict[str, Any] = {}
        for name in sorted(histograms):
            if declared(name):
                frame = histograms[name].window_frame()
                if frame:
                    out_hists[name] = {
                        "bounds": list(histograms[name].bounds),
                        "slots": frame,
                    }
        return {
            "span_s": span,
            "slots": slots,
            "now_epoch": int(_monotonic() / span),
            "counters": out_counters,
            "gauges": out_gauges,
            "histograms": out_hists,
        }

    def prometheus_text(self) -> str:
        """Prometheus text exposition (0.0.4) dump of every instrument:
        one ``# HELP`` + ``# TYPE`` pair per metric family, escaped
        label values, cumulative histogram buckets with a closing
        ``+Inf``."""
        import re as _re

        def sane(name: str) -> str:
            return _re.sub(r"[^a-zA-Z0-9_:]", "_", name)

        lines: List[str] = []

        def family(name: str, kind: str) -> str:
            n = sane(name)
            lines.append(
                f"# HELP {n} {_escape_help(name)} (sparkdl_tpu {kind})")
            lines.append(f"# TYPE {n} {kind}")
            return n

        snap = self.snapshot()
        for name, value in snap["counters"].items():
            n = family(name, "counter")
            lines.append(f"{n} {value}")
        for name, value in snap["gauges"].items():
            if value is None:
                continue
            n = family(name, "gauge")
            lines.append(f"{n} {value}")
        with self._lock:
            hists = dict(self._histograms)
        for name in sorted(hists):
            bounds, counts, count, total = hists[name]._raw()
            n = family(name, "histogram")
            cum = 0
            for i, bound in enumerate(bounds):
                cum += counts[i]
                le = escape_label_value(repr(bound))
                lines.append(f'{n}_bucket{{le="{le}"}} {cum}')
            lines.append(f'{n}_bucket{{le="+Inf"}} {count}')
            lines.append(f"{n}_sum {total}")
            lines.append(f"{n}_count {count}")
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Periodic snapshot exporter (the live half of the run report)
# ---------------------------------------------------------------------------


class SnapshotExporter:
    """Periodic live-snapshot exporter for one telemetry scope.

    Every ``interval_s`` (daemon thread; drop-safe final flush at
    :meth:`close`) a tick:

    - appends one JSON line — sequence number, uptime, windowed +
      cumulative metric snapshots, executor queue/breaker state — to
      ``sparkdl_snapshots_<run_id>.jsonl`` under ``out_dir``;
    - atomically replaces ``sparkdl_metrics_<run_id>.prom`` (temp file +
      ``os.replace``) so a Prometheus textfile collector never reads a
      torn exposition;
    - evaluates the scope's SLO watchdog (``core/slo.py``) so breaches
      surface while the process is alive, not in the post-mortem.

    Without an ``out_dir`` no files are written but ticks still run
    (watchdog + the bounded in-memory timeline that feeds the run
    report). A tick that crashes records one ``telemetry_export_error``
    health event and keeps going — the exporter never takes the run
    down and never dies silently.
    """

    def __init__(self, tel: "Telemetry", interval_s: float,
                 out_dir: Optional[str] = None, watchdog: Any = None,
                 timeline_max: int = 240) -> None:
        if interval_s <= 0:
            raise ValueError(
                f"export_interval_s must be > 0, got {interval_s!r}")
        self.tel = tel
        self.interval_s = float(interval_s)
        self.out_dir = out_dir
        self.watchdog = watchdog
        self.seq = 0
        self.errors = 0
        self.snapshot_path: Optional[str] = None
        self.prom_path: Optional[str] = None
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            # run_id alone is NOT unique across processes: cluster
            # workers pin the coordinator's run_id, so a shared out_dir
            # needs the scope's process suffix to avoid silently
            # clobbering the coordinator's files. The coordinator
            # (process_scope=None) keeps the bare historical names.
            scope = getattr(tel, "process_scope", None)
            suffix = f".{scope}" if scope else ""
            self.snapshot_path = os.path.join(
                out_dir, f"sparkdl_snapshots_{tel.run_id}{suffix}.jsonl")
            self.prom_path = os.path.join(
                out_dir, f"sparkdl_metrics_{tel.run_id}{suffix}.prom")
        self._t0 = _monotonic()
        self._next_due = self._t0 + self.interval_s
        self._tick_lock = threading.Lock()  # thread tick vs close flush
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._timeline: "deque[Dict[str, Any]]" = deque(maxlen=timeline_max)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        # sparkdl: allow(unguarded-shared-write): set once, before the exporter thread exists
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"sparkdl-telemetry-export-{self.tel.run_id}")
        self._thread.start()

    def _loop(self) -> None:
        while True:
            wait_s = max(0.005, min(self._next_due - _monotonic(),
                                    self.interval_s))
            if self._stop.wait(timeout=wait_s):
                return
            self.tick_if_due()

    def close(self) -> None:
        """Stop the thread, then flush one final snapshot — the tail of
        the run (where failures live) is never lost to cadence."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            # sparkdl: allow(unguarded-shared-write): the exporter thread is joined; only close() writes this
            self._thread = None
        self.tick(final=True)

    # -- ticking -------------------------------------------------------------

    def tick_if_due(self) -> bool:
        """Export iff the cadence clock says a snapshot is due."""
        now = _monotonic()
        if now < self._next_due:
            return False
        # sparkdl: allow(unguarded-shared-write): cadence state touched only by the exporter thread (close() only flushes)
        self._next_due = now + self.interval_s
        self.tick()
        return True

    def tick(self, final: bool = False) -> None:
        """One export. Never raises: a crashed tick records ONE
        ``telemetry_export_error`` health event and returns, so the
        exporter thread survives and the next tick gets a fresh try."""
        from sparkdl_tpu.core import health  # lazy: health imports us

        try:
            with self._tick_lock:
                self._export(final=final)
        except Exception as e:  # noqa: BLE001 - recorded, never re-raised
            self.errors += 1
            health.record(health.TELEMETRY_EXPORT_ERROR,
                          error=type(e).__name__, seq=self.seq)
            logging.getLogger(__name__).exception(
                "telemetry snapshot export failed (seq %d): %s",
                self.seq, e)

    def _export(self, final: bool) -> None:
        now = _monotonic()
        tel = self.tel
        self.seq += 1
        slo_state = (self.watchdog.evaluate(tel.metrics, now=now)
                     if self.watchdog is not None else None)
        snap: Dict[str, Any] = {
            "seq": self.seq,
            "run_id": tel.run_id,
            "uptime_s": round(now - self._t0, 6),
            "created_unix_s": round(time.time(), 3),
            "windowed": tel.metrics.window_snapshot(),
            "cumulative": tel.metrics.snapshot(),
            "executor": self._executor_status(),
        }
        serving = self._serving_status()
        if serving is not None:
            snap["serving"] = serving
        cluster = self._cluster_status()
        if cluster is not None:
            snap["cluster"] = cluster
        if slo_state is not None:
            snap["slo"] = slo_state
        if final:
            snap["final"] = True
        self._timeline.append(self._compact(snap))
        if self.snapshot_path is not None:
            # sparkdl: allow(blocking-under-lock): serializing these writes against the close() flush is _tick_lock's whole job
            with open(self.snapshot_path, "a") as f:
                # sparkdl: allow(blocking-under-lock): see the open() above — one writer at a time by design
                f.write(json.dumps(snap, default=str) + "\n")
                f.flush()
        if self.prom_path is not None:
            tmp = self.prom_path + ".tmp"
            # sparkdl: allow(blocking-under-lock): serializing these writes against the close() flush is _tick_lock's whole job
            with open(tmp, "w") as f:
                # sparkdl: allow(blocking-under-lock): see the open() above — one writer at a time by design
                f.write(tel.metrics.prometheus_text())
                # federated cluster series (whole-cluster merged view)
                # append AFTER the local exposition: live scrapes of a
                # cluster coordinator reflect every worker, and the
                # text is empty — file byte-identical — off-path
                # sparkdl: allow(blocking-under-lock): see the open() above — one writer at a time by design
                f.write(self._cluster_prometheus_text())
            os.replace(tmp, self.prom_path)

    @staticmethod
    def _executor_status() -> Optional[Dict[str, Any]]:
        """Queue/breaker state of the device execution service — read
        only when the process already imported it (``sys.modules``, not
        an import: a pure-training job must not pay for the executor
        just because the exporter is on)."""
        import sys

        mod = sys.modules.get("sparkdl_tpu.core.executor")
        if mod is None:
            return None
        return mod.service().status()

    @staticmethod
    def _serving_status() -> Optional[Dict[str, Any]]:
        """Per-deployment replica map of the cluster serving router —
        same ``sys.modules`` stance as :meth:`_executor_status`: a
        process that never imported the cluster serving plane must not
        pay for it (and the key stays absent, keeping single-process
        snapshots byte-identical)."""
        import sys

        mod = sys.modules.get("sparkdl_tpu.serving.cluster")
        if mod is None:
            return None
        return mod.exporter_status()

    @staticmethod
    def _cluster_status() -> Optional[Dict[str, Any]]:
        """The federated cluster-metrics view of the live partition
        router (windowed cluster-wide fold + ``workers_reporting``) —
        same ``sys.modules`` stance as :meth:`_executor_status`: a
        single-process run never imports the cluster plane, and the key
        stays absent (snapshot lines byte-identical) unless a router
        with metrics federation armed is live."""
        import sys

        mod = sys.modules.get("sparkdl_tpu.cluster.router")
        if mod is None:
            return None
        return mod.exporter_status()

    @staticmethod
    def _cluster_prometheus_text() -> str:
        """Federated Prometheus series of the live router, or ``""`` —
        the ``.prom`` analogue of :meth:`_cluster_status` (same absent-
        unless-armed stance, so off-path files stay byte-identical)."""
        import sys

        mod = sys.modules.get("sparkdl_tpu.cluster.router")
        if mod is None:
            return ""
        return mod.exporter_prometheus_text()

    # -- the timeline that feeds RunReport -----------------------------------

    @staticmethod
    def _compact(snap: Dict[str, Any]) -> Dict[str, Any]:
        """One bounded timeline entry per snapshot: windowed activity
        (non-empty instruments only) + the SLO verdicts."""
        windowed = snap["windowed"]
        entry: Dict[str, Any] = {
            "seq": snap["seq"],
            "uptime_s": snap["uptime_s"],
            "windowed_histograms": {
                k: {"count": v["count"], "p50": v["p50"], "p99": v["p99"]}
                for k, v in windowed["histograms"].items() if v["count"]},
            "windowed_counters": {
                k: v for k, v in windowed["counters"].items()
                if v["count"]},
        }
        if snap.get("slo") is not None:
            entry["slo_breached"] = sorted(
                name for name, st in snap["slo"].items() if st["breached"])
            exemplars = {
                name: st["exemplars"]
                for name, st in snap["slo"].items()
                if st["breached"] and st.get("exemplars")}
            if exemplars:
                entry["slo_exemplars"] = exemplars
        if snap.get("final"):
            entry["final"] = True
        return entry

    def timeline_summary(self) -> Dict[str, Any]:
        """The run report's ``timeline`` block: exporter stats + the
        (bounded, tail-keeping) compact snapshot entries."""
        return {
            "export_interval_s": self.interval_s,
            "snapshots": self.seq,
            "errors": self.errors,
            "snapshot_path": self.snapshot_path,
            "prometheus_path": self.prom_path,
            "entries": list(self._timeline),
        }


# ---------------------------------------------------------------------------
# The process-wide scope
# ---------------------------------------------------------------------------

_run_counter = itertools.count(1)


class _RunContextFilter(logging.Filter):
    """Stamps run_id/trace_id onto log records (via the record factory,
    so it reaches records regardless of which handler formats them)."""

    def __init__(self, run_id: str, trace_id: str) -> None:
        super().__init__()
        self.run_id = run_id
        self.trace_id = trace_id

    def filter(self, record: logging.LogRecord) -> bool:
        record.run_id = self.run_id
        record.trace_id = self.trace_id
        return True


class Telemetry:
    """One run's telemetry scope: tracer + metrics + end-of-run report.

    ::

        with Telemetry("nightly-fit", out_dir="/tmp/tel") as tel:
            pipeline.run()
        # exiting wrote sparkdl_run_report_<run_id>.json and
        # sparkdl_trace_<run_id>.json into out_dir

    ``out_dir`` defaults to ``$SPARKDL_TELEMETRY_DIR``; when neither is
    set no files are written and the scope is purely programmatic
    (``tel.tracer`` / ``tel.metrics`` / ``tel.report()``). While the
    scope is active, log records from the ``sparkdl_tpu`` namespace
    carry ``.run_id`` / ``.trace_id`` attributes (structured-logging
    adapter). To fold the active ``HealthMonitor``'s report into the
    run report, enter the monitor BEFORE (outside) the telemetry scope.
    """

    def __init__(self, name: str = "run", out_dir: Optional[str] = None,
                 max_spans: int = 65536,
                 window_s: Optional[float] = 60.0,
                 window_buckets: int = 12,
                 export_interval_s: Optional[float] = None,
                 slo_rules: Optional[Sequence[Any]] = None,
                 run_id: Optional[str] = None,
                 exemplar_k: int = 0,
                 process_scope: Optional[str] = None) -> None:
        self.name = name
        self.out_dir = (out_dir if out_dir is not None
                        else os.environ.get(TELEMETRY_DIR_ENV))
        # run_id pins the identity across process restarts (durable
        # recovery, core/durability.pinned_run_id): the snapshot
        # timeline JSONL appends and the run report path stay THE SAME
        # file before and after a crash. Default: fresh per-scope id.
        self.run_id = run_id or (
            f"{name}-{os.getpid():x}-{next(_run_counter):04x}")
        # process_scope disambiguates output files when several
        # processes share a run_id AND an out_dir (cluster workers pin
        # the coordinator's run_id); None — the coordinator and the
        # durable-resume path — keeps the bare file names.
        self.process_scope = process_scope
        self.tracer = Tracer(trace_id=self.run_id, max_spans=max_spans)
        self.metrics = MetricsRegistry(window_s=window_s,
                                       window_buckets=window_buckets,
                                       exemplar_k=exemplar_k)
        if export_interval_s is None:
            env = os.environ.get(EXPORT_INTERVAL_ENV)
            export_interval_s = float(env) if env else None
        if export_interval_s is not None and export_interval_s <= 0:
            raise ValueError("export_interval_s must be > 0, got "
                             f"{export_interval_s!r}")
        self.export_interval_s = export_interval_s
        if slo_rules is not None and window_s is not None:
            # an EXPLICIT rule window past the ring capacity would
            # silently evaluate over less history than it declares —
            # fail here, where both configs are in hand, not at the
            # first tick. (The shipped defaults adapt instead: a scope
            # with a small ring gets them re-parameterized to fit.)
            for rule in slo_rules:
                if rule.window_s > window_s + 1e-9:
                    raise ValueError(
                        f"SLO rule {rule.name!r} window_s="
                        f"{rule.window_s} exceeds this scope's metric "
                        f"ring capacity (window_s={window_s}); raise "
                        "Telemetry(window_s=...) or shrink the rule "
                        "window")
        self.slo_rules = slo_rules
        self.slo_watchdog: Any = None
        self.exporter: Optional[SnapshotExporter] = None
        self._prev: Optional["Telemetry"] = None
        self._root: Optional[_Span] = None
        self._prev_factory: Any = None
        self._filter = _RunContextFilter(self.run_id, self.run_id)
        self.report_path: Optional[str] = None
        self.trace_path: Optional[str] = None

    # -- context -------------------------------------------------------------

    @property
    def root_context(self) -> Optional[SpanContext]:
        return self._root.context if self._root is not None else None

    def __enter__(self) -> "Telemetry":
        global _active
        with _activation_lock:
            self._prev = _active
            _active = self
            # structured-logging adapter: stamp run/trace ids at record
            # creation so they survive any handler (a Filter on the
            # package logger would miss records emitted via child
            # loggers — logging only runs logger-level filters on the
            # logger actually called)
            prev_factory = logging.getLogRecordFactory()
            self._prev_factory = prev_factory
            flt = self._filter

            def factory(*args: Any, **kwargs: Any) -> logging.LogRecord:
                record = prev_factory(*args, **kwargs)
                if record.name.startswith("sparkdl_tpu"):
                    flt.filter(record)
                return record

            logging.setLogRecordFactory(factory)
        self._root = self.tracer.span(SPAN_RUN, parent=ROOT,
                                      run=self.name)
        self._root.__enter__()
        if self.export_interval_s is not None:
            # lazy: core.slo imports this module for the metric catalog
            from sparkdl_tpu.core import slo as _slo

            rules = self.slo_rules
            if rules is None:
                cap = self.metrics.window_s
                if cap is not None and cap < _slo.DEFAULT_WINDOW_S:
                    # the defaults adapt to a smaller metric ring
                    # instead of refusing the scope
                    rules = _slo.default_rules(window_s=cap)
                else:
                    rules = _slo.DEFAULT_RULES
            self.slo_watchdog = _slo.SLOWatchdog(rules) if rules else None
            self.exporter = SnapshotExporter(
                self, self.export_interval_s, out_dir=self.out_dir,
                watchdog=self.slo_watchdog)
            self.exporter.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        global _active
        if self.exporter is not None:
            # stop + final drop-safe flush BEFORE deactivating: SLO
            # events from the last evaluation still mirror into THIS
            # scope's counters and the active HealthMonitor
            self.exporter.close()
        if self._root is not None:
            # pass the unwinding exception through so the run root span
            # carries the error attribute like every interior span
            exc3 = exc if len(exc) == 3 else (None, None, None)
            self._root.__exit__(*exc3)
        with _activation_lock:
            _active = self._prev
            self._prev = None
            logging.setLogRecordFactory(self._prev_factory)
        if self.out_dir:
            try:
                self.write_report(self.out_dir)
            except OSError as e:
                logging.getLogger(__name__).error(
                    "could not write telemetry report to %r: %s",
                    self.out_dir, e)

    # -- reporting -----------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        return RunReport.build(self)

    def write_report(self, out_dir: str) -> str:
        """Write the run report + Chrome trace JSONs; returns the report
        path (also kept in :attr:`report_path` / :attr:`trace_path`)."""
        os.makedirs(out_dir, exist_ok=True)
        suffix = f".{self.process_scope}" if self.process_scope else ""
        trace_path = os.path.join(
            out_dir, f"sparkdl_trace_{self.run_id}{suffix}.json")
        # tmp + os.replace (analyzer rule atomic-write): a crash while
        # exporting must not leave a torn report that a durable-resume
        # reader would trust
        tmp = f"{trace_path}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.tracer.chrome_trace(), f)
        os.replace(tmp, trace_path)
        report = self.report()
        report["chrome_trace"] = trace_path
        report_path = os.path.join(
            out_dir, f"sparkdl_run_report_{self.run_id}{suffix}.json")
        tmp = f"{report_path}.tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2, default=str)
        os.replace(tmp, report_path)
        self.report_path, self.trace_path = report_path, trace_path
        return report_path


_active: Optional[Telemetry] = None
_activation_lock = threading.Lock()


def active() -> Optional[Telemetry]:
    return _active


def current_context() -> Optional[SpanContext]:
    """The ambient span context on THIS thread: innermost open span,
    else the context attached via :func:`attach`, else the active
    scope's root span. ``None`` without an active scope."""
    tel = _active
    if tel is None:
        return None
    stack = getattr(_tls, "stack", None)
    if stack:
        return stack[-1].context
    base = getattr(_tls, "base", None)
    if base is not None:
        return base
    return tel.root_context


def attach(ctx: Optional[SpanContext]) -> None:
    """Adopt ``ctx`` as this thread's base context: ambient spans opened
    here parent under it. For FRESH worker threads (the prefetcher's
    staging thread); pool threads that outlive a task should pass
    ``parent=`` explicitly instead — an attached base would leak into
    the next task."""
    _tls.base = ctx


def span(name: str, parent: Optional[SpanContext] = None,
         **attributes: Any) -> Any:
    """An open span on the active scope's tracer; the shared
    :data:`NULL_SPAN` singleton (no allocation) when no scope is
    active."""
    tel = _active
    if tel is None:
        return NULL_SPAN
    return tel.tracer.span(name, parent=parent, **attributes)


def count(name: str, n: int = 1) -> None:
    """Bump a counter on the active registry (no-op — one global read —
    without a scope)."""
    tel = _active
    if tel is not None:
        tel.metrics.counter(name).inc(n)


def gauge_set(name: str, value: float) -> None:
    tel = _active
    if tel is not None:
        tel.metrics.gauge(name).set(value)


def observe(name: str, value: float,
            bounds: Sequence[float] = DEFAULT_TIME_BOUNDS,
            exemplar: Optional[SpanContext] = None) -> None:
    """Record one histogram observation, optionally tagged with the span
    context that produced it (kept only by scopes armed with
    ``exemplar_k``; inert — not even stored — otherwise)."""
    tel = _active
    if tel is not None:
        tel.metrics.histogram(name, bounds).observe(value, exemplar)


def remote_span(name: str, start_abs_ns: int, end_abs_ns: int, *,
                pid: Optional[int] = None,
                **attributes: Any) -> Dict[str, Any]:
    """Build the WIRE record for a span measured in a process with no
    tracer of its own (a decode-pool worker): timestamps must already be
    on the ADOPTING process's clock base (worker perf_counter_ns + the
    handshake offset). The adopting side turns it into a real span via
    :meth:`Tracer.record_remote`. The name must be canonical — this is
    the process-boundary half of the span-names lint, enforced at
    build time so a worker cannot ship an unmergeable name."""
    if name not in CANONICAL_SPAN_NAMES:
        raise ValueError(
            f"remote span name {name!r} is not in CANONICAL_SPAN_NAMES; "
            "span names crossing a process boundary must be canonical "
            "(docs/OBSERVABILITY.md)")
    rec: Dict[str, Any] = {
        "name": name,
        "start_ns": int(start_abs_ns),
        "end_ns": int(end_abs_ns),
        "pid": pid if pid is not None else os.getpid(),
    }
    if attributes:
        rec["attributes"] = attributes
    return rec


def clock_handshake(conn: Any, timeout_s: float = 5.0) -> int:
    """Worker half of the cross-process clock exchange (NTP-style, one
    round trip over a dedicated pipe): send a ping, read the parent's
    ``perf_counter_ns`` reply, and return the offset that maps THIS
    process's ``perf_counter_ns`` onto the parent's
    (``parent_ns ≈ local_ns + offset``), assuming symmetric transit.
    Falls back to 0 (clocks assumed aligned — on Linux both processes
    read the same CLOCK_MONOTONIC) if the parent never answers."""
    try:
        t0 = time.perf_counter_ns()
        conn.send(("clock", t0))
        if not conn.poll(timeout_s):
            return 0
        t_parent = conn.recv()
        t1 = time.perf_counter_ns()
        return int(t_parent) - (t0 + t1) // 2
    except (EOFError, OSError):
        return 0


# ---------------------------------------------------------------------------
# Run report
# ---------------------------------------------------------------------------


class RunReport:
    """Builder for the single end-of-run JSON artifact: trace summary +
    metric snapshot + phase/overlap stats + health report."""

    @staticmethod
    def build(tel: Telemetry,
              health_monitor: Any = None) -> Dict[str, Any]:
        # lazy imports: profiling imports this module at module level
        from sparkdl_tpu.core import health as _health
        from sparkdl_tpu.core import profiling as _profiling

        mon = (health_monitor if health_monitor is not None
               else _health.active_monitor())
        return {
            "run_id": tel.run_id,
            "run": tel.name,
            "created_unix_s": round(time.time(), 3),
            "trace": tel.tracer.summary(),
            "metrics": tel.metrics.snapshot(),
            "phases": _profiling.phase_stats(),
            "overlap": _profiling.overlap_stats(),
            "health": mon.report() if mon is not None else None,
            # the live plane's view of the same run: one compact entry
            # per periodic snapshot (None without an exporter)
            "timeline": (tel.exporter.timeline_summary()
                         if tel.exporter is not None else None),
        }
