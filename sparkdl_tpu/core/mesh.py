"""Device mesh construction + sharding helpers.

The rebuild's replacement for the reference's distribution substrate
(Spark partition scheduling + Horovod/NCCL rings; SURVEY.md §2.4, §5.8):
a named-axis ``jax.sharding.Mesh`` over which batch data is sharded on
``data``, parameters optionally sharded on ``model`` (tensor parallelism),
long sequences on ``context`` (ring attention / sequence parallelism), and
experts on ``expert``. Collectives are never hand-written — XLA emits them
over ICI/DCN from these declarative shardings.

Axis names are fixed framework-wide so PartitionSpec rules compose:
``data`` | ``model`` | ``context`` | ``expert``.
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

DATA_AXIS = "data"
MODEL_AXIS = "model"
CONTEXT_AXIS = "context"
EXPERT_AXIS = "expert"

ALL_AXES = (DATA_AXIS, MODEL_AXIS, CONTEXT_AXIS, EXPERT_AXIS)


@dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh shape; -1 on ``data`` absorbs remaining devices.

    On a multi-host pod this is created identically on every process
    (jax.devices() is global); the ``data`` axis spans hosts so per-host
    input pipelines feed their local shard (DCN traffic only where the
    axis crosses hosts — the HorovodRunner-equivalent layout).
    """

    data: int = -1
    model: int = 1
    context: int = 1
    expert: int = 1

    def resolve(self, n_devices: Optional[int] = None) -> Dict[str, int]:
        n = n_devices if n_devices is not None else len(jax.devices())
        fixed = self.model * self.context * self.expert
        if n % fixed != 0:
            raise ValueError(
                f"device count {n} not divisible by model*context*expert={fixed}")
        data = self.data if self.data != -1 else n // fixed
        if data * fixed != n:
            raise ValueError(
                f"mesh shape data={data} model={self.model} "
                f"context={self.context} expert={self.expert} does not cover "
                f"{n} devices")
        return {DATA_AXIS: data, MODEL_AXIS: self.model,
                CONTEXT_AXIS: self.context, EXPERT_AXIS: self.expert}


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh with the framework's named axes.

    Axes of size 1 are kept (harmless, and they let PartitionSpec rules be
    written once for every topology). Device order follows ``jax.devices()``
    which already snakes physical ICI topology on TPU backends.
    """
    config = config or MeshConfig()
    devices = list(devices) if devices is not None else jax.devices()
    shape = config.resolve(len(devices))
    arr = np.asarray(devices).reshape(tuple(shape[a] for a in ALL_AXES))
    return Mesh(arr, ALL_AXES)


def data_parallel_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    return make_mesh(MeshConfig(), devices)


def host_local_mesh(mesh: Optional[Mesh]) -> Optional[Mesh]:
    """Inference-side mesh under multi-host: per-host data parallelism.

    Multi-host TRANSFORM is embarrassingly parallel (Spark's model: each
    executor ran its partitions independently, SURVEY.md §3.1) — there is
    no cross-host collective, so a mesh containing non-local devices is
    replaced by a data mesh over this process's local devices. Single
    process, None, or an already-local mesh pass through unchanged.
    """
    if mesh is None or jax.process_count() <= 1:
        return mesh
    local = set(jax.local_devices())
    if all(d in local for d in mesh.devices.flat):
        return mesh
    nontrivial = {axis: mesh.shape[axis]
                  for axis in (MODEL_AXIS, CONTEXT_AXIS, EXPERT_AXIS)
                  if mesh.shape.get(axis, 1) > 1}
    if nontrivial:
        # Silently discarding a model/context/expert axis would surface
        # much later as an inexplicable per-host OOM (params that were
        # sharded across hosts suddenly replicated); make the loss
        # diagnosable at the substitution site (ADVICE r5).
        logger.warning(
            "host_local_mesh: replacing a multi-host mesh with per-host "
            "data parallelism discards its non-trivial %s axes — "
            "parameter/sequence sharding is lost and per-host memory use "
            "will grow accordingly", nontrivial)
    return data_parallel_mesh(jax.local_devices())


def batch_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard dim 0 (batch) across ``data``, replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, array) -> jax.Array:
    """device_put a host NHWC/ND batch sharded on ``data`` along dim 0."""
    return jax.device_put(array, batch_sharding(mesh, np.ndim(array)))


def data_axis_size(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS]


def pad_to_multiple(n: int, multiple: int) -> int:
    return int(math.ceil(n / multiple) * multiple)


# ---------------------------------------------------------------------------
# Framework default mesh
# ---------------------------------------------------------------------------
# The reference scaled inference by running on every Spark executor
# implicitly; the rebuild's analog is one framework-level default mesh that
# every transformer/UDF uses unless given an explicit ``mesh`` param — so
# ``set_default_mesh(data_parallel_mesh())`` makes the whole API multi-chip.
#
# Two layers (ADVICE r2): ``set_default_mesh`` is process-wide (visible
# from every thread — engine workers included), while ``use_mesh`` scoping
# is a ContextVar, so concurrent transforms in different threads/contexts
# can never observe each other's scoped mesh or race on restore.

import contextvars as _contextvars

_global_default_mesh: Optional[Mesh] = None
_UNSET = object()
_scoped_mesh: "_contextvars.ContextVar" = _contextvars.ContextVar(
    "sparkdl_scoped_mesh", default=_UNSET)


def set_default_mesh(mesh: Optional[Mesh]) -> Optional[Mesh]:
    """Set (or clear, with None) the process-wide default mesh."""
    global _global_default_mesh
    _global_default_mesh = mesh
    return mesh


def get_default_mesh() -> Optional[Mesh]:
    scoped = _scoped_mesh.get()
    if scoped is not _UNSET:
        return scoped
    return _global_default_mesh


class use_mesh:
    """Context manager: ``with use_mesh(mesh): ...`` scopes the default.

    Context-local: ``use_mesh(None)`` masks the process default inside the
    scope; other threads/contexts are unaffected.

    ContextVar scoping cuts both ways (ADVICE r3): a scope entered on the
    driver thread is INVISIBLE to threads spawned inside it, including the
    engine's partition-pool workers. Therefore ``resolveMesh()`` (and any
    ``get_default_mesh()`` call meant to observe a ``use_mesh`` scope) must
    run on the driver thread BEFORE partition closures are built — which
    every in-tree call site does, resolving the mesh eagerly in
    ``_transform`` and capturing the resolved Mesh object into the closure.
    Do not call ``resolveMesh()`` lazily inside a partition op.
    """

    def __init__(self, mesh: Optional[Mesh]) -> None:
        self._mesh = mesh
        self._token = None

    def __enter__(self) -> Optional[Mesh]:
        self._token = _scoped_mesh.set(self._mesh)
        return self._mesh

    def __exit__(self, *exc) -> None:
        _scoped_mesh.reset(self._token)
