"""Resilience kernel: error taxonomy, retry/backoff, deadlines, fault injection.

The reference stack inherited Spark's fault story wholesale: task retry for
partition work, gang restart for Horovod training (SURVEY.md §5.3/§5.4 —
"gang failure meant restarting the job"), and nothing at all on the
inference hot path. This module is the rebuild's single source of truth for
*what is worth retrying* and *how*:

- :func:`classify` splits failures into ``FATAL`` (shape/dtype/programming
  errors — retrying reproduces them bit-for-bit), ``OOM`` (device
  ``RESOURCE_EXHAUSTED`` — retrying at the same batch shape reproduces it,
  but a *smaller* batch can succeed), and ``RETRYABLE`` (preemption,
  transfer stalls, transient runtime/compile errors — the gang/task
  boundary default).
- :class:`RetryPolicy` provides exponential backoff with *deterministic*
  jitter: two processes with the same seed compute identical delays, so
  multi-host gang restarts stay in lockstep instead of thundering in at
  random offsets.
- :class:`Deadline` bounds total retry time.
- :class:`FaultInjector` arms named injection points (see
  :data:`INJECTION_POINTS`) so every retry/degradation path is
  deterministically exercisable on CPU under tier-1 — no real TPU
  preemption required.

Dependency-free by design (stdlib only + no jax import at module level):
every layer — engine, core, train, image, ml — may import it without
cycles.
"""

from __future__ import annotations

import logging
import random
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple, Union

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# Error taxonomy
# ---------------------------------------------------------------------------

#: Failure kinds returned by :func:`classify`.
FATAL = "fatal"
RETRYABLE = "retryable"
OOM = "oom"


class InjectedFault(RuntimeError):
    """Base class for all errors raised by :class:`FaultInjector`."""


class DeviceOOM(InjectedFault):
    """Simulated device allocator exhaustion (XLA ``RESOURCE_EXHAUSTED``)."""

    def __init__(self, msg: str = "RESOURCE_EXHAUSTED: injected device OOM"
                 ) -> None:
        super().__init__(msg)


class Preemption(InjectedFault):
    """Simulated TPU-slice preemption / worker loss (gang failure)."""

    def __init__(self, msg: str = "injected preemption: coordinator "
                 "reported worker UNAVAILABLE") -> None:
        super().__init__(msg)


class TransferStall(InjectedFault):
    """Simulated transient host↔device transfer failure."""

    def __init__(self, msg: str = "injected transfer stall: "
                 "DEADLINE_EXCEEDED staging batch to device") -> None:
        super().__init__(msg)


class WorkerFault(InjectedFault):
    """Simulated engine worker/task failure (a partition task dying mid-run
    or after computing but before delivering its result — RETRYABLE)."""

    def __init__(self, msg: str = "injected worker fault: partition task "
                 "lost (UNAVAILABLE)") -> None:
        super().__init__(msg)


class DeadlineExceeded(RuntimeError):
    """A :class:`Deadline` expired before the guarded work completed."""


class ExecutorOverloaded(RuntimeError):
    """The device execution service shed this request at admission (its
    per-fn queue bound was exceeded in shed mode, or an interactive
    arrival displaced this queued bulk request). RETRYABLE by
    definition: overload is transient, and the engine's classified task
    retry (``run_partition_task``) absorbs the spike with backoff.
    Defined here (not in core.executor) so :func:`classify` stays the
    single taxonomy source without an import cycle."""


class ExecutorCircuitOpen(RuntimeError):
    """The per-model circuit breaker is open: this model's recent
    launches failed terminally, so the service fails fast instead of
    queuing doomed work. RETRYABLE: the caller's bounded backoff rides
    past the cooldown, after which a half-open probe re-tests the model
    — if it healed, traffic flows again; if not, the retry budget
    exhausts without ever paying for a queue slot or a launch."""


class DecodeWorkerLost(RuntimeError):
    """A decode-pool worker process died (or the pool closed) while a
    chunk was in flight and the pool's internal respawn+resubmit budget
    could not recover it (``core/decode_pool.py``). RETRYABLE by
    definition: worker loss is transient infrastructure failure — the
    engine's classified task retry replays the partition, and the pool
    has already respawned its workers by the time the retry arrives.
    Defined here (not in core.decode_pool) so :func:`classify` stays the
    single taxonomy source without an import cycle."""


class ClusterWorkerLost(RuntimeError):
    """A cluster worker process died (EOF on its result pipe) while a
    partition dispatch was in flight and no survivor could absorb the
    re-dispatch (``sparkdl_tpu/cluster/router.py``). RETRYABLE by
    definition: worker loss is transient infrastructure failure — the
    engine's classified task retry re-dispatches the partition, and the
    router re-routes around the dead worker. Defined here (not in the
    cluster package) so :func:`classify` stays the single taxonomy
    source without an import cycle."""


class WorkerDraining(RuntimeError):
    """A task was routed to (or refused by) a cluster worker that is
    draining: it received a preemption warning or a scale-down order and
    accepts no new dispatches while its in-flight tasks finish
    (``sparkdl_tpu/cluster/router.py``). RETRYABLE by definition: the
    work itself is untouched — another worker (or a freshly spawned
    replacement) can run it immediately, and journal-committed
    partitions never re-execute. Defined here (not in the cluster
    package) so :func:`classify` stays the single taxonomy source
    without an import cycle."""


class DrainTimeout(RuntimeError):
    """A draining cluster worker failed to finish its in-flight tasks
    before the drain grace period expired (the preemptor's warning
    window, ``sparkdl_tpu/cluster/router.py``) and was torn down hard.
    RETRYABLE by definition: the interrupted tasks are indistinguishable
    from worker loss — the router re-dispatches them to survivors, and
    journal-committed partitions stay committed. Defined here so
    :func:`classify` stays the single taxonomy source without an import
    cycle."""


class ServingReplicaLost(RuntimeError):
    """A cluster worker serving an online predict died (or every
    surviving replica was draining/lost) and the request could not be
    re-admitted within its failover budget
    (``sparkdl_tpu/serving/cluster.py``). RETRYABLE by definition:
    predict is idempotent and journal-free — the client (or the serving
    router's own deadline-bounded re-admission) simply runs it again on
    a surviving replica. Defined here so :func:`classify` stays the
    single taxonomy source without an import cycle."""


class StaleCheckpointWriter(RuntimeError):
    """A checkpoint save was refused by the fencing token: this process
    belongs to a superseded gang incarnation and a newer writer has
    claimed the directory (``train/checkpoint.py``). FATAL by definition:
    the zombie must die, not retry — every retry would be refused again,
    and letting it through would clobber the newer incarnation's
    checkpoints. Defined here so :func:`classify` stays the single
    taxonomy source without an import cycle."""


# Exception types whose recurrence is deterministic: retrying replays the
# same traceback. ValueError covers shape/dtype contract violations raised
# throughout the framework; jax shape errors are TypeError subclasses.
_FATAL_TYPES: Tuple[type, ...] = (
    ValueError, TypeError, KeyError, IndexError, AttributeError,
    AssertionError, NotImplementedError, ZeroDivisionError,
)

# Message fragments marking device allocator exhaustion (XLA / PJRT wording
# differs per backend+version — status prefix, BFC-allocator prose, bare
# "OOM"; prose matches case-insensitively). "OOM" matches as a standalone
# word only — an unanchored substring would classify e.g. "BLOOM shard
# failed" as a device OOM and burn bucket-halving retries on a
# deterministic error.
_OOM_MARKERS = ("resource_exhausted", "out of memory", "resource exhausted")
_OOM_WORD = re.compile(r"\bOOM\b")

# Message fragments marking transient infrastructure failures (gRPC status
# names the PJRT C API surfaces verbatim, plus prose seen from the TPU
# runtime during preemption/migration events). Checked BEFORE the fatal
# type list: a transient infra failure re-raised through a fatal-typed
# wrapper (e.g. ValueError("UNAVAILABLE: socket closed")) must stay
# retryable.
_TRANSIENT_MARKERS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED",
                      "CANCELLED", "preempt", "socket closed",
                      "connection reset", "Broken pipe")


def classify(err: BaseException) -> str:
    """Classify an exception as ``FATAL``, ``OOM``, or ``RETRYABLE``.

    Precedence: explicit injected types first; then OOM markers (an XLA
    ``RESOURCE_EXHAUSTED`` arrives as a RuntimeError-ish ``XlaRuntimeError``
    whose *message* carries the status); then transient infra markers
    (which override a fatal wrapper type); then the deterministic-failure
    type list; everything else falls to ``RETRYABLE`` — the gang boundary
    has always retried unknown errors (Spark task semantics) and a
    spurious retry is bounded by the policy, while a missed retry loses
    the job.

    An exception carrying a ``failure_kind`` attribute (the engine's
    ``TaskFailure``, which records its terminal attempt's classification)
    is trusted verbatim — a task that failed FATALLY must stay fatal
    through every wrapper, or a gang restart would replay it.
    """
    kind = getattr(err, "failure_kind", None)
    if kind in (FATAL, OOM, RETRYABLE):
        return kind
    if isinstance(err, DeviceOOM):
        return OOM
    if isinstance(err, (Preemption, TransferStall, ExecutorOverloaded,
                        ExecutorCircuitOpen, DecodeWorkerLost,
                        ClusterWorkerLost, WorkerDraining, DrainTimeout,
                        ServingReplicaLost)):
        return RETRYABLE
    if isinstance(err, DeadlineExceeded):
        return FATAL  # the deadline IS the retry budget; never retry past it
    if isinstance(err, StaleCheckpointWriter):
        return FATAL  # fenced-off zombie: every retry would be refused too
    msg = str(err)
    msg_lower = msg.lower()
    if any(m in msg_lower for m in _OOM_MARKERS) or _OOM_WORD.search(msg):
        return OOM
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return RETRYABLE
    if isinstance(err, _FATAL_TYPES):
        return FATAL
    if "INVALID_ARGUMENT" in msg or "FAILED_PRECONDITION" in msg:
        return FATAL
    return RETRYABLE


# ---------------------------------------------------------------------------
# Deadline
# ---------------------------------------------------------------------------

class Deadline:
    """A wall-clock budget: ``Deadline(30).check()`` raises once exceeded.

    ``clock`` is injectable for tests (defaults to ``time.monotonic``).
    ``Deadline(None)`` never expires — callers can thread one value
    unconditionally.
    """

    def __init__(self, timeout_s: Optional[float],
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self.timeout_s = timeout_s
        self._start = clock()

    def remaining(self) -> float:
        if self.timeout_s is None:
            return float("inf")
        return self.timeout_s - (self._clock() - self._start)

    def expired(self) -> bool:
        return self.remaining() <= 0

    def check(self, what: str = "operation") -> None:
        if self.expired():
            raise DeadlineExceeded(
                f"{what} exceeded its {self.timeout_s}s deadline")


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with deterministic jitter.

    ``delay(attempt)`` for attempt 1, 2, ... grows as
    ``base_delay_s * multiplier**(attempt-1)`` capped at ``max_delay_s``,
    then stretched by up to ``jitter`` (a fraction) drawn from an RNG
    seeded by ``(seed, attempt)`` — deterministic per policy, so restarts
    are reproducible and multi-host gangs with a shared seed back off in
    lockstep.
    """

    max_retries: int = 3
    base_delay_s: float = 0.5
    max_delay_s: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.5
    seed: int = 0

    def delay(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (1-indexed)."""
        if attempt < 1:
            raise ValueError(f"attempt is 1-indexed, got {attempt}")
        base = min(self.base_delay_s * self.multiplier ** (attempt - 1),
                   self.max_delay_s)
        if not self.jitter or base <= 0:
            return base
        frac = random.Random((self.seed, attempt)).uniform(0.0, self.jitter)
        return base * (1.0 + frac)

    def execute(self, fn: Callable[[], Any], *,
                deadline: Optional[Deadline] = None,
                on_retry: Optional[Callable[[int, BaseException], None]] = None,
                sleep: Callable[[float], None] = time.sleep,
                what: str = "operation") -> Any:
        """Run ``fn`` with classified retry; FATAL/OOM propagate immediately.

        OOM is *not* retried here because same-shape retry reproduces it —
        callers with a smaller-batch fallback (core.batching) handle OOM
        themselves and use this only for the transient class.
        """
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 - classified below
                kind = classify(e)
                if kind != RETRYABLE:
                    raise
                attempt += 1
                if attempt > self.max_retries:
                    raise
                if deadline is not None:
                    deadline.check(what)
                if on_retry is not None:
                    on_retry(attempt, e)
                d = self.delay(attempt)
                logger.warning("%s failed (%s: %s); retry %d/%d in %.2fs",
                               what, type(e).__name__, e, attempt,
                               self.max_retries, d)
                if d > 0:
                    sleep(d)


# Shared default for the inference hot path (apply_batch / run_batched):
# short fuse, small base delay — a transform must not stall for minutes on
# a partition, and the engine's task retry sits above it anyway.
DEFAULT_INFERENCE_POLICY = RetryPolicy(max_retries=2, base_delay_s=0.2,
                                       max_delay_s=5.0)


# ---------------------------------------------------------------------------
# Fault injection
# ---------------------------------------------------------------------------

#: Registered injection points → (description, default error factory or
#: None for behavioral points that degrade instead of raising).
INJECTION_POINTS: Dict[str, Tuple[str, Optional[Callable[[], BaseException]]]] = {
    "device_oom": ("raised per inference chunk before device dispatch "
                   "(core.batching) — exercises the OOM bucket-halving "
                   "fallback", DeviceOOM),
    "preemption": ("raised per training step after checkpointing "
                   "(train.trainer) — exercises TPURunner's classified "
                   "gang restart + checkpoint resume", Preemption),
    "transfer_stall": ("raised per inference chunk before device dispatch "
                       "(core.batching) — exercises transient retry",
                       TransferStall),
    "decode_error": ("behavioral: image decode paths (image.imageIO, "
                     "ml.image_transformer) treat the row as undecodable "
                     "— exercises null-cell degradation", None),
    "checkpoint_truncate": ("behavioral: CheckpointManager.save corrupts "
                            "the just-written step — exercises restore "
                            "fallback to the previous retained step", None),
    "engine_task": ("raised per partition-task attempt in the engine "
                    "executor (engine/dataframe); ctx carries partition, "
                    "attempt, and phase ('start' before the op chain, "
                    "'finish' after it — a worker dying before delivering "
                    "its computed result) — exercises classified task "
                    "retry", WorkerFault),
    "task_stall": ("behavioral: the engine partition task hangs (sleeps "
                   "past its deadline) instead of failing — exercises the "
                   "supervisor's deadline watchdog", None),
    "decode_pool_worker_crash": (
        "behavioral: the decode pool marks the next submitted chunk so "
        "its worker process exits hard (os._exit) mid-task "
        "(core/decode_pool.py) — exercises worker respawn, chunk "
        "resubmission, and (armed persistently) the RETRYABLE "
        "DecodeWorkerLost exhaustion path", None),
    "process_kill": (
        "behavioral: the durable journal SIGKILLs its own process "
        "immediately AFTER committing a partition record "
        "(core/durability.py); ctx carries partition — exercises "
        "kill -9 resume: a restarted job must load the committed "
        "partitions from spill and recompute only the rest", None),
    "cluster_worker_kill": (
        "behavioral: the cluster router marks the next dispatched "
        "partition so its worker process SIGKILLs itself on receipt "
        "(sparkdl_tpu/cluster/); ctx carries partition — exercises "
        "EOF death detection, precise re-dispatch of the dead worker's "
        "in-flight partitions to survivors, and the merged-report "
        "accounting for a lost worker", None),
    "serving_worker_kill": (
        "behavioral: the cluster serving router marks the next "
        "dispatched predict so its worker process SIGKILLs itself on "
        "receipt (sparkdl_tpu/serving/cluster.py); ctx carries model "
        "and request — exercises replica-death failover: every "
        "in-flight predict on the dead worker re-admits to a surviving "
        "replica within the caller's deadline, with exactly-once "
        "serving_failover accounting", None),
    "cluster_worker_preempt": (
        "behavioral: the cluster router marks the next dispatched "
        "partition so its worker process SIGTERMs itself on receipt — "
        "a spot-VM preemption WARNING, not a kill: the worker still "
        "runs the task, notifies the router it is draining, and exits "
        "cleanly once drained (sparkdl_tpu/cluster/); ctx carries "
        "partition — exercises graceful drain with zero re-execution "
        "instead of the ClusterWorkerLost re-dispatch path", None),
}


@dataclass
class Fault:
    """Arming spec for one injection point.

    Fires on checks ``after <= i < after + times`` (0-indexed occurrence
    count, per point, counted only on checks where ``when(ctx)`` holds).
    ``times=-1`` fires forever. ``error`` overrides the point's default
    error factory (ignored for behavioral points).
    """

    times: int = 1
    after: int = 0
    when: Optional[Callable[[Dict[str, Any]], bool]] = None
    error: Optional[Union[Callable[[], BaseException], BaseException]] = None
    _seen: int = field(default=0, repr=False)
    _fired: int = field(default=0, repr=False)

    def should_fire(self, ctx: Dict[str, Any]) -> bool:
        if self.when is not None and not self.when(ctx):
            return False
        i = self._seen
        self._seen += 1
        if i < self.after:
            return False
        if self.times != -1 and self._fired >= self.times:
            return False
        self._fired += 1
        return True


class FaultInjector:
    """Seeded, named fault injection — a context manager arming the
    process-wide injector (process-wide, not context-local: partition ops
    run on engine pool threads where a ContextVar scope entered on the
    driver thread would be invisible — the ``use_mesh`` lesson, ADVICE r3).

    ::

        with FaultInjector.seeded(0, device_oom=1):
            model.apply_batch(x)            # first chunk OOMs, then heals
        with FaultInjector.seeded(0, preemption=Fault(
                when=lambda ctx: ctx.get("step") == 3)):
            TPURunner(max_restarts=1).run(train_fn)

    ``seed`` feeds the deterministic jitter of any policy built from
    :meth:`retry_policy` and is recorded for reproducibility. Fire counts
    are observable via :attr:`fired` for assertions.
    """

    def __init__(self, faults: Dict[str, Fault], seed: int = 0) -> None:
        unknown = set(faults) - set(INJECTION_POINTS)
        if unknown:
            raise ValueError(
                f"Unknown injection point(s) {sorted(unknown)}; "
                f"registered: {sorted(INJECTION_POINTS)}")
        self.faults = faults
        self.seed = seed
        self.fired: Dict[str, int] = {name: 0 for name in faults}
        self._lock = threading.Lock()
        self._prev: Optional["FaultInjector"] = None

    @classmethod
    def seeded(cls, seed: int = 0, **faults) -> "FaultInjector":
        """Build from kwargs: ``point=N`` (fire N times), ``point=Fault(...)``,
        or ``point=<exception instance/class>`` (fire once with it)."""
        specs: Dict[str, Fault] = {}
        for name, value in faults.items():
            if isinstance(value, Fault):
                specs[name] = value
            elif isinstance(value, bool):
                specs[name] = Fault(times=-1 if value else 0)
            elif isinstance(value, int):
                specs[name] = Fault(times=value)
            elif isinstance(value, BaseException) or (
                    isinstance(value, type)
                    and issubclass(value, BaseException)):
                specs[name] = Fault(times=1, error=value)
            else:
                raise TypeError(
                    f"{name}={value!r}: expected int, bool, Fault, or an "
                    "exception")
        return cls(specs, seed=seed)

    def retry_policy(self, **overrides) -> RetryPolicy:
        """A policy sharing this injector's seed (deterministic delays)."""
        return RetryPolicy(seed=self.seed, **overrides)

    # -- the check, called from injection sites ------------------------------

    def _fire(self, point: str, ctx: Dict[str, Any]
              ) -> Optional[BaseException]:
        fault = self.faults.get(point)
        if fault is None:
            return None
        with self._lock:
            if not fault.should_fire(ctx):
                return None
            self.fired[point] += 1
        desc, default_error = INJECTION_POINTS[point]
        err = fault.error if fault.error is not None else default_error
        if err is None:
            return InjectedFault(f"injected {point}")  # behavioral marker
        if isinstance(err, BaseException):
            return err
        return err()

    # -- activation ----------------------------------------------------------

    def __enter__(self) -> "FaultInjector":
        global _active
        with _activation_lock:
            self._prev = _active
            _active = self
        return self

    def __exit__(self, *exc) -> None:
        global _active
        with _activation_lock:
            _active = self._prev
            self._prev = None


_active: Optional[FaultInjector] = None
_activation_lock = threading.Lock()


def active_injector() -> Optional[FaultInjector]:
    return _active


def inject(point: str, **ctx: Any) -> None:
    """Raise the armed fault at ``point`` (no-op with no active injector).

    Production cost when idle: one global read + None check.
    """
    injector = _active
    if injector is None:
        return
    err = injector._fire(point, ctx)
    if err is not None:
        logger.warning("FaultInjector: firing %r (%s)", point, err)
        raise err


def should_fire(point: str, **ctx: Any) -> bool:
    """Behavioral variant: True when the armed fault at ``point`` fires.

    Used where injection means *degrading* (undecodable row, truncated
    checkpoint) rather than raising.
    """
    injector = _active
    if injector is None:
        return False
    fired = injector._fire(point, ctx) is not None
    if fired:
        logger.warning("FaultInjector: firing behavioral point %r", point)
    return fired
