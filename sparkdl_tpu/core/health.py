"""Run-health telemetry: counters and events for the supervision layers.

Every resilience mechanism in the framework (engine task retry/hedging/
quarantine, the batching layer's OOM re-chunking, TPURunner gang restarts,
Trainer checkpoint resumes, data-plane decode degradation) reports what it
did into one :class:`HealthMonitor`, so a run's operator can answer "what
actually went wrong, and what did the framework do about it?" from a
single structured report instead of grepping warnings.

Scoping mirrors :class:`~sparkdl_tpu.core.resilience.FaultInjector`:
monitors activate process-wide (engine partition ops run on pool threads
where a ContextVar scope entered on the driver would be invisible), nest,
and restore the previous monitor on exit. With no active monitor,
:func:`record` is a single global read + ``None`` check — the hot paths
pay nothing when nobody is listening.

Dependency-free by design (stdlib only): every layer may import it
without cycles.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

from sparkdl_tpu.core import telemetry

logger = logging.getLogger(__name__)

# Canonical event names fed by the framework's own layers. Callers may
# record arbitrary additional events; these are the ones the docs and the
# chaos suite key off.
TASK_STARTED = "task_started"            # engine: a partition task began
TASK_RETRIED = "task_retried"            # engine: classified-retryable retry
TASK_FAILED = "task_failed"              # engine: terminal task failure
TASK_HEDGED = "task_hedged"              # engine: straggler duplicate launched
HEDGE_WON = "hedge_won"                  # engine: the duplicate finished first
TASK_QUARANTINED = "task_quarantined"    # engine: poisoned partition dropped
TASK_DEADLINE_EXCEEDED = "task_deadline_exceeded"  # engine: watchdog fired
CHUNK_RETRY = "chunk_retry"              # batching: transient chunk retry
OOM_RECHUNK = "oom_rechunk"              # batching: bucket-halving fallback
GANG_RESTART = "gang_restart"            # runner: classified gang restart
GANG_FATAL = "gang_fatal"                # runner: fatal/OOM raise, no restart
GANG_FAILED = "gang_failed"              # runner: restart budget exhausted
FIT_RESUMED = "fit_resumed"              # trainer: resumed from a checkpoint
FIT_COMPLETED = "fit_completed"          # trainer: fit loop finished
DECODE_DEGRADED = "decode_degraded"      # data plane: row degraded to null
DECODE_POOL_RESPAWN = "decode_pool_respawn"  # decode pool: worker process
                                         # died and was respawned
PREFETCH_REPORT = "prefetch_report"      # pipeline: per-stream staging summary
                                         # (staged/stalls/stall_s/max_depth)
EXECUTOR_SHED = "executor_shed"          # executor: admission shed a request
EXECUTOR_DEADLINE_SHED = "executor_deadline_shed"  # executor: request
                                         # expired in queue, dropped pre-launch
BREAKER_OPEN = "breaker_open"            # executor: circuit breaker tripped
BREAKER_PROBE = "breaker_probe"          # executor: half-open probe admitted
BREAKER_CLOSED = "breaker_closed"        # executor: probe succeeded, recovered
SLO_BREACH = "slo_breach"                # slo: rule held in breach past its
                                         # hold-down (paired with recovery)
SLO_RECOVERED = "slo_recovered"          # slo: breached rule back in budget
TELEMETRY_EXPORT_ERROR = "telemetry_export_error"  # telemetry: exporter
                                         # tick crashed (skipped, not fatal)
DURABLE_RESUMED = "durable_resumed"      # durability: a journal with
                                         # committed partitions was resumed
DURABLE_PARTITION_RESTORED = "durable_partition_restored"  # durability:
                                         # committed partition loaded from
                                         # spill instead of recomputed
DURABLE_JOURNAL_TORN = "durable_journal_torn"  # durability: torn/corrupt
                                         # journal record or spill hash
                                         # mismatch discarded, not trusted
DECODE_POOL_SHM_SWEPT = "decode_pool_shm_swept"  # decode pool: orphaned
                                         # segment of a dead owner unlinked
CHECKPOINT_CHECKSUM_REJECTED = "checkpoint_checksum_rejected"  # checkpoint:
                                         # restore refused a bit-rotted file
CHECKPOINT_FENCED = "checkpoint_fenced"  # checkpoint: stale-incarnation
                                         # writer refused by fencing token
SERVING_SHED = "serving_shed"            # serving: SLO-aware admission
                                         # rejected a request pre-device
SERVING_CUTOVER = "serving_cutover"      # serving: active version flipped
                                         # (deploys AND rollbacks)
SERVING_SHADOW_COMPARED = "serving_shadow_compared"  # serving: one shadow
                                         # request compared vs active
SERVING_SHADOW_ERROR = "serving_shadow_error"  # serving: shadow leg raised
                                         # (never fails the request)
SERVING_EVICTED = "serving_evicted"      # serving: residency dropped a
                                         # model's weights + jit state
SERVING_COLD_START = "serving_cold_start"  # serving: loader ran on a
                                         # residency miss (first load OR
                                         # reload after eviction)
SERVING_FAILOVER = "serving_failover"    # serving: one in-flight predict
                                         # re-admitted to a surviving
                                         # replica after its worker died
                                         # (exactly one event per moved
                                         # request)
SERVING_PREPARE_FAILED = "serving_prepare_failed"  # serving: a cluster
                                         # cutover's prepare phase failed
                                         # on some worker — rolled back,
                                         # v1 still serving everywhere
WARMUP_COMPLETED = "warmup_completed"    # serving: a deployment's full
                                         # bucket ladder was AOT-compiled
                                         # (and kernel shootouts settled)
                                         # before it took traffic
CLUSTER_WORKER_STARTED = "cluster_worker_started"  # cluster: a worker
                                         # process was spawned
CLUSTER_WORKER_LOST = "cluster_worker_lost"  # cluster: a worker died
                                         # (EOF on its result pipe)
CLUSTER_REDISPATCH = "cluster_redispatch"  # cluster: a dead worker's
                                         # in-flight partition re-sent
                                         # to a survivor
CLUSTER_SCALE_UP = "cluster_scale_up"    # cluster: autoscaler spawned a
                                         # worker under queue pressure
CLUSTER_SCALE_DOWN = "cluster_scale_down"  # cluster: autoscaler retired
                                         # an idle worker via drain
CLUSTER_WORKER_DRAINING = "cluster_worker_draining"  # cluster: a worker
                                         # stopped taking dispatches
                                         # (preemption warning or
                                         # scale-down order)
CLUSTER_WORKER_DRAINED = "cluster_worker_drained"  # cluster: a draining
                                         # worker finished its in-flight
                                         # tasks and exited cleanly
CLUSTER_PREEMPTION_NOTICE = "cluster_preemption_notice"  # cluster: a
                                         # worker reported SIGTERM-with-
                                         # warning (spot-VM preemption)
CLUSTER_METRICS_STALE = "cluster_metrics_stale"  # cluster: a worker's
                                         # federation frames aged out of
                                         # the live fold (stale or dead)
POSTMORTEM_DUMPED = "postmortem_dumped"  # cluster: the flight recorder
                                         # wrote a breach/death-triggered
                                         # postmortem bundle
TENANT_THROTTLED = "tenant_throttled"    # executor: fair queueing held a
                                         # tenant's requests back while
                                         # another tenant's were released


class HealthMonitor:
    """Thread-safe per-run counters + a bounded structured event log.

    ::

        with HealthMonitor("nightly-fit") as mon:
            pipeline.run()
        report = mon.report()          # {'counters': {...}, ...}
        assert mon.count("task_retried") == 1

    Counters are unbounded (one int per event name); the event log keeps
    the first ``max_events`` events with their context kwargs and counts
    the overflow, so a pathological retry storm cannot exhaust memory.
    """

    def __init__(self, name: str = "run", max_events: int = 2048) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._events: List[Dict[str, Any]] = []
        self._max_events = max_events
        self._dropped_events = 0
        self._dropped_by_event: Dict[str, int] = {}
        self._prev: Optional["HealthMonitor"] = None

    # -- recording -----------------------------------------------------------

    def record(self, event: str, n: int = 1, **ctx: Any) -> None:
        """Count ``event`` (``n`` occurrences) and log one context entry.
        Overflow past ``max_events`` is never silent: the drop is counted
        (total and per event name) and surfaced in :meth:`report`."""
        with self._lock:
            self._counters[event] = self._counters.get(event, 0) + n
            if len(self._events) < self._max_events:
                entry: Dict[str, Any] = {"event": event}
                if n != 1:
                    entry["n"] = n
                entry.update(ctx)
                self._events.append(entry)
            else:
                self._dropped_events += 1
                self._dropped_by_event[event] = \
                    self._dropped_by_event.get(event, 0) + 1

    # -- querying ------------------------------------------------------------

    def count(self, event: str) -> int:
        with self._lock:
            return self._counters.get(event, 0)

    def dropped_events(self) -> int:
        """Events the bounded log overflowed (counters stay exact)."""
        with self._lock:
            return self._dropped_events

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def events(self, event: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            if event is None:
                return list(self._events)
            return [e for e in self._events if e["event"] == event]

    def quarantined(self) -> List[Dict[str, Any]]:
        """The quarantine registry: one entry per dropped partition."""
        return self.events(TASK_QUARANTINED)

    def report(self) -> Dict[str, Any]:
        """The per-run health report (structured, JSON-able)."""
        with self._lock:
            return {
                "run": self.name,
                "counters": dict(sorted(self._counters.items())),
                "quarantined": [e for e in self._events
                                if e["event"] == TASK_QUARANTINED],
                "events_recorded": len(self._events),
                "events_dropped": self._dropped_events,
                "events_dropped_by_event": dict(
                    sorted(self._dropped_by_event.items())),
            }

    def log_report(self, level: int = logging.INFO) -> None:
        rep = self.report()
        if not rep["counters"]:
            logger.log(level, "health report for %r: no events recorded",
                       self.name)
            return
        counters = ", ".join(f"{k}={v}" for k, v in rep["counters"].items())
        logger.log(level, "health report for %r: %s (%d event(s) recorded"
                   "%s)", self.name, counters, rep["events_recorded"],
                   f", {rep['events_dropped']} dropped"
                   if rep["events_dropped"] else "")

    # -- activation ----------------------------------------------------------

    def __enter__(self) -> "HealthMonitor":
        global _active
        with _activation_lock:
            self._prev = _active
            _active = self
        return self

    def __exit__(self, *exc) -> None:
        global _active
        with _activation_lock:
            _active = self._prev
            self._prev = None
        # Job-end hook: one report per run, when the monitor deactivates
        # (NOT per Trainer.fit — an HPO search runs dozens of fits under
        # one monitor and cumulative counters would mislead per fit).
        if self._counters:
            self.log_report()


_active: Optional[HealthMonitor] = None
_activation_lock = threading.Lock()


def active_monitor() -> Optional[HealthMonitor]:
    return _active


def record(event: str, n: int = 1, **ctx: Any) -> None:
    """Record into the active monitor (no-op — one global read — without
    one). Every record is also mirrored into the active telemetry
    scope's metrics registry as the counter
    ``sparkdl.health.<event>`` — one choke point, so the run report's
    metric snapshot and the HealthMonitor counts agree exactly."""
    mon = _active
    if mon is not None:
        mon.record(event, n=n, **ctx)
    if telemetry.active() is not None:
        telemetry.count(telemetry.HEALTH_METRIC_PREFIX + event, n)


def log_report(level: int = logging.INFO) -> None:
    """Log the active monitor's report (no-op without one) — the
    job-end hook ``Trainer.fit`` and long pipelines call."""
    mon = _active
    if mon is not None:
        mon.log_report(level)
