"""Static-shape batching utilities.

XLA compiles one program per input shape; variable row counts per partition
would retrace endlessly. Everything device-bound therefore runs at a fixed
``batch_size``: partitions are chunked, the tail chunk is zero-padded and
the pad rows dropped after compute. (The reference had the same constraint
implicitly — TF graphs with fixed input sizes; SURVEY.md §7 "Dynamic
shapes".)
"""

from __future__ import annotations

from typing import Callable, Iterator, Optional, Tuple

import numpy as np


def pad_batch(arr: np.ndarray, batch_size: int) -> Tuple[np.ndarray, int]:
    """Zero-pad dim 0 up to ``batch_size``; returns (padded, n_valid)."""
    n = arr.shape[0]
    if n == batch_size:
        return arr, n
    if n > batch_size:
        raise ValueError(f"batch of {n} rows exceeds batch_size {batch_size}")
    pad_widths = [(0, batch_size - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_widths), n


def iter_batches(arr: np.ndarray, batch_size: int
                 ) -> Iterator[Tuple[np.ndarray, int]]:
    """Yield (padded_chunk, n_valid) fixed-shape chunks over dim 0."""
    n = arr.shape[0]
    if n == 0:
        return
    for start in range(0, n, batch_size):
        yield pad_batch(arr[start:start + batch_size], batch_size)


def run_batched(fn: Callable[[np.ndarray], object], arr: np.ndarray,
                batch_size: int) -> np.ndarray:
    """Apply a fixed-batch device fn over all rows, concatenating outputs.

    ``fn`` must accept a (batch_size, ...) array and return a device array
    whose dim 0 aligns with the input rows. JAX's async dispatch overlaps
    the host staging of chunk k+1 with device compute of chunk k: we
    dispatch all chunks before blocking on any result.
    """
    outs = []
    valids = []
    for chunk, n_valid in iter_batches(arr, batch_size):
        outs.append(fn(chunk))  # dispatched async; do not block here
        valids.append(n_valid)
    if not outs:
        # Preserve the output *element* shape for empty inputs: run one
        # dummy padded batch through shape inference only.
        import jax

        dummy = jax.eval_shape(fn, jax.ShapeDtypeStruct(
            (batch_size,) + arr.shape[1:], arr.dtype))
        return np.zeros((0,) + tuple(dummy.shape[1:]),
                        dtype=np.dtype(dummy.dtype))
    host = [np.asarray(o)[:v] for o, v in zip(outs, valids)]
    return np.concatenate(host, axis=0)
