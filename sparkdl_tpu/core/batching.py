"""Static-shape batching utilities.

XLA compiles one program per input shape; variable row counts per partition
would retrace endlessly. Everything device-bound therefore runs at a fixed
``batch_size``: partitions are chunked, the tail chunk is zero-padded and
the pad rows dropped after compute. (The reference had the same constraint
implicitly — TF graphs with fixed input sizes; SURVEY.md §7 "Dynamic
shapes".)
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from sparkdl_tpu.core import health, resilience, telemetry

logger = logging.getLogger(__name__)

# Transfer economics of the staging path (r3, measured with true barriers —
# scalar fetched through a jitted reduction; block_until_ready is NOT a
# reliable barrier here, see core/profiling.py):
#   host→device ~47 MB/s regardless of chunking; device→host ~100 ms fixed
#   latency + ~92 MB/s. A chunked device_put + on-device reassembly was
#   tried and measured NO faster (the apparent 1.5 GB/s for small puts was
#   async dispatch, not completed DMA). The levers that DO work: transfer
#   uint8 not float32 (4x), resize to the model input size on the host
#   BEFORE transfer when that shrinks bytes (native batch resizer), and
#   fetch each partition's outputs as ONE device-concatenated array
#   instead of one fetch per bucket (saves the ~100 ms fixed latency per
#   batch).


def pad_batch(arr: np.ndarray, batch_size: int) -> Tuple[np.ndarray, int]:
    """Zero-pad dim 0 up to ``batch_size``; returns (padded, n_valid)."""
    n = arr.shape[0]
    if n == batch_size:
        return arr, n
    if n > batch_size:
        raise ValueError(f"batch of {n} rows exceeds batch_size {batch_size}")
    pad_widths = [(0, batch_size - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_widths), n


def bucket_size(n: int, batch_size: int, multiple: int = 1,
                min_bucket: int = 8) -> int:
    """Smallest power-of-two bucket ≥ n (capped at batch_size, rounded up
    to ``multiple`` for mesh data-axis divisibility).

    Tail chunks pad to their bucket instead of the full batch_size — a
    32-row partition behind a batch_size=128 transformer transfers 32-ish
    rows, not 128 (4x padding waste measured on the e2e path). Buckets are
    powers of two so compile count stays O(log batch_size).
    """
    b = min_bucket
    while b < n:
        b <<= 1
    b = min(b, batch_size)
    b = max(b, n)  # n > batch_size: bucket covers n (public-helper use)
    if b % multiple:
        b = int(-(-b // multiple) * multiple)
    return b


def iter_batches(arr: np.ndarray, batch_size: int, multiple: int = 1
                 ) -> Iterator[Tuple[np.ndarray, int]]:
    """Yield (padded_chunk, n_valid) fixed-shape chunks over dim 0; the
    tail chunk pads to its power-of-two bucket, not full batch_size."""
    n = arr.shape[0]
    if n == 0:
        return
    for start in range(0, n, batch_size):
        chunk = arr[start:start + batch_size]
        yield pad_batch(chunk, bucket_size(len(chunk), batch_size, multiple))


def iter_batches_tree(tree, batch_size: int, multiple: int = 1):
    """``iter_batches`` over a pytree of dim-0-aligned arrays.

    Multi-input models take a dict of arrays sharing the batch dim
    (the reference ``TFTransformer``'s feed-dict analog); every leaf is
    chunked and padded identically.
    """
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    n = leaves[0].shape[0]
    for leaf in leaves[1:]:
        if leaf.shape[0] != n:
            raise ValueError(
                f"multi-input batch dims disagree: {leaf.shape[0]} vs {n}")
    if n == 0:
        return
    for start in range(0, n, batch_size):
        chunk_leaves = []
        n_valid = min(batch_size, n - start)
        bucket = bucket_size(n_valid, batch_size, multiple)
        for leaf in leaves:
            padded, _ = pad_batch(leaf[start:start + batch_size], bucket)
            chunk_leaves.append(padded)
        yield treedef.unflatten(chunk_leaves), n_valid


def element_signature(tree) -> Tuple:
    """Per-leaf (element shape, dtype) signature of a dim-0-batched pytree.

    The identity under which rows are interchangeable: the executor's
    coalescer only concatenates requests sharing a signature, and the
    empty-output template memoization keys on it.
    """
    import jax

    return tuple((tuple(leaf.shape[1:]), str(leaf.dtype))
                 for leaf in jax.tree_util.tree_leaves(tree))


def _valid_rows(chunk, n_valid: int):
    """Strip pad rows: the original (unpadded) rows of a padded chunk."""
    import jax

    return jax.tree_util.tree_map(lambda leaf: leaf[:n_valid], chunk)


def _dispatch_chunk(fn: Callable, chunk, n_valid: int,
                    multiple: int, policy: resilience.RetryPolicy
                    ) -> List[Tuple[object, int]]:
    """Dispatch one padded chunk with classified retry + OOM re-chunking.

    Returns ``[(device_out, n_valid), ...]`` in row order — one pair
    normally, several when an OOM forced the chunk to re-run as smaller
    sub-chunks. Semantics per failure kind (core.resilience):

    - FATAL: propagate immediately; retrying a shape/dtype error replays it.
    - RETRYABLE: bounded backoff retry via ``policy.execute`` (same chunk,
      same shape — one compiled program).
    - OOM: halve the bucket, re-chunk THIS chunk's valid rows, recurse —
      the padded rows are zeros, so dropping them and re-padding at the
      smaller bucket computes the same per-row values (outputs stay
      bit-identical and order-preserving). An OOM at the minimal bucket
      (≤ the mesh data-axis multiple) propagates to apply_batch's
      whole-call fallback.
    """
    import jax

    rows = jax.tree_util.tree_leaves(chunk)[0].shape[0]

    def attempt():
        resilience.inject("device_oom", rows=rows, valid=n_valid)
        resilience.inject("transfer_stall", rows=rows)
        return [(fn(chunk), n_valid)]  # dispatched async; no block here

    try:
        return policy.execute(
            attempt, what=f"chunk dispatch (bucket {rows})",
            on_retry=lambda a, e: health.record(
                health.CHUNK_RETRY, bucket=rows, attempt=a,
                error=type(e).__name__))
    except Exception as e:  # noqa: BLE001 - classified below
        if resilience.classify(e) != resilience.OOM:
            raise
        half = rows // 2
        if half < max(1, multiple):
            raise
        health.record(health.OOM_RECHUNK, bucket=rows, half=half)
        logger.warning(
            "device OOM at bucket %d (%s); re-chunking %d valid "
            "row(s) at bucket %d", rows, e, n_valid, half)
        out: List[Tuple[object, int]] = []
        for sub, sub_valid in iter_batches_tree(
                _valid_rows(chunk, n_valid), half, multiple):
            out.extend(_dispatch_chunk(fn, sub, sub_valid,
                                       multiple, policy))
        return out


# Memoized empty-output templates: (id(fn), element shapes/dtypes) →
# (weakref-to-fn, output element shapes/dtypes + treedef). The fn is held
# WEAKLY with a drop-on-collect callback, so memoization never pins a
# discarded model's jitted closure (and the weights it captures); the
# stored ref also guards against an id() recycled onto a different fn.
# Non-weakref-able callables fall back to a strong ref (rare; bounded by
# the caller's own lifetime management).
_EMPTY_TEMPLATES: Dict[Tuple, Tuple[Callable[[], Any], Any]] = {}
_EMPTY_LOCK = threading.Lock()


def _empty_result(fn: Callable, tree, batch_size: int):
    """Zero-row output matching ``fn``'s output element shapes.

    The shape inference (``jax.eval_shape`` — a full trace) runs once per
    (fn, input element shape/dtype) and is memoized: the output element
    shape does not depend on the batch size, so every later empty call
    rebuilds the zero-row arrays from the cached template. The trace uses
    ``fn.__sparkdl_trace_target__`` when present (``ModelFunction.jitted``'s
    compile-span wrapper exposes it; a dedicated attribute so a caller's
    own functools-wrapped fn is never unwrapped by accident): tracing the
    wrapper itself would record a phantom compile span and hide the real
    first-launch one.
    """
    import weakref

    import jax

    key = (id(fn), element_signature(tree))
    with _EMPTY_LOCK:
        hit = _EMPTY_TEMPLATES.get(key)
    if hit is None or hit[0]() is not fn:
        dummy_in = jax.tree_util.tree_map(
            lambda leaf: jax.ShapeDtypeStruct(
                (batch_size,) + leaf.shape[1:], leaf.dtype), tree)
        dummy = jax.eval_shape(
            getattr(fn, "__sparkdl_trace_target__", fn), dummy_in)
        out_leaves, treedef_out = jax.tree_util.tree_flatten(dummy)
        template = ([(tuple(d.shape[1:]), np.dtype(d.dtype))
                     for d in out_leaves], treedef_out)

        def _drop(_ref, _key=key):
            with _EMPTY_LOCK:
                _EMPTY_TEMPLATES.pop(_key, None)

        try:
            ref: Callable[[], Any] = weakref.ref(fn, _drop)
        except TypeError:  # non-weakref-able callable: strong fallback
            ref = (lambda _fn=fn: _fn)
        with _EMPTY_LOCK:
            _EMPTY_TEMPLATES[key] = (ref, template)
    else:
        template = hit[1]
    elements, treedef_out = template
    return treedef_out.unflatten(
        [np.zeros((0,) + shape, dtype=dtype) for shape, dtype in elements])


def _record_chunk_metrics(chunk, n_valid: int) -> None:
    """Feed the active telemetry scope's bucket-occupancy / padding-waste
    instruments (docs/OBSERVABILITY.md metric catalog). One global read
    when no scope is active."""
    tel = telemetry.active()
    if tel is None:
        return
    import jax

    bucket = jax.tree_util.tree_leaves(chunk)[0].shape[0]
    valid = tel.metrics.counter(telemetry.M_BATCH_ROWS)
    pad = tel.metrics.counter(telemetry.M_BATCH_PAD_ROWS)
    valid.inc(n_valid)
    pad.inc(bucket - n_valid)
    tel.metrics.histogram(telemetry.M_BATCH_BUCKET_ROWS,
                          telemetry.POW2_BOUNDS).observe(bucket)
    total = valid.value + pad.value
    if total:
        tel.metrics.gauge(telemetry.M_PADDING_WASTE).set(
            pad.value / total)


def run_batched(fn: Callable, tree, batch_size: int,
                multiple: int = 1,
                retry_policy: Optional[resilience.RetryPolicy] = None,
                prefetch: int = 2):
    """Apply a fixed-batch device fn over all rows, concatenating outputs.

    ``tree``: one array or a pytree of dim-0-aligned arrays (multi-input
    models). ``fn`` must accept the padded chunk and return a device array
    (or pytree of them) whose dim 0 aligns with the input rows (jit
    specializes per bucket shape). Host chunk staging (the pad copies of
    ``iter_batches_tree``) runs ``prefetch`` chunks ahead on a background
    staging thread (``core.pipeline.DevicePrefetcher``; 0 = inline), and
    JAX's async dispatch overlaps the H2D transfer + device compute of
    chunk k with the staging of chunk k+1: all chunks are dispatched
    before blocking on any result, and the per-bucket outputs are
    concatenated ON DEVICE so the host pays ONE device→host fetch per
    leaf per call instead of one ~100 ms round-trip per bucket. Pad rows
    of a single-bucket call are sliced off ON DEVICE before that fetch —
    a small tail-bucket partition transfers its valid rows only, not up
    to 2× of them at the ~92 MB/s D2H link. ``multiple``: bucket-size
    divisibility constraint (mesh data axis).

    Per-chunk failures are classified (core.resilience): transient errors
    retry with backoff, device OOM re-chunks at a halved bucket (results
    stay bit-identical and order-preserving), fatal errors propagate.
    Staged chunks stay host-resident numpy, so the OOM re-chunk path
    re-pads on the host exactly as before. ``retry_policy=None`` uses
    ``resilience.DEFAULT_INFERENCE_POLICY``.
    """
    import jax

    from sparkdl_tpu.core import pipeline

    policy = (retry_policy if retry_policy is not None
              else resilience.DEFAULT_INFERENCE_POLICY)
    outs = []
    valids = []
    # single-chunk inputs (the dominant engine featurize case: one
    # partition chunk <= batch_size rows) have no k+1 to stage ahead —
    # skip the staging thread entirely, it could only add overhead
    rows = jax.tree_util.tree_leaves(tree)[0].shape[0]
    if rows <= batch_size:
        prefetch = 0
    with pipeline.DevicePrefetcher(
            iter_batches_tree(tree, batch_size, multiple),
            depth=prefetch, name="run_batched") as staged:
        for chunk, n_valid in staged:
            _record_chunk_metrics(chunk, n_valid)
            for out, v in _dispatch_chunk(fn, chunk, n_valid, multiple,
                                          policy):
                outs.append(out)
                valids.append(v)
    if not outs:
        # Preserve the output *element* shape for empty inputs (memoized
        # per (fn, element shape/dtype) — empty partitions in a
        # quarantined stream must not pay repeated tracing).
        return _empty_result(fn, tree, batch_size)

    flat_outs = [jax.tree_util.tree_flatten(o) for o in outs]
    treedef_out = flat_outs[0][1]
    result_leaves = []
    for j in range(len(flat_outs[0][0])):
        leaf_per_batch = [f[0][j] for f in flat_outs]
        if len(leaf_per_batch) == 1:
            # slice pad rows off ON DEVICE before the fetch: a tail-bucket
            # partition transfers only its valid rows over the ~92 MB/s
            # D2H link instead of the full padded bucket (ISSUE 3)
            leaf = leaf_per_batch[0]
            if valids[0] < leaf.shape[0]:
                leaf = leaf[:valids[0]]
            result_leaves.append(np.asarray(leaf))
            continue
        import jax.numpy as jnp

        fetched = np.asarray(jnp.concatenate(leaf_per_batch, axis=0))
        host = []
        off = 0
        for o, v in zip(leaf_per_batch, valids):
            host.append(fetched[off:off + v])
            off += o.shape[0]
        result_leaves.append(np.concatenate(host, axis=0))
    return treedef_out.unflatten(result_leaves)
