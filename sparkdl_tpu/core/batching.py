"""Static-shape batching utilities.

XLA compiles one program per input shape; variable row counts per partition
would retrace endlessly. Everything device-bound therefore runs at a fixed
``batch_size``: partitions are chunked, the tail chunk is zero-padded and
the pad rows dropped after compute. (The reference had the same constraint
implicitly — TF graphs with fixed input sizes; SURVEY.md §7 "Dynamic
shapes".)
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from sparkdl_tpu.core import health, resilience, telemetry

logger = logging.getLogger(__name__)

# Transfer economics of the staging path (r3, measured with true barriers —
# scalar fetched through a jitted reduction; block_until_ready is NOT a
# reliable barrier here, see core/profiling.py):
#   host→device ~47 MB/s regardless of chunking; device→host ~100 ms fixed
#   latency + ~92 MB/s. A chunked device_put + on-device reassembly was
#   tried and measured NO faster (the apparent 1.5 GB/s for small puts was
#   async dispatch, not completed DMA). The levers that DO work: transfer
#   uint8 not float32 (4x), resize to the model input size on the host
#   BEFORE transfer when that shrinks bytes (native batch resizer), and
#   fetch each partition's outputs as ONE device-concatenated array
#   instead of one fetch per bucket (saves the ~100 ms fixed latency per
#   batch).


def pad_batch(arr: np.ndarray, batch_size: int) -> Tuple[np.ndarray, int]:
    """Zero-pad dim 0 up to ``batch_size``; returns (padded, n_valid)."""
    n = arr.shape[0]
    if n == batch_size:
        return arr, n
    if n > batch_size:
        raise ValueError(f"batch of {n} rows exceeds batch_size {batch_size}")
    pad_widths = [(0, batch_size - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_widths), n


def _round_up(value: int, multiple: int) -> int:
    return int(-(-value // multiple) * multiple)


def bucket_size(n: int, batch_size: int, multiple: int = 1,
                min_bucket: int = 8) -> int:
    """Smallest power-of-two bucket ≥ n (capped, rounded up to ``multiple``
    for mesh data-axis divisibility).

    Tail chunks pad to their bucket instead of the full batch_size — a
    32-row partition behind a batch_size=128 transformer transfers 32-ish
    rows, not 128 (4x padding waste measured on the e2e path). Buckets are
    powers of two so compile count stays O(log batch_size).

    The cap is ``batch_size`` rounded up to ``multiple``: rounding AFTER
    capping at the raw batch_size used to return buckets above the cap a
    non-multiple ``batch_size`` implied (e.g. n=40, batch_size=40,
    multiple=16 must give 48 = roundup(40, 16), never more) — the result
    is always ≤ max(roundup(batch_size), roundup(n)).
    """
    b = min_bucket
    while b < n:
        b <<= 1
    cap = batch_size
    if multiple > 1 and cap % multiple:
        cap = _round_up(cap, multiple)
    b = min(b, cap)
    b = max(b, n)  # n > batch_size: bucket covers n (public-helper use)
    if multiple > 1 and b % multiple:
        b = _round_up(b, multiple)
    return b


def iter_batches(arr: np.ndarray, batch_size: int, multiple: int = 1,
                 planner: Optional["BucketPlanner"] = None
                 ) -> Iterator[Tuple[np.ndarray, int]]:
    """Yield (padded_chunk, n_valid) fixed-shape chunks over dim 0; the
    tail chunk pads to its bucket, not full batch_size — the power-of-two
    ladder by default, or ``planner``'s telemetry-tuned ladder."""
    n = arr.shape[0]
    if n == 0:
        return
    for start in range(0, n, batch_size):
        chunk = arr[start:start + batch_size]
        bucket = (planner.plan(len(chunk)) if planner is not None
                  else bucket_size(len(chunk), batch_size, multiple))
        yield pad_batch(chunk, bucket)


def iter_batches_tree(tree, batch_size: int, multiple: int = 1,
                      planner: Optional["BucketPlanner"] = None):
    """``iter_batches`` over a pytree of dim-0-aligned arrays.

    Multi-input models take a dict of arrays sharing the batch dim
    (the reference ``TFTransformer``'s feed-dict analog); every leaf is
    chunked and padded identically.
    """
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    n = leaves[0].shape[0]
    for leaf in leaves[1:]:
        if leaf.shape[0] != n:
            raise ValueError(
                f"multi-input batch dims disagree: {leaf.shape[0]} vs {n}")
    if n == 0:
        return
    for start in range(0, n, batch_size):
        chunk_leaves = []
        n_valid = min(batch_size, n - start)
        bucket = (planner.plan(n_valid) if planner is not None
                  else bucket_size(n_valid, batch_size, multiple))
        for leaf in leaves:
            padded, _ = pad_batch(leaf[start:start + batch_size], bucket)
            chunk_leaves.append(padded)
        yield treedef.unflatten(chunk_leaves), n_valid


def element_signature(tree) -> Tuple:
    """Per-leaf (element shape, dtype) signature of a dim-0-batched pytree.

    The identity under which rows are interchangeable: the executor's
    coalescer only concatenates requests sharing a signature, and the
    empty-output template memoization keys on it.
    """
    import jax

    return tuple((tuple(leaf.shape[1:]), str(leaf.dtype))
                 for leaf in jax.tree_util.tree_leaves(tree))


def _valid_rows(chunk, n_valid: int):
    """Strip pad rows: the original (unpadded) rows of a padded chunk."""
    import jax

    return jax.tree_util.tree_map(lambda leaf: leaf[:n_valid], chunk)


def _dispatch_chunk(fn: Callable, chunk, n_valid: int,
                    multiple: int, policy: resilience.RetryPolicy
                    ) -> List[Tuple[object, int]]:
    """Dispatch one padded chunk with classified retry + OOM re-chunking.

    Returns ``[(device_out, n_valid), ...]`` in row order — one pair
    normally, several when an OOM forced the chunk to re-run as smaller
    sub-chunks. Semantics per failure kind (core.resilience):

    - FATAL: propagate immediately; retrying a shape/dtype error replays it.
    - RETRYABLE: bounded backoff retry via ``policy.execute`` (same chunk,
      same shape — one compiled program).
    - OOM: halve the bucket, re-chunk THIS chunk's valid rows, recurse —
      the padded rows are zeros, so dropping them and re-padding at the
      smaller bucket computes the same per-row values (outputs stay
      bit-identical and order-preserving). An OOM at the minimal bucket
      (≤ the mesh data-axis multiple) propagates to apply_batch's
      whole-call fallback.
    """
    import jax

    rows = jax.tree_util.tree_leaves(chunk)[0].shape[0]

    def attempt():
        resilience.inject("device_oom", rows=rows, valid=n_valid)
        resilience.inject("transfer_stall", rows=rows)
        return [(fn(chunk), n_valid)]  # dispatched async; no block here

    try:
        return policy.execute(
            attempt, what=f"chunk dispatch (bucket {rows})",
            on_retry=lambda a, e: health.record(
                health.CHUNK_RETRY, bucket=rows, attempt=a,
                error=type(e).__name__))
    except Exception as e:  # noqa: BLE001 - classified below
        if resilience.classify(e) != resilience.OOM:
            raise
        half = rows // 2
        if half < max(1, multiple):
            raise
        health.record(health.OOM_RECHUNK, bucket=rows, half=half)
        logger.warning(
            "device OOM at bucket %d (%s); re-chunking %d valid "
            "row(s) at bucket %d", rows, e, n_valid, half)
        out: List[Tuple[object, int]] = []
        for sub, sub_valid in iter_batches_tree(
                _valid_rows(chunk, n_valid), half, multiple):
            out.extend(_dispatch_chunk(fn, sub, sub_valid,
                                       multiple, policy))
        return out


# Memoized empty-output templates: (id(fn), element shapes/dtypes) →
# (weakref-to-fn, output element shapes/dtypes + treedef). The fn is held
# WEAKLY with a drop-on-collect callback, so memoization never pins a
# discarded model's jitted closure (and the weights it captures); the
# stored ref also guards against an id() recycled onto a different fn.
# Non-weakref-able callables fall back to a strong ref (rare; bounded by
# the caller's own lifetime management).
_EMPTY_TEMPLATES: Dict[Tuple, Tuple[Callable[[], Any], Any]] = {}
_EMPTY_LOCK = threading.Lock()


def _empty_result(fn: Callable, tree, batch_size: int):
    """Zero-row output matching ``fn``'s output element shapes.

    The shape inference (``jax.eval_shape`` — a full trace) runs once per
    (fn, input element shape/dtype) and is memoized: the output element
    shape does not depend on the batch size, so every later empty call
    rebuilds the zero-row arrays from the cached template. The trace uses
    ``fn.__sparkdl_trace_target__`` when present (``ModelFunction.jitted``'s
    compile-span wrapper exposes it; a dedicated attribute so a caller's
    own functools-wrapped fn is never unwrapped by accident): tracing the
    wrapper itself would record a phantom compile span and hide the real
    first-launch one.
    """
    import weakref

    import jax

    key = (id(fn), element_signature(tree))
    with _EMPTY_LOCK:
        hit = _EMPTY_TEMPLATES.get(key)
    if hit is None or hit[0]() is not fn:
        dummy_in = jax.tree_util.tree_map(
            lambda leaf: jax.ShapeDtypeStruct(
                (batch_size,) + leaf.shape[1:], leaf.dtype), tree)
        dummy = jax.eval_shape(
            getattr(fn, "__sparkdl_trace_target__", fn), dummy_in)
        out_leaves, treedef_out = jax.tree_util.tree_flatten(dummy)
        template = ([(tuple(d.shape[1:]), np.dtype(d.dtype))
                     for d in out_leaves], treedef_out)

        def _drop(_ref, _key=key):
            with _EMPTY_LOCK:
                _EMPTY_TEMPLATES.pop(_key, None)

        try:
            ref: Callable[[], Any] = weakref.ref(fn, _drop)
        except TypeError:  # non-weakref-able callable: strong fallback
            ref = (lambda _fn=fn: _fn)
        with _EMPTY_LOCK:
            _EMPTY_TEMPLATES[key] = (ref, template)
    else:
        template = hit[1]
    elements, treedef_out = template
    return treedef_out.unflatten(
        [np.zeros((0,) + shape, dtype=dtype) for shape, dtype in elements])


def _record_chunk_metrics(chunk, n_valid: int) -> None:
    """Feed the active telemetry scope's bucket-occupancy / padding-waste
    instruments (docs/OBSERVABILITY.md metric catalog). One global read
    when no scope is active."""
    tel = telemetry.active()
    if tel is None:
        return
    import jax

    bucket = jax.tree_util.tree_leaves(chunk)[0].shape[0]
    valid = tel.metrics.counter(telemetry.M_BATCH_ROWS)
    pad = tel.metrics.counter(telemetry.M_BATCH_PAD_ROWS)
    valid.inc(n_valid)
    pad.inc(bucket - n_valid)
    tel.metrics.histogram(telemetry.M_BATCH_BUCKET_ROWS,
                          telemetry.POW2_BOUNDS).observe(bucket)
    total = valid.value + pad.value
    if total:
        tel.metrics.gauge(telemetry.M_PADDING_WASTE).set(
            pad.value / total)


# ---------------------------------------------------------------------------
# Telemetry-tuned bucket ladder (docs/PERF.md "Launch shaping & precision")
# ---------------------------------------------------------------------------

#: Retune cadence: the ladder is re-solved every N observed launches.
PLANNER_UPDATE_EVERY = 64
#: Hysteresis: a candidate ladder is adopted only when it cuts the
#: predicted pad rows by at least this fraction vs the current ladder —
#: marginal wins never pay a recompile.
PLANNER_HYSTERESIS = 0.10
#: Hard bound on ladder adoptions per planner: with the rung count capped
#: at the power-of-two ladder's length, total compile count stays
#: O(log batch_size) for the process lifetime.
PLANNER_MAX_UPDATES = 8
#: Observed-size histogram bound (distinct sizes kept exactly; partition
#: sizes are highly repetitive in practice).
_PLANNER_MAX_SIZES = 128

_LADDER_STORE_BASENAME = "sparkdl_bucket_ladders.json"


def ladder_store_path() -> Optional[str]:
    """Learned-ladder persistence file, beside the persistent compilation
    cache (``$SPARKDL_COMPILE_CACHE_DIR``): a warm process reloads the
    tuned ladder together with the compiled programs it selected, so the
    retune (and its compiles) are paid once per cluster, not per process.
    None when the cache dir is not configured (no persistence)."""
    import os

    from sparkdl_tpu import COMPILE_CACHE_DIR_ENV

    cache_dir = os.environ.get(COMPILE_CACHE_DIR_ENV)
    if not cache_dir:
        return None
    return os.path.join(cache_dir, _LADDER_STORE_BASENAME)


def _pow2_ladder(batch_size: int, multiple: int, min_bucket: int
                 ) -> Tuple[int, ...]:
    """The blind ladder: every bucket ``bucket_size`` can return for
    n ≤ batch_size. Seeding the planner with it makes a cold planner
    byte-identical to the unplanned path."""
    rungs = set()
    b = min_bucket
    n = 1
    while n <= batch_size:
        rungs.add(bucket_size(n, batch_size, multiple, min_bucket))
        if n == b:
            b <<= 1
        n = min(b, batch_size) if n < batch_size else batch_size + 1
    return tuple(sorted(rungs))


class BucketPlanner:
    """Per-compiled-fn telemetry-tuned bucket ladder.

    Feeds on the same launch-size stream that drives the padding-waste
    gauge and the ``sparkdl.executor.coalesce_rows`` /
    ``coalesce_requests`` histograms (``plan``/``observe`` are called at
    exactly the call sites that feed those instruments), and periodically
    re-solves the ladder to minimize predicted pad rows over the observed
    size distribution. Bounded: at most as many rungs as the power-of-two
    ladder, adoption gated on a ≥ ``PLANNER_HYSTERESIS`` predicted win
    (and at most ``PLANNER_MAX_UPDATES`` adoptions), so compile count
    stays O(log batch_size). When a telemetry scope is active, each
    adoption bumps ``sparkdl.batching.bucket_ladder_update`` and sets the
    ``sparkdl.batching.planner_waste`` gauge to the predicted pad
    fraction under the new ladder. Thread-safe.
    """

    def __init__(self, batch_size: int, multiple: int = 1,
                 min_bucket: int = 8, name: str = "model",
                 update_every: int = PLANNER_UPDATE_EVERY,
                 hysteresis: float = PLANNER_HYSTERESIS,
                 ladder: Optional[Tuple[int, ...]] = None) -> None:
        self.batch_size = int(batch_size)
        self.multiple = max(1, int(multiple))
        self.min_bucket = int(min_bucket)
        self.name = name
        self.update_every = max(1, int(update_every))
        self.hysteresis = float(hysteresis)
        self._lock = threading.Lock()
        self._counts: Dict[int, int] = {}
        self._since_update = 0
        self._updates = 0
        self._cap = bucket_size(self.batch_size, self.batch_size,
                                self.multiple, self.min_bucket)
        base = _pow2_ladder(self.batch_size, self.multiple, self.min_bucket)
        self._ladder: Tuple[int, ...] = (
            tuple(sorted(set(ladder))) if ladder else base)
        # the top rung must cover every admissible n (≤ batch_size)
        if not self._ladder or self._ladder[-1] < self._cap:
            self._ladder = tuple(sorted(set(self._ladder) | {self._cap}))

    # -- lookup ---------------------------------------------------------------

    def ladder(self) -> Tuple[int, ...]:
        with self._lock:
            return self._ladder

    def bucket_for(self, n: int, cap: Optional[int] = None) -> int:
        """Smallest ladder rung ≥ n. ``cap`` below this planner's
        batch_size (a tighter ``coalesce_max_rows``) falls back to the
        blind ladder at that cap — a foreign cap must not graft new
        shapes onto the tuned ladder."""
        if cap is not None and cap < self.batch_size:
            return bucket_size(n, cap, self.multiple, self.min_bucket)
        with self._lock:
            for rung in self._ladder:
                if rung >= n:
                    return rung
        # n above the ladder (public-helper use): cover it
        return bucket_size(n, self.batch_size, self.multiple,
                           self.min_bucket)

    def plan(self, n: int) -> int:
        """``observe`` + ``bucket_for`` — the one-call form the batching
        iterators use per chunk."""
        self.observe(n)
        return self.bucket_for(n)

    # -- learning -------------------------------------------------------------

    def observe(self, n: int) -> None:
        """Record one requested launch of ``n`` valid rows; retune every
        ``update_every`` observations."""
        if n <= 0 or n > self.batch_size:
            return
        retune = False
        with self._lock:
            if len(self._counts) < _PLANNER_MAX_SIZES or n in self._counts:
                self._counts[n] = self._counts.get(n, 0) + 1
            self._since_update += 1
            if (self._since_update >= self.update_every
                    and self._updates < PLANNER_MAX_UPDATES):
                self._since_update = 0
                retune = True
        if retune:
            self._retune()

    def _padded_rows(self, ladder: Tuple[int, ...],
                     counts: Dict[int, int]) -> float:
        total = 0.0
        for n, c in counts.items():
            rung = next((r for r in ladder if r >= n), self._cap)
            total += c * (rung - n)
        return total

    def _retune(self) -> None:
        """Re-solve the ladder over the observed size histogram (exact DP
        over candidate rungs — distinct observed sizes are few), gated on
        hysteresis. Reads the live padding-waste gauge as a cheap trigger:
        when the measured waste is already negligible there is nothing to
        win and no recompile is worth paying."""
        tel = telemetry.active()
        if tel is not None:
            waste = tel.metrics.gauge(telemetry.M_PADDING_WASTE).value
            if waste is not None and waste < 0.02:
                return
        with self._lock:
            counts = dict(self._counts)
            current = self._ladder
            max_rungs = len(_pow2_ladder(self.batch_size, self.multiple,
                                         self.min_bucket))
        if not counts:
            return
        candidate = self._solve(counts, max_rungs)
        cost_now = self._padded_rows(current, counts)
        cost_new = self._padded_rows(candidate, counts)
        if candidate == current or cost_new > (1.0 - self.hysteresis) * cost_now:
            return
        with self._lock:
            self._ladder = candidate
            self._updates += 1
        valid = float(sum(n * c for n, c in counts.items()))
        waste_after = (cost_new / (cost_new + valid)
                       if cost_new + valid else 0.0)
        logger.info("%s: bucket ladder retuned to %s (predicted pad "
                    "fraction %.3f)", self.name, candidate, waste_after)
        if telemetry.active() is not None:
            telemetry.count(telemetry.M_BUCKET_LADDER_UPDATE)
            telemetry.gauge_set(telemetry.M_PLANNER_WASTE, waste_after)
        _persist_ladder(self)

    def _solve(self, counts: Dict[int, int], max_rungs: int
               ) -> Tuple[int, ...]:
        """Pick ≤ max_rungs rungs minimizing total pad rows over the
        observed sizes. Candidates are the observed sizes rounded up to
        the mesh multiple, plus the cap (which is always a rung so any
        n ≤ batch_size stays coverable). Exact DP: for S candidates,
        O(S² · max_rungs) — S is small by construction."""
        cands = sorted({min(_round_up(n, self.multiple), self._cap)
                        for n in counts} | {self._cap})
        sizes = sorted(counts)
        # weight[j] = rows observed at size ≤ cands[j] but > cands[j-1]
        # cost(i, j): pad rows of sizes in (cands[i], cands[j]] padded to
        # cands[j] (sizes ≤ cands[i] are covered by a lower rung).
        INF = float("inf")

        def seg_cost(lo: int, hi: int) -> float:
            # pad-to-hi cost of every observed size in (lo, hi]
            return sum(c * (hi - n) for n, c in counts.items()
                       if lo < n <= hi)

        S = len(cands)
        # dp[k][j]: min cost covering all sizes ≤ cands[j] with k rungs,
        # the highest being cands[j]
        dp = [[INF] * S for _ in range(max_rungs + 1)]
        choice: Dict[Tuple[int, int], int] = {}
        for j in range(S):
            dp[1][j] = seg_cost(0, cands[j])
        for k in range(2, max_rungs + 1):
            for j in range(S):
                best, arg = dp[k - 1][j], None  # k-1 rungs already enough
                for i in range(j):
                    c = dp[k - 1][i] + seg_cost(cands[i], cands[j])
                    if c < best:
                        best, arg = c, i
                dp[k][j] = best
                if arg is not None:
                    choice[(k, j)] = arg
        # top rung must be the cap rung (last candidate)
        j = S - 1
        k = max_rungs
        rungs = [cands[j]]
        while k > 1:
            arg = choice.get((k, j))
            if arg is None:
                k -= 1
                continue
            j = arg
            rungs.append(cands[j])
            k -= 1
        return tuple(sorted(set(rungs)))

    # -- persistence ----------------------------------------------------------

    def _store_key(self) -> str:
        return f"{self.name}|{self.batch_size}|{self.multiple}"


def _persist_ladder(planner: BucketPlanner) -> None:
    """Merge this planner's ladder into the store file (atomic replace;
    concurrent writers race whole-file, last wins — the ladder is a cache,
    not a source of truth)."""
    import json
    import os

    path = ladder_store_path()
    if path is None:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        doc = {"version": 1, "ladders": {}}
        try:
            with open(path) as f:
                loaded = json.load(f)
            if isinstance(loaded, dict) and loaded.get("version") == 1:
                doc = loaded
        except (OSError, ValueError):
            pass
        doc.setdefault("ladders", {})[planner._store_key()] = \
            list(planner.ladder())
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except OSError as e:  # persistence is best-effort
        logger.warning("could not persist bucket ladder to %s: %s", path, e)


def _load_ladder(name: str, batch_size: int, multiple: int
                 ) -> Optional[Tuple[int, ...]]:
    import json

    path = ladder_store_path()
    if path is None:
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
        rungs = doc.get("ladders", {}).get(f"{name}|{batch_size}|{multiple}")
        if rungs and all(isinstance(r, int) and r > 0 for r in rungs):
            return tuple(sorted(set(rungs)))
    except (OSError, ValueError, AttributeError):
        pass
    return None


# Process-wide planner registry: the executor's coalesced launches and the
# chunked apply_batch path share one planner per (model, batch_size,
# multiple), so both feed (and benefit from) the same learned ladder.
_PLANNERS: Dict[Tuple, BucketPlanner] = {}
_PLANNER_LOCK = threading.Lock()


def planner_for(name: str, batch_size: int, multiple: int = 1,
                min_bucket: int = 8) -> BucketPlanner:
    """The shared planner for one (model name, batch_size, multiple)
    ladder; created seeded with the persisted ladder when one exists."""
    key = (name, int(batch_size), int(multiple))
    with _PLANNER_LOCK:
        planner = _PLANNERS.get(key)
    if planner is not None:
        return planner
    # persisted-ladder file I/O stays OUTSIDE the lock; two racers build
    # equivalent planners and setdefault keeps exactly one
    planner = BucketPlanner(batch_size, multiple, min_bucket=min_bucket,
                            name=name,
                            ladder=_load_ladder(name, batch_size, multiple))
    with _PLANNER_LOCK:
        return _PLANNERS.setdefault(key, planner)


def default_planner(name: str, batch_size: int, multiple: int = 1
                    ) -> Optional[BucketPlanner]:
    """``planner_for`` gated on ``EngineConfig.bucket_ladder``: None under
    ``"pow2"`` (the escape hatch restores the blind ladder everywhere).
    Core stays importable without the engine — no engine, no knob, tuned
    by default."""
    try:
        from sparkdl_tpu.engine.dataframe import EngineConfig
        mode = EngineConfig.bucket_ladder
    except ImportError:  # pragma: no cover - engine-less deployments
        mode = "tuned"
    if mode != "tuned":
        return None
    return planner_for(name, batch_size, multiple)


def reset_planners() -> None:
    """Drop every learned ladder (test/bench isolation)."""
    with _PLANNER_LOCK:
        _PLANNERS.clear()


def run_batched(fn: Callable, tree, batch_size: int,
                multiple: int = 1,
                retry_policy: Optional[resilience.RetryPolicy] = None,
                prefetch: int = 2,
                planner: Optional[BucketPlanner] = None):
    """Apply a fixed-batch device fn over all rows, concatenating outputs.

    ``tree``: one array or a pytree of dim-0-aligned arrays (multi-input
    models). ``fn`` must accept the padded chunk and return a device array
    (or pytree of them) whose dim 0 aligns with the input rows (jit
    specializes per bucket shape). Host chunk staging (the pad copies of
    ``iter_batches_tree``) runs ``prefetch`` chunks ahead on a background
    staging thread (``core.pipeline.DevicePrefetcher``; 0 = inline), and
    JAX's async dispatch overlaps the H2D transfer + device compute of
    chunk k with the staging of chunk k+1: all chunks are dispatched
    before blocking on any result, and the per-bucket outputs are
    concatenated ON DEVICE so the host pays ONE device→host fetch per
    leaf per call instead of one ~100 ms round-trip per bucket. Pad rows
    of a single-bucket call are sliced off ON DEVICE before that fetch —
    a small tail-bucket partition transfers its valid rows only, not up
    to 2× of them at the ~92 MB/s D2H link. ``multiple``: bucket-size
    divisibility constraint (mesh data axis).

    Per-chunk failures are classified (core.resilience): transient errors
    retry with backoff, device OOM re-chunks at a halved bucket (results
    stay bit-identical and order-preserving), fatal errors propagate.
    Staged chunks stay host-resident numpy, so the OOM re-chunk path
    re-pads on the host exactly as before. ``retry_policy=None`` uses
    ``resilience.DEFAULT_INFERENCE_POLICY``.
    """
    import jax

    from sparkdl_tpu.core import pipeline

    policy = (retry_policy if retry_policy is not None
              else resilience.DEFAULT_INFERENCE_POLICY)
    outs = []
    valids = []
    # single-chunk inputs (the dominant engine featurize case: one
    # partition chunk <= batch_size rows) have no k+1 to stage ahead —
    # skip the staging thread entirely, it could only add overhead
    rows = jax.tree_util.tree_leaves(tree)[0].shape[0]
    if rows <= batch_size:
        prefetch = 0
    with pipeline.DevicePrefetcher(
            iter_batches_tree(tree, batch_size, multiple, planner=planner),
            depth=prefetch, name="run_batched") as staged:
        for chunk, n_valid in staged:
            _record_chunk_metrics(chunk, n_valid)
            for out, v in _dispatch_chunk(fn, chunk, n_valid, multiple,
                                          policy):
                outs.append(out)
                valids.append(v)
    if not outs:
        # Preserve the output *element* shape for empty inputs (memoized
        # per (fn, element shape/dtype) — empty partitions in a
        # quarantined stream must not pay repeated tracing).
        return _empty_result(fn, tree, batch_size)

    flat_outs = [jax.tree_util.tree_flatten(o) for o in outs]
    treedef_out = flat_outs[0][1]
    result_leaves = []
    for j in range(len(flat_outs[0][0])):
        leaf_per_batch = [f[0][j] for f in flat_outs]
        if len(leaf_per_batch) == 1:
            # slice pad rows off ON DEVICE before the fetch: a tail-bucket
            # partition transfers only its valid rows over the ~92 MB/s
            # D2H link instead of the full padded bucket (ISSUE 3)
            leaf = leaf_per_batch[0]
            if valids[0] < leaf.shape[0]:
                leaf = leaf[:valids[0]]
            result_leaves.append(np.asarray(leaf))
            continue
        import jax.numpy as jnp

        fetched = np.asarray(jnp.concatenate(leaf_per_batch, axis=0))
        host = []
        off = 0
        for o, v in zip(leaf_per_batch, valids):
            host.append(fetched[off:off + v])
            off += o.shape[0]
        result_leaves.append(np.concatenate(host, axis=0))
    return treedef_out.unflatten(result_leaves)
