"""Tracing / profiling subsystem (SURVEY.md §5.1).

The reference had no in-tree profiling (users hand-instrumented Spark UI /
TF timelines). TPU-native equivalent, three layers:

1. **Phase timers** — always-on, ~100ns wall-clock accumulators around the
   host pipeline phases (decode, stage, device execution). Read with
   ``phase_stats()``; they answer "is the MXU starved by the host?" without
   a trace.
2. **Trace annotations** — ``annotate("phase")`` adds a named span to any
   captured ``jax.profiler`` trace (and feeds the phase timers).
3. **Trace capture** — ``maybe_trace()`` wraps a block in
   ``jax.profiler.trace(dir)`` when ``SPARKDL_PROFILE_DIR`` is set, so any
   workload (bench.py, a transform, a fit) can be traced without code
   changes. Verified working over the Axon PJRT tunnel (r3): the captured
   ``.trace.json.gz`` attributes per-fusion device time.

Timing methodology note (r3, measured): under the remote PJRT tunnel a
*cross-dispatch* ``block_until_ready`` is NOT a reliable completion
barrier — independently dispatched executions can report ready while
compute is still in flight (measured 8192-matmul chains "completing" at
86,000 TFLOPS). In-program loops (``lax.fori_loop`` with a loop-carried
dependence) + a scalar ``device_get`` are reliable; bench.py uses exactly
that.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Dict, Iterator, Optional

from sparkdl_tpu.core import telemetry

_lock = threading.Lock()
_phase_totals: Dict[str, float] = {}
_phase_counts: Dict[str, int] = {}

PROFILE_DIR_ENV = "SPARKDL_PROFILE_DIR"

# Canonical phase names for the async input pipeline (core/pipeline.py).
# HOST_WAIT is the starvation timer: seconds the device-driving thread
# spent waiting for the staging thread to deliver a batch. With the
# pipeline overlapped, host ETL phases (sparkdl.decode / sparkdl.stage /
# sparkdl.stage_batch) accumulate on the STAGING thread concurrently with
# sparkdl.train_step on the main thread — phase totals can legitimately
# sum past wall-clock; HOST_WAIT is the serial remainder the host still
# costs the device. DEVICE_SYNC times the deferred step-counter barriers
# (Trainer.fit sync points), i.e. real device execution the host waited
# out, where the pre-pipeline sparkdl.train_step span folded dispatch and
# execution together.
HOST_WAIT = "sparkdl.host_wait"
STAGE_BATCH = "sparkdl.stage_batch"
DEVICE_SYNC = "sparkdl.device_sync"

# Host ETL phases whose time the pipeline can hide behind device compute
# (used by overlap accounting: bench.py's overlap_ratio).
HOST_ETL_PHASES = ("sparkdl.decode", "sparkdl.stage", STAGE_BATCH,
                   "sparkdl.host_stage", "sparkdl.host_resize")


@contextlib.contextmanager
def annotate(name: str, **attributes: Any) -> Iterator[None]:
    """Named span: feeds phase timers, any active profiler trace, and —
    when a ``core.telemetry`` scope is active — the telemetry tracer
    (ambient-parented, so existing phase names become correlated spans
    for free). ``attributes`` ride on the telemetry span only; the
    phase timers stay name-keyed aggregates."""
    import jax.profiler

    t0 = time.perf_counter()
    with telemetry.span(name, **attributes):
        with jax.profiler.TraceAnnotation(name):
            yield
    dt = time.perf_counter() - t0
    with _lock:
        _phase_totals[name] = _phase_totals.get(name, 0.0) + dt
        _phase_counts[name] = _phase_counts.get(name, 0) + 1


def add_phase_time(name: str, seconds: float, count: int = 1) -> None:
    """Feed a phase timer directly (no span) — for waits measured by the
    async pipeline where a TraceAnnotation per queue-get would be noise."""
    with _lock:
        _phase_totals[name] = _phase_totals.get(name, 0.0) + seconds
        _phase_counts[name] = _phase_counts.get(name, 0) + count


def overlap_stats() -> Dict[str, float]:
    """Overlap accounting for the async input pipeline.

    ``host_etl_s``: host decode/stage seconds (the work the pipeline can
    hide). ``host_wait_s``: seconds the device-driving thread actually
    waited on the host (starvation). ``overlap_ratio``: fraction of host
    ETL hidden behind device compute — 1.0 means the host was never the
    bottleneck, 0.0 means fully serial (every ETL second stalled the
    device, the pre-pipeline behavior).
    """
    stats = phase_stats()
    etl = sum(stats[p]["total_s"] for p in HOST_ETL_PHASES if p in stats)
    wait = stats.get(HOST_WAIT, {}).get("total_s", 0.0)
    ratio = 1.0 if etl <= 0 else max(0.0, min(1.0, 1.0 - wait / etl))
    return {"host_etl_s": etl, "host_wait_s": wait, "overlap_ratio": ratio}


def phase_stats(reset: bool = False) -> Dict[str, Dict[str, float]]:
    """{phase: {total_s, count, mean_s}} accumulated since last reset."""
    with _lock:
        out = {
            name: {
                "total_s": total,
                "count": _phase_counts[name],
                "mean_s": total / _phase_counts[name],
            }
            for name, total in _phase_totals.items()
        }
        if reset:
            _phase_totals.clear()
            _phase_counts.clear()
    return out


def reset_phase_stats() -> None:
    phase_stats(reset=True)


@contextlib.contextmanager
def maybe_trace(trace_dir: Optional[str] = None) -> Iterator[bool]:
    """Capture a jax.profiler trace when enabled, else no-op.

    Enabled when ``trace_dir`` is passed or ``SPARKDL_PROFILE_DIR`` is set.
    Yields whether tracing is active.
    """
    target = trace_dir or os.environ.get(PROFILE_DIR_ENV)
    if not target:
        yield False
        return
    import jax.profiler

    with jax.profiler.trace(target):
        yield True
