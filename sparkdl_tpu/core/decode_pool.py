"""Multi-process host decode pool (ISSUE 9 tentpole).

The device featurizes 9.5k-23.6k images/s/chip but every e2e
files→decode→featurize bench sat at ~94 images/s with ``sparkdl.decode``
the dominant host phase (BENCH_r05): JPEG decode on the PIL fallback is
CPU- and GIL-bound Python, so the engine's partition *threads* cannot
parallelize it. This module is the tf.data/DALI-style parallel-ingest
stage rebuilt host-side: ``EngineConfig.decode_workers`` spawn-context
worker processes fan the image blobs of a partition out, decode to HWC
uint8, and hand the pixels back through POSIX shared memory — the
multi-MB decoded arrays never travel through a pickle pipe; only the
(small) compressed blobs go out and (tiny) shape metadata comes back.

Design points:

- **Spawn, never fork**: the parent owns a live JAX/PJRT runtime; a
  forked child inheriting device handles is undefined behavior. Workers
  are ``multiprocessing.get_context("spawn")`` processes that import only
  the image codec stack (``sparkdl_tpu.core`` is lazy — no jax import,
  ~0.2 s startup per worker, no device footprint).
- **Order-preserving**: a :meth:`DecodePool.decode` call slices its blob
  list into contiguous chunks, fans the chunks out, and reassembles
  results by slice position — per-blob decode-time variance reorders
  nothing.
- **Bit-identical**: workers run the exact inline decoder
  (``imageIO.decodePoolChunk`` — the same ONE native threaded batch
  call per fixed-geometry chunk, the same PIL fallback), fault
  injection + health accounting stay in the SUBMITTING process, and an
  exception the inline path would raise (an unsupported channel count)
  ships back typed and re-raises at the submitting call site instead of
  degrading to null rows — pool on/off produces identical rows,
  identical ``decode_degraded`` events, and identical failures.
  ``decode_workers=0`` (default) never touches this module.
- **Crash-tolerant**: a worker process dying (including the armed
  ``decode_pool_worker_crash`` injection point, which makes the worker
  ``os._exit(1)`` mid-task) is detected by the waiters' poll, the worker
  is respawned (one ``decode_pool_respawn`` health event per death), and
  every possibly-lost chunk is resubmitted; a chunk that dies
  :data:`_MAX_ATTEMPTS` times fails with
  :class:`~sparkdl_tpu.core.resilience.DecodeWorkerLost` — classified
  RETRYABLE, so the engine's supervised task retry replays the partition.
- **Bounded**: at most ``EngineConfig.decode_pool_inflight`` chunks
  (default ``2 × workers``) are in flight pool-wide — host memory for
  decoded-but-unconsumed pixels stays O(inflight × chunk), and a fast
  submitter backpressures instead of ballooning the task queue.
- **Clean shutdown**: :meth:`DecodePool.close` (ctx-manager /
  ``__del__`` safety net) poisons and joins every worker, drains every
  result pipe to EOF so every orphaned shared-memory segment is
  unlinked, stops the collector thread, and fails mid-stream waiters —
  no leaked process, no leaked segment.
- **Observable**: a ``sparkdl.decode_pool`` span per decode call (parents
  under the calling partition task's trace), a pool queue-depth gauge, a
  workers-busy gauge, and a per-blob decode-latency histogram
  (chunk-amortized, measured in the worker, shipped with the result
  metadata).

Result transport: each worker owns a PRIVATE result pipe (single
writer — no shared result-queue lock a process killed mid-delivery
could die holding; the parent sees the death as EOF) multiplexed by
one collector thread via ``multiprocessing.connection.wait``; a reaped
worker's pipe is retained until drained to EOF so buffered results
(and their shared-memory segments) are never dropped unadopted.

Shared-memory lifecycle: the WORKER creates one segment per chunk,
packs the decoded arrays back-to-back, unregisters the segment from its
own ``resource_tracker`` (ownership transfers with the message) and
closes its mapping; the parent's collector thread attaches, copies each
array out (the one copy the batch-stacking consumer needs anyway), then
closes **and unlinks**. A result arriving for an already-resolved or
abandoned chunk (crash resubmission races, close mid-stream) is adopted
the same way before being dropped, so segments cannot leak whichever
side wins a race.

Docs: docs/PERF.md "Parallel host ingest"; metric catalog rows in
docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import atexit
import itertools
import logging
import multiprocessing as mp
import os
import re
import threading
import time
from multiprocessing import resource_tracker, shared_memory
from queue import Empty
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sparkdl_tpu.core import health, resilience, telemetry

logger = logging.getLogger(__name__)

# One spawn context for every pool (module-level so the thread-lifecycle
# analyzer rule can resolve `_MP_CTX.Process(...)` as a process factory).
_MP_CTX = mp.get_context("spawn")

# Waiter/submitter poll granularity: bounds worker-crash detection
# latency without a dedicated monitor thread.
_WAIT_POLL_S = 0.05
# Blobs per worker task: small enough that unequal per-blob decode times
# balance across workers, large enough to amortize the queue round trip.
_MAX_CHUNK = 32
# Total tries per chunk across worker deaths before the chunk fails with
# a (RETRYABLE) DecodeWorkerLost.
_MAX_ATTEMPTS = 3

# Idle-worker orphan watch: a worker blocked on its task queue wakes this
# often to check whether its owner (the submitting parent) still exists.
# A kill -9'd parent can never deliver the poison pill, so reparenting is
# the worker's only death signal — without it every orphaned worker
# lingers forever.
_ORPHAN_POLL_S = 5.0

# Run-scoped shared-memory naming: sdlshm_<ownerpid>_<workerpid>_<seq>
# (all hex). Embedding the OWNER pid in the name is what makes leaked
# segments attributable — a kill -9'd run's in-flight segments carry a
# dead pid, and the next pool startup sweeps them (ISSUE 11 satellite).
_SHM_PREFIX = "sdlshm"
_SHM_DIR = "/dev/shm"
_SHM_NAME_RE = re.compile(
    rf"^{_SHM_PREFIX}_([0-9a-f]+)_[0-9a-f]+_[0-9a-f]+$")
_shm_counter = itertools.count(1)

# True inside a spawned worker (set by _worker_main): a worker must never
# route its own decodes back into a pool (and EngineConfig in the fresh
# interpreter defaults to decode_workers=0 anyway — belt and braces).
_IN_WORKER = False


def _create_segment(owner_pid: int, size: int) -> shared_memory.SharedMemory:
    """A run-scoped segment named ``sdlshm_<ownerpid>_<workerpid>_<seq>``
    so :func:`sweep_orphaned_segments` can attribute (and reclaim) the
    segments a kill -9'd owner left behind. A name collision (pid reuse
    against a stale leftover) just advances the sequence number."""
    while True:
        name = (f"{_SHM_PREFIX}_{owner_pid:x}_{os.getpid():x}_"
                f"{next(_shm_counter):x}")
        try:
            return shared_memory.SharedMemory(name=name, create=True,
                                              size=size)
        except FileExistsError:  # stale leftover from a reused pid
            continue


def _pack_result(arrays: Sequence[Optional[np.ndarray]],
                 decode_s: Sequence[float],
                 owner_pid: int) -> Dict[str, Any]:
    """Worker-side: pack decoded HWC uint8 arrays into ONE shared-memory
    segment; the queue message carries only names/shapes/offsets."""
    meta: Dict[str, Any] = {
        "shapes": [None if a is None else tuple(a.shape) for a in arrays],
        "offsets": [None] * len(arrays),
        "decode_s": list(decode_s),
        "shm": None,
    }
    total = sum(a.nbytes for a in arrays if a is not None)
    if not total:
        return meta
    seg = _create_segment(owner_pid, total)
    try:
        off = 0
        for i, a in enumerate(arrays):
            if a is None:
                continue
            a = np.ascontiguousarray(a, dtype=np.uint8)
            dst = np.ndarray(a.shape, dtype=np.uint8, buffer=seg.buf,
                             offset=off)
            np.copyto(dst, a)
            meta["offsets"][i] = off
            off += a.nbytes
        meta["shm"] = seg.name
    finally:
        try:
            # ownership transfers to the parent with the result message:
            # without this, the worker's resource_tracker would warn (or
            # double-unlink) at worker exit for a segment the parent owns
            resource_tracker.unregister(seg._name, "shared_memory")
        except Exception:  # pragma: no cover - tracker API drift
            pass
        seg.close()
    return meta


def _adopt_result(meta: Dict[str, Any]) -> List[Optional[np.ndarray]]:
    """Parent-side: attach the chunk's segment, copy the packed region
    out in ONE memcpy, then close AND unlink — the segment's life ends
    here regardless of whether a waiter still wants the arrays.

    The returned arrays are consecutive views over that single flat
    uint8 buffer (``_pack_result`` packs them gap-free), which the
    columnar plane (``imageIO.imageArraysToStructColumn``) detects and
    wraps zero-copy into an Arrow binary child — so a decoded chunk
    costs exactly one copy between shm and the device transfer."""
    shapes = meta["shapes"]
    arrays: List[Optional[np.ndarray]] = [None] * len(shapes)
    name = meta.get("shm")
    if name is None:
        return arrays
    seg = shared_memory.SharedMemory(name=name)
    try:
        end = 0
        for shape, off in zip(shapes, meta["offsets"]):
            if shape is not None:
                end = max(end, off + int(np.prod(shape)))
        flat = np.frombuffer(seg.buf, dtype=np.uint8, count=end).copy()
        for i, shape in enumerate(shapes):
            if shape is None:
                continue
            off = meta["offsets"][i]
            arrays[i] = flat[off:off + int(np.prod(shape))].reshape(shape)
    finally:
        seg.close()
        try:
            seg.unlink()
        except FileNotFoundError:  # pragma: no cover - double-free race
            pass
    return arrays


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:  # alive, owned by someone else
        return True
    return True


def sweep_orphaned_segments() -> int:
    """Unlink decode-pool shm segments whose embedded owner pid is dead.

    Normal runs adopt-and-unlink every segment (and close() drains the
    stragglers), but a kill -9'd owner leaks its in-flight segments in
    /dev/shm forever. Pool startup calls this; it only ever touches
    names matching this module's ``sdlshm_`` scheme with a dead owner,
    so concurrent runs (live owners) are untouched, and unlink races
    between two sweepers are benign. Returns the number reclaimed.
    """
    try:
        entries = os.listdir(_SHM_DIR)
    except OSError:  # platform without /dev/shm: nothing to sweep
        return 0
    swept = 0
    for entry in entries:
        m = _SHM_NAME_RE.match(entry)
        if m is None or _pid_alive(int(m.group(1), 16)):
            continue
        try:
            os.unlink(os.path.join(_SHM_DIR, entry))
        except OSError:  # lost the race to another sweeper
            continue
        swept += 1
        logger.warning("decode pool: swept orphaned shm segment %s "
                       "(owner dead)", entry)
    if swept:
        health.record(health.DECODE_POOL_SHM_SWEPT, n=swept)
    return swept


def _worker_main(tasks: Any, conn: Any, owner_pid: int,
                 clock_conn: Any = None) -> None:
    """Worker process loop: decode chunks until the ``None`` poison pill.

    Runs in a fresh spawn interpreter: ``sparkdl_tpu.core`` is lazy, so
    the import below pulls only numpy/pyarrow/PIL and the native loader
    — never jax. Undecodable blobs degrade per row inside
    ``decodePoolChunk`` (``None`` rows); exceptions the INLINE decoder
    would raise (bad channel counts) ship back as a typed chunk error
    and re-raise in the submitting process — pool on/off fail
    identically. Results travel over this worker's PRIVATE ``conn``
    (one writer per pipe — no shared queue lock a dying worker could
    wedge); only the armed ``decode_pool_worker_crash`` marker kills
    the process.

    ``owner_pid`` is the spawning parent: it names this worker's shm
    segments (sweepability), and an idle worker polls for its death —
    a kill -9'd parent never sends the poison pill, so reparenting
    (``os.getppid() != owner_pid``) is the exit signal that keeps
    orphaned workers from living forever.
    """
    global _IN_WORKER
    _IN_WORKER = True
    from sparkdl_tpu.image import imageIO  # one heavy import per worker

    # one NTP-style round trip against the parent's perf_counter_ns so
    # chunk spans measured here land on the coordinator's timeline
    # (offset 0 if the parent never answers — see clock_handshake)
    clock_offset = 0
    if clock_conn is not None:
        clock_offset = telemetry.clock_handshake(clock_conn)
        clock_conn.close()

    while True:
        try:
            task = tasks.get(timeout=_ORPHAN_POLL_S)
        except Empty:
            if os.getppid() != owner_pid:  # orphaned: owner died hard
                conn.close()
                return
            continue
        if task is None:
            conn.close()
            return
        task_id, blobs, target_size, channels, crash, ctx = task
        if crash:
            os._exit(1)  # injected worker crash: die without cleanup
        t0_ns = time.perf_counter_ns()
        try:
            arrays = imageIO.decodePoolChunk(
                blobs, target_size=target_size, channels=channels)
        # sparkdl: allow(broad-retry): not a retry — the error ships to the submitting process and re-raises there with inline-path semantics
        except Exception as e:  # noqa: BLE001 - re-raised parent-side
            conn.send((task_id, {"error": (type(e).__name__, str(e))}))
            continue
        t1_ns = time.perf_counter_ns()
        per_blob = (t1_ns - t0_ns) / 1e9 / max(1, len(blobs))
        result = _pack_result(arrays, [per_blob] * len(blobs), owner_pid)
        if ctx is not None:
            # a ctx rides the task only when the submitter had an active
            # trace; timestamps rebased onto the parent's clock here so
            # the adopting side never needs this worker's offset
            result["span"] = telemetry.remote_span(
                telemetry.SPAN_DECODE_CHUNK,
                t0_ns + clock_offset, t1_ns + clock_offset,
                blobs=len(blobs))
        conn.send((task_id, result))


class _Chunk:
    """One fan-out unit: a contiguous slice of a decode call's blobs,
    plus everything needed to resubmit it after a worker crash."""

    __slots__ = ("blobs", "target_size", "channels", "ctx", "event",
                 "result", "error", "attempts")

    def __init__(self, blobs: List[Optional[bytes]], target_size,
                 channels, ctx=None) -> None:
        self.blobs = blobs
        self.target_size = target_size
        self.channels = channels
        self.ctx = ctx  # submitter's span context; None when tracing off
        self.event = threading.Event()
        self.result: Optional[List[Optional[np.ndarray]]] = None
        self.error: Optional[BaseException] = None
        self.attempts = 1


def _rebuild_error(type_name: str, msg: str) -> BaseException:
    """Reconstruct a worker-side exception in the parent, preserving the
    builtin type so ``resilience.classify`` sees what the inline path
    would have raised (a ValueError stays FATAL across the process
    boundary)."""
    import builtins

    etype = getattr(builtins, type_name, None)
    if isinstance(etype, type) and issubclass(etype, Exception):
        try:
            return etype(msg)
        except Exception:  # pragma: no cover - exotic ctor signature
            pass
    return RuntimeError(f"{type_name}: {msg}")


class _Worker:
    """One worker process plus its PRIVATE task queue, its PRIVATE
    result pipe, and the ids of the chunks dispatched to it.

    Private channels per worker (instead of shared queues) buy three
    guarantees: a crashed worker's in-queue tasks die WITH it (they are
    precisely re-dispatched from ``assigned`` — no blanket
    resubmission, no stale tasks outliving the crash); a process killed
    while blocked in ``Queue.get`` — which holds the queue's reader
    lock — wedges only its own abandoned queue, never its siblings';
    and a process killed MID-RESULT-DELIVERY corrupts only its own pipe
    (each pipe has exactly one writer, the worker's main thread — there
    is no shared result-queue write lock to die holding), which the
    collector sees as EOF and the reaper turns into a respawn."""

    __slots__ = ("proc", "queue", "conn", "clock", "assigned")

    def __init__(self, proc: Any, queue: Any, conn: Any,
                 clock: Any) -> None:
        self.proc = proc
        self.queue = queue
        self.conn = conn  # parent's read end; None once EOF-drained
        self.clock = clock  # clock-handshake pipe; None once answered
        self.assigned: set = set()


class DecodePool:
    """N spawn-context decode worker processes, each with a PRIVATE task
    queue in and a PRIVATE result pipe back (multiplexed by one
    collector thread — see the module docstring's crash-safety
    rationale; there is no shared channel a dying worker can wedge).

    ::

        with DecodePool(workers=8) as pool:
            arrays = pool.decode(blobs, target_size=(224, 224), channels=3)

    ``decode`` is thread-safe: concurrent partition tasks share the pool
    (and the ``decode_pool_inflight`` backpressure bound). Callers
    normally never construct one — :func:`maybe_pool` manages the
    process-wide instance from ``EngineConfig.decode_workers``.
    """

    def __init__(self, workers: int,
                 inflight: Optional[int] = None) -> None:
        if workers < 1:
            raise ValueError(f"decode pool needs >= 1 worker, got {workers}")
        self.workers = int(workers)
        self.inflight = int(inflight) if inflight else 2 * self.workers
        if self.inflight < 1:
            raise ValueError(
                f"decode_pool_inflight must be >= 1, got {inflight!r}")
        # reclaim what a previous kill -9'd run left behind BEFORE this
        # run starts creating its own segments
        sweep_orphaned_segments()
        self._lock = threading.Lock()
        self._pending: Dict[int, _Chunk] = {}
        self._ids = itertools.count(1)
        self._sem = threading.BoundedSemaphore(self.inflight)
        self._closed = False
        self.respawns = 0  # worker deaths survived (tests/debugging)
        # parent-internal wakeup pipe: nudges the collector out of its
        # connection.wait when the conn list changes (respawn) or the
        # pool closes
        self._wake_r, self._wake_w = _MP_CTX.Pipe(duplex=False)
        # conns of reaped (replaced) workers, kept until the collector
        # drains them to EOF: a dead worker may have delivered results
        # — with live shared-memory names — that are still buffered in
        # its pipe, and dropping the conn would leak the segments
        self._retired_conns: List[Any] = []
        # clock pipes of reaped workers: drained to EOF by the collector
        # (a worker may die before pinging, or with a ping buffered)
        self._retired_clocks: List[Any] = []
        # incremental append (not a comprehension): a spawn failing at
        # worker k must leave workers 0..k-1 reachable so the cleanup
        # below can poison/join them instead of leaking live processes
        self._workers: List[_Worker] = []
        try:
            for i in range(self.workers):
                self._workers.append(self._spawn(i))
        except BaseException:
            for worker in self._workers:
                worker.queue.put(None)
                worker.proc.join(timeout=10.0)
                worker.queue.cancel_join_thread()
                worker.queue.close()
                worker.conn.close()
                if worker.clock is not None:
                    worker.clock.close()
            self._wake_r.close()
            self._wake_w.close()
            self._closed = True
            raise
        self._collector = threading.Thread(
            target=self._collect, name="sparkdl-decode-pool-collector",
            daemon=True)
        self._collector.start()

    def _spawn(self, index: int) -> _Worker:
        queue = _MP_CTX.Queue()
        recv_conn, send_conn = _MP_CTX.Pipe(duplex=False)
        # dedicated duplex pipe for the one-shot clock handshake: the
        # collector answers the worker's ping with perf_counter_ns
        clock_parent, clock_child = _MP_CTX.Pipe()
        proc = _MP_CTX.Process(
            target=_worker_main,
            args=(queue, send_conn, os.getpid(), clock_child),
            name=f"sparkdl-decode-{index}", daemon=True)
        proc.start()
        # drop the parent's copy of the write end: the worker owns the
        # only writer, so worker death shows up as EOF on recv_conn
        send_conn.close()
        clock_child.close()
        return _Worker(proc, queue, recv_conn, clock_parent)

    @property
    def closed(self) -> bool:
        return self._closed

    # -- the public decode call ----------------------------------------------

    def decode(self, blobs: Sequence[Optional[bytes]],
               target_size: Optional[Tuple[int, int]] = None,
               channels: Optional[int] = None
               ) -> List[Optional[np.ndarray]]:
        """Decode ``blobs`` to HWC uint8 arrays, in submission order.

        ``None``/undecodable blobs come back as ``None`` rows (the
        tolerant contract — the caller owns health accounting). With
        ``target_size`` and ``channels`` both set the workers run the
        fused-resize batch decoder; otherwise each blob keeps its source
        geometry/channels (the ``readImages`` default-decoder contract).
        """
        if not blobs:
            return []
        with telemetry.span(telemetry.SPAN_DECODE_POOL, blobs=len(blobs)):
            per = max(1, min(_MAX_CHUNK,
                             -(-len(blobs) // (self.workers * 2))))
            chunks = [self._submit(list(blobs[s:s + per]), target_size,
                                   channels)
                      for s in range(0, len(blobs), per)]
            out: List[Optional[np.ndarray]] = []
            for chunk in chunks:
                out.extend(self._await(chunk))
            return out

    # -- submission / waiting ------------------------------------------------

    def _submit(self, blobs: List[Optional[bytes]], target_size,
                channels) -> _Chunk:
        # bounded in-flight: backpressure here, with crash detection so
        # a dead pool cannot wedge a submitter forever
        while not self._sem.acquire(timeout=_WAIT_POLL_S):
            if self._closed:
                raise resilience.DecodeWorkerLost(
                    "decode pool closed while a submit was waiting for "
                    "an in-flight slot")
            self._reap_crashed()
        chunk = _Chunk(blobs, target_size, channels,
                       telemetry.current_context())
        with self._lock:
            if self._closed:
                self._sem.release()
                raise resilience.DecodeWorkerLost(
                    "decode pool closed before the chunk was submitted")
            task_id = next(self._ids)
            self._pending[task_id] = chunk
            depth = len(self._pending)
            self._dispatch_locked(task_id, chunk)
        if telemetry.active() is not None:
            telemetry.gauge_set(telemetry.M_DECODE_POOL_DEPTH, depth)
            telemetry.gauge_set(telemetry.M_DECODE_POOL_BUSY,
                                min(depth, self.workers))
        return chunk

    def _dispatch_locked(self, task_id: int, chunk: _Chunk) -> None:
        """Hand a chunk to the least-loaded worker (caller holds the
        lock). The injected ``decode_pool_worker_crash`` marker rides on
        the task, so the chosen worker dies while holding exactly this
        chunk — the respawn path's precise-resubmission bookkeeping is
        what the injection exercises."""
        worker = min(self._workers, key=lambda w: len(w.assigned))
        worker.assigned.add(task_id)
        crash = resilience.should_fire("decode_pool_worker_crash")
        worker.queue.put((task_id, chunk.blobs, chunk.target_size,
                          chunk.channels, crash, chunk.ctx))

    def _await(self, chunk: _Chunk) -> List[Optional[np.ndarray]]:
        while not chunk.event.wait(_WAIT_POLL_S):
            self._reap_crashed()
        if chunk.error is not None:
            raise chunk.error
        return chunk.result  # type: ignore[return-value]

    # -- crash detection / respawn -------------------------------------------

    def _reap_crashed(self) -> None:
        """Respawn dead workers and re-dispatch exactly the chunks they
        held.

        The per-worker queues make the loss set precise: a dead worker's
        ``assigned`` ids (intersected with still-pending chunks — it may
        have delivered a result just before dying) are the ONLY chunks
        re-dispatched, each with its attempt counter bumped; its queue —
        including any not-yet-consumed tasks, which are in the loss set
        — is abandoned with it. A chunk whose resubmission budget is
        spent fails with a RETRYABLE DecodeWorkerLost so the engine's
        classified task retry replays the whole partition. A duplicate
        result (the worker delivered AND died) is adopted and dropped by
        the collector, so shared memory never leaks whichever side wins.
        """
        dead: List[str] = []
        redispatch: List[Tuple[int, _Chunk]] = []
        failed: List[_Chunk] = []
        with self._lock:
            if self._closed:
                return
            for i, worker in enumerate(self._workers):
                if worker.proc.is_alive():
                    continue
                if worker.conn is not None:
                    # hand the dead worker's pipe to the collector: any
                    # buffered results (and their shm segments) must
                    # still be drained before the conn is closed
                    self._retired_conns.append(worker.conn)
                if worker.clock is not None:
                    # likewise the clock pipe: a buffered ping (or the
                    # death EOF) must be consumed, never left to leak
                    self._retired_clocks.append(worker.clock)
                # abandon the dead worker's task queue WITHOUT joining
                # its feeder thread: with >1 pipe-buffer of pickled
                # tasks queued to a worker that will never read them,
                # the feeder blocks in write forever, and the default
                # Queue finalizer would join it (= hang) at exit
                worker.queue.cancel_join_thread()
                worker.queue.close()
                self._workers[i] = self._spawn(i)
                dead.append(worker.proc.name)
                self.respawns += 1
                for task_id in sorted(worker.assigned):
                    chunk = self._pending.get(task_id)
                    if chunk is None:
                        continue  # delivered just before dying
                    chunk.attempts += 1
                    if chunk.attempts > _MAX_ATTEMPTS:
                        del self._pending[task_id]
                        failed.append(chunk)
                    else:
                        redispatch.append((task_id, chunk))
            if not dead:
                return
            for task_id, chunk in redispatch:
                self._dispatch_locked(task_id, chunk)
        # the collector may be blocked in connection.wait on the OLD conn
        # list; nudge it so the respawned workers' pipes are watched
        self._wake_w.send_bytes(b"r")
        for name in dead:
            logger.warning(
                "decode pool worker %s died; respawned (re-dispatched %d "
                "of its chunk(s))", name, len(redispatch))
            health.record(health.DECODE_POOL_RESPAWN, worker=name)
        for chunk in failed:
            chunk.error = resilience.DecodeWorkerLost(
                f"decode pool worker died {_MAX_ATTEMPTS} times while "
                "this chunk was in flight")
            chunk.event.set()
            self._sem.release()

    # -- the collector thread ------------------------------------------------

    def _collect(self) -> None:
        """Multiplex every worker's private result pipe. EOF on a pipe
        (worker exited — poison pill, crash, or killed mid-send) retires
        that conn after its buffered results are drained; crash respawn
        itself stays the waiters' reaper's job. Exits once the pool is
        closed and every conn has been drained to EOF — which is exactly
        the drain-everything guarantee the shared-memory lifecycle
        needs."""
        from multiprocessing import connection as _mpc

        while True:
            with self._lock:
                conn_map = {w.conn: w for w in self._workers
                            if w.conn is not None}
                clock_map = {w.clock: w for w in self._workers
                             if w.clock is not None}
                retired = list(self._retired_conns)
                retired_clocks = list(self._retired_clocks)
                done = (self._closed and not conn_map and not clock_map
                        and not retired and not retired_clocks)
            if done:
                return
            for ready in _mpc.wait(list(conn_map) + list(clock_map)
                                   + retired + retired_clocks
                                   + [self._wake_r]):
                if ready is self._wake_r:
                    try:
                        self._wake_r.recv_bytes()
                    except (EOFError, OSError):  # pragma: no cover
                        pass
                    continue
                if ready in clock_map or ready in retired_clocks:
                    # one-shot clock handshake: answer the worker's ping
                    # with THIS process's perf_counter_ns, then retire
                    # the pipe (EOF here means the worker died first; a
                    # send to a reaped worker's pipe fails harmlessly)
                    try:
                        ready.recv()
                        ready.send(time.perf_counter_ns())
                    except (EOFError, OSError):
                        pass
                    ready.close()
                    with self._lock:
                        worker = clock_map.get(ready)
                        if worker is not None and worker.clock is ready:
                            worker.clock = None
                        if ready in self._retired_clocks:
                            self._retired_clocks.remove(ready)
                    continue
                try:
                    task_id, meta = ready.recv()
                except (EOFError, OSError):
                    # worker exited; its buffered results were already
                    # delivered in order before EOF (the waiters' poll
                    # respawns crashed workers)
                    ready.close()
                    with self._lock:
                        worker = conn_map.get(ready)
                        if worker is not None and worker.conn is ready:
                            worker.conn = None
                        if ready in self._retired_conns:
                            self._retired_conns.remove(ready)
                    continue
                self._resolve(task_id, meta)

    def _resolve(self, task_id: int, meta: Dict[str, Any]) -> None:
        error = meta.get("error")
        # adopt (and free) the segment BEFORE looking the chunk up:
        # duplicates and abandoned chunks must still unlink
        arrays = None if error is not None else _adopt_result(meta)
        with self._lock:
            chunk = self._pending.pop(task_id, None)
            depth = len(self._pending)
            for worker in self._workers:
                worker.assigned.discard(task_id)
        if chunk is None:
            return  # crash-resubmission duplicate, already resolved
        if error is not None:
            # what the inline decoder would have raised, re-raised at
            # the submitting call site with its builtin type intact
            chunk.error = _rebuild_error(*error)
        else:
            chunk.result = arrays
        chunk.event.set()
        self._sem.release()
        tel = telemetry.active()
        if tel is not None:
            telemetry.gauge_set(telemetry.M_DECODE_POOL_DEPTH, depth)
            telemetry.gauge_set(telemetry.M_DECODE_POOL_BUSY,
                                min(depth, self.workers))
            rec = meta.get("span")
            if rec is not None and chunk.ctx is not None:
                # adopt the worker-measured chunk span under the context
                # captured at submit time — the worker has no tracer, so
                # the span id is allocated here
                tel.tracer.record_remote(
                    rec["name"], chunk.ctx, rec["start_ns"],
                    rec["end_ns"], pid=rec["pid"],
                    process=f"decode-{rec['pid']}",
                    **rec.get("attributes", {}))
            for dt in meta.get("decode_s", ()):
                telemetry.observe(telemetry.M_DECODE_POOL_DECODE_S, dt,
                                  exemplar=chunk.ctx)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Join and reap everything: workers, collector, queues, shared
        memory. Idempotent; safe mid-stream (waiters fail with a
        RETRYABLE DecodeWorkerLost rather than hanging)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            abandoned = list(self._pending.values())
            self._pending.clear()
            workers = list(self._workers)
        for worker in workers:
            worker.queue.put(None)  # poison pill on each private queue
        for worker in workers:
            worker.proc.join(timeout=10.0)
            if worker.proc.is_alive():  # pragma: no cover - wedged worker
                worker.proc.terminate()
                worker.proc.join(timeout=10.0)
            # a dead worker never consumed its pill; don't let the
            # queue's feeder thread block interpreter exit on it
            worker.queue.cancel_join_thread()
            worker.queue.close()
        # the joins above closed every worker's pipe write end, so the
        # collector drains each conn to EOF — adopting and unlinking
        # every remaining segment — then sees closed + no live conns and
        # exits; the wake byte covers it being parked on an empty list
        self._wake_w.send_bytes(b"c")
        self._collector.join()
        for chunk in abandoned:
            chunk.error = resilience.DecodeWorkerLost(
                "decode pool closed mid-stream")
            chunk.event.set()
            self._sem.release()
        self._wake_w.close()
        self._wake_r.close()

    def __enter__(self) -> "DecodePool":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def __del__(self) -> None:  # safety net only; callers use close()/with
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


# ---------------------------------------------------------------------------
# The process-wide pool (EngineConfig.decode_workers is the ONE knob)
# ---------------------------------------------------------------------------

_pool_lock = threading.Lock()
_pool: Optional[DecodePool] = None
_pool_key: Optional[Tuple[int, Optional[int]]] = None


def maybe_pool() -> Optional[DecodePool]:
    """The process-wide pool per ``EngineConfig.decode_workers``, or
    ``None`` when the pool is disabled (``decode_workers=0``, the
    bit-identical inline default) or when called from inside a worker.
    Reconfiguring the knobs closes the old pool and spawns a new one."""
    if _IN_WORKER:
        return None
    from sparkdl_tpu.engine.dataframe import EngineConfig

    EngineConfig.validate()
    workers = EngineConfig.decode_workers
    if not workers:
        return None
    key = (workers, EngineConfig.decode_pool_inflight)
    global _pool, _pool_key
    with _pool_lock:
        stale = _pool
        if stale is not None and _pool_key == key and not stale.closed:
            return stale
        _pool = None
    if stale is not None:
        stale.close()  # outside the lock: close() joins processes
    with _pool_lock:
        if _pool is None or _pool_key != key or _pool.closed:
            _pool = DecodePool(workers,
                               inflight=EngineConfig.decode_pool_inflight)
            _pool_key = key
        return _pool


def shutdown() -> None:
    """Close the process-wide pool (tests, bench mode flips, atexit)."""
    global _pool
    with _pool_lock:
        pool, _pool = _pool, None
    if pool is not None:
        pool.close()


atexit.register(shutdown)
