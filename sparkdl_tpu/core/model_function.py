"""ModelFunction — the model abstraction at the center of the framework.

Parity map (SURVEY.md §7): the reference's ``TFInputGraph`` /
``GraphFunction`` carried a serialized TF graph plus input/output endpoint
names, ingested from five formats and composed by graph splicing. The
TPU-native equivalent is *a pure function + a params pytree + an input
spec*:

- composition is function composition (``with_preprocess`` /
  ``with_postprocess``), traced and fused into ONE XLA program by ``jit``;
- the ingestion matrix (``fromFlax``, ``fromFunction``, ``fromMsgpack``,
  ``fromOrbax``, ``fromJaxExport``) mirrors ``TFInputGraph.fromGraph /
  fromGraphDef / fromSavedModel[WithSignature] / fromCheckpoint[...]``;
- ``fromJaxExport`` is the frozen-graph analog: a serialized StableHLO
  artifact with weights baked in, runnable without the Python model class;
- execution is shape-specialized and cached (one compile per batch size /
  mesh), with batches padded to static shapes (core.batching).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sparkdl_tpu.core import batching, telemetry
from sparkdl_tpu.core.mesh import batch_sharding, replicated


@dataclass(frozen=True)
class TensorSpec:
    """Shape/dtype contract for one model input; dim 0 None = batch."""

    shape: Tuple[Optional[int], ...]
    dtype: str = "float32"

    def with_batch(self, batch_size: int) -> Tuple[int, ...]:
        return tuple(batch_size if d is None else d for d in self.shape)

    @property
    def element_shape(self) -> Tuple[int, ...]:
        return tuple(d for d in self.shape[1:])

    def spatial_size(self) -> Optional[Tuple[int, int]]:
        """(H, W) for a static NHWC spec; None when not image-shaped."""
        if len(self.shape) == 4 and None not in self.shape[1:3]:
            return (self.shape[1], self.shape[2])
        return None


#: Inference precisions :meth:`ModelFunction.with_dtype` accepts — the
#: same vocabulary ``EngineConfig.inference_precision`` validates.
PRECISIONS = ("float32", "bfloat16", "int8")

# Marker keys of a quantized-weight leaf: a {_Q8_WEIGHTS: int8 array,
# _Q8_SCALE: f32 per-channel scales} dict standing in for the original
# float leaf. Dicts flatten transparently under jit, so the quantized
# tree passes the jit boundary with no custom pytree registration.
_Q8_WEIGHTS = "__sparkdl_q8_weights__"
_Q8_SCALE = "__sparkdl_q8_scale__"


def _is_q8_leaf(x) -> bool:
    return isinstance(x, dict) and _Q8_WEIGHTS in x


def _kernels_or_none():
    """``core.kernels`` iff ``EngineConfig.pallas_kernels`` is armed —
    lazy and knob-gated so ``"off"`` never imports the Pallas machinery
    (the byte-identity pin asserts it stays out of ``sys.modules``)."""
    try:
        from sparkdl_tpu.engine.dataframe import EngineConfig
    except Exception:
        return None
    if getattr(EngineConfig, "pallas_kernels", "off") == "off":
        return None
    from sparkdl_tpu.core import kernels
    return kernels


def _route_preproc_or_none(x, target_hw, out_dtype, family: str):
    kernels = _kernels_or_none()
    if kernels is None:
        return None
    return kernels.route_preproc(x, target_hw, out_dtype, family=family)


def _ensure_kernels_autotuned(inner, x, model: str) -> None:
    """Settle every kernel verdict ``inner(x)`` depends on BEFORE its
    first trace (core/kernels.py accept-if-faster shootouts, run at the
    deployment's actual shapes). No-op unless the knob is 'autotune'."""
    kernels = _kernels_or_none()
    if kernels is None:
        return
    kernels.ensure_autotuned(inner, x, model=model)


def _dequantize_tree(variables):
    """In-program dequantize of every quantized leaf to bfloat16 (the
    q · scale multiply fuses into the consuming matmul/conv); remaining
    float leaves cast to bfloat16 so the model stays dtype-consistent."""
    def deq(x):
        if _is_q8_leaf(x):
            return (x[_Q8_WEIGHTS].astype(jnp.bfloat16)
                    * x[_Q8_SCALE].astype(jnp.bfloat16))
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(jnp.bfloat16)
        return x

    return jax.tree_util.tree_map(deq, variables, is_leaf=_is_q8_leaf)


_DONATION_WARNING_MSG = "Some donated buffers were not usable"


def _silence_donation_warning() -> None:
    """uint8-staged batches can never alias float outputs, so XLA warns
    "Some donated buffers were not usable" on every such launch; the
    donation is still a correct no-op there, and the warning is pure
    noise for a library-internal decision the caller didn't make.

    Installed at module import (below) AND re-asserted per donating jit
    build: jax's own tracing paths (e.g. ``jnp.mean`` via
    ``jax._src.numpy.reductions``) enter ``warnings.catch_warnings()``,
    whose exit RESTORES the process-global filter list from a snapshot —
    a concurrent trace on another partition thread can therefore wipe a
    filter installed after import, so presence is re-checked rather than
    tracked with a one-shot flag."""
    import warnings

    for f in warnings.filters:
        pattern = getattr(f[1], "pattern", None)
        if pattern == _DONATION_WARNING_MSG:
            return
    warnings.filterwarnings("ignore", message=_DONATION_WARNING_MSG)


_silence_donation_warning()


class ModelFunction:
    """A pure ``apply(variables, x) -> y`` + variables + input spec.

    ``apply_fn`` must be jax-traceable and side-effect free. ``variables``
    is any pytree (Flax ``{'params': ...}`` dicts, raw arrays, or None for
    frozen exported artifacts whose weights are baked in).
    """

    # True when the registry selected an inference-specialized fast apply
    # (models/*_fast.py); set post-construction by the registry builders.
    fast_path = False

    # True iff tracing this model's apply can consult a core/kernels.py
    # route (Flax-backed bodies — ConvBN/SeparableConvBN kernel_family
    # opt-ins — and resized() wrappers with the preproc route). Gates
    # the pre-trace autotune collection pass: an arbitrary fromFunction
    # callable has no routes, and eval_shape-tracing it anyway would run
    # its Python body a second time — observable (and contract-breaking:
    # a FATAL error's fn body must run exactly once) when the callable
    # has side effects.
    kernel_routable = False

    def __init__(self, apply_fn: Callable[[Any, jax.Array], jax.Array],
                 variables: Any, input_spec: TensorSpec,
                 name: str = "model",
                 trainable_mask: Any = None) -> None:
        self.apply_fn = apply_fn
        self.variables = variables
        self.input_spec = input_spec
        self.name = name
        # Optional bool pytree matching ``variables``: False leaves are
        # non-trainable (e.g. ingested Keras BatchNorm moving stats) and the
        # Trainer masks their updates. None = everything trainable.
        self.trainable_mask = trainable_mask
        self._jit_cache: Dict[Tuple, Callable] = {}
        # Concurrent partition tasks race the first jitted() build; the
        # executor keys its coalescing state on id(fn), so two racers
        # minting distinct fns would silently split the coalescer into
        # per-thread states (and recompile). Double-checked under this
        # lock.
        self._jit_lock = threading.Lock()
        self._flat_cache: Optional["ModelFunction"] = None
        self._resize_cache: Dict[Tuple[int, int], "ModelFunction"] = {}
        self._precision_cache: Dict[str, "ModelFunction"] = {}

    # -- cluster transport ----------------------------------------------------

    def __getstate__(self) -> Dict[str, Any]:
        # Op chains cross process boundaries via cloudpickle when the
        # cluster plane is armed (cluster/worker.py). What defines the
        # model — apply_fn, variables, spec — ships; the jit cache
        # (process-local compiled handles), its lock, and the derived-
        # model caches are per-process state the receiving worker must
        # rebuild on first use, so they are stripped rather than pickled.
        state = self.__dict__.copy()
        state["_jit_cache"] = {}
        state["_jit_lock"] = None
        state["_flat_cache"] = None
        state["_resize_cache"] = {}
        state["_precision_cache"] = {}
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        self._jit_lock = threading.Lock()

    # -- construction matrix (TFInputGraph parity) ---------------------------

    @classmethod
    def fromFunction(cls, fn: Callable, variables: Any, input_spec: TensorSpec,
                     name: str = "fn") -> "ModelFunction":
        """From an in-memory pure function — ``TFInputGraph.fromGraph`` analog."""
        return cls(fn, variables, input_spec, name=name)

    @classmethod
    def fromFlax(cls, module, variables: Any, input_spec: TensorSpec,
                 name: Optional[str] = None, **apply_kwargs) -> "ModelFunction":
        """From a Flax module + variables (``fromGraphDef`` analog).

        ``apply_kwargs`` are closed over (e.g. ``train=False``); mutable
        collections are not updated — inference semantics.
        """

        def apply_fn(vs, x):
            return module.apply(vs, x, **apply_kwargs)

        out = cls(apply_fn, variables, input_spec,
                  name=name or type(module).__name__)
        out.kernel_routable = True
        return out

    @classmethod
    def fromMsgpack(cls, path: str, module, input_spec: TensorSpec,
                    name: Optional[str] = None, **apply_kwargs) -> "ModelFunction":
        """From Flax msgpack bytes on disk (``fromCheckpoint`` analog).

        The module provides the pytree structure; weights are restored into
        a freshly-initialized template so structure mismatches fail loudly.
        """
        import flax.serialization as fser

        template = _init_template(module, input_spec)
        with open(path, "rb") as f:
            variables = fser.from_bytes(template, f.read())
        return cls.fromFlax(module, variables, input_spec,
                            name=name or type(module).__name__, **apply_kwargs)

    @classmethod
    def fromOrbax(cls, directory: str, module, input_spec: TensorSpec,
                  name: Optional[str] = None, **apply_kwargs) -> "ModelFunction":
        """From an Orbax checkpoint directory (``fromSavedModel`` analog)."""
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            template = _init_template(module, input_spec)
            variables = ckptr.restore(os.path.abspath(directory), template)
        return cls.fromFlax(module, variables, input_spec,
                            name=name or type(module).__name__, **apply_kwargs)

    @classmethod
    def fromJaxExport(cls, path_or_bytes, name: str = "exported"
                      ) -> "ModelFunction":
        """From a serialized ``jax.export`` artifact — the frozen-graph path.

        Weights are baked into the StableHLO program (the reference's
        ``strip_and_freeze_until`` produced exactly this kind of artifact
        from TF graphs); no Python model class is needed to run it.
        """
        import jax.export as jex

        if isinstance(path_or_bytes, (bytes, bytearray)):
            blob = bytes(path_or_bytes)
        else:
            with open(path_or_bytes, "rb") as f:
                blob = f.read()
        exported = jex.deserialize(blob)

        def aval_to_spec(aval) -> TensorSpec:
            shape = tuple(None if not isinstance(d, int) else int(d)
                          for d in aval.shape)
            return TensorSpec(shape, np.dtype(aval.dtype).name)

        # in_tree describes the ((args,), kwargs) of the exported call;
        # rebuild the input structure (array or {name: spec} dict).
        args, _kwargs = jax.tree_util.tree_unflatten(
            exported.in_tree, list(exported.in_avals))
        spec = jax.tree_util.tree_map(aval_to_spec, args[0])

        def apply_fn(_vs, x):
            return exported.call(x)

        return cls(apply_fn, None, spec, name=name)

    # -- serialization -------------------------------------------------------

    def toMsgpack(self, path: str) -> None:
        import flax.serialization as fser

        with open(path, "wb") as f:
            f.write(fser.to_bytes(self.variables))

    def toOrbax(self, directory: str) -> None:
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(os.path.abspath(directory), self.variables)
            ckptr.wait_until_finished()

    def toJaxExport(self, path: Optional[str] = None,
                    batch_size: Optional[int] = None) -> bytes:
        """Serialize as StableHLO with weights baked in.

        With ``batch_size=None`` the batch dim is exported symbolically so
        the artifact runs at any batch size; pass a fixed size if symbolic
        export is unsupported for the program. Dict input specs export with
        ONE shared symbolic batch dim across all inputs.
        """
        import jax.export as jex

        def fn(x):
            return self.apply_fn(self.variables, x)

        scope = jex.SymbolicScope() if batch_size is None else None

        def make_arg(spec: TensorSpec):
            if batch_size is None:
                dims = ",".join(["b"] + [str(d) for d in spec.element_shape])
                shape = jex.symbolic_shape(dims, scope=scope)
            else:
                shape = spec.with_batch(batch_size)
            return jax.ShapeDtypeStruct(shape, jnp.dtype(spec.dtype))

        if isinstance(self.input_spec, dict):
            arg = {name: make_arg(spec)
                   for name, spec in self.input_spec.items()}
        else:
            arg = make_arg(self.input_spec)
        exported = jex.export(jax.jit(fn))(arg)
        blob = exported.serialize()
        if path is not None:
            with open(path, "wb") as f:
                f.write(blob)
        return blob

    # -- composition (graph-splicing parity) ---------------------------------

    def with_preprocess(self, pre: Callable[[jax.Array], jax.Array],
                        input_spec: Optional[TensorSpec] = None
                        ) -> "ModelFunction":
        """Return a ModelFunction computing ``apply(vars, pre(x))``.

        ``pre`` must be jax-traceable; it fuses into the same XLA program
        (the reference spliced ``buildSpImageConverter`` graph pieces in
        front — here it is function composition, SURVEY.md §3.2).
        """
        apply_fn = self.apply_fn

        def fn(vs, x):
            return apply_fn(vs, pre(x))

        out = ModelFunction(fn, self.variables,
                            input_spec or self.input_spec, name=self.name,
                            trainable_mask=self.trainable_mask)
        self._propagate_float_source(out)
        return out

    def _propagate_float_source(self, wrapped: "ModelFunction") -> None:
        """Composition wrappers must keep the pre-bf16-cast weights
        reachable, or persistence silently falls back to the truncated
        variables (the with_compute_dtype contract, ADVICE r4). Kernel
        routability rides along: a wrapper closes over the parent's
        apply, so its routes are still in the traced body."""
        source = getattr(self, "float_source", None)
        if source is not None:
            wrapped.float_source = source
        if self.kernel_routable:
            wrapped.kernel_routable = True

    def with_postprocess(self, post: Callable[[jax.Array], jax.Array]
                         ) -> "ModelFunction":
        apply_fn = self.apply_fn

        def fn(vs, x):
            return post(apply_fn(vs, x))

        out = ModelFunction(fn, self.variables, self.input_spec,
                            name=self.name,
                            trainable_mask=self.trainable_mask)
        self._propagate_float_source(out)
        return out

    def with_compute_dtype(self, dtype) -> "ModelFunction":
        """Run this model in ``dtype`` (e.g. bfloat16 for MXU inference):
        float weights cast once here, input casts in-program, output casts
        back to the original output dtype. Used by the registry's
        ingestion-backed named models, whose keras-derived apply is
        float32 by construction."""
        import jax.numpy as jnp

        dtype = jnp.dtype(dtype)
        apply_fn = self.apply_fn
        variables = jax.tree.map(
            lambda a: a.astype(dtype)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
            else a, self.variables)

        def fn(vs, x):
            # jnp.asarray first: an eager numpy input would otherwise flow
            # numpy's promotion rules through the graph (np-bf16 * python
            # float -> f32, unlike JAX's weak-type rules) and break
            # dtype-strict convs mid-model. tree.map, not a bare astype:
            # multi-input models feed a dict of arrays.
            x = jax.tree.map(lambda a: jnp.asarray(a).astype(dtype), x)
            out = apply_fn(vs, x)
            return jax.tree.map(lambda o: o.astype(jnp.float32), out)

        out = ModelFunction(fn, variables, self.input_spec, name=self.name,
                            trainable_mask=self.trainable_mask)
        # Persistence must write the PRE-cast weights (ADVICE r4: a bf16
        # model's msgpack artifact would otherwise store truncated values
        # that switching back to f32 cannot recover). Chain through an
        # existing source so re-casting a cast model keeps the original.
        out.float_source = getattr(self, "float_source", self)
        return out

    def with_dtype(self, precision: str) -> "ModelFunction":
        """The validated-knob precision entry point
        (``EngineConfig.inference_precision`` threads through here at the
        executor choke point — direct per-call-site use is flagged by the
        ``executor-choke-point`` lint).

        ``"float32"`` returns ``self`` untouched — the one-knob escape
        hatch stays bit-identical to the unconverted model. ``"bfloat16"``
        is :meth:`with_compute_dtype` (outputs cast back to float32;
        per-element |Δ| ≤ ~1e-2 relative on tanh/softmax-bounded heads —
        docs/PERF.md "Launch shaping & precision" for the contract).
        ``"int8"`` post-training-quantizes the weights symmetric
        per-channel (ndim≥2 float leaves; biases/norm stats stay float)
        and computes in bfloat16. Memoized per precision so the jit cache
        behind each variant is shared across calls.
        """
        if precision not in PRECISIONS:
            raise ValueError(
                f"precision must be one of {PRECISIONS}, got {precision!r}")
        if precision == "float32":
            return self
        out = self._precision_cache.get(precision)
        if out is None:
            # build OUTSIDE the lock (int8 quantization fetches weights to
            # host), publish under it with setdefault: concurrent first
            # calls must converge on ONE variant — the executor's
            # coalescing state is keyed on the variant's jitted fn
            # identity, so two racing winners would silently split
            # coalescing. A losing build is discarded unused.
            if precision == "bfloat16":
                built = self.with_compute_dtype(jnp.bfloat16)
            else:
                built = self._quantized_int8()
            built.compute_dtype = precision
            with self._jit_lock:
                out = self._precision_cache.setdefault(precision, built)
        return out

    def _quantized_int8(self) -> "ModelFunction":
        """Weight-only post-training quantization: symmetric per-channel
        (last axis) int8 for every float leaf with ndim ≥ 2 — the
        matmul/conv kernels that dominate featurize-head FLOPs and bytes.
        Weights dequantize IN-PROGRAM to bfloat16 (q · scale fuses into
        the consuming op), activations run bfloat16, outputs cast back to
        float32. 4× smaller resident weights than float32 on top of the
        bf16 math-speed win."""
        apply_fn = self.apply_fn

        def quant(a):
            if not (hasattr(a, "dtype")
                    and jnp.issubdtype(a.dtype, jnp.floating)
                    and getattr(a, "ndim", 0) >= 2):
                return a
            arr = np.asarray(a, dtype=np.float32)
            axes = tuple(range(arr.ndim - 1))
            scale = np.max(np.abs(arr), axis=axes) / 127.0
            scale = np.where(scale == 0.0, 1.0, scale).astype(np.float32)
            return {_Q8_WEIGHTS: jnp.asarray(
                        np.clip(np.rint(arr / scale), -127, 127)
                        .astype(np.int8)),
                    _Q8_SCALE: jnp.asarray(scale)}

        variables = jax.tree.map(quant, self.variables)

        def fn(vs, x):
            deq = _dequantize_tree(vs)
            x = jax.tree.map(
                lambda a: jnp.asarray(a).astype(jnp.bfloat16), x)
            out = apply_fn(deq, x)
            return jax.tree.map(lambda o: o.astype(jnp.float32), out)

        # trainable_mask dropped deliberately: quantized weights are an
        # inference-only artifact, not a training starting point.
        out = ModelFunction(fn, variables, self.input_spec, name=self.name)
        out.float_source = getattr(self, "float_source", self)
        out.kernel_routable = self.kernel_routable
        return out

    def flattened(self) -> "ModelFunction":
        """Flatten outputs to (batch, -1) — the ``buildFlattener`` analog.

        Memoized: callers invoke this per transform() call, and a fresh
        ModelFunction would mean a fresh jit cache — i.e. a full XLA
        recompile of the model on EVERY transform (measured ~13s/call over
        the remote PJRT tunnel).
        """
        if self._flat_cache is None:
            with self._jit_lock:
                if self._flat_cache is None:
                    self._flat_cache = self.with_postprocess(
                        lambda y: y.reshape(y.shape[0], -1))
        return self._flat_cache

    def resized(self, src_size: Tuple[int, int],
                target_size: Optional[Tuple[int, int]] = None
                ) -> "ModelFunction":
        """Model preceded by ON-DEVICE bilinear resize from (H, W) inputs.

        ``target_size`` defaults to the input spec's spatial dims; pass it
        explicitly when the caller's requested size differs from (or the
        spec lacks) static spatial dims. The reference spliced
        ``tf.image.resize_bilinear`` into the graph in front of the model
        (``buildSpImageConverter``, SURVEY.md §3.2) — device-side, no
        antialias; ``jax.image.resize`` with ``antialias=False`` reproduces
        that. Memoized per (src, target) pair (one XLA program each).

        This is the fused-preprocess entry (docs/PERF.md "Columnar data
        plane"): under ``EngineConfig.fused_preprocess`` the transformer
        ships raw uint8 at source size and composes this in front of the
        normalize mode and forward pass, so cast/resize/normalize/forward
        are one compiled program (the cast below is exact for 0-255
        uint8, so fp32 results match host-f32 staging bit for bit).
        """
        target = (tuple(target_size) if target_size is not None
                  else self.input_spec.spatial_size())
        if target is None or tuple(src_size) == target:
            return self
        th, tw = target
        cache = self._resize_cache
        key = (tuple(src_size), target)
        if key not in cache:
            model_name = self.name
            out_dtype = jnp.dtype(self.input_spec.dtype)

            def pre(x):
                # Fused-kernel opt-in (core/kernels.py): one Pallas
                # launch for cast+resize when the accept-if-faster
                # autotune adopted this site; None keeps the XLA pair.
                fused = _route_preproc_or_none(x, (th, tw), out_dtype,
                                               model_name)
                if fused is not None:
                    return fused
                xf = x.astype(out_dtype)
                return jax.image.resize(
                    xf, (x.shape[0], th, tw, x.shape[3]),
                    method="bilinear", antialias=False)

            spec = TensorSpec((None, int(src_size[0]), int(src_size[1]),
                               self.input_spec.shape[3]),
                              self.input_spec.dtype)
            wrapped = self.with_preprocess(pre, input_spec=spec)
            # pre() consults route_preproc regardless of what the parent
            # body contains, so the wrapper is always collection-worthy.
            wrapped.kernel_routable = True
            cache[key] = wrapped
        return cache[key]

    # -- residency accounting (sparkdl_tpu/serving/residency.py) -------------

    def weight_bytes(self) -> int:
        """Total bytes of the variables pytree — the HBM residency
        manager's byte accounting for budget/eviction decisions. Counts
        every array leaf (q8 weight dicts flatten to their int8 payload
        plus per-channel scales, so quantized models account at their
        real quantized size, not the float source's)."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(self.variables):
            nbytes = getattr(leaf, "nbytes", None)
            if nbytes is not None:
                total += int(nbytes)
        return total

    def device_variants(self) -> list:
        """This model plus every memoized derived ModelFunction reachable
        from it (precision casts, the flattener, resize wrappers —
        transitively). The derived variants close over THIS model's
        weights and own their own jit caches, so eviction must visit all
        of them: clearing only the root's cache would leave a bf16
        variant's compiled executable pinning the weights."""
        seen: Dict[int, "ModelFunction"] = {}
        stack: list = [self]
        while stack:
            m = stack.pop()
            if id(m) in seen:
                continue
            seen[id(m)] = m
            flat = getattr(m, "_flat_cache", None)
            if flat is not None:
                stack.append(flat)
            stack.extend(getattr(m, "_precision_cache", {}).values())
            stack.extend(getattr(m, "_resize_cache", {}).values())
        return list(seen.values())

    def release_device_state(self) -> None:
        """Drop every compiled executable (jit cache) across this model
        and its derived variants, and forget the variants themselves —
        the eviction primitive behind the serving residency manager. The
        weights pytree is untouched (the owner decides whether to drop
        its reference); the next :meth:`jitted` call recompiles, which
        is exactly the cold-start cost the ``sparkdl.model_load`` span
        makes visible."""
        for m in self.device_variants():
            with m._jit_lock:
                m._jit_cache.clear()
        with self._jit_lock:
            self._flat_cache = None
            self._resize_cache.clear()
            self._precision_cache.clear()

    # -- execution -----------------------------------------------------------

    def jitted(self, mesh=None, donate_batch: bool = False) -> Callable:
        """Compiled ``batch -> output`` closure over the variables.

        The traced program casts the input to the spec dtype FIRST (a no-op
        when it already matches), so batches can stage in uint8 — 4x fewer
        host→HBM DMA bytes than float32 — with normalize/preprocess fused
        after the on-device cast. With a mesh, inputs are sharded batch-wise
        over ``data`` and variables are replicated — XLA lays collectives
        over ICI as needed. Cache key: (mesh, donate) — the Mesh object
        itself (hashable); an ``id()`` key could alias a freed mesh's
        recycled address to a stale entry (VERDICT r2 weak #7).
        Shape/dtype specialization is jit's own cache.
        """
        key = (mesh, donate_batch)
        cached = self._jit_cache.get(key)
        if cached is not None:
            return cached
        with self._jit_lock:
            cached = self._jit_cache.get(key)
            if cached is not None:
                return cached
            fn = self._build_jitted(mesh, donate_batch)
            self._jit_cache[key] = fn
            return fn

    def _build_jitted(self, mesh, donate_batch: bool) -> Callable:
        if donate_batch:
            _silence_donation_warning()

        specs = self.input_spec
        inner_apply = self.apply_fn

        def cast_one(x, spec):
            dtype = jnp.dtype(spec.dtype)
            return x.astype(dtype) if x.dtype != dtype else x

        def apply_fn(vs, x):
            if isinstance(specs, dict):
                x = {name: cast_one(x[name], spec)
                     for name, spec in specs.items()}
            else:
                x = cast_one(x, specs)
            return inner_apply(vs, x)

        if mesh is None:
            variables = self.variables
            kwargs: Dict[str, Any] = {"donate_argnums": (1,)} if donate_batch else {}
            jfn = jax.jit(apply_fn, **kwargs)
            inner = lambda x: jfn(variables, x)  # noqa: E731
        else:
            variables = jax.device_put(self.variables, replicated(mesh))
            kwargs = {"donate_argnums": (0,)} if donate_batch else {}
            inner = jax.jit(lambda x: apply_fn(variables, x),
                            in_shardings=(batch_sharding(mesh),),
                            out_shardings=batch_sharding(mesh), **kwargs)

        # First launch of a new input shape traces+compiles synchronously
        # inside the call — record it as a `sparkdl.compile` span so
        # bucket-ladder compile storms are visible in the run report
        # (set membership per dispatch otherwise; races at worst record a
        # duplicate span). jax's persistent compilation cache, when wired
        # via SPARKDL_COMPILE_CACHE_DIR (package __init__), makes these
        # spans near-zero on warm processes.
        seen_shapes: set = set()
        name = self.name
        routable = self.kernel_routable

        def fn(x, _inner=inner, _seen=seen_shapes):
            shape_key = tuple((tuple(leaf.shape), str(leaf.dtype))
                              for leaf in jax.tree_util.tree_leaves(x))
            if shape_key in _seen:
                return _inner(x)
            # First sight of a shape: settle the fused-kernel verdicts
            # for this exact geometry (an abstract pass + at most one
            # shootout per new site) so the trace below routes against
            # decided winners — a request never mid-trace-auditions.
            # Gated on kernel_routable: the collection pass eval_shape-
            # traces the body, which re-runs Python side effects — only
            # route-bearing bodies (Flax / resized) may pay that trace.
            if routable:
                _ensure_kernels_autotuned(_inner, x, name)
            with telemetry.span(telemetry.SPAN_COMPILE, model=name,
                                shapes=repr(shape_key)):
                out = _inner(x)
            _seen.add(shape_key)
            return out

        # Shape-inference callers (batching._empty_result) must trace the
        # UNWRAPPED program: tracing this wrapper would record a phantom
        # zero-cost compile span and mark the shape seen, hiding the real
        # first-launch compile from the run report. A dedicated attribute,
        # NOT functools' `__wrapped__` — a caller's own wraps()-decorated
        # fn must not have its inner fn traced by accident.
        fn.__sparkdl_trace_target__ = inner
        return fn

    def stage_inputs(self, array):
        """Host-side staging cast for :meth:`apply_batch` (and the device
        execution service, core/executor.py): uint8 stays uint8 — the
        jitted program casts on device, quartering the transfer bytes —
        anything else is cast to the spec dtype. Idempotent."""
        def stage_cast(arr, spec):
            arr = np.asarray(arr)
            if arr.dtype != np.uint8 and arr.dtype != np.dtype(spec.dtype):
                arr = arr.astype(spec.dtype)
            return arr

        if isinstance(self.input_spec, dict):
            return {name: stage_cast(array[name], spec)
                    for name, spec in self.input_spec.items()}
        return stage_cast(array, self.input_spec)

    def bucket_params(self, batch_size: int, mesh=None) -> Tuple[int, int]:
        """(effective batch_size, bucket multiple) for a mesh: the batch
        pads so every data-axis shard is equal (1 without a mesh)."""
        if mesh is None:
            return batch_size, 1
        from sparkdl_tpu.core.mesh import data_axis_size, pad_to_multiple

        multiple = data_axis_size(mesh)
        return pad_to_multiple(batch_size, multiple), multiple

    def apply_batch(self, array, batch_size: int = 64,
                    mesh=None, retry_policy=None,
                    prefetch: int = 2, donate: bool = False,
                    planner: Optional[batching.BucketPlanner] = None
                    ) -> np.ndarray:
        """Run over N rows with fixed-shape padded chunks; returns numpy.

        ``array``: one ndarray, or — for multi-input models whose
        ``input_spec`` is a ``{name: TensorSpec}`` dict — a dict of
        dim-0-aligned ndarrays (the reference ``TFTransformer`` feed-dict
        analog); outputs mirror the model's structure. uint8 input stages
        as uint8 (the jitted program casts on device — quarter the
        transfer bytes); anything else is cast host-side to the spec dtype.

        Runtime failures are classified per chunk (core.resilience):
        transient errors retry with backoff; a device OOM re-chunks at a
        halved bucket, preserving row order and values; fatal errors
        propagate untouched. OOMs that only surface at the deferred
        device→host fetch (async dispatch) re-run the whole call at a
        halved ``batch_size`` — inputs are host-resident, so the re-run is
        idempotent.

        ``prefetch``: chunk-staging depth of the async input pipeline
        (core.pipeline; 0 = inline staging) — the featurize/transform
        analog of the Trainer's prefetcher (ISSUE 3).

        ``donate=True`` donates each staged input chunk to its launch
        (``jitted(donate_batch=True)``): XLA reuses the input's HBM for
        the outputs, so peak memory drops by one batch. Host-staged numpy
        chunks stay intact (donation only consumes the device-side
        buffer) — the OOM re-chunk path re-pads from the host exactly as
        before. A caller passing a device-resident ``jax.Array`` gives up
        that buffer: reading it after the call raises.

        ``planner``: telemetry-tuned bucket ladder (``core.batching``)
        replacing the blind power-of-two tail buckets; must have been
        built for this call's effective batch_size/multiple
        (``batching.planner_for``). On an OOM re-run at a halved
        batch_size the planner is dropped — its ladder no longer matches.
        """
        from sparkdl_tpu.core import resilience

        array = self.stage_inputs(array)
        fn = self.jitted(mesh=mesh, donate_batch=donate)
        batch_size, multiple = self.bucket_params(batch_size, mesh)
        if planner is not None and planner.batch_size != batch_size:
            planner = None  # foreign ladder: fall back to pow2
        while True:
            try:
                return batching.run_batched(fn, array, batch_size,
                                            multiple=multiple,
                                            retry_policy=retry_policy,
                                            prefetch=prefetch,
                                            planner=planner)
            except Exception as e:  # noqa: BLE001 - classified below
                half = batch_size // 2
                if (resilience.classify(e) != resilience.OOM
                        or half < max(1, multiple)):
                    raise
                import logging

                logging.getLogger(__name__).warning(
                    "%s: device OOM at batch_size %d (%s); re-running at %d",
                    self.name, batch_size, e, half)
                batch_size = half
                planner = None  # halved ladder: planner no longer matches

    def __call__(self, x) -> jax.Array:
        return self.apply_fn(self.variables, x)

    def __repr__(self) -> str:
        if isinstance(self.input_spec, dict):
            inputs = ", ".join(
                f"{k}={s.shape} {s.dtype}" for k, s in self.input_spec.items())
            return f"ModelFunction({self.name}, inputs=({inputs}))"
        return (f"ModelFunction({self.name}, input={self.input_spec.shape} "
                f"{self.input_spec.dtype})")


# InputModel: the public alias emphasizing the ingestion role (TFInputGraph
# parity name in this framework's vocabulary).
InputModel = ModelFunction


def _init_template(module, input_spec: TensorSpec):
    """Abstract variables template (ShapeDtypeStructs) for weight restore.

    eval_shape avoids materializing weights: both flax.from_bytes and Orbax
    restore only need the pytree structure + leaf shapes/dtypes.
    """
    x = jnp.zeros(input_spec.with_batch(1), dtype=input_spec.dtype)
    return jax.eval_shape(lambda: module.init(jax.random.PRNGKey(0), x))
