"""Image struct schema + codecs — the L3 data contract of the framework.

Parity: upstream ``python/sparkdl/image/imageIO.py`` (SURVEY.md §2.1; the
reference mount was empty this round so cites are package-level). The
reference defined the image-struct schema aligned with Spark 2.3+
``ImageSchema`` — fields ``(origin, height, width, nChannels, mode, data)``
with OpenCV-style mode codes — plus numpy↔struct codecs, PIL decode, and
``readImagesWithCustomFn``. This rebuild keeps the exact field contract
(so reference users find the same schema) but stores columns as **Arrow**
struct arrays: binary image bytes stay contiguous and zero-copy between the
engine's partitions and host staging buffers feeding TPU HBM.

Decode fast path: the native C++ loader (libjpeg/libpng + fused
resize/normalize, ``sparkdl_tpu/native``) when built; PIL fallback always
works.
"""

from __future__ import annotations

import logging
import os
from collections import namedtuple
from typing import Callable, Iterable, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from sparkdl_tpu.core import health, profiling, resilience

logger = logging.getLogger(__name__)


def _injected_decode_error(**ctx) -> bool:
    """The decode_error behavioral point, recorded in the health monitor
    (one DECODE_DEGRADED per degraded row, so a run's report shows how
    many rows the data plane dropped to null)."""
    if resilience.should_fire("decode_error", **ctx):
        health.record(health.DECODE_DEGRADED, injected=True)
        return True
    return False

# ---------------------------------------------------------------------------
# Schema: field-for-field the Spark ImageSchema struct the reference used.
# ---------------------------------------------------------------------------

imageSchema = pa.struct([
    pa.field("origin", pa.string()),
    pa.field("height", pa.int32()),
    pa.field("width", pa.int32()),
    pa.field("nChannels", pa.int32()),
    pa.field("mode", pa.int32()),
    pa.field("data", pa.binary()),
])

imageFields: List[str] = [f.name for f in imageSchema]

ImageType = namedtuple("ImageType", ["name", "ocvType", "nChannels", "dtype"])

# OpenCV type codes, as used by Spark's ImageSchema (uint8) and extended by
# the reference to float32 images.
SUPPORTED_OCV_TYPES = (
    ImageType("CV_8UC1", 0, 1, "uint8"),
    ImageType("CV_32FC1", 5, 1, "float32"),
    ImageType("CV_8UC3", 16, 3, "uint8"),
    ImageType("CV_32FC3", 21, 3, "float32"),
    ImageType("CV_8UC4", 24, 4, "uint8"),
    ImageType("CV_32FC4", 29, 4, "float32"),
)

_OCV_BY_NAME = {t.name: t for t in SUPPORTED_OCV_TYPES}
_OCV_BY_CODE = {t.ocvType: t for t in SUPPORTED_OCV_TYPES}


def imageTypeByName(name: str) -> ImageType:
    try:
        return _OCV_BY_NAME[name]
    except KeyError:
        raise ValueError(f"Unsupported image mode name {name!r}; "
                         f"supported: {sorted(_OCV_BY_NAME)}") from None


def imageTypeByCode(code: int) -> ImageType:
    try:
        return _OCV_BY_CODE[int(code)]
    except KeyError:
        raise ValueError(f"Unsupported image mode code {code}; "
                         f"supported: {sorted(_OCV_BY_CODE)}") from None


def imageTypeForArray(array: np.ndarray) -> ImageType:
    if array.ndim != 3:
        raise ValueError(f"Image array must be HWC (3-D), got shape {array.shape}")
    channels = array.shape[2]
    if array.dtype == np.uint8:
        kind = "CV_8UC"
    elif array.dtype == np.float32:
        kind = "CV_32FC"
    else:
        raise ValueError(f"Unsupported image array dtype {array.dtype}; "
                         "use uint8 or float32")
    return imageTypeByName(f"{kind}{channels}")


# ---------------------------------------------------------------------------
# numpy <-> struct codecs
# ---------------------------------------------------------------------------

def imageArrayToStruct(imgArray: np.ndarray, origin: str = "") -> dict:
    """Encode an HWC numpy array as an image-struct dict (schema above)."""
    if imgArray.ndim == 2:
        imgArray = imgArray[:, :, None]
    imgArray = np.ascontiguousarray(imgArray)
    imType = imageTypeForArray(imgArray)
    height, width, nChannels = imgArray.shape
    return {
        "origin": origin,
        "height": int(height),
        "width": int(width),
        "nChannels": int(nChannels),
        "mode": imType.ocvType,
        "data": imgArray.tobytes(),
    }


def imageStructToArray(imageRow) -> np.ndarray:
    """Decode an image-struct (dict or Arrow struct scalar) to HWC numpy."""
    if isinstance(imageRow, pa.StructScalar):
        imageRow = imageRow.as_py()
    imType = imageTypeByCode(imageRow["mode"])
    shape = (imageRow["height"], imageRow["width"], imageRow["nChannels"])
    return np.frombuffer(imageRow["data"], dtype=imType.dtype).reshape(shape)


def imageStructsToBatchArray(structs: Sequence[dict],
                             target_size: Optional[Tuple[int, int]] = None,
                             dtype: Optional[str] = "float32",
                             channels: int = 3) -> np.ndarray:
    """Decode many image structs to one NHWC batch, resizing if needed.

    This is the host-side staging step that feeds ``device_put``: output is a
    single contiguous NHWC array so transfer to HBM is one DMA. With
    ``dtype=None`` the source dtype is preserved when uniform (uint8 images
    stage as uint8 — 4x fewer DMA bytes than float32; the device program
    casts after transfer) and promoted to float32 when mixed. Empty input
    keeps NHWC rank when ``target_size`` is known (empty partitions flow
    through filter/dropna and must not change rank downstream).
    """
    batch, _kept, _dropped = _stage_structs(structs, target_size, dtype,
                                            channels, tolerant=False)
    return batch


def imageStructsToBatchArrayTolerant(
        structs: Sequence[dict],
        target_size: Optional[Tuple[int, int]] = None,
        dtype: Optional[str] = "float32",
        channels: int = 3
) -> Tuple[np.ndarray, List[int], int]:
    """Like :func:`imageStructsToBatchArray`, but malformed rows degrade.

    Rows whose struct cannot be decoded (bad mode code, data bytes that
    don't match the declared shape, injected ``decode_error`` faults)
    are DROPPED instead of aborting the whole partition — Spark's
    corrupt-image convention (the reference read such rows as null
    structs). Returns ``(batch, kept_indices, n_dropped)`` where
    ``kept_indices`` indexes ``structs`` for the rows present in
    ``batch`` (order-preserving).
    """
    return _stage_structs(structs, target_size, dtype, channels,
                          tolerant=True)


def _stage_structs(structs, target_size, dtype, channels, tolerant: bool
                   ) -> Tuple[np.ndarray, List[int], int]:
    """Shared staging core: one implementation so the strict and tolerant
    paths can never drift apart in resize/dtype/empty-shape semantics."""
    arrays: List[np.ndarray] = []
    kept: List[int] = []
    dropped = 0
    for i, s in enumerate(structs):
        try:
            if tolerant and resilience.should_fire("decode_error"):
                raise ValueError("injected decode_error")
            arr = imageStructToArray(s)
            if (target_size is not None
                    and arr.shape[:2] != tuple(target_size)):
                arr = resizeImageArray(arr, target_size)
            arrays.append(arr if dtype is None
                          else np.asarray(arr, dtype=dtype))
            kept.append(i)
        except Exception as e:  # noqa: BLE001 - per-row degradation
            if not tolerant:
                raise
            dropped += 1
            logger.debug("dropping undecodable image row %d: %s", i, e)
    if dropped:
        health.record(health.DECODE_DEGRADED, n=dropped, stage="structs")
    if arrays:
        if dtype is None and len({a.dtype for a in arrays}) > 1:
            arrays = [np.asarray(a, dtype="float32") for a in arrays]
        return np.stack(arrays), kept, dropped
    empty_dtype = dtype or "uint8"
    if target_size is not None:
        empty = np.zeros((0, target_size[0], target_size[1], channels),
                         dtype=empty_dtype)
    else:
        empty = np.zeros((0,), dtype=empty_dtype)
    return empty, kept, dropped


def arrowImageBatch(col) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Zero-copy NHWC batch from a *uniform* Arrow image-struct column.

    Returns ``(batch, valid_indices)`` — ``batch`` is an (N,H,W,C) view into
    the column's contiguous binary values buffer (no per-row Python, no
    copies; VERDICT r2 weak #4) — or None when rows are non-uniform (mixed
    sizes/modes), in which case callers use the per-row path.

    ``valid_indices`` indexes the non-null rows of ``col`` (int64).
    """
    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    n = len(col)
    if n == 0:
        return None
    if col.null_count:
        valid_mask = np.asarray(col.is_valid())
        valid_idx = np.nonzero(valid_mask)[0]
        if valid_idx.size == 0:
            return None
        col = col.filter(pa.array(valid_mask))
    else:
        valid_idx = np.arange(n)
    heights = col.field("height").to_numpy(zero_copy_only=False)
    widths = col.field("width").to_numpy(zero_copy_only=False)
    channels = col.field("nChannels").to_numpy(zero_copy_only=False)
    modes = col.field("mode").to_numpy(zero_copy_only=False)
    if (heights.min() != heights.max() or widths.min() != widths.max()
            or channels.min() != channels.max()
            or modes.min() != modes.max()):
        return None
    h, w, c = int(heights[0]), int(widths[0]), int(channels[0])
    try:
        im_type = imageTypeByCode(int(modes[0]))
    except ValueError:
        return None
    dtype = np.dtype(im_type.dtype)
    data = col.field("data")
    if isinstance(data, pa.ChunkedArray):
        data = data.combine_chunks()
    if data.null_count:
        return None
    row_bytes = h * w * c * dtype.itemsize
    buffers = data.buffers()
    if len(buffers) < 3 or buffers[2] is None:
        return None
    offsets = np.frombuffer(buffers[1], dtype=np.int32,
                            count=len(data) + 1 + data.offset)[data.offset:]
    if not np.all(np.diff(offsets) == row_bytes):
        return None  # ragged payloads — metadata lied; per-row path validates
    values = np.frombuffer(buffers[2], dtype=np.uint8)
    start = int(offsets[0])
    end = int(offsets[-1])
    batch = values[start:end].view(dtype).reshape(len(data), h, w, c)
    return batch, valid_idx


def _structColumnPerRow(arrays: Sequence[Optional[np.ndarray]],
                        origins: Sequence[str]) -> pa.Array:
    """Per-row image-struct column builder — the compatibility path for
    ragged batches and ``EngineConfig.columnar_images = False``."""
    # sparkdl: allow(columnar-hot-path): THE per-row fallback the
    # columnar builder degrades to for ragged/odd-dtype batches; uniform
    # batches never reach it
    values = [imageArrayToStruct(np.asarray(a), origin=o)
              if a is not None else None
              for a, o in zip(arrays, origins)]
    return pa.array(values, type=imageSchema)


def imageArraysToStructColumn(arrays: Sequence[Optional[np.ndarray]],
                              origins: Sequence[str]) -> pa.Array:
    """Image-struct column from decoded HWC arrays (None = null row).

    Columnar fast path (``EngineConfig.columnar_images``, docs/PERF.md
    "Columnar data plane"): a uniform-shape/-dtype batch packs into ONE
    contiguous values buffer wrapped as the column's binary child —
    zero-copy when the arrays are already consecutive views of one base
    buffer (the decode pool's single-copy adoption), one vectorized
    ``np.stack`` otherwise — and the height/width/channels/mode children
    are vectorized int32 arrays. No per-row dict, no per-row
    ``tobytes``; :func:`arrowImageBatch` recovers the NHWC view
    downstream without copying. The column is logically identical to the
    per-row builder's output; ragged batches (mixed shapes/dtypes,
    2-D grayscale) and ``columnar_images = False`` take the per-row
    path.
    """
    from sparkdl_tpu.engine.dataframe import EngineConfig  # lazy: no cycle

    n = len(arrays)
    if n == 0 or not EngineConfig.columnar_images:
        return _structColumnPerRow(arrays, origins)
    valid = [i for i, a in enumerate(arrays) if a is not None]
    if not valid:
        return pa.array([None] * n, type=imageSchema)
    first = arrays[valid[0]]
    if (not isinstance(first, np.ndarray) or first.ndim != 3
            or any(not isinstance(arrays[i], np.ndarray)
                   or arrays[i].shape != first.shape
                   or arrays[i].dtype != first.dtype for i in valid[1:])):
        return _structColumnPerRow(arrays, origins)
    try:
        mode = imageTypeForArray(first).ocvType
    except ValueError:  # dtype outside the OpenCV codes
        return _structColumnPerRow(arrays, origins)
    h, w, c = first.shape
    row_bytes = h * w * c * first.dtype.itemsize
    if row_bytes * len(valid) > np.iinfo(np.int32).max:
        # pa.binary() carries int32 offsets; a partition this large is
        # pathological anyway — let the per-row builder chunk it
        return _structColumnPerRow(arrays, origins)
    flat = _contiguousValues([arrays[i] for i in valid], row_bytes)
    lengths = np.zeros(n, dtype=np.int64)
    lengths[valid] = row_bytes  # null rows: zero-length payload slots
    offsets = np.zeros(n + 1, dtype=np.int32)
    np.cumsum(lengths, out=offsets[1:])
    data_child = pa.Array.from_buffers(
        pa.binary(), n, [None, pa.py_buffer(offsets), pa.py_buffer(flat)])
    meta = np.zeros(n, dtype=np.int32)
    children = [pa.array(["" if o is None else o for o in origins],
                         type=pa.string())]
    for fill in (h, w, c, mode):
        col = meta.copy()
        col[valid] = fill
        children.append(pa.array(col))
    children.append(data_child)
    mask = None
    if len(valid) < n:
        null_mask = np.ones(n, dtype=bool)
        null_mask[valid] = False
        mask = pa.array(null_mask)
    return pa.StructArray.from_arrays(
        children, names=[f.name for f in imageSchema], mask=mask)


def _contiguousValues(arrs: List[np.ndarray], row_bytes: int) -> np.ndarray:
    """One flat uint8 buffer holding every array's pixels, in order.

    Zero-copy when the arrays are already consecutive C-contiguous views
    of a single 1-D uint8 base (what ``decode_pool._adopt_result`` hands
    back): the base's spanning slice IS the values buffer. Otherwise one
    vectorized ``np.stack`` — a single memcpy, never a per-row Python
    hop."""
    base = arrs[0].base
    if (isinstance(base, np.ndarray) and base.ndim == 1
            and base.dtype == np.uint8 and base.flags["C_CONTIGUOUS"]):
        base_ptr = base.__array_interface__["data"][0]
        ptr0 = arrs[0].__array_interface__["data"][0]
        if all(a.base is base and a.flags["C_CONTIGUOUS"]
               and a.__array_interface__["data"][0] == ptr0 + k * row_bytes
               for k, a in enumerate(arrs)):
            start = ptr0 - base_ptr
            return base[start:start + row_bytes * len(arrs)]
    return np.ascontiguousarray(np.stack(arrs)).view(np.uint8).reshape(-1)


# ---------------------------------------------------------------------------
# Decode / resize (native fast path, PIL fallback)
# ---------------------------------------------------------------------------

def _pil_decode(data_or_path, target_size=None) -> Optional[np.ndarray]:
    from io import BytesIO
    from PIL import Image

    try:
        if isinstance(data_or_path, (bytes, bytearray)):
            img = Image.open(BytesIO(data_or_path))
        else:
            img = Image.open(data_or_path)
        if img.mode not in ("L", "RGB", "RGBA"):
            img = img.convert("RGB")
        if target_size is not None:
            # PIL size is (W, H); target_size is (H, W) like the model spec.
            img = img.resize((target_size[1], target_size[0]), Image.BILINEAR)
        return np.asarray(img)
    except Exception:
        return None


def decodeImageBytes(data: bytes, target_size=None,
                     channels: Optional[int] = None) -> Optional[np.ndarray]:
    """Decode compressed image bytes → HWC uint8 array (None on failure).

    ``channels=None`` preserves the source's own channel count (grayscale
    stays 1-channel, like Spark's ImageSchema reader); pass 3 to force RGB
    — the model-staging contract, so the per-row path matches the batch
    decoder's output for the same input (ADVICE r2 consistency fix).
    """
    from sparkdl_tpu.native import loader as native_loader

    if channels is not None:
        if target_size is not None:
            # decode_error injection happens inside the batch decoder —
            # checking here too would consume two fault occurrences per
            # decode and mistarget occurrence-indexed Faults
            return decodeImageBytesBatch([data], target_size,
                                         channels=channels)[0]
        if _injected_decode_error():
            return None
        # no target size: native decode (fast path, GIL released)
        # preserves channels; coerce after
        if native_loader.available():
            arr = native_loader.decode(data, target_size=None)
            if arr is not None:
                return forceChannels(arr, channels)
        out = _pil_decode_channels(data, None, channels)
        if out is None:
            health.record(health.DECODE_DEGRADED, stage="bytes")
        return out
    if _injected_decode_error():
        return None
    if native_loader.available():
        arr = native_loader.decode(data, target_size=target_size)
        if arr is not None:
            return arr
    out = _pil_decode(data, target_size=target_size)
    if out is None:
        health.record(health.DECODE_DEGRADED, stage="bytes")
    return out


def stripFileScheme(uri: str) -> str:
    """Normalize 'file://<path>' / 'file:<path>' URIs (both emitted by Spark
    and by this package's readers) to a plain filesystem path."""
    if uri.startswith("file://"):
        return uri[7:]
    if uri.startswith("file:"):
        return uri[5:]
    return uri


def decodeImageFile(path: str, target_size=None,
                    channels: Optional[int] = None) -> Optional[np.ndarray]:
    """Decode an image file URI → HWC uint8 array (None on failure)."""
    path = stripFileScheme(path)
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return None
    return decodeImageBytes(data, target_size=target_size, channels=channels)


def decodeImageBytesBatch(blobs: Sequence[Optional[bytes]],
                          target_size: Tuple[int, int],
                          channels: int = 3) -> List[Optional[np.ndarray]]:
    """Decode a partition's worth of compressed blobs at once.

    Fast paths, in order: the multi-process decode pool when
    ``EngineConfig.decode_workers > 0`` (``core/decode_pool.py`` — the
    whole list fans out to worker processes and comes back through
    shared memory, order-preserving and pixel-identical to the inline
    path); else ONE call into the threaded C++ ``sdl_decode_batch`` (the
    GIL is released for the whole batch — SURVEY.md §7 hard-part #2, MXU
    starvation); blobs the native decoder rejects (or all blobs, when
    the library isn't built) fall back to PIL individually. Returns one
    HWC uint8 array (or None) per input blob, order-preserving. Fault
    injection and health accounting happen HERE, in the submitting
    process, regardless of path — pool on/off is event-identical.
    """
    out: List[Optional[np.ndarray]] = [None] * len(blobs)
    valid = [i for i, b in enumerate(blobs)
             if b and not _injected_decode_error()]
    if not valid:
        return out
    picked = [blobs[i] for i in valid]
    pool = _maybe_decode_pool(len(picked))
    if pool is not None:
        decoded = pool.decode(picked, target_size=target_size,
                              channels=channels)
    else:
        decoded = _decodeValidBlobs(picked, target_size, channels)
    for j, i in enumerate(valid):
        out[i] = decoded[j]
    undecodable = sum(1 for i in valid if out[i] is None)
    if undecodable:
        # genuinely corrupt blobs (injected fires were counted above)
        health.record(health.DECODE_DEGRADED, n=undecodable, stage="bytes")
    return out


def _maybe_decode_pool(n_blobs: int):
    """The process-wide decode pool, or None when disabled / not worth a
    round trip (single-blob calls — the per-row ``decodeImageFile`` path
    — stay inline: one IPC round trip per row would cost more than the
    decode)."""
    if n_blobs < 2:
        return None
    from sparkdl_tpu.core import decode_pool

    return decode_pool.maybe_pool()


def _decodeValidBlobs(blobs: Sequence[bytes], target_size: Tuple[int, int],
                      channels: int) -> List[Optional[np.ndarray]]:
    """Decode non-null blobs to fixed-geometry HWC uint8 (no fault
    injection, no health accounting — the caller owns both). Shared by
    the inline path and the decode-pool workers so the two can never
    drift apart in pixel semantics.

    The PIL fallback hoists the channel-mode lookup and reuses ONE
    scratch buffer across the loop instead of allocating a fresh
    ``BytesIO`` (and re-validating ``channels``) per failing blob.
    """
    from sparkdl_tpu.native import loader as native_loader

    out: List[Optional[np.ndarray]] = [None] * len(blobs)
    res = native_loader.decode_batch_status(list(blobs), target_size,
                                            channels=channels)
    if res is not None:
        batch, ok = res
        for i in range(len(blobs)):
            if ok[i]:
                out[i] = batch[i]
    remaining = [i for i in range(len(blobs)) if out[i] is None]
    if not remaining:
        return out
    from io import BytesIO

    from PIL import Image

    try:
        mode = _PIL_MODE_BY_CHANNELS[channels]
    except KeyError:
        raise ValueError(
            f"Unsupported channel count {channels}; "
            f"supported: {sorted(_PIL_MODE_BY_CHANNELS)}") from None
    scratch = BytesIO()
    for i in remaining:
        scratch.seek(0)
        scratch.truncate()
        scratch.write(blobs[i])
        scratch.seek(0)
        try:
            img = Image.open(scratch).convert(mode)
            if target_size is not None:
                img = img.resize((target_size[1], target_size[0]),
                                 Image.BILINEAR)
            arr = np.asarray(img)
            if arr.ndim == 2:
                arr = arr[:, :, None]
            out[i] = arr
        # sparkdl: allow(broad-retry): per-blob degradation to a null row, not a retry — callers count the Nones and record decode_degraded
        except Exception:  # noqa: BLE001 - per-blob degradation
            out[i] = None
    return out


def decodePoolChunk(blobs: Sequence[Optional[bytes]],
                    target_size: Optional[Tuple[int, int]] = None,
                    channels: Optional[int] = None
                    ) -> List[Optional[np.ndarray]]:
    """One decode-pool chunk, decoded worker-side with inline-path
    semantics. The fixed-geometry path batches the WHOLE chunk through
    :func:`_decodeValidBlobs` — one native threaded call per chunk, not
    one per blob, so arming the pool on a native-enabled host keeps the
    C++ batch decoder's throughput. Errors the inline path would raise
    (an unsupported channel count, a coercion failure) PROPAGATE — the
    pool ships them back to the submitting process and re-raises there,
    so pool on/off fail identically instead of degrading to null rows.
    """
    present = [i for i, b in enumerate(blobs) if b]
    out: List[Optional[np.ndarray]] = [None] * len(blobs)
    if target_size is not None and channels is not None:
        decoded = _decodeValidBlobs([blobs[i] for i in present],
                                    target_size, channels)
        for j, i in enumerate(present):
            out[i] = decoded[j]
        return out
    for i in present:
        out[i] = decodePoolBlob(blobs[i], target_size=target_size,
                                channels=channels)
    return out


def decodePoolBlob(blob: Optional[bytes],
                   target_size: Optional[Tuple[int, int]] = None,
                   channels: Optional[int] = None
                   ) -> Optional[np.ndarray]:
    """One blob decoded with the EXACT inline-path pixel semantics but
    no fault injection and no health recording — the decode-pool worker
    entry (``core/decode_pool.py``). Injection and health accounting
    stay in the submitting process so pool on/off is event-identical.
    """
    if not blob:
        return None
    if target_size is not None and channels is not None:
        return _decodeValidBlobs([blob], target_size, channels)[0]
    from sparkdl_tpu.native import loader as native_loader

    if native_loader.available():
        arr = native_loader.decode(blob, target_size=target_size)
        if arr is not None:
            return forceChannels(arr, channels) if channels is not None \
                else arr
    if channels is not None:
        return _pil_decode_channels(blob, target_size, channels)
    return _pil_decode(blob, target_size=target_size)


_PIL_MODE_BY_CHANNELS = {1: "L", 3: "RGB", 4: "RGBA"}


def forceChannels(arr: np.ndarray, channels: int) -> np.ndarray:
    """Coerce an HWC uint8 array to a channel count, PIL-convert semantics
    (L→RGB replicates, RGBA→RGB drops alpha, RGB→L is ITU-R 601 luma)."""
    have = arr.shape[2]
    if have == channels:
        return arr
    if channels == 3:
        if have == 1:
            return np.repeat(arr, 3, axis=2)
        if have == 4:
            return np.ascontiguousarray(arr[:, :, :3])
    if channels == 1 and have in (3, 4):
        luma = (arr[:, :, 0] * 0.299 + arr[:, :, 1] * 0.587
                + arr[:, :, 2] * 0.114)
        return luma.astype(np.uint8)[:, :, None]
    if channels == 4 and have == 3:
        alpha = np.full(arr.shape[:2] + (1,), 255, dtype=np.uint8)
        return np.concatenate([arr, alpha], axis=2)
    raise ValueError(f"Cannot coerce {have}-channel image to {channels}")


def _pil_decode_channels(data: bytes, target_size, channels: int
                         ) -> Optional[np.ndarray]:
    """PIL decode forced to a fixed channel count (the batch-staging
    contract: every row must match the native decoder's output channels).
    Supported: 1 (grayscale), 3 (RGB), 4 (RGBA); others raise."""
    from io import BytesIO

    from PIL import Image

    try:
        mode = _PIL_MODE_BY_CHANNELS[channels]
    except KeyError:
        raise ValueError(
            f"Unsupported channel count {channels}; "
            f"supported: {sorted(_PIL_MODE_BY_CHANNELS)}") from None
    try:
        img = Image.open(BytesIO(data))
        img = img.convert(mode)
        if target_size is not None:
            img = img.resize((target_size[1], target_size[0]), Image.BILINEAR)
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr
    except Exception:
        return None


def decodeImageFilesBatch(uris: Sequence[Optional[str]],
                          target_size: Tuple[int, int],
                          channels: int = 3) -> List[Optional[np.ndarray]]:
    """Read + batch-decode image files; one HWC uint8 (or None) per URI."""
    blobs: List[Optional[bytes]] = []
    for uri in uris:
        if uri is None:
            blobs.append(None)
            continue
        try:
            with open(stripFileScheme(uri), "rb") as f:
                blobs.append(f.read())
        except OSError:
            blobs.append(None)
    return decodeImageBytesBatch(blobs, target_size, channels=channels)


def resizeBatchArray(batch: np.ndarray, target_size: Tuple[int, int]
                     ) -> np.ndarray:
    """Vectorized bilinear resize of an NHWC batch (numpy, any dtype).

    Pixel-center sampling WITHOUT antialiasing — the same convention as the
    native ``sdl_resize_batch`` and the on-device ``ModelFunction.resized``
    path (they agree to uint8 rounding), NOT the PIL path used by the
    per-row keras-semantics loaders. Serves as the host fallback when the
    native library is unavailable or the dtype is not uint8.
    """
    n, sh, sw, c = batch.shape
    th, tw = target_size
    if (sh, sw) == (th, tw):
        return batch
    fy = np.clip((np.arange(th) + 0.5) * (sh / th) - 0.5, 0, sh - 1)
    fx = np.clip((np.arange(tw) + 0.5) * (sw / tw) - 0.5, 0, sw - 1)
    y0 = fy.astype(np.int64)
    y1 = np.minimum(y0 + 1, sh - 1)
    x0 = fx.astype(np.int64)
    x1 = np.minimum(x0 + 1, sw - 1)
    wy = (fy - y0).astype(np.float32)[None, :, None, None]
    wx = (fx - x0).astype(np.float32)[None, None, :, None]
    b = batch.astype(np.float32, copy=False)
    top = b[:, y0][:, :, x0] * (1 - wx) + b[:, y0][:, :, x1] * wx
    bot = b[:, y1][:, :, x0] * (1 - wx) + b[:, y1][:, :, x1] * wx
    out = top * (1 - wy) + bot * wy
    if batch.dtype == np.uint8:
        return np.clip(out + 0.5, 0, 255).astype(np.uint8)
    return out.astype(batch.dtype)


def resizeImageArray(arr: np.ndarray, target_size: Tuple[int, int]) -> np.ndarray:
    """Bilinear-resize an HWC array to (H, W). Host-side, numpy/PIL only."""
    from PIL import Image

    th, tw = target_size
    if arr.shape[:2] == (th, tw):
        return arr
    in_dtype = arr.dtype
    if in_dtype == np.uint8:
        img = Image.fromarray(arr.squeeze(-1) if arr.shape[2] == 1 else arr)
        out = np.asarray(img.resize((tw, th), Image.BILINEAR))
        if out.ndim == 2:
            out = out[:, :, None]
        return out
    # float path: resize channel-planes via PIL 'F' mode
    planes = [
        np.asarray(Image.fromarray(arr[:, :, c], mode="F").resize((tw, th), Image.BILINEAR))
        for c in range(arr.shape[2])
    ]
    return np.stack(planes, axis=-1).astype(in_dtype)


# ---------------------------------------------------------------------------
# DataFrame readers (parity: readImagesWithCustomFn / readImages)
# ---------------------------------------------------------------------------

_IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png", ".gif", ".bmp")


def listImageFiles(path: str) -> List[str]:
    path = stripFileScheme(path)
    if os.path.isfile(path):
        return [path]
    found = []
    for root, _dirs, files in os.walk(path):
        for fname in sorted(files):
            if fname.lower().endswith(_IMAGE_EXTENSIONS):
                found.append(os.path.join(root, fname))
    return sorted(found)


def _decodeBlobsDefault(blobs: Sequence[Optional[bytes]]
                        ) -> List[Optional[np.ndarray]]:
    """Default-decoder (:func:`decodeImageBytes`, no target size, source
    channels preserved) over a partition's blobs: the decode pool fans
    the list out to worker processes when armed, else the inline per-blob
    loop. Fault injection and per-row ``decode_degraded`` accounting stay
    in this (the submitting) process on both paths, in row order — pool
    on/off is bit- and event-identical."""
    out: List[Optional[np.ndarray]] = [None] * len(blobs)
    present = [i for i, b in enumerate(blobs) if b is not None]
    pool = _maybe_decode_pool(len(present))
    if pool is None:
        for i in present:
            out[i] = decodeImageBytes(blobs[i])
        return out
    valid = [i for i in present if not _injected_decode_error()]
    decoded = pool.decode([blobs[i] for i in valid])
    for j, i in enumerate(valid):
        out[i] = decoded[j]
        if decoded[j] is None:
            # mirror decodeImageBytes's per-row event exactly
            health.record(health.DECODE_DEGRADED, stage="bytes")
    return out


def _readImagesDecodePartition(batch) -> pa.Array:
    """Whole-partition decode op for the DEFAULT ``readImages`` decoder:
    read every file, batch-decode (pool-aware), wrap as an image-struct
    column — columnar (zero-copy, docs/PERF.md "Columnar data plane")
    when the partition decodes uniform."""
    idx = batch.schema.get_field_index("filePath")
    # sparkdl: allow(columnar-hot-path): string URI column — per-row
    # Python strings are the product here, not pixels
    uris = batch.column(idx).to_pylist()
    with profiling.annotate("sparkdl.decode", rows=len(uris)):
        blobs: List[Optional[bytes]] = []
        for uri in uris:
            try:
                with open(stripFileScheme(uri), "rb") as f:
                    blobs.append(f.read())
            except OSError:
                blobs.append(None)
        arrays = _decodeBlobsDefault(blobs)
    return imageArraysToStructColumn(arrays, uris)


def readImagesWithCustomFn(path: str, decode_f: Callable[[bytes], Optional[np.ndarray]],
                           numPartition: Optional[int] = None):
    """Read images under ``path`` with a custom decode fn → image DataFrame.

    Parity: upstream ``imageIO.readImagesWithCustomFn``. Returns an engine
    DataFrame with a single ``image`` struct column (plus ``filePath``);
    undecodable files yield null image structs, as the reference did.

    The DEFAULT decoder (:func:`decodeImageBytes`) runs as a
    whole-partition batch op so the multi-process decode pool
    (``EngineConfig.decode_workers``, docs/PERF.md "Parallel host
    ingest") can fan the partition's blobs out; with the pool off the op
    degrades to the identical per-row decode loop. A custom ``decode_f``
    keeps strict per-row semantics.
    """
    from sparkdl_tpu.engine import dataframe as edf  # lazy: avoid cycle

    files = listImageFiles(path)

    # Only the (cheap) file listing is eager; decode runs lazily inside the
    # engine's partition-parallel, retry-guarded column op.
    paths_df = edf.DataFrame.fromRows(
        [{"filePath": "file:" + f} for f in files],
        schema=pa.schema([pa.field("filePath", pa.string())]),
        numPartitions=numPartition)

    if decode_f is decodeImageBytes:
        return paths_df.withColumnBatch("image", _readImagesDecodePartition,
                                        outputType=imageSchema)

    def load(uri: str):
        try:
            with open(stripFileScheme(uri), "rb") as f:
                raw = f.read()
        except OSError:
            return None
        arr = decode_f(raw)
        if arr is None:
            return None
        return imageArrayToStruct(np.asarray(arr), origin=uri)

    return paths_df.withColumn("image", load, inputCols=["filePath"],
                               outputType=imageSchema)


def readImages(path: str, numPartition: Optional[int] = None):
    """Read images with the default decoder (native fast path / PIL)."""
    return readImagesWithCustomFn(path, decodeImageBytes, numPartition)
