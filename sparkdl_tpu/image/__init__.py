"""Image schema + host-side image I/O (reference L3 data layer)."""

from sparkdl_tpu.image import imageIO
from sparkdl_tpu.image.imageIO import (
    imageSchema,
    imageFields,
    imageArrayToStruct,
    imageStructToArray,
    readImages,
    readImagesWithCustomFn,
)

__all__ = [
    "imageIO",
    "imageSchema",
    "imageFields",
    "imageArrayToStruct",
    "imageStructToArray",
    "readImages",
    "readImagesWithCustomFn",
]
