"""Core ``Param``/``Params`` machinery (Spark ML semantics, dependency-free).

Reimplements the behavioral contract of ``pyspark.ml.param.Params`` that the
reference's L5 param layer extends (SURVEY.md §1 L5, §5.6): instance-level
param maps layered over class-level defaults, copy-with-extra semantics used
by ``fit(dataset, paramMap)``, and keyword-only constructors.
"""

from __future__ import annotations

import copy as _copy
import functools
import inspect
from typing import Any, Callable, Dict, Iterator, List, Optional


class Param:
    """A parameter descriptor with self-contained documentation.

    Mirrors ``pyspark.ml.param.Param``: identity is ``(parent, name)``.
    ``parent`` is the uid of the owning :class:`Params` instance once bound,
    or the owning class name for class-level declarations.
    """

    def __init__(self, parent: Any, name: str, doc: str,
                 typeConverter: Optional[Callable[[Any], Any]] = None):
        self.parent = parent.uid if isinstance(parent, Params) else str(parent)
        self.name = str(name)
        self.doc = str(doc)
        self.typeConverter = typeConverter or (lambda v: v)

    def _copy_new_parent(self, parent: "Params") -> "Param":
        new = _copy.copy(self)
        new.parent = parent.uid
        return new

    def __str__(self) -> str:
        return f"{self.parent}__{self.name}"

    def __repr__(self) -> str:
        return f"Param(parent={self.parent!r}, name={self.name!r}, doc={self.doc!r})"

    def __hash__(self) -> int:
        return hash(str(self))

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Param) and str(self) == str(other)


_uid_counters: Dict[str, int] = {}


def _gen_uid(cls_name: str) -> str:
    n = _uid_counters.get(cls_name, 0)
    _uid_counters[cls_name] = n + 1
    return f"{cls_name}_{n:04x}"


def keyword_only(func: Callable) -> Callable:
    """Force keyword-only invocation and stash kwargs on the instance.

    The reference uses pyspark's ``@keyword_only`` on every Transformer /
    Estimator ``__init__`` and ``setParams`` so that ``_set(**kwargs)`` can
    apply exactly the user-passed values. Same contract here: the wrapped
    function can read ``self._input_kwargs``.
    """

    @functools.wraps(func)
    def wrapper(self, *args: Any, **kwargs: Any) -> Any:
        if args:
            raise TypeError(
                f"{func.__name__}() only accepts keyword arguments, got "
                f"{len(args)} positional")
        self._input_kwargs = kwargs
        return func(self, **kwargs)

    wrapper._keyword_only = True  # type: ignore[attr-defined]
    return wrapper


class Params:
    """Mixin for components that carry typed parameters.

    Subclasses declare class-level :class:`Param` attributes; on first
    instantiation each is re-bound to the instance (fresh ``parent`` uid) so
    two instances never share mutable param state. Values resolve through
    two layers: the instance ``_paramMap`` (explicitly set) over
    ``_defaultParamMap`` (declared defaults) — identical to Spark ML.
    """

    def __init__(self) -> None:
        self.uid = _gen_uid(type(self).__name__)
        self._paramMap: Dict[Param, Any] = {}
        self._defaultParamMap: Dict[Param, Any] = {}
        self._params_cache: Optional[List[Param]] = None
        self._copy_params()

    def _copy_params(self) -> None:
        for name in dir(type(self)):
            attr = getattr(type(self), name, None)
            if isinstance(attr, Param):
                setattr(self, name, attr._copy_new_parent(self))

    # -- declaration / lookup ------------------------------------------------

    @property
    def params(self) -> List[Param]:
        if self._params_cache is None:
            self._params_cache = sorted(
                (getattr(self, name) for name in dir(self)
                 if name != "params" and isinstance(getattr(self, name, None), Param)),
                key=lambda p: p.name)
        return self._params_cache

    def hasParam(self, paramName: str) -> bool:
        attr = getattr(self, paramName, None)
        return isinstance(attr, Param)

    def getParam(self, paramName: str) -> Param:
        param = getattr(self, paramName, None)
        if not isinstance(param, Param):
            raise ValueError(f"{type(self).__name__} has no param {paramName!r}")
        return param

    def _resolveParam(self, param) -> Param:
        if isinstance(param, Param):
            self._shouldOwn(param)
            return param
        if isinstance(param, str):
            return self.getParam(param)
        raise TypeError(f"cannot resolve {param!r} as a param")

    def _shouldOwn(self, param: Param) -> None:
        if not (param.parent == self.uid and self.hasParam(param.name)):
            raise ValueError(f"Param {param} does not belong to {self.uid}")

    # -- set / get -----------------------------------------------------------

    def set(self, param, value: Any) -> "Params":
        param = self._resolveParam(param)
        try:
            value = param.typeConverter(value)
        except (TypeError, ValueError) as e:
            raise TypeError(
                f"Invalid value for param {param.name}: {e}") from e
        self._paramMap[param] = value
        return self

    def _set(self, **kwargs: Any) -> "Params":
        for name, value in kwargs.items():
            if value is not None:
                self.set(self.getParam(name), value)
        return self

    def _setDefault(self, **kwargs: Any) -> "Params":
        for name, value in kwargs.items():
            param = self.getParam(name)
            if value is not None:
                value = param.typeConverter(value)
            self._defaultParamMap[param] = value
        return self

    def clear(self, param) -> "Params":
        self._paramMap.pop(self._resolveParam(param), None)
        return self

    def isSet(self, param) -> bool:
        return self._resolveParam(param) in self._paramMap

    def hasDefault(self, param) -> bool:
        return self._resolveParam(param) in self._defaultParamMap

    def isDefined(self, param) -> bool:
        return self.isSet(param) or self.hasDefault(param)

    def getOrDefault(self, param) -> Any:
        param = self._resolveParam(param)
        if param in self._paramMap:
            return self._paramMap[param]
        if param in self._defaultParamMap:
            return self._defaultParamMap[param]
        raise KeyError(f"Param {param.name} is not set and has no default")

    def getDefault(self, param) -> Any:
        return self._defaultParamMap[self._resolveParam(param)]

    # -- param maps / copy (fit(df, paramMap) semantics) ---------------------

    def extractParamMap(self, extra: Optional[Dict[Param, Any]] = None) -> Dict[Param, Any]:
        merged = dict(self._defaultParamMap)
        merged.update(self._paramMap)
        if extra:
            merged.update(extra)
        return merged

    def copy(self, extra: Optional[Dict[Param, Any]] = None) -> "Params":
        """Deep-ish copy: new instance, same uid, params re-bound, extra applied.

        Spark ML keeps the uid across ``copy`` — downstream code (param maps
        keyed by (uid, name)) relies on that, so we do too.
        """
        that = _copy.copy(self)
        that._paramMap = dict(self._paramMap)
        that._defaultParamMap = dict(self._defaultParamMap)
        that._params_cache = None
        that._copy_params_keep_uid()
        if extra:
            # pyspark semantics: extra entries whose param the new instance
            # does not own are silently ignored (lets one param map fan out
            # across pipeline stages, each taking only what it owns).
            for param, value in extra.items():
                if isinstance(param, Param) and that.hasParam(param.name):
                    that._paramMap[that.getParam(param.name)] = value
        return that

    def _copy_params_keep_uid(self) -> None:
        # Re-bind Param descriptors so they compare equal under the kept uid;
        # remap existing entries onto the re-bound keys.
        old_pm, old_dm = self._paramMap, self._defaultParamMap
        by_name_pm = {p.name: v for p, v in old_pm.items()}
        by_name_dm = {p.name: v for p, v in old_dm.items()}
        for name in dir(type(self)):
            attr = getattr(type(self), name, None)
            if isinstance(attr, Param):
                setattr(self, name, attr._copy_new_parent(self))
        self._paramMap = {self.getParam(n): v for n, v in by_name_pm.items()}
        self._defaultParamMap = {self.getParam(n): v for n, v in by_name_dm.items()}

    def _copyValues(self, to: "Params", extra: Optional[Dict[Param, Any]] = None) -> "Params":
        paramMap = self.extractParamMap(extra)
        for param, value in paramMap.items():
            if to.hasParam(param.name):
                to._paramMap[to.getParam(param.name)] = value
        return to

    # -- docs ----------------------------------------------------------------

    def explainParam(self, param) -> str:
        param = self._resolveParam(param)
        values = []
        if self.hasDefault(param):
            values.append(f"default: {self.getDefault(param)!r}")
        if self.isSet(param):
            values.append(f"current: {self._paramMap[param]!r}")
        state = ", ".join(values) if values else "undefined"
        return f"{param.name}: {param.doc} ({state})"

    def explainParams(self) -> str:
        return "\n".join(self.explainParam(p) for p in self.params)

    def __repr__(self) -> str:
        return f"{type(self).__name__}(uid={self.uid})"
