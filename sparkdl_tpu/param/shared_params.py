"""Shared param mixins used across transformers/estimators.

Parity: upstream ``python/sparkdl/param/shared_params.py`` +
``image_params.py`` (SURVEY.md §2.1). The reference's mixins were
``HasInputCol/HasOutputCol/HasLabelCol``, ``HasKerasModel``,
``HasKerasOptimizer``, ``HasKerasLoss``, ``HasOutputMode``, and
``CanLoadImage``; the TPU rebuild keeps the names and semantics, swapping
Keras/TF payloads for JAX-native ones (``ModelFunction``, optax optimizers).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from sparkdl_tpu.param.base import Param, Params
from sparkdl_tpu.param.converters import SparkDLTypeConverters, TypeConverters


class HasInputCol(Params):
    inputCol = Param(
        "HasInputCol", "inputCol", "name of the input column",
        typeConverter=SparkDLTypeConverters.toColumnName)

    def setInputCol(self, value: str) -> "HasInputCol":
        return self._set(inputCol=value)

    def getInputCol(self) -> str:
        return self.getOrDefault(self.inputCol)


class HasOutputCol(Params):
    outputCol = Param(
        "HasOutputCol", "outputCol", "name of the output column",
        typeConverter=SparkDLTypeConverters.toColumnName)

    def setOutputCol(self, value: str) -> "HasOutputCol":
        return self._set(outputCol=value)

    def getOutputCol(self) -> str:
        return self.getOrDefault(self.outputCol)


class HasLabelCol(Params):
    labelCol = Param(
        "HasLabelCol", "labelCol",
        "name of the label column (one-hot or class-index encoded)",
        typeConverter=SparkDLTypeConverters.toColumnName)

    def setLabelCol(self, value: str) -> "HasLabelCol":
        return self._set(labelCol=value)

    def getLabelCol(self) -> str:
        return self.getOrDefault(self.labelCol)


class HasOutputMode(Params):
    outputMode = Param(
        "HasOutputMode", "outputMode",
        "how model output is written: 'vector' (flattened 1-D) or 'image' "
        "(re-encoded image struct)",
        typeConverter=SparkDLTypeConverters.toOutputMode)

    def setOutputMode(self, value: str) -> "HasOutputMode":
        return self._set(outputMode=value)

    def getOutputMode(self) -> str:
        return self.getOrDefault(self.outputMode)


class HasBatchSize(Params):
    batchSize = Param(
        "HasBatchSize", "batchSize",
        "device batch size; rows are padded to this for static XLA shapes",
        typeConverter=TypeConverters.toInt)

    def setBatchSize(self, value: int) -> "HasBatchSize":
        return self._set(batchSize=value)

    def getBatchSize(self) -> int:
        return self.getOrDefault(self.batchSize)


class HasPriority(Params):
    """Mixin: the device execution service's admission lane for this
    component's requests (``core/executor.py`` overload protection,
    docs/RESILIENCE.md "Overload & graceful degradation"): the coalescer
    drains ``"interactive"`` requests first and sheds ``"bulk"`` first,
    so batch featurize can never starve online traffic. ``None`` (unset)
    falls back to ``EngineConfig.executor_default_priority``."""

    priority = Param(
        "HasPriority", "priority",
        "executor admission lane: 'interactive' (drained first, shed "
        "last) or 'bulk' (the batch default). None falls back to "
        "EngineConfig.executor_default_priority",
        typeConverter=SparkDLTypeConverters.toPriority)

    def setPriority(self, value: Optional[str]) -> "HasPriority":
        if value is None:
            self.clear(self.priority)
            return self
        return self._set(priority=value)

    def getPriority(self) -> Optional[str]:
        return (self.getOrDefault(self.priority)
                if self.isDefined(self.priority) else None)


class HasMesh(Params):
    """Mixin: an optional ``jax.sharding.Mesh`` for multi-chip execution.

    When unset, components fall back to the framework default mesh
    (``sparkdl_tpu.core.mesh.set_default_mesh``) — the analog of the
    reference's implicit "run on every executor" scale-out (SURVEY.md §3.1):
    batches shard over the mesh's ``data`` axis, weights are replicated,
    XLA emits the collectives over ICI/DCN.
    """

    mesh = Param(
        "HasMesh", "mesh",
        "optional jax.sharding.Mesh; batch shards over its 'data' axis. "
        "None falls back to the framework default mesh (set_default_mesh)",
        typeConverter=TypeConverters.identity)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(mesh=None)

    def setMesh(self, value) -> "HasMesh":
        if value is None:
            self.clear(self.mesh)
            return self
        return self._set(mesh=value)

    def getMesh(self):
        return self.getOrDefault(self.mesh)

    def resolveMesh(self):
        """Explicit param if set, else the framework default mesh.

        Must be called on the driver thread before partition closures are
        built: ``use_mesh`` scoping is ContextVar-local and invisible to
        engine pool workers (see ``core.mesh.use_mesh``). Resolve eagerly
        in ``_transform`` and capture the Mesh into the closure.
        """
        from sparkdl_tpu.core.mesh import get_default_mesh

        mesh = self.getOrDefault(self.mesh)
        return mesh if mesh is not None else get_default_mesh()


class HasModelFunction(Params):
    """The rebuild's analog of the reference's ``tfInputGraph``/Keras-model
    params: a :class:`sparkdl_tpu.core.model_function.ModelFunction` —
    or a served model NAME (str), resolved through the process-wide
    serving registry at each transform call, so batch transformers
    follow deployments/cutovers/rollbacks like online requests do."""

    modelFunction = Param(
        "HasModelFunction", "modelFunction",
        "ModelFunction to apply (pure apply fn + params pytree + input "
        "spec), or the name of a serving-registry deployment",
        typeConverter=SparkDLTypeConverters.toModelFunction)

    def setModelFunction(self, value: Any) -> "HasModelFunction":
        return self._set(modelFunction=value)

    def getModelFunction(self):
        value = self.getOrDefault(self.modelFunction)
        if isinstance(value, str):
            # lazy import: param must stay importable without serving
            from sparkdl_tpu.serving.registry import default_registry

            return default_registry().model(value)
        return value


class HasInputDType(Params):
    inputDType = Param(
        "HasInputDType", "inputDType",
        "numpy dtype name the input column is cast to before device transfer",
        typeConverter=TypeConverters.toString)

    def setInputDType(self, value: str) -> "HasInputDType":
        return self._set(inputDType=value)

    def getInputDType(self) -> str:
        return self.getOrDefault(self.inputDType)


class CanLoadImage(Params):
    """Mixin for components that load image files from a URI column.

    Parity: upstream ``CanLoadImage.loadImagesInternal`` — a UDF mapping
    URI → decoded PIL image → user preprocessor → image struct. Here the
    decode path is the imageIO host pipeline (native C++ decoder when built,
    PIL fallback) and the result is an Arrow image-struct column.
    """

    imageLoader = Param(
        "CanLoadImage", "imageLoader",
        "callable URI -> HWC float/uint8 numpy array (decode + preprocess); "
        "None uses the default decode+resize for the model's input size",
        typeConverter=TypeConverters.identity)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(imageLoader=None)

    def setImageLoader(self, value: Optional[Callable]) -> "CanLoadImage":
        if value is None:
            # _set skips None (keyword_only ctor semantics); an explicit
            # None here means "back to the default decode+resize".
            self.clear(self.imageLoader)
            return self
        return self._set(imageLoader=value)

    def getImageLoader(self) -> Optional[Callable]:
        return self.getOrDefault(self.imageLoader)

    def loadImagesInternal(self, dataframe, inputCol: str, outputCol: str,
                           target_size=None):
        """Add ``outputCol`` of image structs decoded from URI ``inputCol``.

        Runs host-side, partition-parallel (the reference ran it as a Spark
        Python-worker UDF; here it is an engine map over Arrow partitions).
        Default path with a known target size: the WHOLE partition decodes
        in one call into ``imageIO.decodeImageFilesBatch`` — the
        multi-process decode pool when ``EngineConfig.decode_workers > 0``
        (docs/PERF.md "Parallel host ingest"), else the threaded C++
        batch decoder (GIL released, PIL fallback per failing image) —
        the hot-path fix for SURVEY.md §7 hard-part #2. A custom
        ``imageLoader`` keeps per-row semantics.
        """
        from sparkdl_tpu.core import profiling  # lazy: avoid import cycle
        from sparkdl_tpu.image import imageIO

        loader = self.getOrDefault(self.imageLoader)

        if loader is None and target_size is not None:
            import pyarrow as pa

            def load_partition(batch: "pa.RecordBatch") -> "pa.Array":
                # span feeds phase_stats() — the estimator pipeline's
                # decode phase is decode-dominated and must be visible
                # (VERDICT r3 weak #5)
                with profiling.annotate("sparkdl.decode"):
                    idx = batch.schema.get_field_index(inputCol)
                    uris = batch.column(idx).to_pylist()
                    arrays = imageIO.decodeImageFilesBatch(uris, target_size)
                    # columnar zero-copy struct column when the decoded
                    # batch is uniform (docs/PERF.md "Columnar data
                    # plane"); per-row fallback otherwise
                    return imageIO.imageArraysToStructColumn(
                        arrays, [u or "" for u in uris])

            return dataframe.withColumnBatch(
                outputCol, load_partition, outputType=imageIO.imageSchema)

        def load_one(uri: str):
            with profiling.annotate("sparkdl.decode"):
                if loader is not None:
                    arr = loader(uri)
                else:
                    # channels=3 keeps per-row output identical to the
                    # batch decoder's forced-RGB contract (ADVICE r2:
                    # grayscale must not change channel count depending on
                    # which path ran)
                    arr = imageIO.decodeImageFile(
                        uri, target_size=target_size, channels=3)
                if arr is None:
                    return None
                return imageIO.imageArrayToStruct(arr)

        return dataframe.withColumn(
            outputCol, load_one, inputCols=[inputCol],
            outputType=imageIO.imageSchema)


class HasKerasModel(Params):
    """Mixin: a Keras model supplied as a file path or in-memory object.

    Parity: upstream ``HasKerasModel`` carried an HDF5 ``modelFile``; here
    ``.h5``/``.keras`` files load through keras and are ingested by the
    generic layer-DAG walker into a jitted ModelFunction
    (models.keras_ingest), so any supported Keras model runs on TPU.
    """

    modelFile = Param(
        "HasKerasModel", "modelFile",
        "path to a saved Keras model (.h5 or .keras)",
        typeConverter=TypeConverters.toString)
    model = Param(
        "HasKerasModel", "model",
        "in-memory Keras model object (alternative to modelFile)",
        typeConverter=TypeConverters.identity)

    def __init__(self) -> None:
        super().__init__()
        self._mf_cache = None

    def setModelFile(self, value: str) -> "HasKerasModel":
        self._mf_cache = None
        return self._set(modelFile=value)

    def getModelFile(self) -> Optional[str]:
        return self.getOrDefault(self.modelFile) if self.isDefined(self.modelFile) else None

    def setModel(self, value: Any) -> "HasKerasModel":
        self._mf_cache = None
        return self._set(model=value)

    def getModel(self) -> Any:
        return self.getOrDefault(self.model) if self.isDefined(self.model) else None

    def _invalidate_model_cache_if_set(self, kwargs) -> None:
        """For keyword_only setParams paths that bypass the setters."""
        if {"model", "modelFile"} & set(kwargs):
            self._mf_cache = None

    def copy(self, extra=None):
        # the ingested ModelFunction is immutable, so copies share the cache
        # unless the extra map swaps the model itself
        that = super().copy(extra)
        if extra and any(getattr(p, "name", None) in ("model", "modelFile")
                         for p in extra):
            that._mf_cache = None
        return that

    def loadKerasModelAsFunction(self):
        """Resolve model/modelFile to a ModelFunction (generic ingestion).

        Single-IO only at THIS surface: the Keras transformers/estimator
        bind one input column to one output column. Multi-input/-output
        models ingest fine via ``keras_to_model_function`` directly and
        serve through ``TPUTransformer`` ``inputMapping``/``outputMapping``.
        """
        from sparkdl_tpu.models.convert import load_keras_file
        from sparkdl_tpu.models.keras_ingest import keras_to_model_function

        model = self.getModel()
        if model is None:
            path = self.getModelFile()
            if path is None:
                raise ValueError("set either model or modelFile")
            model = load_keras_file(path)
        mf = keras_to_model_function(model)
        if isinstance(mf.input_spec, dict) or len(model.outputs) > 1:
            raise ValueError(
                f"{type(self).__name__} binds one input column to one "
                "output column; this Keras model has "
                f"{len(model.inputs)} inputs / {len(model.outputs)} "
                "outputs — use TPUTransformer with inputMapping/"
                "outputMapping for multi-IO models")
        return mf

    def cachedModelFunction(self):
        """loadKerasModelAsFunction with one ingestion per model value."""
        if self._mf_cache is None:
            self._mf_cache = self.loadKerasModelAsFunction()
        return self._mf_cache


class HasKerasOptimizer(Params):
    """Parity: upstream ``HasKerasOptimizer`` (keras optimizer name).

    The TPU estimator trains with optax; the accepted names map onto optax
    constructors (estimators module) while keeping keras-style spelling.
    """

    kerasOptimizer = Param(
        "HasKerasOptimizer", "kerasOptimizer",
        "optimizer name: one of 'adam', 'sgd', 'rmsprop', 'adagrad', "
        "'adamw'",
        typeConverter=TypeConverters.toString)

    def setKerasOptimizer(self, value: str) -> "HasKerasOptimizer":
        return self._set(kerasOptimizer=value)

    def getKerasOptimizer(self) -> str:
        return self.getOrDefault(self.kerasOptimizer)


class HasKerasLoss(Params):
    """Parity: upstream ``HasKerasLoss`` (keras loss name)."""

    kerasLoss = Param(
        "HasKerasLoss", "kerasLoss",
        "loss name: one of 'categorical_crossentropy', "
        "'sparse_categorical_crossentropy', 'binary_crossentropy', 'mse', "
        "'mae'",
        typeConverter=TypeConverters.toString)

    def setKerasLoss(self, value: str) -> "HasKerasLoss":
        return self._set(kerasLoss=value)

    def getKerasLoss(self) -> str:
        return self.getOrDefault(self.kerasLoss)
