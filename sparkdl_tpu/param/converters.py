"""Type converters for set-time param validation.

Parity: ``pyspark.ml.param.TypeConverters`` plus the reference's
``SparkDLTypeConverters`` (upstream ``python/sparkdl/param/converters.py``,
SURVEY.md §2.1 — cites are package-level, the reference mount was empty).
The reference validated TF-tensor↔column-name mappings; the TPU rebuild
validates model-io↔column-name mappings and model/mesh handles instead.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np


class TypeConverters:
    """Coercing validators mirroring ``pyspark.ml.param.TypeConverters``."""

    @staticmethod
    def identity(value: Any) -> Any:
        return value

    @staticmethod
    def toString(value: Any) -> str:
        if isinstance(value, str):
            return value
        raise TypeError(f"Could not convert {value!r} to string")

    @staticmethod
    def toInt(value: Any) -> int:
        if isinstance(value, bool):
            raise TypeError(f"Could not convert bool {value!r} to int")
        if isinstance(value, (int, np.integer)):
            return int(value)
        if isinstance(value, float) and value.is_integer():
            return int(value)
        raise TypeError(f"Could not convert {value!r} to int")

    @staticmethod
    def toFloat(value: Any) -> float:
        if isinstance(value, bool):
            raise TypeError(f"Could not convert bool {value!r} to float")
        if isinstance(value, (int, float, np.integer, np.floating)):
            return float(value)
        raise TypeError(f"Could not convert {value!r} to float")

    @staticmethod
    def toBoolean(value: Any) -> bool:
        if isinstance(value, bool):
            return value
        raise TypeError(f"Could not convert {value!r} to bool")

    @staticmethod
    def toList(value: Any) -> List[Any]:
        if isinstance(value, (list, tuple)):
            return list(value)
        if isinstance(value, np.ndarray):
            return value.tolist()
        raise TypeError(f"Could not convert {value!r} to list")

    @staticmethod
    def toListString(value: Any) -> List[str]:
        return [TypeConverters.toString(v) for v in TypeConverters.toList(value)]

    @staticmethod
    def toListInt(value: Any) -> List[int]:
        return [TypeConverters.toInt(v) for v in TypeConverters.toList(value)]

    @staticmethod
    def toListFloat(value: Any) -> List[float]:
        return [TypeConverters.toFloat(v) for v in TypeConverters.toList(value)]


class SparkDLTypeConverters:
    """Framework-specific converters (reference parity, TPU-native payloads).

    Where the reference validated ``{tf.Tensor-name: column-name}`` dicts for
    ``TFTransformer`` (upstream ``SparkDLTypeConverters.asColumnToTensorNameMap``
    etc.), the rebuild validates ``{model-input-name: column-name}`` maps for
    :class:`sparkdl_tpu.ml.tensor_transformer.TPUTransformer`'s multi-IO
    ``inputMapping``/``outputMapping`` params.
    """

    @staticmethod
    def toColumnName(value: Any) -> str:
        name = TypeConverters.toString(value)
        if not name:
            raise TypeError("column name must be non-empty")
        return name

    @staticmethod
    def asColumnToInputMap(value: Any) -> Dict[str, str]:
        """``{column-name: model-input-name}`` with string keys/values."""
        if not isinstance(value, dict):
            raise TypeError(f"Could not convert {value!r} to col->input map")
        out = {}
        for k, v in sorted(value.items()):
            out[SparkDLTypeConverters.toColumnName(k)] = TypeConverters.toString(v)
        return out

    @staticmethod
    def asOutputToColumnMap(value: Any) -> Dict[str, str]:
        """``{model-output-name: column-name}`` with string keys/values."""
        if not isinstance(value, dict):
            raise TypeError(f"Could not convert {value!r} to output->col map")
        out = {}
        for k, v in sorted(value.items()):
            out[TypeConverters.toString(k)] = SparkDLTypeConverters.toColumnName(v)
        return out

    @staticmethod
    def toModelFunction(value: Any):
        """Validate a ModelFunction-like object (duck-typed to avoid
        cycles) — or a string naming a serving-registry deployment,
        resolved to the ACTIVE version's model at transform time (so a
        hot-swap reaches batch transformers too)."""
        if isinstance(value, str):
            if not value:
                raise TypeError(
                    "modelFunction name must be non-empty (a serving "
                    "registry deployment name)")
            return value
        if hasattr(value, "apply_fn") and hasattr(value, "variables"):
            return value
        raise TypeError(
            f"Expected a ModelFunction (has .apply_fn/.variables) or a "
            f"served model name (str), got {type(value).__name__}")

    @staticmethod
    def supportedNameConverter(supportedList: List[str]):
        """Converter factory: value must be one of ``supportedList``.

        Mirrors the reference's converter used for ``modelName`` on
        ``DeepImagePredictor``/``DeepImageFeaturizer``.
        """

        def converter(value: Any) -> str:
            if value in supportedList:
                return value
            raise TypeError(f"{value!r} is not in the supported list {supportedList}")

        return converter

    @staticmethod
    def toOutputMode(value: Any) -> str:
        mode = TypeConverters.toString(value)
        if mode not in ("vector", "image"):
            raise TypeError(f"outputMode must be 'vector' or 'image', got {mode!r}")
        return mode

    @staticmethod
    def toPriority(value: Any) -> str:
        lane = TypeConverters.toString(value)
        if lane not in ("interactive", "bulk"):
            raise TypeError(
                f"priority must be 'interactive' or 'bulk', got {lane!r}")
        return lane
