"""Spark-ML-style ``Params`` system — the config/flag layer of the framework.

Parity target: the reference's param layer (``python/sparkdl/param/`` in the
upstream ``spark-deep-learning`` tree, per SURVEY.md §2.1 / §5.6 — the
reference mount was empty this round, so no file:line cites are possible).
The reference builds on ``pyspark.ml.param.Params``; pyspark is not in this
environment, so the full contract is re-implemented here from scratch:

- ``Param``: a typed, documented parameter *descriptor* attached to a class.
- ``Params``: mixin giving per-instance param maps (`set`/`getOrDefault`),
  defaults, ``extractParamMap``, ``copy`` with extra-map override, and
  ``explainParams`` — the semantics Spark ML Pipelines rely on.
- ``TypeConverters``: set-time validation/coercion.
- ``keyword_only``: the ctor pattern used by every Transformer/Estimator.

Everything downstream (transformers, estimators, the SQL-UDF registrar)
configures itself through this module; there are no global flags.
"""

from sparkdl_tpu.param.base import Param, Params, keyword_only
from sparkdl_tpu.param.converters import TypeConverters, SparkDLTypeConverters
from sparkdl_tpu.param.shared_params import (
    HasInputCol,
    HasOutputCol,
    HasLabelCol,
    HasOutputMode,
    HasBatchSize,
    HasModelFunction,
    HasInputDType,
    CanLoadImage,
)

__all__ = [
    "Param",
    "Params",
    "keyword_only",
    "TypeConverters",
    "SparkDLTypeConverters",
    "HasInputCol",
    "HasOutputCol",
    "HasLabelCol",
    "HasOutputMode",
    "HasBatchSize",
    "HasModelFunction",
    "HasInputDType",
    "CanLoadImage",
]
