"""Benchmark harness — one JSON line per metric. The headline metric
(InceptionV3 featurize images/sec/chip) is measured once and emitted both
FIRST (so a truncated run still records it) and as the final line (the
driver parses the last line).

Measures the five BASELINE.json configs on the real TPU chip:

  1. device featurize throughput, InceptionV3 (headline, images/sec/chip)
  2. end-to-end pipeline: JPEG files -> readImages -> DeepImageFeaturizer
  3. batch inference: DeepImagePredictor ResNet50 / Xception
  4. SQL UDF rows/sec via selectExpr
  5. fine-tune step time (MobileNetV2) + DP train step time (ResNet50)

Timing methodology (r3, measured — see core/profiling.py docstring):
cross-dispatch ``block_until_ready`` is NOT a reliable completion barrier
under the Axon PJRT tunnel, and each host round-trip costs ~90 ms. Device
throughput is therefore measured *inside* one XLA program: a
``lax.fori_loop`` whose body has a loop-carried dependence (a tiny
perturbation of the input from the running mean — defeats loop-invariant
hoisting, adds one elementwise pass), timed by the slope between a short
and a long loop, fetching only a scalar. Pipeline/UDF/fit numbers are
wall-clock over real materializations (min of repeats, after warmup).

The r1/r2 numbers (4,896 / 4,514 img/s) used dispatch-loop timing whose
overhead (~90 ms round-trip + a 4 MB fetch over ~8 iterations) hid ~40%
of real throughput and produced the phantom "r2 regression"; measured
properly the same r2 code runs ~7.3k img/s. vs_baseline stays null — the
reference publishes no numbers (BASELINE.json ``published: {}``).

Run ``python bench.py --headline`` for just the headline metric;
``SPARKDL_PROFILE_DIR=/tmp/trace python bench.py`` captures a profiler
trace of everything.
"""

import glob
import json
import os
import re
import sys
import tempfile
import time
from functools import partial

import numpy as np

HEADLINE_BATCH = 128
FLOPS_PER_IMG_INCEPTION = 5.7e9   # fwd, 2*MACs, 299x299
FLOPS_PER_IMG_RESNET50 = 7.75e9   # fwd, 2*MACs, 224x224
FLOPS_PER_IMG_DENSENET121 = 5.7e9   # fwd, 2*MACs, 224x224
FLOPS_PER_IMG_EFFNETB0 = 0.78e9     # fwd, 2*MACs, 224x224
PEAK_TFLOPS_BF16 = 197            # v5e

# Metrics where a SMALLER value is the improvement (step times).
_LOWER_IS_BETTER = ("ms/step",)


def _load_prior_round():
    """metric -> (value, unit, round_tag) from the newest BENCH_r*.json.

    The driver writes BENCH_r{N}.json after each round with the bench
    stdout under "tail" (one JSON object per line, possibly truncated).
    The reference itself publishes no numbers (BASELINE.json
    ``published: {}``), so "baseline" for regression purposes is the
    previous round's driver-captured envelope (VERDICT r3 #2).
    """
    best = {}
    paths = sorted(glob.glob(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "BENCH_r*.json")))
    if not paths:
        return best
    path = paths[-1]
    tag = re.search(r"BENCH_(r\d+)", os.path.basename(path)).group(1)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return best
    for line in str(doc.get("tail", "")).splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec and "value" in rec:
            value = float(rec["value"])
            if value <= 0:  # invalid-measurement marker (e.g. -1)
                continue
            best[rec["metric"]] = (value, rec.get("unit", ""), tag)
    return best


_PRIOR = None


def emit(metric, value, unit, **extra):
    """One JSON line. vs_baseline = this value vs the previous round's
    driver-captured value for the same metric, normalized so >1.0 is an
    improvement (inverted for ms/step where lower is better)."""
    global _PRIOR
    if _PRIOR is None:
        _PRIOR = _load_prior_round()
    rec = {"metric": metric, "value": round(float(value), 2), "unit": unit,
           "vs_baseline": None}
    prior = _PRIOR.get(metric)
    if prior and prior[0] > 0 and value > 0:
        ratio = (prior[0] / float(value)) if unit in _LOWER_IS_BETTER \
            else (float(value) / prior[0])
        rec["vs_baseline"] = round(ratio, 4)
        rec["baseline_value"] = prior[0]
        rec["baseline_round"] = prior[2]
    rec.update(extra)
    print(json.dumps(rec), flush=True)
    return rec


def make_slope_measurer(apply_fn, variables, x_np, ks=(2, 18), repeats=4):
    """Compile once, measure many: returns ``measure() -> (img/s, spread)``.

    spread = relative spread of the repeated long-loop timings (the
    variance guard VERDICT r2 asked for). The jitted loop is built once so
    repeated measurements share one compiled program (remote-tunnel
    compiles cost ~13s each).
    """
    import jax
    import jax.numpy as jnp

    xd = jax.device_put(x_np)

    @partial(jax.jit, static_argnums=2)
    def loop(v, x, k):
        def body(i, acc):
            out = apply_fn(v, x + acc * 1e-12)
            return acc + jnp.mean(out.astype(jnp.float32))
        return jax.lax.fori_loop(0, k, body, 0.0)

    for k in ks:
        jax.device_get(loop(variables, xd, k))  # compile + warm

    def measure():
        res, spreads = {}, {}
        for k in ks:
            ts = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                jax.device_get(loop(variables, xd, k))
                ts.append(time.perf_counter() - t0)
            res[k] = min(ts)
            spreads[k] = (max(ts) - min(ts)) / min(ts)
        per_batch = (res[ks[1]] - res[ks[0]]) / (ks[1] - ks[0])
        return x_np.shape[0] / per_batch, spreads[ks[1]]

    return measure


def measured_flops_per_image(apply_fn, variables, x_np, fallback):
    """Forward FLOPs/image from the compiler's own cost model
    (``jax.jit(fn).lower(...).cost_analysis()`` — the compiled variant
    returns a LIST of per-computation dicts on some backends, handled
    here), falling back to the registry's analytic 2*MACs constant
    (``ModelSpec.flops_per_image``) when the backend reports none — OR
    reports less than it: a program containing Pallas kernels counts
    only what each kernel's ``cost_estimate`` declares (possibly
    nothing), so an under-reported analysis would silently DEFLATE the
    work estimate and with it MFU's denominator... and the adopted
    kernel would look like an MFU regression (or, flipped, a partial
    analysis could inflate images/sec-normalized MFU). Preferring
    whichever is LARGER keeps the denominator the full analytic work
    regardless of how much of the program the compiler can see.
    Returns ``(flops_per_image, source)``."""
    import jax

    analyzed = 0.0
    try:
        cost = jax.jit(apply_fn).lower(variables, x_np).cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        flops = float(cost.get("flops", 0.0)) if cost else 0.0
        if flops > 0:
            analyzed = flops / x_np.shape[0]
    except Exception:  # noqa: BLE001 - the cost model is best-effort
        pass
    if analyzed >= float(fallback):
        return analyzed, "cost_analysis"
    return float(fallback), ("registry_constant" if analyzed == 0.0
                             else "registry_constant(partial_analysis)")


def bench_device_featurize(name, size, flops_per_img):
    """Best of 3 measurements: the real chip's clock state drifts between
    consecutive runs (measured 10.1k -> 7.8k across back-to-back processes
    with identical code), and the metric compares code versions, so the
    best sustained measurement is the comparable one.

    One DISCARDED warmup measurement runs first (ISSUE 9 satellite): the
    run-0 compile/clock-ramp exclusion PR 3 applied to the reported
    spread never covered the recorded runs themselves, and the ingested
    registry legs (DenseNet121/EfficientNetB0 — keras build + layer-DAG
    walk, the slowest warmups) kept shipping a run 0 that was pure
    artifact (BENCH_r05: EfficientNetB0 runs [16028.9, 23613.8, 23320.9]
    — a 0.47 "spread" entirely from run 0, steady runs within 1.3%).
    With the warmup discarded, EVERY recorded run is steady state, so
    the spread covers all of them and vs_baseline compares like with
    like on every leg, ingested included.
    """
    import jax.numpy as jnp

    from sparkdl_tpu.models import registry

    mf = registry.build_featurizer(name, weights="random",
                                   dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 255, size=(HEADLINE_BATCH,) + size + (3,)
                     ).astype(np.float32)
    spec = registry.get_model_spec(name)
    flops, flops_src = measured_flops_per_image(
        mf.apply_fn, mf.variables, x,
        spec.flops_per_image or flops_per_img)
    measure = make_slope_measurer(mf.apply_fn, mf.variables, x)
    measure()  # discarded warmup: compile residue + clock ramp
    runs = [measure() for _ in range(3)]
    ips, spread = max(runs, key=lambda r: r[0])
    values = [r[0] for r in runs]
    # cross-run spread over the recorded (all-steady) runs, alongside
    # the winning run's own long-loop spread
    cross = (max(values) - min(values)) / min(values)
    mfu = ips * flops / 1e12 / PEAK_TFLOPS_BF16
    return (ips, max(spread, cross), mfu, [round(v, 1) for v in values],
            {"flops_per_image": round(flops / 1e9, 3),
             "flops_source": flops_src})


def bench_kernel_autotune(name="InceptionV3", size=(299, 299)):
    """ISSUE 20 tentpole leg: the flagship featurize with the fused
    Pallas kernel plane OFF vs under the accept-if-faster autotune,
    ONE record.

    The autotune mode settles every per-rung verdict BEFORE the
    measured runs (the same eval-shape collection + shootout path the
    first-launch wrapper and the serving warmup use), so the measured
    throughput is pure steady state — no shootout cost leaks into the
    slope. The record carries both modes' images/sec/chip + MFU, the
    per-rung verdict table (adopted/rejected, reason, the measured
    xla/pallas timing pair, numeric delta), and the shootout wall
    time. On a host backend every candidate records a clean rejection
    (no Mosaic lowering) and the two modes run byte-identical
    programs — the record then documents an all-rejected autotune,
    not a win.

    Both modes build with ``fast=False``: the fused-kernel registry
    routes through the structural Flax units (ConvBN/SeparableConvBN),
    while InceptionV3's default fast path is an orthogonal
    hand-specialization that bypasses them — holding it off on BOTH
    sides isolates exactly the kernel plane."""
    import jax.numpy as jnp

    from sparkdl_tpu.core import batching
    from sparkdl_tpu.engine.dataframe import EngineConfig
    from sparkdl_tpu.models import registry

    spec = registry.get_model_spec(name)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 255, size=(HEADLINE_BATCH,) + size + (3,)
                     ).astype(np.float32)

    saved = EngineConfig.snapshot()
    modes, verdicts, autotune_s = {}, {}, 0.0
    try:
        for mode in ("off", "autotune"):
            EngineConfig.pallas_kernels = mode
            # a FRESH ModelFunction per mode: routing happens at trace
            # time, so a shared jit cache would let mode A's compiled
            # program answer for mode B
            mf = registry.build_featurizer(name, weights="random",
                                           dtype=jnp.bfloat16, fast=False)
            if mode == "autotune":
                from sparkdl_tpu.core import kernels
                kernels.reset()
                eff, mult = mf.bucket_params(HEADLINE_BATCH)
                planner = batching.default_planner(name, eff, mult)
                rungs = (planner.ladder() if planner is not None
                         else batching._pow2_ladder(eff, mult, 8))
                t0 = time.perf_counter()
                for rung in rungs:
                    xr = np.zeros((int(rung),) + size + (3,), np.float32)
                    kernels.ensure_autotuned(
                        lambda a: mf.apply_fn(mf.variables, a), xr,
                        model=name)
                autotune_s = time.perf_counter() - t0
                verdicts = {
                    k: {f: v[f] for f in ("adopted", "reason", "xla_s",
                                          "pallas_s", "max_abs_err")
                        if v.get(f) is not None}
                    for k, v in kernels.verdicts_snapshot().items()}
            flops, flops_src = measured_flops_per_image(
                mf.apply_fn, mf.variables, x,
                spec.flops_per_image or FLOPS_PER_IMG_INCEPTION)
            measure = make_slope_measurer(mf.apply_fn, mf.variables, x)
            measure()  # discarded warmup: compile residue + clock ramp
            runs = [measure() for _ in range(2)]
            ips, spread = max(runs, key=lambda r: r[0])
            modes[mode] = {
                "images_per_sec": round(ips, 2),
                "spread": round(spread, 4),
                "mfu": round(ips * flops / 1e12 / PEAK_TFLOPS_BF16, 4),
                "flops_source": flops_src,
            }
    finally:
        EngineConfig.restore(saved)
    adopted = sum(1 for v in verdicts.values() if v.get("adopted"))
    return {
        "off": modes["off"],
        "autotune": modes["autotune"],
        "speedup": round(modes["autotune"]["images_per_sec"]
                         / max(modes["off"]["images_per_sec"], 1e-9), 4),
        "adopted": adopted,
        "rejected": len(verdicts) - adopted,
        "autotune_s": round(autotune_s, 3),
        "verdicts": verdicts,
    }


def _write_jpegs(directory, n, rng):
    from PIL import Image

    paths = []
    for i in range(n):
        arr = rng.integers(0, 255, size=(330, 400, 3), dtype=np.uint8)
        p = os.path.join(directory, f"img_{i:04d}.jpg")
        Image.fromarray(arr).save(p, quality=85)
        paths.append(p)
    return paths


def _hist_summary(snapshot, name):
    """Compact {count,p50,p95,p99,min,max} from a telemetry snapshot's
    histogram — the distribution the perf trajectory carries instead of
    a single mean (ISSUE 4 satellite)."""
    h = snapshot["histograms"].get(name)
    if not h or not h["count"]:
        return None
    return {"count": h["count"],
            "p50": round(h["p50"], 6), "p95": round(h["p95"], 6),
            "p99": round(h["p99"], 6), "min": round(h["min"], 6),
            "max": round(h["max"], 6)}


def bench_e2e_featurize(n_images=384):
    """Config 1 end-to-end: files -> readImages -> featurize -> collect.

    The measured repeats run under a telemetry scope so the emitted
    record carries the padding-waste gauge and the partition-task
    duration distribution alongside the throughput mean."""
    import jax.numpy as jnp

    from sparkdl_tpu.core import telemetry
    from sparkdl_tpu.image.imageIO import readImages
    from sparkdl_tpu.ml import DeepImageFeaturizer

    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as d:
        _write_jpegs(d, n_images, rng)
        t = DeepImageFeaturizer(inputCol="image", outputCol="features",
                                modelName="InceptionV3",
                                batchSize=HEADLINE_BATCH,
                                dtype=jnp.bfloat16, weights="random")

        def run():
            df = readImages(d, numPartition=4)
            out = t.transform(df).select("features").collect()
            assert len(out) == n_images
        run()  # warmup: compile + host caches
        with telemetry.Telemetry("bench_e2e_featurize") as tel:
            best, spread = _best_of(run)
        snap = tel.metrics.snapshot()
    summary = {
        "padding_waste": snap["gauges"].get(telemetry.M_PADDING_WASTE),
        "task_duration_s": _hist_summary(snap, telemetry.M_TASK_DURATION_S),
    }
    return n_images / best, spread, summary


def bench_parallel_ingest(n_images=384, workers=None):
    """ISSUE 9 tentpole leg: e2e files→readImages→InceptionV3 featurize
    with the multi-process decode pool OFF vs ON (workers=cpu_count) in
    ONE record.

    This is the exact pipeline ROADMAP item 2 calls the whole
    bottleneck: decode is GIL-bound host Python while the device idles.
    Emits images/sec for both modes, the speedup, per-mode phase
    breakdowns (``sparkdl.decode`` vs ``sparkdl.device_apply`` wall
    seconds), and ``device_rate_fraction`` — pooled e2e images/sec over
    the device-only featurize rate for the same model, the "host ingest
    at device speed" ratio the tentpole targets (≥ 0.5 means e2e within
    2× of device-only)."""
    import jax.numpy as jnp

    from sparkdl_tpu.core import decode_pool, profiling, telemetry
    from sparkdl_tpu.engine.dataframe import EngineConfig
    from sparkdl_tpu.image.imageIO import readImages
    from sparkdl_tpu.ml import DeepImageFeaturizer
    from sparkdl_tpu.models import registry

    workers = workers or (os.cpu_count() or 1)
    rng = np.random.default_rng(0)
    saved = EngineConfig.snapshot()
    results = {}
    phases = {}
    try:
        with tempfile.TemporaryDirectory() as d:
            _write_jpegs(d, n_images, rng)
            t = DeepImageFeaturizer(inputCol="image", outputCol="features",
                                    modelName="InceptionV3",
                                    batchSize=HEADLINE_BATCH,
                                    dtype=jnp.bfloat16, weights="random")

            def run():
                df = readImages(d, numPartition=4)
                out = t.transform(df).select("features").collect()
                assert len(out) == n_images

            run()  # warmup: compile + host caches (pool off)
            for mode, n_workers in (("pool_off", 0), ("pool_on", workers)):
                EngineConfig.decode_workers = n_workers
                if n_workers:
                    run()  # warmup the pool too: worker spawn + imports
                profiling.reset_phase_stats()
                with telemetry.Telemetry(f"bench_parallel_ingest_{mode}") \
                        as tel:
                    best, spread = _best_of(run)
                snap = tel.metrics.snapshot()
                results[mode] = (n_images / best, spread, snap)
                phases[mode] = {name: round(s["total_s"], 3)
                                for name, s in
                                profiling.phase_stats().items()}
    finally:
        EngineConfig.restore(saved)
        decode_pool.shutdown()
    # device-only rate for the same model: one slope measurement after a
    # discarded warmup (the denominator of device_rate_fraction)
    mf = registry.build_featurizer("InceptionV3", weights="random",
                                   dtype=jnp.bfloat16)
    x = rng.integers(0, 255, size=(HEADLINE_BATCH, 299, 299, 3)
                     ).astype(np.float32)
    measure = make_slope_measurer(mf.apply_fn, mf.variables, x)
    measure()  # discarded warmup
    device_ips, _ = measure()
    ips_on, sp_on, snap_on = results["pool_on"]
    ips_off, sp_off, _ = results["pool_off"]
    pool_tel = {
        "decode_s": _hist_summary(snap_on,
                                  telemetry.M_DECODE_POOL_DECODE_S),
        "queue_depth": snap_on["gauges"].get(
            telemetry.M_DECODE_POOL_DEPTH),
        "workers_busy": snap_on["gauges"].get(
            telemetry.M_DECODE_POOL_BUSY),
    }
    return (ips_on, sp_on, ips_off, sp_off, workers, phases,
            device_ips, ips_on / max(device_ips, 1e-9), pool_tel)


def bench_concurrent_featurize(name="EfficientNetB0", n_images=256,
                               partitions=8, size=(224, 224),
                               flops_per_img=FLOPS_PER_IMG_EFFNETB0):
    """ISSUE 5 satellite: concurrent-partition featurize — 8 partitions
    of small chunks through the engine pool, coalescing ON vs OFF.

    This is the workload the device execution service (core/executor.py)
    targets: each partition stages only n_images/partitions rows (a
    fraction of the batch), so without coalescing the device runs
    ``partitions`` small launches and dispatch overhead dominates for a
    cheap model. The ON run executes under a telemetry scope so the
    emitted record carries the coalesce-size / queue-wait distributions
    that prove the merging actually happened."""
    import jax.numpy as jnp
    import pyarrow as pa

    from sparkdl_tpu.core import telemetry
    from sparkdl_tpu.engine.dataframe import DataFrame, EngineConfig
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.ml import DeepImageFeaturizer

    rng = np.random.default_rng(0)
    rows = [{"image": imageIO.imageArrayToStruct(
        rng.integers(0, 255, size=size + (3,), dtype=np.uint8))}
        for _ in range(n_images)]
    schema = pa.schema([pa.field("image", imageIO.imageSchema)])
    df = DataFrame.fromRows(rows, schema=schema, numPartitions=partitions)
    t = DeepImageFeaturizer(inputCol="image", outputCol="features",
                            modelName=name, batchSize=HEADLINE_BATCH,
                            dtype=jnp.bfloat16, weights="random")

    def run():
        out = t.transform(df).select("features").collect()
        assert len(out) == n_images

    saved = EngineConfig.coalesce
    tel_summary = None
    results = {}
    try:
        for coalesce in (False, True):
            EngineConfig.coalesce = coalesce
            run()  # warmup: this mode's bucket-ladder compiles
            if coalesce:
                with telemetry.Telemetry("bench_concurrent") as tel:
                    best, spread = _best_of(run)
                    # windowed (last-window) snapshots next to the
                    # cumulative ones (ISSUE 7): captured inside the
                    # scope, right after the measured repeats, so the
                    # window holds exactly this bench's traffic
                    wsnap = tel.metrics.window_snapshot()
                snap = tel.metrics.snapshot()
                tel_summary = {
                    "coalesce_requests": _hist_summary(
                        snap, telemetry.M_COALESCE_REQUESTS),
                    "coalesce_rows": _hist_summary(
                        snap, telemetry.M_COALESCE_ROWS),
                    "queue_wait_s": _hist_summary(
                        snap, telemetry.M_QUEUE_WAIT_S),
                    "launch_s": _hist_summary(snap, telemetry.M_LAUNCH_S),
                    "occupancy": snap["gauges"].get(
                        telemetry.M_EXECUTOR_OCCUPANCY),
                    "windowed": {
                        "window_s": wsnap["window_s"],
                        "queue_wait_s": _hist_summary(
                            wsnap, telemetry.M_QUEUE_WAIT_S),
                        "launch_s": _hist_summary(
                            wsnap, telemetry.M_LAUNCH_S),
                    },
                }
            else:
                best, spread = _best_of(run)
            results[coalesce] = (n_images / best, spread)
    finally:
        EngineConfig.coalesce = saved
    ips_on, sp_on = results[True]
    ips_off, sp_off = results[False]
    mfu = ips_on * flops_per_img / 1e12 / PEAK_TFLOPS_BF16
    return (ips_on, sp_on, mfu, ips_off, sp_off, tel_summary)


def bench_overload_featurize(name="EfficientNetB0", n_bulk=192,
                             bulk_partitions=8, n_interactive=24,
                             interactive_partitions=2, size=(224, 224)):
    """ISSUE 6 satellite: burst-submit concurrent featurize partitions
    past the executor queue bound (docs/RESILIENCE.md "Overload &
    graceful degradation").

    Two transformers share ONE ModelFunction (same compiled fn = same
    executor queue): a wide bulk flood plus a small interactive job,
    racing on separate threads. Shedding ON pins tiny queue caps in shed
    mode — the engine's classified retry absorbs the ExecutorOverloaded
    sheds — vs OFF (unbounded defaults) in one record, carrying the shed
    rate, queue-wait p99, and the interactive-vs-bulk latency split that
    shows the priority lanes protecting the small job under the flood."""
    import threading

    import pyarrow as pa

    from sparkdl_tpu.core import executor as device_executor
    from sparkdl_tpu.core import health, telemetry
    from sparkdl_tpu.engine.dataframe import DataFrame, EngineConfig
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.ml import TPUImageTransformer
    from sparkdl_tpu.models import registry as model_registry

    rng = np.random.default_rng(0)
    schema = pa.schema([pa.field("image", imageIO.imageSchema)])

    def frame(n, partitions):
        rows = [{"image": imageIO.imageArrayToStruct(
            rng.integers(0, 255, size=size + (3,), dtype=np.uint8))}
            for _ in range(n)]
        return DataFrame.fromRows(rows, schema=schema,
                                  numPartitions=partitions)

    df_bulk = frame(n_bulk, bulk_partitions)
    df_int = frame(n_interactive, interactive_partitions)
    mf = model_registry.build_featurizer(name, weights="random")
    t_bulk = TPUImageTransformer(inputCol="image", outputCol="features",
                                 modelFunction=mf,
                                 batchSize=HEADLINE_BATCH)
    t_int = TPUImageTransformer(inputCol="image", outputCol="features",
                                modelFunction=mf, batchSize=HEADLINE_BATCH,
                                priority="interactive")

    def run_pair():
        lat = {}

        def one(key, t, df, n):
            t0 = time.perf_counter()
            out = t.transform(df).select("features").collect()
            assert len(out) == n
            lat[key] = time.perf_counter() - t0

        threads = [
            threading.Thread(target=one,
                             args=("bulk", t_bulk, df_bulk, n_bulk)),
            threading.Thread(target=one, args=("interactive", t_int,
                                               df_int, n_interactive)),
        ]
        for th in threads:  # bulk first: the flood is queued when the
            th.start()      # interactive job arrives
        for th in threads:
            th.join()
        return lat

    saved = EngineConfig.snapshot()
    results = {}
    try:
        run_pair()  # warmup: compile + host caches, unbounded
        for shed in (False, True):
            if shed:
                EngineConfig.executor_max_queued_requests = 2
                EngineConfig.executor_overload_mode = "shed"
                EngineConfig.max_task_retries = 50
                EngineConfig.task_retry_delay_s = 0.01
            EngineConfig.max_workers = (bulk_partitions
                                        + interactive_partitions)
            device_executor.reset()  # fresh queue/shed gauges per mode
            with telemetry.Telemetry("bench_overload") as tel:
                lat = run_pair()
                # last-window view captured in-scope, right after the
                # flood (ISSUE 7): the windowed shed rate and queue-wait
                # distribution, next to the cumulative ones
                wsnap = tel.metrics.window_snapshot()
            snap = tel.metrics.snapshot()
            shed_metric = (telemetry.HEALTH_METRIC_PREFIX
                           + health.EXECUTOR_SHED)
            wsheds = wsnap["counters"].get(shed_metric,
                                           {"count": 0, "rate_per_s": 0})
            results["shed_on" if shed else "shed_off"] = {
                "interactive_s": round(lat["interactive"], 4),
                "bulk_s": round(lat["bulk"], 4),
                "sheds": snap["counters"].get(shed_metric, 0),
                "shed_rate": snap["gauges"].get(
                    telemetry.M_EXECUTOR_SHED_RATE),
                "queue_wait_s": _hist_summary(snap,
                                              telemetry.M_QUEUE_WAIT_S),
                "windowed": {
                    "window_s": wsnap["window_s"],
                    "sheds": wsheds["count"],
                    "shed_rate_per_s": wsheds["rate_per_s"],
                    "queue_wait_s": _hist_summary(
                        wsnap, telemetry.M_QUEUE_WAIT_S),
                },
            }
    finally:
        EngineConfig.restore(saved)
        device_executor.reset()
    results["interactive_ips_shed_on"] = round(
        n_interactive / results["shed_on"]["interactive_s"], 2)
    return results


def bench_serving(name="EfficientNetB0", n_interactive=64,
                  n_clients=4, n_bulk=96, bulk_partitions=4,
                  size=(224, 224), shadow_fraction=0.25):
    """ISSUE 13 leg: row-level interactive requests through
    ``ModelServer.predict`` flooding beside a bulk featurize job on the
    SAME executor (docs/SERVING.md).

    One serving plane: v1 active with a latency target (so admission can
    shed off the windowed queue-wait p99), v2 shadowed at
    ``shadow_fraction``, both under a byte-budgeted residency manager.
    The record carries the p50/p99 request latency, the shed rate, the
    shadow overhead fraction (shadow device seconds per active device
    second, from the recorded comparison events), and the cold-start
    (eviction-then-reload) latency from the ``sparkdl.model_load``
    path."""
    import threading

    import pyarrow as pa

    from sparkdl_tpu.core import executor as device_executor
    from sparkdl_tpu.core import health, telemetry
    from sparkdl_tpu.core.health import HealthMonitor
    from sparkdl_tpu.engine.dataframe import DataFrame, EngineConfig
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.ml import TPUImageTransformer
    from sparkdl_tpu.models import registry as model_registry
    from sparkdl_tpu.serving import (ModelRegistry, ModelServer,
                                     ResidencyManager, ServingOverloaded)

    rng = np.random.default_rng(0)
    mf_v1 = model_registry.build_featurizer(name, weights="random")
    mf_v2 = model_registry.build_featurizer(name, weights="random")
    budget = 4 * (mf_v1.weight_bytes() + mf_v2.weight_bytes())
    res = ResidencyManager(budget_bytes=budget)
    reg = ModelRegistry(residency=res)
    srv = ModelServer(reg)
    reg.deploy("featurizer", "v1", model=mf_v1, latency_target_ms=500.0,
               batch_size=HEADLINE_BATCH)
    reg.deploy("featurizer", "v2", model=mf_v2,
               batch_size=HEADLINE_BATCH)
    reg.shadow("featurizer", "v2", fraction=shadow_fraction)

    bulk_rows = [{"image": imageIO.imageArrayToStruct(
        rng.integers(0, 255, size=size + (3,), dtype=np.uint8))}
        for _ in range(n_bulk)]
    df_bulk = DataFrame.fromRows(
        bulk_rows,
        schema=pa.schema([pa.field("image", imageIO.imageSchema)]),
        numPartitions=bulk_partitions)
    # the bulk job shares the ACTIVE version's ModelFunction — one
    # compiled fn, one executor coalescing state, so the flood and the
    # row-level requests genuinely contend
    t_bulk = TPUImageTransformer(inputCol="image", outputCol="features",
                                 modelFunction=reg.model("featurizer"),
                                 batchSize=HEADLINE_BATCH)
    requests = rng.normal(size=(n_interactive,) + size + (3,)) \
        .astype(np.float32)

    saved = EngineConfig.snapshot()
    try:
        device_executor.reset()
        srv.predict("featurizer", requests[0])  # compile v1+v2, load both

        # cold start: evict the active version (unpin first — the
        # registry pinned it) and time the reload the next request pays
        res.pin("featurizer", "v1", False)
        assert res.evict("featurizer", "v1")
        res.pin("featurizer", "v1", True)
        with HealthMonitor("serving-cold") as cold_mon:
            srv.predict("featurizer", requests[0])
        (cold_ev,) = cold_mon.events(health.SERVING_COLD_START)
        cold_start_s = cold_ev["seconds"]

        # ISSUE 20 satellite: the cold-start split the AOT warmup
        # targets. Each mode deploys a FRESH lazy-loader deployment —
        # the evict/reload path above hands back the same Python
        # ModelFunction with its jit cache intact, so only a fresh
        # build exposes a real first-request compile to measure.
        # Warmup-on pays the ladder at deploy time; its first request
        # must then land near steady state.
        def _cold_first_request(warm):
            EngineConfig.serving_warmup = warm
            reg_c = ModelRegistry(residency=None)
            srv_c = ModelServer(reg_c)
            t0 = time.perf_counter()
            reg_c.deploy("coldprobe", "v1", loader=lambda: (
                model_registry.build_featurizer(name, weights="random")),
                batch_size=HEADLINE_BATCH)
            deploy_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            srv_c.predict("coldprobe", requests[0])
            return {"deploy_s": round(deploy_s, 3),
                    "first_request_ms": round(
                        (time.perf_counter() - t0) * 1e3, 3)}

        warmup_cold_start = {"warmup_off": _cold_first_request(False),
                             "warmup_on": _cold_first_request(True)}
        EngineConfig.serving_warmup = False

        latencies, sheds = [], [0]
        lat_lock = threading.Lock()

        def client(cid):
            for i in range(cid, n_interactive, n_clients):
                try:
                    got = srv.predict("featurizer", requests[i])
                except ServingOverloaded:
                    with lat_lock:
                        sheds[0] += 1
                    continue
                with lat_lock:
                    latencies.append(got.latency_s)

        with telemetry.Telemetry("bench_serving") as tel:
            with HealthMonitor("serving-flood") as mon:
                t0 = time.perf_counter()
                bulk = threading.Thread(
                    target=lambda: t_bulk.transform(df_bulk)
                    .select("features").collect())
                clients = [threading.Thread(target=client, args=(c,))
                           for c in range(n_clients)]
                bulk.start()  # the flood is in the queue first
                for th in clients:
                    th.start()
                for th in clients:
                    th.join()
                bulk.join()
                elapsed = time.perf_counter() - t0
            snap = tel.metrics.snapshot()
    finally:
        EngineConfig.restore(saved)
        device_executor.reset()

    compared = mon.events(health.SERVING_SHADOW_COMPARED)
    shadow_s = sum(e["shadow_s"] for e in compared)
    answered = sorted(latencies)
    total_request_s = sum(answered)
    return {
        "answered": len(answered),
        "request_p50_ms": round(
            float(np.percentile(answered, 50)) * 1e3, 3),
        "request_p99_ms": round(
            float(np.percentile(answered, 99)) * 1e3, 3),
        "shed": sheds[0],
        "shed_rate_per_s": round(sheds[0] / elapsed, 3),
        "shadowed_requests": len(compared),
        # seconds spent on the shadow leg per second of total request
        # serving — what mirroring `shadow_fraction` of traffic costs
        "shadow_overhead_frac": round(shadow_s / total_request_s, 4)
        if total_request_s else None,
        "cold_start_s": round(cold_start_s, 4),
        "cold_start_bytes": cold_ev["bytes"],
        "warmup_cold_start": warmup_cold_start,
        "request_s": _hist_summary(snap, telemetry.M_SERVING_REQUEST_S),
        "elapsed_s": round(elapsed, 3),
    }


def bench_serving_failover(name="EfficientNetB0", size=(224, 224),
                           n_steady=32, n_chaos=32, n_swap=24,
                           workers=2, deadline_ms=120_000.0):
    """ISSUE 17 leg: the cluster serving plane under replica death and
    a live hot swap (docs/SERVING.md "Cluster serving").

    One deployment replicated across ``workers`` cluster processes.
    Three phases on the same stack, ONE record: (a) steady-state
    request p99; (b) SIGKILL one of the replicas mid-stream — every
    request must still complete inside its deadline via failover, and
    the record carries the failover-phase p99 beside the steady p99
    plus the exactly-once ``serving_failover`` count; (c) a
    cluster-atomic hot swap under a single-threaded request stream —
    because the caller is sequential, responses are strictly ordered,
    so ``cutover_mix_window_ms`` (how long v1 completions kept landing
    after the first v2 completion) is race-free and MUST be 0."""
    import os
    import signal
    import threading

    from sparkdl_tpu.cluster import router as cluster_router
    from sparkdl_tpu.core import executor as device_executor
    from sparkdl_tpu.core import health
    from sparkdl_tpu.core.health import HealthMonitor
    from sparkdl_tpu.engine.dataframe import EngineConfig
    from sparkdl_tpu.models import registry as model_registry
    from sparkdl_tpu.serving import ModelRegistry, ModelServer

    rng = np.random.default_rng(0)
    requests = rng.normal(
        size=(max(n_steady, n_chaos, n_swap),) + size + (3,)) \
        .astype(np.float32)

    saved = EngineConfig.snapshot()
    try:
        device_executor.reset()
        EngineConfig.cluster_workers = workers
        EngineConfig.serving_cluster = True
        reg = ModelRegistry()
        srv = ModelServer(reg)
        reg.deploy("featurizer", "v1",
                   model=model_registry.build_featurizer(
                       name, weights="random"),
                   batch_size=HEADLINE_BATCH)
        reg.deploy("featurizer", "v2",
                   model=model_registry.build_featurizer(
                       name, weights="random"),
                   batch_size=HEADLINE_BATCH)
        srv.predict("featurizer", requests[0],
                    deadline_ms=deadline_ms)  # compile + warm a replica

        def stream(n, log):
            for i in range(n):
                got = srv.predict("featurizer", requests[i],
                                  deadline_ms=deadline_ms)
                log.append((time.perf_counter(), got.latency_s,
                            got.version))

        steady = []
        stream(n_steady, steady)

        # chaos: kill -9 the hot replica a few requests into the stream
        router = cluster_router.maybe_router()
        replicas = srv.status()["cluster"]["featurizer"]["replicas"]
        hot_name = next(w for w, v in replicas.items() if v["resident"])
        hot = next(w for w in router._workers
                   if w.proc.name == hot_name and w.proc.is_alive())
        chaos = []
        with HealthMonitor("serving-failover") as mon:
            killer = threading.Timer(
                0.0, lambda: os.kill(hot.proc.pid, signal.SIGKILL))
            killer.start()
            stream(n_chaos, chaos)
            killer.join()
        moved = len(mon.events(health.SERVING_FAILOVER))

        # hot swap under a sequential stream: fire the cutover from a
        # side thread while the caller keeps requesting
        swap_log = []
        cut = threading.Timer(
            0.0, lambda: srv.cutover("featurizer", "v2"))
        cut.start()
        stream(n_swap, swap_log)
        cut.join()
        v1_ends = [t for t, _, v in swap_log if v == "v1"]
        v2_ends = [t for t, _, v in swap_log if v == "v2"]
        mix_window_ms = (
            max(0.0, (max(v1_ends) - min(v2_ends)) * 1e3)
            if v1_ends and v2_ends else 0.0)
    finally:
        cluster_router.shutdown()
        EngineConfig.restore(saved)
        device_executor.reset()

    def p(lats, q):
        return round(float(np.percentile(
            [l for _, l, _ in lats], q)) * 1e3, 3)

    return {
        "steady_p50_ms": p(steady, 50),
        "steady_p99_ms": p(steady, 99),
        "failover_p50_ms": p(chaos, 50),
        "failover_p99_ms": p(chaos, 99),
        "answered_under_kill": len(chaos),
        "moved_requests": moved,
        "cutover_mix_window_ms": round(mix_window_ms, 3),
        "swap_versions_served": sorted({v for _, _, v in swap_log}),
    }


def bench_exporter_overhead(name="EfficientNetB0", n_images=128,
                            partitions=8, size=(224, 224)):
    """ISSUE 7 satellite: the periodic snapshot exporter's cost on a
    real featurize loop — images/sec with the exporter ON (0.2 s
    snapshot cadence + default SLO watchdog, files to a temp dir) vs
    OFF, under otherwise-identical telemetry scopes. The acceptance
    budget is < 5% overhead: the live plane must be cheap enough to
    leave on in production."""
    import jax.numpy as jnp
    import pyarrow as pa

    from sparkdl_tpu.core import telemetry
    from sparkdl_tpu.engine.dataframe import DataFrame
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.ml import DeepImageFeaturizer

    rng = np.random.default_rng(0)
    rows = [{"image": imageIO.imageArrayToStruct(
        rng.integers(0, 255, size=size + (3,), dtype=np.uint8))}
        for _ in range(n_images)]
    schema = pa.schema([pa.field("image", imageIO.imageSchema)])
    df = DataFrame.fromRows(rows, schema=schema,
                            numPartitions=partitions)
    t = DeepImageFeaturizer(inputCol="image", outputCol="features",
                            modelName=name, batchSize=HEADLINE_BATCH,
                            dtype=jnp.bfloat16, weights="random")

    def run():
        out = t.transform(df).select("features").collect()
        assert len(out) == n_images

    run()  # warmup: compile + host caches
    with telemetry.Telemetry("bench_exporter_off"):
        t_off, sp_off = _best_of(run)
    with tempfile.TemporaryDirectory() as d:
        with telemetry.Telemetry("bench_exporter_on", out_dir=d,
                                 export_interval_s=0.2) as tel_on:
            t_on, sp_on = _best_of(run)
        snapshots = tel_on.exporter.seq
    return (n_images / t_on, n_images / t_off, sp_on, sp_off, snapshots)


def bench_durable_ingest(n_images=256):
    """ISSUE 11 satellite: the write-ahead partition journal's cost on
    the e2e files→readImages→featurize pipeline, durability off vs on in
    ONE record.

    The durable leg clears the journal's job dirs before every rep —
    otherwise rep 2+ would measure journal REPLAY (zero recompute, reads
    instead of writes) and flatter the number. Acceptance: the overhead
    fraction stays under 5% — durability must be cheap enough to leave
    on for any long-running job."""
    import shutil

    import jax.numpy as jnp

    from sparkdl_tpu.engine.dataframe import EngineConfig
    from sparkdl_tpu.image.imageIO import readImages
    from sparkdl_tpu.ml import DeepImageFeaturizer

    rng = np.random.default_rng(0)
    saved = EngineConfig.snapshot()
    results = {}
    try:
        with tempfile.TemporaryDirectory() as d, \
                tempfile.TemporaryDirectory() as durable:
            _write_jpegs(d, n_images, rng)
            t = DeepImageFeaturizer(inputCol="image", outputCol="features",
                                    modelName="EfficientNetB0",
                                    batchSize=HEADLINE_BATCH,
                                    dtype=jnp.bfloat16, weights="random")

            def run():
                if EngineConfig.durable_dir:
                    for name in os.listdir(durable):
                        shutil.rmtree(os.path.join(durable, name),
                                      ignore_errors=True)
                df = readImages(d, numPartition=4)
                out = t.transform(df).select("features").collect()
                assert len(out) == n_images

            run()  # warmup: compile + host caches
            for mode, root in (("durable_off", None),
                               ("durable_on", durable)):
                EngineConfig.durable_dir = root
                best, spread = _best_of(run)
                results[mode] = (n_images / best, spread)
    finally:
        EngineConfig.restore(saved)
    ips_on, sp_on = results["durable_on"]
    ips_off, sp_off = results["durable_off"]
    return (ips_on, sp_on, ips_off, sp_off,
            1 - ips_on / max(ips_off, 1e-9))


def bench_cluster_featurize(name="EfficientNetB0", n_images=256,
                            workers=2):
    """ISSUE 14 satellite: the e2e files→readImages→featurize pipeline
    in-process (cluster_workers=0) vs fanned across the cluster plane
    (cluster_workers=2) in ONE record.

    Beyond the rate pair, the record carries what only the merged
    cross-worker report can show: per-worker phase breakdowns (each
    worker's ``profiling.phase_stats`` from its end-of-run snapshot),
    the rows-per-worker balance the load-aware dispatch produced, and
    the router overhead fraction — 1 − (worker-measured op-chain
    seconds / coordinator-measured dispatch wall seconds), i.e. the
    share of dispatch time spent on transport + routing rather than
    executing the chain."""
    import jax.numpy as jnp

    from sparkdl_tpu.cluster import router as cluster_router
    from sparkdl_tpu.engine.dataframe import EngineConfig
    from sparkdl_tpu.image.imageIO import readImages
    from sparkdl_tpu.ml import DeepImageFeaturizer

    rng = np.random.default_rng(0)
    saved = EngineConfig.snapshot()
    results = {}
    report = None
    router_stats = {}
    try:
        with tempfile.TemporaryDirectory() as d:
            _write_jpegs(d, n_images, rng)
            t = DeepImageFeaturizer(inputCol="image", outputCol="features",
                                    modelName=name,
                                    batchSize=HEADLINE_BATCH,
                                    dtype=jnp.bfloat16, weights="random")

            def run():
                df = readImages(d, numPartition=4)
                out = t.transform(df).select("features").collect()
                assert len(out) == n_images

            run()  # warmup: compile + host caches (cluster off)
            for mode, n_workers in (("cluster_off", 0),
                                    ("cluster_on", workers)):
                EngineConfig.cluster_workers = n_workers
                if n_workers:
                    run()  # warmup the workers: spawn + per-worker compile
                best, spread = _best_of(run)
                results[mode] = (n_images / best, spread)
            # measured-window router accounting: totals accumulate from
            # the warmup on, so take the live router's view before close
            router = cluster_router.maybe_router()
            router_stats = {
                "dispatch_s": router.dispatch_s_total,
                "exec_s": router.exec_s_total,
            }
    finally:
        EngineConfig.restore(saved)
        cluster_router.shutdown()
    report = cluster_router.last_cluster_report() or {}
    ips_on, sp_on = results["cluster_on"]
    ips_off, sp_off = results["cluster_off"]
    dispatch_s = router_stats.get("dispatch_s", 0.0)
    overhead = 1 - router_stats.get("exec_s", 0.0) / max(dispatch_s, 1e-9)
    worker_phases = {
        w: {phase: round(s.get("total_s", 0.0), 3)
            for phase, s in (snap.get("phases") or {}).items()}
        for w, snap in (report.get("workers") or {}).items()}
    return {
        "ips_on": ips_on, "sp_on": sp_on,
        "ips_off": ips_off, "sp_off": sp_off,
        "workers": workers,
        "router_overhead_frac": overhead,
        "rows_per_worker": report.get("rows_per_worker", {}),
        "exec_s_per_worker": report.get("exec_s_per_worker", {}),
        "worker_phases": worker_phases,
        "health_consistent": report.get("health_consistent"),
    }


def bench_tracing_overhead(name="EfficientNetB0", n_images=256,
                           workers=2):
    """ISSUE 15 satellite: the cross-process tracing plane's cost on the
    cluster featurize path — the same e2e files→readImages→featurize
    pipeline across 2 workers with distributed tracing armed (a
    coordinator telemetry scope: span context on every dispatch,
    worker-side spans + shipped rings, exemplar reservoirs) vs tracing
    off (no scope: ctx rides as None, workers ship nothing), in ONE
    record. The acceptance budget is < 3% overhead: propagation must be
    cheap enough to leave on wherever the cluster plane runs.

    The armed leg re-spawns the workers INSIDE the scope — the
    coordinator's root context ships in the worker boot blob, so a
    router spawned before the scope would measure a half-armed plane."""
    import jax.numpy as jnp

    from sparkdl_tpu.cluster import router as cluster_router
    from sparkdl_tpu.core import telemetry
    from sparkdl_tpu.engine.dataframe import EngineConfig
    from sparkdl_tpu.image.imageIO import readImages
    from sparkdl_tpu.ml import DeepImageFeaturizer

    rng = np.random.default_rng(0)
    saved = EngineConfig.snapshot()
    results = {}
    trace_stats = {}
    try:
        with tempfile.TemporaryDirectory() as d:
            _write_jpegs(d, n_images, rng)
            t = DeepImageFeaturizer(inputCol="image", outputCol="features",
                                    modelName=name,
                                    batchSize=HEADLINE_BATCH,
                                    dtype=jnp.bfloat16, weights="random")

            def run():
                df = readImages(d, numPartition=4)
                out = t.transform(df).select("features").collect()
                assert len(out) == n_images

            EngineConfig.cluster_workers = workers
            run()  # warmup: spawn workers + compile everywhere
            best, spread = _best_of(run)
            results["off"] = (n_images / best, spread)
            cluster_router.shutdown()  # the armed leg needs a fresh spawn
            with telemetry.Telemetry("bench_tracing_armed",
                                     exemplar_k=4) as tel:
                run()  # warmup: respawn with the root ctx in the boot blob
                best, spread = _best_of(run)
                results["armed"] = (n_images / best, spread)
                cluster_router.shutdown()  # adopt worker rings in-scope
                rep = cluster_router.last_cluster_report() or {}
                trace_stats = {
                    "remote_adopted":
                        tel.tracer.summary()["remote_adopted"],
                    "workers_shipped": {
                        w: acct["shipped"] for w, acct in
                        (rep.get("trace", {}).get("workers")
                         or {}).items()},
                }
    finally:
        EngineConfig.restore(saved)
        cluster_router.shutdown()
    ips_on, sp_on = results["armed"]
    ips_off, sp_off = results["off"]
    return {
        "ips_armed": ips_on, "sp_armed": sp_on,
        "ips_off": ips_off, "sp_off": sp_off,
        "workers": workers,
        "overhead_frac": 1 - ips_on / max(ips_off, 1e-9),
        **trace_stats,
    }


def bench_federation_overhead(name="EfficientNetB0", n_images=256,
                              workers=2, cadence_s=0.25):
    """ISSUE 19 satellite: the metrics federation plane's cost on the
    cluster featurize path — the same e2e files→readImages→featurize
    pipeline across 2 workers with federation armed (workers ship
    windowed delta frames on the cadence; the coordinator folds them
    and runs the federated SLO watchdog on every frame) vs off
    (``cluster_federation_s`` unset: no frames, no fold, the
    pre-federation pipe protocol), in ONE record. The acceptance budget
    is < 3% overhead: shipping the whole cluster's live metrics must be
    cheap enough to leave on wherever the cluster plane runs.

    Both legs run inside a telemetry scope (the tracing bench already
    prices the scope itself) and the armed leg re-spawns the workers —
    the cadence rides the worker boot config, so a router spawned
    before the knob flip would measure a half-armed plane."""
    import jax.numpy as jnp

    from sparkdl_tpu.cluster import router as cluster_router
    from sparkdl_tpu.core import telemetry
    from sparkdl_tpu.engine.dataframe import EngineConfig
    from sparkdl_tpu.image.imageIO import readImages
    from sparkdl_tpu.ml import DeepImageFeaturizer

    rng = np.random.default_rng(0)
    saved = EngineConfig.snapshot()
    results = {}
    fed_stats = {}
    try:
        with tempfile.TemporaryDirectory() as d:
            _write_jpegs(d, n_images, rng)
            t = DeepImageFeaturizer(inputCol="image", outputCol="features",
                                    modelName=name,
                                    batchSize=HEADLINE_BATCH,
                                    dtype=jnp.bfloat16, weights="random")

            def run():
                df = readImages(d, numPartition=4)
                out = t.transform(df).select("features").collect()
                assert len(out) == n_images

            EngineConfig.cluster_workers = workers
            with telemetry.Telemetry("bench_federation_off"):
                run()  # warmup: spawn workers + compile everywhere
                best, spread = _best_of(run)
                results["off"] = (n_images / best, spread)
                cluster_router.shutdown()
            EngineConfig.cluster_federation_s = cadence_s
            with telemetry.Telemetry("bench_federation_armed",
                                     exemplar_k=4):
                run()  # warmup: respawn with the cadence in the boot blob
                best, spread = _best_of(run)
                results["armed"] = (n_images / best, spread)
                cluster_router.shutdown()  # merge reports in-scope
                rep = cluster_router.last_cluster_report() or {}
                fed = rep.get("federation") or {}
                fed_stats = {
                    "frames_ingested": fed.get("frames_ingested"),
                    "workers_known": fed.get("workers_known"),
                }
    finally:
        EngineConfig.restore(saved)
        cluster_router.shutdown()
    ips_on, sp_on = results["armed"]
    ips_off, sp_off = results["off"]
    return {
        "ips_armed": ips_on, "sp_armed": sp_on,
        "ips_off": ips_off, "sp_off": sp_off,
        "workers": workers, "cadence_s": cadence_s,
        "overhead_frac": 1 - ips_on / max(ips_off, 1e-9),
        **fed_stats,
    }


def bench_autoscale(n_flood=10, n_paid=2, sleep_s=0.25):
    """ISSUE 16: elastic capacity, two measurements in one record.

    (1) Cluster elasticity — a hand-driven ``autoscale_tick`` against a
    hot windowed queue-wait p99: scale-up latency (decision → the new
    worker spawned and joined dispatch) and graceful-drain duration
    (drain start → clean snapshot-shipping exit) from the router's
    autoscale event ledger.

    (2) Per-tenant fairness under sustained overload — a flooding
    tenant vs a weighted light tenant on the executor choke point: the
    light tenant's queue-wait p99 alone (before) and mid-flood (after),
    plus the flood's own tail, read from the per-tenant metric series
    the fair queueing emits.
    """
    import threading

    import jax
    import jax.numpy as jnp

    from sparkdl_tpu.cluster import router as cluster_router
    from sparkdl_tpu.core import executor, telemetry
    from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
    from sparkdl_tpu.engine.dataframe import EngineConfig

    out = {}

    # -- (1) cluster elasticity: hot tick -> spawn, cold tick -> drain
    saved = EngineConfig.snapshot()
    try:
        EngineConfig.cluster_autoscale = True
        EngineConfig.cluster_min_workers = 1
        EngineConfig.cluster_max_workers = 2
        EngineConfig.autoscale_cooldown_s = 0.001
        router = cluster_router.ClusterRouter(workers=1)
        router._autoscale_stop.set()  # ticks driven by hand, not the loop
        if router._autoscale_thread is not None:
            router._autoscale_thread.join(timeout=10)
        try:
            with telemetry.Telemetry(out_dir=""):
                for _ in range(16):
                    telemetry.observe(telemetry.M_QUEUE_WAIT_S, 1.0)
                t0 = time.monotonic()
                assert router.autoscale_tick() == "up"
                out["scale_up_s"] = round(time.monotonic() - t0, 4)
            time.sleep(0.01)  # past the (tiny) cooldown
            # scope closed: no windowed p99 reads as cold -> drain
            assert router.autoscale_tick() == "down"
            deadline = time.monotonic() + 30
            drained = []
            while time.monotonic() < deadline and not drained:
                drained = [e for e in router.autoscale_events
                           if e["action"] == "drained"]
                time.sleep(0.02)
            out["drain_s"] = (round(drained[0]["drain_s"], 4)
                              if drained else None)
            out["autoscale_events"] = [e["action"]
                                       for e in router.autoscale_events]
        finally:
            router.close()
    finally:
        EngineConfig.restore(saved)
        cluster_router.shutdown()

    # -- (2) tenant fairness: paid p99 alone vs mid-flood
    saved = EngineConfig.snapshot()
    executor.reset()
    try:
        EngineConfig.coalesce_max_rows = 4  # small cap: DRR arbitrates
        EngineConfig.executor_tenant_weights = {"paid": 8}
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(size=(6, 3)).astype(np.float32))

        def apply_fn(vs, x):
            x = jax.pure_callback(lambda a: (time.sleep(sleep_s), a)[1],
                                  jax.ShapeDtypeStruct(x.shape, x.dtype),
                                  x)
            return jnp.tanh(x @ vs)

        mf = ModelFunction(apply_fn, w, TensorSpec((None, 6), "float32"),
                           name="bench_autoscale_fairness")

        def submit(tenant, seed):
            executor.execute(
                mf,
                np.random.default_rng(seed).normal(
                    size=(2, 6)).astype(np.float32),
                batch_size=32, tenant=tenant)

        def tenant_p99(snap, tenant):
            h = snap["histograms"].get(
                telemetry.tenant_queue_wait_metric(tenant))
            return None if h is None else h.get("p99")

        def fan(pairs, stagger_after=None):
            threads = [threading.Thread(target=submit, args=p)
                       for p in pairs]
            for i, t in enumerate(threads):
                if stagger_after is not None and i == stagger_after:
                    time.sleep(0.05)  # the flood is queued first
                t.start()
            for t in threads:
                t.join(timeout=120)

        with telemetry.Telemetry(out_dir="") as tel:
            fan([("paid", 100 + i) for i in range(max(n_paid, 4))])
            before = tenant_p99(tel.metrics.window_snapshot(), "paid")
        executor.reset()
        with telemetry.Telemetry(out_dir="") as tel:
            fan([("flood", i) for i in range(n_flood)]
                + [("paid", 100 + i) for i in range(n_paid)],
                stagger_after=n_flood)
            snap = tel.metrics.window_snapshot()
        out["tenant_paid_p99_before_s"] = (
            None if before is None else round(before, 4))
        after = tenant_p99(snap, "paid")
        out["tenant_paid_p99_overload_s"] = (
            None if after is None else round(after, 4))
        flood = tenant_p99(snap, "flood")
        out["tenant_flood_p99_overload_s"] = (
            None if flood is None else round(flood, 4))
    finally:
        executor.reset()
        EngineConfig.restore(saved)
    return out


def bench_precision_featurize(name="EfficientNetB0", n_images=128,
                              size=(224, 224), batch_size=64):
    """ISSUE 12 satellite: fp32 / bf16 / int8 featurize throughput AND
    max output delta vs fp32 in ONE record, through the engine choke
    point (``EngineConfig.inference_precision`` → executor → ``with_dtype``)
    so the measured path is exactly what pipelines run. On CPU smoke the
    throughputs may be neutral; the deltas are the portable part."""
    from sparkdl_tpu.core import executor as device_executor
    from sparkdl_tpu.engine.dataframe import EngineConfig
    from sparkdl_tpu.models import registry

    mf = registry.build_featurizer(name, weights="random")
    rng = np.random.default_rng(0)
    x = rng.integers(0, 255, size=(n_images,) + size + (3,)
                     ).astype(np.float32)
    saved = EngineConfig.snapshot()
    results = {}
    base = None
    out = {}
    try:
        for precision in ("float32", "bfloat16", "int8"):
            EngineConfig.inference_precision = precision
            device_executor.reset()

            def run():
                out["y"] = device_executor.execute(mf, x,
                                                   batch_size=batch_size)

            run()  # warmup: compile the precision variant
            best, spread = _best_of(run)
            y = np.asarray(out["y"], np.float32)
            if base is None:
                base = y
            delta = float(np.abs(y - base).max())
            results[precision] = {
                "images_per_sec": round(n_images / best, 2),
                "spread": round(spread, 4),
                "max_delta_vs_fp32": delta,
                # normalized by the fp32 output scale — random-weight
                # features are tiny, so the absolute delta alone misreads
                "max_rel_delta_vs_fp32": round(
                    delta / max(float(np.abs(base).max()), 1e-30), 6),
            }
    finally:
        device_executor.reset()
        EngineConfig.restore(saved)
    return results


def bench_bucket_ladder(sizes=(17, 17, 17, 17, 9, 23), batch_size=64,
                        feat_dim=256):
    """ISSUE 12 tentpole leg: skewed partition sizes (nothing near a
    power-of-two rung) through the executor, blind pow2 ladder vs the
    telemetry-tuned planner in ONE record. The planner is warmed past the
    retune threshold first; the measured scope then reads the
    POST-tuning padding-waste gauge, which must come in strictly below
    the pow2 run's (the acceptance gate)."""
    import jax.numpy as jnp

    from sparkdl_tpu.core import batching, telemetry
    from sparkdl_tpu.core import executor as device_executor
    from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
    from sparkdl_tpu.engine.dataframe import EngineConfig

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(feat_dim, 64)).astype(np.float32)
                    * 0.05)

    def apply_fn(vs, x):
        return jnp.tanh(x @ vs)

    chunks = [rng.normal(size=(n, feat_dim)).astype(np.float32)
              for n in sizes]
    rows_per_pass = sum(sizes)
    # enough passes to cross the retune threshold at least twice
    warm_passes = (2 * batching.PLANNER_UPDATE_EVERY) // len(sizes) + 1
    saved = EngineConfig.snapshot()
    results = {}
    try:
        for ladder in ("pow2", "tuned"):
            EngineConfig.bucket_ladder = ladder
            batching.reset_planners()
            device_executor.reset()
            mf = ModelFunction(apply_fn, w,
                               TensorSpec((None, feat_dim), "float32"),
                               name=f"ladder_{ladder}")

            def run():
                for c in chunks:
                    device_executor.execute(mf, c, batch_size=batch_size)

            # warm under a live scope: compiles + the observation stream
            # the retune feeds on (the waste gauge gates retunes)
            with telemetry.Telemetry(f"bench_ladder_warm_{ladder}") as warm:
                for _ in range(warm_passes):
                    run()
                updates = int(warm.metrics.snapshot()["counters"].get(
                    telemetry.M_BUCKET_LADDER_UPDATE, 0))
            # measured: a FRESH scope so the gauge reflects only the
            # post-tuning steady state
            with telemetry.Telemetry(f"bench_ladder_{ladder}") as tel:
                best, spread = _best_of(run)
                snap = tel.metrics.snapshot()
            results[ladder] = {
                "rows_per_sec": round(rows_per_pass / best, 2),
                "spread": round(spread, 4),
                "padding_waste": round(
                    snap["gauges"].get(telemetry.M_PADDING_WASTE, 0.0), 4),
                "ladder_updates": updates,
            }
    finally:
        device_executor.reset()
        batching.reset_planners()
        EngineConfig.restore(saved)
    return results


def bench_batch_inference(name, n_images=256, size=(224, 224)):
    """Config 2: DeepImagePredictor over an in-memory image DataFrame."""
    import jax.numpy as jnp
    import pyarrow as pa

    from sparkdl_tpu.engine.dataframe import DataFrame
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.ml import DeepImagePredictor

    rng = np.random.default_rng(0)
    rows = [{"image": imageIO.imageArrayToStruct(
        rng.integers(0, 255, size=size + (3,), dtype=np.uint8))}
        for _ in range(n_images)]
    schema = pa.schema([pa.field("image", imageIO.imageSchema)])
    df = DataFrame.fromRows(rows, schema=schema, numPartitions=4)
    t = DeepImagePredictor(inputCol="image", outputCol="pred",
                           modelName=name, batchSize=HEADLINE_BATCH,
                           dtype=jnp.bfloat16, weights="random")

    def run():
        out = t.transform(df).select("pred").collect()
        assert len(out) == n_images
    run()
    best, spread = _best_of(run)
    return n_images / best, spread


def bench_udf(n_rows=256):
    """Config 3: model as SQL UDF over an image column via selectExpr."""
    import jax.numpy as jnp
    import pyarrow as pa

    from sparkdl_tpu.engine.dataframe import DataFrame
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.models import registry as model_registry
    from sparkdl_tpu.udf import registerImageUDF

    rng = np.random.default_rng(0)
    rows = [{"image": imageIO.imageArrayToStruct(
        rng.integers(0, 255, size=(299, 299, 3), dtype=np.uint8))}
        for _ in range(n_rows)]
    schema = pa.schema([pa.field("image", imageIO.imageSchema)])
    df = DataFrame.fromRows(rows, schema=schema, numPartitions=4)
    mf = model_registry.build_predictor("InceptionV3", weights="random",
                                        dtype=jnp.bfloat16)
    registerImageUDF("bench_inception_udf", mf, batchSize=HEADLINE_BATCH)

    def run():
        out = df.selectExpr("bench_inception_udf(image) as pred").collect()
        assert len(out) == n_rows
    run()
    best, spread = _best_of(run)
    return n_rows / best, spread


def bench_streaming_fit(n_images=768):
    """Config 4 END-TO-END (VERDICT r3 #3): JPEG files -> URI frame ->
    streaming decode -> KerasImageFileEstimator.fit of a real MobileNetV2
    (keras-ingested), mixed precision.

    ONE estimator is reused across fits, so the ingested ModelFunction's
    compiled-step cache (trainer.py) makes every fit after the first
    compile-free; the STEADY-STATE rate is still measured as the epoch
    marginal ``2n / (t(3 epochs) - t(1 epoch))`` so any residual one-time
    cost cancels. The phase breakdown (decode / stage / train_step wall
    seconds, 3-epoch run) shows whether host decode starves the MXU
    (SURVEY.md §7 #2). With the async pipeline (ISSUE 3) host phases run
    on the staging thread and overlap sparkdl.train_step, so the emitted
    ``host_wait_s`` (starvation seconds the device-driving thread spent
    waiting on host ETL) and ``overlap_ratio`` (fraction of host ETL
    hidden behind device work; 0 = the old serial behavior) are the
    fields that show the pipeline's win in the trajectory.

    The 3-epoch measurement runs under a telemetry scope (ISSUE 4), so
    the emitted record also carries DISTRIBUTIONS — the steps/sec
    histogram over sync windows, host step-dispatch intervals, prefetch
    stall seconds — not just the throughput mean.

    Pooled variant (ISSUE 9 satellite): the same marginal measurement
    repeats with the multi-process decode pool armed
    (``EngineConfig.decode_workers = cpu_count``), emitted in the same
    record as ``pooled`` — the streaming-fit ingest is decode-dominated
    (r05: 24 s of sparkdl.decode), so this is where the pool's win shows
    up in the trajectory."""
    from sparkdl_tpu.core import decode_pool, profiling, telemetry
    from sparkdl_tpu.engine.dataframe import DataFrame, EngineConfig
    from sparkdl_tpu.ml import KerasImageFileEstimator

    import keras

    rng = np.random.default_rng(0)
    saved = EngineConfig.snapshot()
    pool_workers = os.cpu_count() or 1
    try:
        with tempfile.TemporaryDirectory() as d:
            paths = _write_jpegs(d, n_images, rng)
            rows = [{"uri": p, "label": i % 10}
                    for i, p in enumerate(paths)]
            df = DataFrame.fromRows(rows, numPartitions=8)
            est = KerasImageFileEstimator(
                inputCol="uri", outputCol="preds", labelCol="label",
                model=keras.applications.MobileNetV2(weights=None,
                                                     classes=10),
                kerasOptimizer="sgd",
                kerasLoss="sparse_categorical_crossentropy")

            def fit(epochs):
                est.setKerasFitParams(
                    {"epochs": epochs, "batch_size": 64,
                     "learning_rate": 0.01, "shuffle": True,
                     "streaming": True, "mixed_precision": True})
                est.fit(df)

            def marginal_rate(tel_name):
                """Steady-state epoch marginal: 2n / (t(3) - t(1))."""
                t1 = min(_timed(lambda: fit(1)) for _ in range(2))
                profiling.reset_phase_stats()
                with telemetry.Telemetry(tel_name) as tel:
                    t3 = min(_timed(lambda: fit(3)) for _ in range(2))
                snap = tel.metrics.snapshot()
                phases = {name: round(s["total_s"], 3)
                          for name, s in profiling.phase_stats().items()}
                overlap = profiling.overlap_stats()
                marginal = t3 - t1
                rate = (2 * n_images / marginal if marginal >= 0.5
                        else -1.0)
                return rate, phases, overlap, snap

            fit(1)  # warmup: ingestion + step compile + host caches
            sips, phases, overlap, snap = marginal_rate(
                "bench_streaming_fit")
            EngineConfig.decode_workers = pool_workers
            fit(1)  # warmup the pool: worker spawn + imports
            psips, pphases, poverlap, _psnap = marginal_rate(
                "bench_streaming_fit_pooled")
    finally:
        EngineConfig.restore(saved)
        decode_pool.shutdown()
    def device_rate_fraction(rate, run_phases):
        """e2e rate / device-only rate — ROADMAP item-1's trajectory
        metric (1.0 = the device never waits on host ETL). The phase
        window after reset_phase_stats covers two fit(3) runs, so the
        train_step phase saw 6 * n_images images."""
        ts = run_phases.get("sparkdl.train_step")
        if not ts or rate <= 0:
            return None
        return round(rate / (6 * n_images / ts), 4)

    tel_summary = {
        "steps_per_sec": _hist_summary(snap, telemetry.M_STEPS_PER_SEC),
        "step_time_s": _hist_summary(snap, telemetry.M_STEP_TIME_S),
        "prefetch_stall_s": _hist_summary(snap,
                                          telemetry.M_PREFETCH_STALL_S),
        "padding_waste": snap["gauges"].get(telemetry.M_PADDING_WASTE),
        "overlap": {k: round(v, 4) for k, v in overlap.items()},
        "device_rate_fraction": device_rate_fraction(sips, phases),
    }
    pooled = {
        "images_per_sec": round(psips, 2),
        "decode_workers": pool_workers,
        "phases": pphases,
        "host_wait_s": round(poverlap["host_wait_s"], 3),
        "overlap_ratio": round(poverlap["overlap_ratio"], 4),
        "speedup": (round(psips / sips, 4) if sips > 0 and psips > 0
                    else None),
        "device_rate_fraction": device_rate_fraction(psips, pphases),
    }
    # the invalid-marginal marker (-1.0) propagates as the headline value
    # so a tunnel-noise round can't poison the next vs_baseline
    return sips, phases, overlap, tel_summary, pooled


def bench_train_step(model_name, batch_size, mesh=None, compute_dtype=None):
    """Step time via in-order stream: time K steps, barrier on final loss."""
    import jax

    from sparkdl_tpu.models import registry
    from sparkdl_tpu.train import Trainer

    spec = registry.get_model_spec(model_name)
    module = spec.builder(include_top=True, classes=spec.classes)
    h, w = spec.input_size
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, size=(batch_size, h, w, 3)).astype(np.float32)
    y = np.eye(spec.classes, dtype=np.float32)[
        rng.integers(0, spec.classes, size=batch_size)]
    import jax.numpy as jnp
    variables = jax.jit(module.init)(jax.random.PRNGKey(0),
                                     jnp.zeros((1, h, w, 3), jnp.float32))
    trainer, state = Trainer.from_flax(module, variables, optimizer="sgd",
                                       learning_rate=0.01, mesh=mesh,
                                       compute_dtype=compute_dtype)
    step = trainer.make_train_step(donate=False)
    xd, yd = jax.device_put(x), jax.device_put(y)
    state, m = step(state, xd, yd)
    jax.device_get(m["loss"])  # compile + warm

    def run_k(k):
        nonlocal state
        t0 = time.perf_counter()
        last = None
        for _ in range(k):
            state, last = step(state, xd, yd)
        jax.device_get(last["loss"])  # in-order stream barrier
        return time.perf_counter() - t0

    run_k(2)
    smalls = [run_k(2) for _ in range(3)]
    larges = [run_k(10) for _ in range(3)]
    spread = (max(larges) - min(larges)) / min(larges)
    return (min(larges) - min(smalls)) / 8, spread


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _best_of(fn, n=3):
    """(best_seconds, relative_spread) over n timed repeats (VERDICT r3 #2:
    every wall-clock metric carries a spread, not just the device ones)."""
    ts = [_timed(fn) for _ in range(n)]
    return min(ts), (max(ts) - min(ts)) / min(ts)


def main():
    from sparkdl_tpu.core import profiling

    headline_only = "--headline" in sys.argv
    with profiling.maybe_trace():
        # headline measured and emitted FIRST (so a truncated run still
        # records it), then re-emitted verbatim as the LAST line (the
        # driver parses the final line)
        ips, spread, mfu, runs, flops = bench_device_featurize(
            "InceptionV3", (299, 299), FLOPS_PER_IMG_INCEPTION)
        headline = emit("images/sec/chip (InceptionV3 featurize)", ips,
                        "images/sec/chip", spread=round(spread, 4),
                        mfu=round(mfu, 4), runs=runs, flops=flops)
        if not headline_only:
            e2e, sp, e2e_tel = bench_e2e_featurize()
            emit("e2e images/sec (files->readImages->InceptionV3 featurize)",
                 e2e, "images/sec", spread=round(sp, 4), telemetry=e2e_tel)

            # parallel host ingest (ISSUE 9): the SAME e2e pipeline with
            # the multi-process decode pool off vs on, plus the
            # host-vs-device rate ratio the tentpole targets
            (pips, psp, pips_off, psp_off, pworkers, pphases, dev_ips,
             dev_frac, ptel) = bench_parallel_ingest()
            emit("parallel ingest e2e images/sec (files->decode pool->"
                 "InceptionV3 featurize)", pips, "images/sec",
                 spread=round(psp, 4), pool_off=round(pips_off, 2),
                 pool_off_spread=round(psp_off, 4),
                 pool_speedup=round(pips / max(pips_off, 1e-9), 4),
                 decode_workers=pworkers, phases=pphases,
                 device_only_ips=round(dev_ips, 2),
                 device_rate_fraction=round(dev_frac, 4),
                 decode_pool=ptel)

            # cross-partition coalescing (ISSUE 5): the tentpole's win
            # lands here — 8 partitions of small chunks, one metric with
            # coalescing on (the default) vs off
            (cips, csp, cmfu, cips_off, csp_off,
             ctel) = bench_concurrent_featurize()
            emit("concurrent featurize images/sec/chip (EfficientNetB0, "
                 "8 partitions, coalesced)", cips, "images/sec/chip",
                 spread=round(csp, 4), mfu=round(cmfu, 4),
                 coalesce_off=round(cips_off, 2),
                 coalesce_off_spread=round(csp_off, 4),
                 coalesce_speedup=round(cips / max(cips_off, 1e-9), 4),
                 telemetry=ctel)
            # overload protection (ISSUE 6): burst past the executor
            # queue bound — interactive-vs-bulk latency split and shed
            # accounting, shedding on vs off in one record
            ov = bench_overload_featurize()
            emit("overload featurize interactive images/sec "
                 "(EfficientNetB0 flood past queue bound, shed mode)",
                 ov["interactive_ips_shed_on"], "images/sec",
                 shed_on=ov["shed_on"], shed_off=ov["shed_off"])
            # online serving plane (ISSUE 13): row-level requests beside
            # a bulk featurize flood — request latency tail, shed rate,
            # shadow overhead and the eviction-reload cold start
            sv = bench_serving()
            emit("serving request p99 ms (EfficientNetB0 row-level "
                 "predict beside bulk flood)", sv["request_p99_ms"],
                 "ms/step", p50_ms=sv["request_p50_ms"],
                 answered=sv["answered"], shed=sv["shed"],
                 shed_rate_per_s=sv["shed_rate_per_s"],
                 shadowed_requests=sv["shadowed_requests"],
                 shadow_overhead_frac=sv["shadow_overhead_frac"],
                 cold_start_s=sv["cold_start_s"],
                 cold_start_bytes=sv["cold_start_bytes"],
                 warmup_cold_start=sv["warmup_cold_start"],
                 request_s=sv["request_s"], elapsed_s=sv["elapsed_s"])
            # cluster serving failover (ISSUE 17): SIGKILL one of two
            # replicas mid-stream — failover-phase p99 beside steady
            # p99, and the hot-swap mix window, which must be 0
            fo = bench_serving_failover()
            emit("serving failover p99 ms (EfficientNetB0, kill 1-of-2 "
                 "replicas mid-stream)", fo["failover_p99_ms"],
                 "ms/step", steady_p99_ms=fo["steady_p99_ms"],
                 steady_p50_ms=fo["steady_p50_ms"],
                 failover_p50_ms=fo["failover_p50_ms"],
                 answered_under_kill=fo["answered_under_kill"],
                 moved_requests=fo["moved_requests"],
                 cutover_mix_window_ms=fo["cutover_mix_window_ms"],
                 swap_versions_served=fo["swap_versions_served"])
            # live observability plane (ISSUE 7): the periodic exporter's
            # cost must stay under 5% — measured on the same featurize
            # loop with the exporter on vs off
            (xips_on, xips_off, xsp_on, xsp_off,
             xsnaps) = bench_exporter_overhead()
            emit("exporter-on featurize images/sec (EfficientNetB0, "
                 "0.2s snapshot cadence)", xips_on, "images/sec",
                 spread=round(xsp_on, 4),
                 exporter_off=round(xips_off, 2),
                 exporter_off_spread=round(xsp_off, 4),
                 overhead_frac=round(1 - xips_on / max(xips_off, 1e-9), 4),
                 snapshots=xsnaps)
            # durable job recovery (ISSUE 11): the write-ahead partition
            # journal must cost < 5% on the same e2e featurize pipeline
            (dips_on, dsp_on, dips_off, dsp_off,
             dfrac) = bench_durable_ingest()
            emit("durable ingest e2e images/sec (files->readImages->"
                 "EfficientNetB0 featurize, journal on)", dips_on,
                 "images/sec", spread=round(dsp_on, 4),
                 durable_off=round(dips_off, 2),
                 durable_off_spread=round(dsp_off, 4),
                 overhead_frac=round(dfrac, 4))
            # cluster inference plane (ISSUE 14): the same e2e featurize
            # fanned across 2 worker processes vs in-process — rate
            # pair, per-worker phase breakdowns from the merged report,
            # dispatch balance, and the router's transport overhead
            cl = bench_cluster_featurize()
            emit("cluster featurize e2e images/sec (files->readImages->"
                 "EfficientNetB0 featurize, 2 workers)", cl["ips_on"],
                 "images/sec", spread=round(cl["sp_on"], 4),
                 cluster_off=round(cl["ips_off"], 2),
                 cluster_off_spread=round(cl["sp_off"], 4),
                 cluster_workers=cl["workers"],
                 router_overhead_frac=round(cl["router_overhead_frac"], 4),
                 rows_per_worker=cl["rows_per_worker"],
                 exec_s_per_worker=cl["exec_s_per_worker"],
                 worker_phases=cl["worker_phases"],
                 health_consistent=cl["health_consistent"])
            # cross-process tracing (ISSUE 15): the distributed-tracing
            # plane (ctx on every dispatch, worker span rings, tail
            # exemplars) on the same cluster featurize, armed vs off —
            # the acceptance budget is < 3% overhead
            tr = bench_tracing_overhead()
            emit("tracing-armed cluster featurize images/sec "
                 "(EfficientNetB0, 2 workers, exemplar_k=4)",
                 tr["ips_armed"], "images/sec",
                 spread=round(tr["sp_armed"], 4),
                 tracing_off=round(tr["ips_off"], 2),
                 tracing_off_spread=round(tr["sp_off"], 4),
                 overhead_frac=round(tr["overhead_frac"], 4),
                 remote_adopted=tr.get("remote_adopted"),
                 workers_shipped=tr.get("workers_shipped"))
            # metrics federation (ISSUE 19): workers shipping windowed
            # delta frames + the coordinator's fold and federated SLO
            # watchdog on the same cluster featurize, armed vs off —
            # the acceptance budget is < 3% overhead
            fd = bench_federation_overhead()
            emit("federation-armed cluster featurize images/sec "
                 "(EfficientNetB0, 2 workers, 0.25s frame cadence)",
                 fd["ips_armed"], "images/sec",
                 spread=round(fd["sp_armed"], 4),
                 federation_off=round(fd["ips_off"], 2),
                 federation_off_spread=round(fd["sp_off"], 4),
                 overhead_frac=round(fd["overhead_frac"], 4),
                 frames_ingested=fd.get("frames_ingested"),
                 workers_known=fd.get("workers_known"))
            # elastic capacity (ISSUE 16): autoscale decision->join
            # latency + graceful-drain duration from the event ledger,
            # and the weighted light tenant's queue-wait p99 before vs
            # during a sustained flood (fair queueing holding the line)
            au = bench_autoscale()
            emit("autoscale scale-up latency (1->2 workers, hot "
                 "queue-wait p99)", au["scale_up_s"], "seconds",
                 drain_s=au["drain_s"],
                 autoscale_events=au["autoscale_events"],
                 tenant_paid_p99_before_s=au["tenant_paid_p99_before_s"],
                 tenant_paid_p99_overload_s=(
                     au["tenant_paid_p99_overload_s"]),
                 tenant_flood_p99_overload_s=(
                     au["tenant_flood_p99_overload_s"]))

            # fused Pallas kernels (ISSUE 20): the flagship featurize
            # with the kernel plane off vs the accept-if-faster
            # autotune — per-rung verdicts ride along; adopted kernels
            # must be strictly faster, a host backend records a clean
            # all-rejected pair
            ka = bench_kernel_autotune()
            emit("kernel-autotune featurize images/sec/chip "
                 "(InceptionV3, fused Pallas off vs autotune)",
                 ka["autotune"]["images_per_sec"], "images/sec/chip",
                 off=ka["off"], autotune=ka["autotune"],
                 speedup=ka["speedup"], adopted=ka["adopted"],
                 rejected=ka["rejected"], autotune_s=ka["autotune_s"],
                 verdicts=ka["verdicts"])
            # raw-speed inference (ISSUE 12): the precision ladder —
            # fp32/bf16/int8 throughput AND max output delta, one record
            prec = bench_precision_featurize()
            emit("precision featurize images/sec (EfficientNetB0 "
                 "fp32/bf16/int8, engine choke point)",
                 prec["bfloat16"]["images_per_sec"], "images/sec",
                 fp32=prec["float32"], bf16=prec["bfloat16"],
                 int8=prec["int8"],
                 bf16_speedup=round(
                     prec["bfloat16"]["images_per_sec"]
                     / max(prec["float32"]["images_per_sec"], 1e-9), 4),
                 int8_speedup=round(
                     prec["int8"]["images_per_sec"]
                     / max(prec["float32"]["images_per_sec"], 1e-9), 4))
            # launch shaping (ISSUE 12): skewed partition sizes, blind
            # pow2 ladder vs telemetry-tuned planner — the post-tuning
            # padding-waste gauge must come in strictly below pow2's
            lad = bench_bucket_ladder()
            emit("tuned-ladder featurize rows/sec (skewed partitions "
                 "17/9/23, batch 64)",
                 lad["tuned"]["rows_per_sec"], "rows/sec",
                 spread=lad["tuned"]["spread"], pow2=lad["pow2"],
                 tuned=lad["tuned"],
                 padding_waste_pow2=lad["pow2"]["padding_waste"],
                 padding_waste_tuned=lad["tuned"]["padding_waste"],
                 waste_strictly_reduced=(
                     lad["tuned"]["padding_waste"]
                     < lad["pow2"]["padding_waste"]))

            for name, size in (("ResNet50", (224, 224)),
                               ("Xception", (299, 299))):
                ips, sp = bench_batch_inference(name, size=size)
                emit(f"batch inference images/sec ({name} predict)",
                     ips, "images/sec", spread=round(sp, 4))
            rps, sp = bench_udf()
            emit("SQL UDF rows/sec (InceptionV3 via selectExpr)",
                 rps, "rows/sec", spread=round(sp, 4))
            sips, phases, overlap, fit_tel, fit_pooled = \
                bench_streaming_fit()
            emit("e2e streaming fit images/sec (files->decode->MobileNetV2 "
                 "train)", sips, "images/sec", phases=phases,
                 host_wait_s=round(overlap["host_wait_s"], 3),
                 overlap_ratio=round(overlap["overlap_ratio"], 4),
                 device_rate_fraction=fit_tel["device_rate_fraction"],
                 telemetry=fit_tel, pooled=fit_pooled)
            st, sp = bench_train_step("MobileNetV2", 64)
            st16, sp16 = bench_train_step("MobileNetV2", 64,
                                          compute_dtype="bfloat16")
            emit("fine-tune step time (MobileNetV2 b64)", st * 1e3, "ms/step",
                 images_per_sec=round(64 / st, 2), spread=round(sp, 4),
                 mixed_precision_ms=round(st16 * 1e3, 2),
                 mixed_precision_images_per_sec=round(64 / st16, 2),
                 mixed_precision_spread=round(sp16, 4))
            st, sp = bench_train_step("ResNet50", 64)
            st16, sp16 = bench_train_step("ResNet50", 64,
                                          compute_dtype="bfloat16")
            emit("DP train step time (ResNet50 b64, 1 chip)", st * 1e3,
                 "ms/step", images_per_sec=round(64 / st, 2),
                 spread=round(sp, 4),
                 mixed_precision_ms=round(st16 * 1e3, 2),
                 mixed_precision_images_per_sec=round(64 / st16, 2),
                 mixed_precision_spread=round(sp16, 4))

            # device throughput for the other flagship CNN: ResNet50's big
            # uniform convs hit ~48% MFU (vs InceptionV3's branchy ~29%)
            rips, _, rmfu, rruns, rflops = bench_device_featurize(
                "ResNet50", (224, 224), FLOPS_PER_IMG_RESNET50)
            emit("images/sec/chip (ResNet50 featurize)", rips,
                 "images/sec/chip", mfu=round(rmfu, 4), runs=rruns,
                 flops=rflops)

            # ingestion-backed zoo coverage (VERDICT r4 #9): driver-capture
            # the generic keras layer-DAG walker's program so regressions
            # in that path surface as vs_baseline drops, not just
            # builder-local notes. Two representatives: the concat-bound
            # (DenseNet121) and the dw/SE conv-bound (EfficientNetB0)
            # regimes measured in docs/PERF.md.
            for name, flops in (("DenseNet121", FLOPS_PER_IMG_DENSENET121),
                                ("EfficientNetB0", FLOPS_PER_IMG_EFFNETB0)):
                iips, isp, imfu, iruns, iflops = bench_device_featurize(
                    name, (224, 224), flops)
                emit(f"images/sec/chip ({name} featurize, ingested)", iips,
                     "images/sec/chip", spread=round(isp, 4),
                     mfu=round(imfu, 4), runs=iruns, flops=iflops)

            # re-emit the headline as the final line for tail parsers
            print(json.dumps(headline), flush=True)


if __name__ == "__main__":
    main()
