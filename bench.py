"""Benchmark harness — one JSON line for the driver.

Headline metric (BASELINE.md / BASELINE.json): images/sec/chip for
DeepImageFeaturizer-equivalent InceptionV3 featurize. Runs on the real
TPU chip (no platform override); the model executes in bfloat16 on the
MXU with device-resident weights, host staging excluded (the metric is
device throughput, matching the reference's per-executor Session.run
hot loop, SURVEY.md §3.1).

The reference publishes no numbers (BASELINE.json ``published: {}``), so
``vs_baseline`` is null until a measured reference exists.
"""

import json
import time

import numpy as np


def bench_inception_featurize(batch_size: int = 512, iters: int = 8,
                              warmup: int = 2) -> float:
    import jax
    import jax.numpy as jnp

    from sparkdl_tpu.models import registry

    mf = registry.build_featurizer("InceptionV3", weights="random",
                                   dtype=jnp.bfloat16)
    fn = mf.jitted()
    rng = np.random.default_rng(0)
    x = rng.integers(0, 255, size=(batch_size, 299, 299, 3)).astype(np.float32)
    xd = jax.device_put(x)
    # Timing uses device_get on the LAST queued output: under the Axon PJRT
    # tunnel block_until_ready does not actually wait, so fetching the final
    # result is the only reliable completion barrier. Execution is in-order,
    # so this measures all queued iterations.
    for _ in range(warmup):
        jax.device_get(fn(xd))
    t0 = time.perf_counter()
    outs = [fn(xd) for _ in range(iters)]
    jax.device_get(outs[-1])
    dt = time.perf_counter() - t0
    return batch_size * iters / dt


def main() -> None:
    images_per_sec = bench_inception_featurize()
    print(json.dumps({
        "metric": "images/sec/chip (InceptionV3 featurize)",
        "value": round(images_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": None,
    }))


if __name__ == "__main__":
    main()
