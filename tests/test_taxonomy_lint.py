"""Static check: no bare `except Exception: retry` loops bypassing
core.resilience.classify (ISSUE 2 satellite; keeps the error taxonomy the
single source of truth).

The rule: inside a `for`/`while` loop, a broad handler (`except:`,
`except Exception`, `except BaseException`) must either re-raise
somewhere in its body or consult the taxonomy (reference `classify` or
the `resilience` module). A handler that swallows broadly and lets the
loop re-attempt is exactly the blind-retry shape PR 1/2 removed — FATAL
user errors would be silently replayed.

Deliberate broad swallows that are NOT retries (per-row degradation that
re-raises conditionally already passes; anything else) can opt out with a
`# taxonomy-ok: <reason>` comment on the `except` line.
"""

import ast
import pathlib

ROOT = pathlib.Path(__file__).resolve().parent.parent / "sparkdl_tpu"

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def _consults_taxonomy_or_raises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Name) and node.id in ("classify",
                                                      "resilience"):
            return True
        if isinstance(node, ast.Attribute) and node.attr == "classify":
            return True
    return False


class _LoopHandlerVisitor(ast.NodeVisitor):
    def __init__(self, source_lines):
        self.loop_depth = 0
        self.lines = source_lines
        self.violations = []

    def _loop(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_While = visit_AsyncFor = _loop

    def visit_Try(self, node):
        for handler in node.handlers:
            if (self.loop_depth > 0 and _is_broad(handler)
                    and not _consults_taxonomy_or_raises(handler)
                    and "taxonomy-ok" not in
                    self.lines[handler.lineno - 1]):
                self.violations.append(handler.lineno)
        self.generic_visit(node)

    # TryStar (3.11 except*) gets the same treatment if it ever appears
    visit_TryStar = visit_Try


def test_no_blind_broad_retry_loops():
    offenders = []
    for path in sorted(ROOT.rglob("*.py")):
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
        visitor = _LoopHandlerVisitor(source.splitlines())
        visitor.visit(tree)
        offenders.extend(f"{path.relative_to(ROOT.parent)}:{line}"
                         for line in visitor.violations)
    assert not offenders, (
        "broad except inside a loop without re-raise or "
        "core.resilience.classify — blind retry would replay FATAL "
        "errors. Route the handler through resilience.classify (or mark "
        "a deliberate non-retry swallow with '# taxonomy-ok: <reason>'): "
        f"{offenders}")


def test_lint_catches_the_old_blind_retry_shape():
    """Self-test: the pre-supervision `_run_partition` loop (retry every
    failure blindly) must trip the lint."""
    bad = (
        "def run(ops, batch):\n"
        "    for attempt in range(3):\n"
        "        try:\n"
        "            return ops(batch)\n"
        "        except Exception as e:\n"
        "            last = e\n"
    )
    tree = ast.parse(bad)
    v = _LoopHandlerVisitor(bad.splitlines())
    v.visit(tree)
    assert v.violations == [5]
