"""Static check: no bare `except Exception: retry` loops bypassing
core.resilience.classify (ISSUE 2 satellite; keeps the error taxonomy the
single source of truth).

The rule: inside a `for`/`while` loop, a broad handler (`except:`,
`except Exception`, `except BaseException`) must either re-raise
somewhere in its body or consult the taxonomy (reference `classify` or
the `resilience` module). A handler that swallows broadly and lets the
loop re-attempt is exactly the blind-retry shape PR 1/2 removed — FATAL
user errors would be silently replayed.

Deliberate broad swallows that are NOT retries (per-row degradation that
re-raises conditionally already passes; anything else) can opt out with a
`# taxonomy-ok: <reason>` comment on the `except` line.
"""

import ast
import pathlib

ROOT = pathlib.Path(__file__).resolve().parent.parent / "sparkdl_tpu"

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def _consults_taxonomy_or_raises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Name) and node.id in ("classify",
                                                      "resilience"):
            return True
        if isinstance(node, ast.Attribute) and node.attr == "classify":
            return True
    return False


class _LoopHandlerVisitor(ast.NodeVisitor):
    def __init__(self, source_lines):
        self.loop_depth = 0
        self.lines = source_lines
        self.violations = []

    def _loop(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_While = visit_AsyncFor = _loop

    def visit_Try(self, node):
        for handler in node.handlers:
            if (self.loop_depth > 0 and _is_broad(handler)
                    and not _consults_taxonomy_or_raises(handler)
                    and "taxonomy-ok" not in
                    self.lines[handler.lineno - 1]):
                self.violations.append(handler.lineno)
        self.generic_visit(node)

    # TryStar (3.11 except*) gets the same treatment if it ever appears
    visit_TryStar = visit_Try


def test_no_blind_broad_retry_loops():
    offenders = []
    for path in sorted(ROOT.rglob("*.py")):
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
        visitor = _LoopHandlerVisitor(source.splitlines())
        visitor.visit(tree)
        offenders.extend(f"{path.relative_to(ROOT.parent)}:{line}"
                         for line in visitor.violations)
    assert not offenders, (
        "broad except inside a loop without re-raise or "
        "core.resilience.classify — blind retry would replay FATAL "
        "errors. Route the handler through resilience.classify (or mark "
        "a deliberate non-retry swallow with '# taxonomy-ok: <reason>'): "
        f"{offenders}")


def test_lint_catches_the_old_blind_retry_shape():
    """Self-test: the pre-supervision `_run_partition` loop (retry every
    failure blindly) must trip the lint."""
    bad = (
        "def run(ops, batch):\n"
        "    for attempt in range(3):\n"
        "        try:\n"
        "            return ops(batch)\n"
        "        except Exception as e:\n"
        "            last = e\n"
    )
    tree = ast.parse(bad)
    v = _LoopHandlerVisitor(bad.splitlines())
    v.visit(tree)
    assert v.violations == [5]


# ---------------------------------------------------------------------------
# Async-pipeline lint (ISSUE 3): Trainer.fit's step loop must never block
# on the device outside the designated sync helpers. A blocking fetch —
# `int(...)` / `float(...)` on a device scalar, `np.asarray`,
# `jax.device_get`, `block_until_ready` — inside the loop body
# re-serializes host staging with device compute (the exact regression the
# DevicePrefetcher removed). Blocking fetches belong in the pre-loop
# helper closures (`sync` / `save_checkpoint`), which the loop calls only
# at sync points; nested function DEFINITIONS are therefore exempt, direct
# calls in the loop body are not.
# ---------------------------------------------------------------------------

_BLOCKING_NAMES = {"int", "float"}
_BLOCKING_ATTRS = {"asarray", "device_get", "block_until_ready"}


def _blocking_calls_in_fit_loops(tree: ast.AST):
    """Lines of blocking-fetch calls inside Trainer.fit's own loops."""
    fit = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Trainer":
            for item in node.body:
                if isinstance(item, ast.FunctionDef) and item.name == "fit":
                    fit = item
    assert fit is not None, "Trainer.fit not found"

    class _LoopFinder(ast.NodeVisitor):
        """Collect fit's own loops, NOT those inside nested functions
        (helper closures run off the hot path or at sync points)."""

        def __init__(self):
            self.loops = []

        def visit_FunctionDef(self, node):
            if node is not fit:
                return  # don't descend into nested defs
            self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef

        def _loop(self, node):
            self.loops.append(node)
            self.generic_visit(node)

        visit_For = visit_While = visit_AsyncFor = _loop

    finder = _LoopFinder()
    finder.visit(fit)
    assert finder.loops, "Trainer.fit has no step loop?"

    def _walk_pruned(node):
        """ast.walk, but do not descend into nested function definitions:
        a def inside the loop only BLOCKS if called there — its call-site
        is what we check."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            yield child
            yield from _walk_pruned(child)

    violations = []
    for loop in finder.loops:
        for node in _walk_pruned(loop):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id in _BLOCKING_NAMES:
                violations.append(node.lineno)
            elif isinstance(f, ast.Attribute) and f.attr in _BLOCKING_ATTRS:
                violations.append(node.lineno)
    return sorted(set(violations))


def test_trainer_step_loop_has_no_blocking_device_fetch():
    path = ROOT / "train" / "trainer.py"
    tree = ast.parse(path.read_text(), filename=str(path))
    offenders = _blocking_calls_in_fit_loops(tree)
    assert not offenders, (
        "blocking device fetch inside Trainer.fit's step loop (lines "
        f"{offenders} of train/trainer.py) — int()/float()/np.asarray/"
        "jax.device_get/block_until_ready there re-serialize the async "
        "input pipeline. Move the fetch into the designated sync helpers "
        "(sync/save_checkpoint) and call them only at sync points.")


def test_lint_catches_the_old_per_step_sync_shape():
    """Self-test: the pre-pipeline loop body (`step = int(state.step)`
    per step, plus a device_get checkpoint fetch) must trip the lint —
    while helper DEFINITIONS (pre-loop or even inside the loop) stay
    exempt: only their call-sites block."""
    bad = (
        "class Trainer:\n"
        "    def fit(self, state, batches):\n"
        "        def sync(st):\n"
        "            return int(st.step)\n"  # pre-loop helper: exempt
        "        for x, y in batches:\n"
        "            def fetch():\n"
        "                return int(state.step)\n"  # nested DEF: exempt
        "            state, m = step(state, x, y)\n"
        "            step_n = int(state.step)\n"  # line 9: violation
        "            ckpt.save(step_n, jax.device_get(state))\n"  # line 10
        "        return state\n"
    )
    assert _blocking_calls_in_fit_loops(ast.parse(bad)) == [9, 10]


# ---------------------------------------------------------------------------
# Canonical span/phase name lint (ISSUE 4): every name passed to
# profiling.annotate() or telemetry.span() in sparkdl_tpu/ must be declared
# in core.telemetry.CANONICAL_SPAN_NAMES — a typo'd phase name would
# otherwise silently fork a timer (and a trace track) instead of failing.
# Names arriving as profiling/telemetry module constants resolve through
# the live modules; dynamic names (the annotate/span wrappers forwarding a
# parameter) are skipped — only literals and known constants are checkable.
# ---------------------------------------------------------------------------

from sparkdl_tpu.core import profiling as _profiling  # noqa: E402
from sparkdl_tpu.core import telemetry as _telemetry  # noqa: E402

_SPAN_CALL_NAMES = {"annotate", "span"}


def _resolve_name_arg(arg: ast.expr):
    """String value of a span-name argument, or None when dynamic."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    attr = None
    if isinstance(arg, ast.Attribute):  # profiling.STAGE_BATCH
        attr = arg.attr
    elif isinstance(arg, ast.Name):     # SPAN_RUN inside telemetry.py
        attr = arg.id
    if attr is not None:
        for mod in (_profiling, _telemetry):
            value = getattr(mod, attr, None)
            if isinstance(value, str):
                return value
    return None


def _span_names_in(tree: ast.AST):
    """(name, lineno) for every statically-resolvable annotate()/span()
    call in the tree."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        fname = (f.id if isinstance(f, ast.Name)
                 else f.attr if isinstance(f, ast.Attribute) else None)
        if fname not in _SPAN_CALL_NAMES:
            continue
        name = _resolve_name_arg(node.args[0])
        if name is not None:
            out.append((name, node.lineno))
    return out


def test_every_span_name_is_canonical():
    catalog = _telemetry.CANONICAL_SPAN_NAMES
    offenders = []
    for path in sorted(ROOT.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for name, line in _span_names_in(tree):
            if name not in catalog:
                offenders.append(
                    f"{path.relative_to(ROOT.parent)}:{line}: {name!r}")
    assert not offenders, (
        "span/phase name not declared in "
        "core.telemetry.CANONICAL_SPAN_NAMES — a typo'd name silently "
        "forks a timer and a trace track. Add the name to the catalog "
        f"(and docs/OBSERVABILITY.md) or fix the typo: {offenders}")


def test_span_name_lint_catches_typo_and_resolves_constants():
    """Self-test: a typo'd literal trips the check; module-constant names
    resolve to their canonical strings."""
    bad = (
        "from sparkdl_tpu.core import profiling, telemetry\n"
        "with profiling.annotate('sparkdl.train_stepp'):\n"  # typo
        "    pass\n"
        "with telemetry.span(telemetry.SPAN_FIT):\n"         # constant
        "    pass\n"
        "with profiling.annotate(profiling.STAGE_BATCH):\n"  # constant
        "    pass\n"
        "with telemetry.span(dynamic_name):\n"               # skipped
        "    pass\n"
    )
    names = _span_names_in(ast.parse(bad))
    assert ("sparkdl.train_stepp", 2) in names
    assert ("sparkdl.fit", 4) in names
    assert ("sparkdl.stage_batch", 6) in names
    assert len(names) == 3  # the dynamic name is not checkable
    resolved = [n for n, _ in names]
    assert "sparkdl.train_stepp" not in _telemetry.CANONICAL_SPAN_NAMES
    assert all(n in _telemetry.CANONICAL_SPAN_NAMES
               for n in resolved if n != "sparkdl.train_stepp")


# ---------------------------------------------------------------------------
# Executor choke-point lint (ISSUE 5): the inference data plane's device
# entry goes through core/executor.py's `execute` — the coalescing choke
# point. A transformer (or UDF, or engine op) calling `apply_batch` /
# `jitted` directly would silently regress the featurize route back to
# per-partition launches, invisible until the next bench round. Only the
# choke point itself and the model layer it wraps may touch those
# methods; training (train/) owns its own step programs and is exempt.
# ---------------------------------------------------------------------------

_DEVICE_ENTRY_ATTRS = {"apply_batch", "jitted"}
# The featurize/serving route that MUST go through the executor. The
# choke point itself (core/executor.py) and the model layer it delegates
# to (core/model_function.py) live outside these scopes by design; the
# training path (train/) owns its own step programs and is exempt.
_CHOKE_SCOPES = ("ml", "udf", "engine", "image")


def _direct_device_entry_calls(tree: ast.AST):
    """Lines of direct `<obj>.apply_batch(...)` / `<obj>.jitted(...)`
    calls in the tree."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _DEVICE_ENTRY_ATTRS:
            out.append(node.lineno)
    return sorted(out)


def test_featurize_route_enters_device_via_executor_choke_point():
    offenders = []
    for scope in _CHOKE_SCOPES:
        for path in sorted((ROOT / scope).rglob("*.py")):
            tree = ast.parse(path.read_text(), filename=str(path))
            offenders.extend(
                f"{path.relative_to(ROOT.parent)}:{line}"
                for line in _direct_device_entry_calls(tree))
    assert not offenders, (
        "direct apply_batch/jitted call on the engine featurize route — "
        "device entry must go through core.executor.execute (the "
        "coalescing choke point), or concurrent partitions silently "
        "regress to per-partition launches (docs/PERF.md "
        "'Cross-partition coalescing'): "
        f"{offenders}")


def test_choke_point_lint_catches_direct_apply_batch():
    """Self-test: the pre-executor transformer shape (calling the model's
    apply_batch / jitted straight from the partition op) must trip."""
    bad = (
        "def apply_partition(batch):\n"
        "    out = model.apply_batch(stacked, batch_size=64)\n"
        "    fn = model.jitted(mesh=mesh)\n"
        "    good = device_executor.execute(model, stacked)\n"
        "    return out\n"
    )
    assert _direct_device_entry_calls(ast.parse(bad)) == [2, 3]


# ---------------------------------------------------------------------------
# Health-event name lint (ISSUE 6): every `health.record(...)` call site in
# sparkdl_tpu/ must pass a constant DECLARED in core/health.py as its event
# name — a bare string would silently fork a counter (and escape the docs
# catalog, the chaos accounting, and the sparkdl.health.* telemetry
# mirrors) on the first typo.
# ---------------------------------------------------------------------------

from sparkdl_tpu.core import health as _health  # noqa: E402

#: Event-name constants declared in core/health.py: UPPERCASE module
#: attributes holding strings.
_HEALTH_EVENT_CONSTANTS = {
    name for name in vars(_health)
    if name.isupper() and isinstance(getattr(_health, name), str)
}


def _bad_health_record_calls(tree: ast.AST):
    """(lineno, reason) for every `health.record(...)` call whose event
    argument is not a `health.<CONSTANT>` reference to a string constant
    declared in core/health.py."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        # the framework-wide convention: `health.record(...)` on the
        # imported module object (never `from ... import record`)
        if not (isinstance(f, ast.Attribute) and f.attr == "record"
                and isinstance(f.value, ast.Name)
                and f.value.id == "health"):
            continue
        if not node.args:
            out.append((node.lineno, "no event argument"))
            continue
        arg = node.args[0]
        if (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            out.append((node.lineno, f"bare string {arg.value!r}"))
            continue
        if not (isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)
                and arg.value.id == "health"):
            out.append((node.lineno, "event name is not a "
                                     "health.<CONSTANT> reference"))
            continue
        if arg.attr not in _HEALTH_EVENT_CONSTANTS:
            out.append((node.lineno,
                        f"health.{arg.attr} is not declared in "
                        "core/health.py"))
    return out


def test_every_health_record_uses_a_declared_constant():
    offenders = []
    for path in sorted(ROOT.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for line, reason in _bad_health_record_calls(tree):
            offenders.append(
                f"{path.relative_to(ROOT.parent)}:{line}: {reason}")
    assert not offenders, (
        "health.record() call site not using a constant declared in "
        "core/health.py — a typo'd or ad-hoc event name silently forks a "
        "counter outside the docs catalog and the telemetry mirror. "
        f"Declare the event in core/health.py and reference it: {offenders}")


def test_health_record_lint_catches_typos_and_bare_strings():
    """Self-test: a bare string event, a typo'd constant, and a local
    variable all trip; a declared constant passes."""
    bad = (
        "from sparkdl_tpu.core import health\n"
        "health.record('task_retried', partition=1)\n"      # bare string
        "health.record(health.TASK_RETIRED)\n"              # typo'd name
        "health.record(evt, partition=1)\n"                 # dynamic name
        "health.record(health.TASK_RETRIED, partition=1)\n"  # ok
        "mon.record('whatever')\n"                          # not the hook
    )
    flagged = _bad_health_record_calls(ast.parse(bad))
    assert [line for line, _ in flagged] == [2, 3, 4]
    assert "TASK_RETIRED" in flagged[1][1]
    # the constants set is non-trivial and holds the canonical events
    assert "TASK_RETRIED" in _HEALTH_EVENT_CONSTANTS
    assert "BREAKER_OPEN" in _HEALTH_EVENT_CONSTANTS


# ---------------------------------------------------------------------------
# SLO metric-name lint (ISSUE 7): every SLORule constructed in core/slo.py
# must name a DECLARED metric — an entry in
# core.telemetry.CANONICAL_METRIC_NAMES or a `sparkdl.health.<event>`
# mirror of a constant declared in core/health.py. A typo'd metric would
# watch nothing forever; SLORule.__post_init__ enforces the same at
# runtime, but this lint catches it before any scope ever runs (and on
# rules built from concatenated module constants, where a typo'd constant
# name would otherwise only surface at import time).
# ---------------------------------------------------------------------------

#: Declared health-event VALUES (the strings the mirrors are named after).
_HEALTH_EVENT_VALUES = {
    getattr(_health, name) for name in _HEALTH_EVENT_CONSTANTS
}

_SLO_CONST_MODULES = ("telemetry", "health", "profiling", "slo")
_UNRESOLVED = object()  # a module-constant reference that doesn't resolve


def _resolve_string_expr(node):
    """Static string value of an expression: literals, telemetry./
    health./profiling. module constants (bare names resolve too, for
    constants referenced inside their own module), and `+`
    concatenations of those. ``_UNRESOLVED`` for a module-constant
    reference that does not exist (a typo'd constant); None when the
    expression is genuinely dynamic (a local variable)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    attr = None
    flag_missing = False
    if isinstance(node, ast.Attribute):
        attr = node.attr
        flag_missing = (isinstance(node.value, ast.Name)
                        and node.value.id in _SLO_CONST_MODULES)
    elif isinstance(node, ast.Name):
        attr = node.id
    if attr is not None:
        for mod in (_telemetry, _health, _profiling):
            value = getattr(mod, attr, None)
            if isinstance(value, str):
                return value
        return _UNRESOLVED if flag_missing else None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _resolve_string_expr(node.left)
        right = _resolve_string_expr(node.right)
        if left is _UNRESOLVED or right is _UNRESOLVED:
            return _UNRESOLVED
        if left is not None and right is not None:
            return left + right
    return None


def _bad_slo_rule_metrics(tree: ast.AST):
    """(lineno, reason) for every `SLORule(...)` whose metric argument
    does not statically resolve to a declared metric name."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        fname = (f.id if isinstance(f, ast.Name)
                 else f.attr if isinstance(f, ast.Attribute) else None)
        if fname != "SLORule":
            continue
        metric_arg = None
        for kw in node.keywords:
            if kw.arg == "metric":
                metric_arg = kw.value
        if metric_arg is None and len(node.args) >= 2:
            metric_arg = node.args[1]
        if metric_arg is None:
            out.append((node.lineno, "no metric argument"))
            continue
        metric = _resolve_string_expr(metric_arg)
        if metric is _UNRESOLVED:
            out.append((node.lineno,
                        "metric references an undeclared module constant"))
            continue
        if metric is None:
            continue  # dynamic: SLORule's runtime validation covers it
        if metric in _telemetry.CANONICAL_METRIC_NAMES:
            continue
        prefix = _telemetry.HEALTH_METRIC_PREFIX
        if (metric.startswith(prefix)
                and metric[len(prefix):] in _HEALTH_EVENT_VALUES):
            continue
        out.append((node.lineno, f"undeclared metric {metric!r}"))
    return out


def test_every_slo_rule_metric_is_declared():
    path = ROOT / "core" / "slo.py"
    tree = ast.parse(path.read_text(), filename=str(path))
    # the lint is not vacuous: slo.py really constructs rules
    assert any(isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
               and n.func.id == "SLORule" for n in ast.walk(tree))
    offenders = [f"core/slo.py:{line}: {reason}"
                 for line, reason in _bad_slo_rule_metrics(tree)]
    assert not offenders, (
        "SLO rule metric not declared in core.telemetry."
        "CANONICAL_METRIC_NAMES (or as a sparkdl.health.<event> mirror "
        "of a core/health.py constant) — a typo'd metric watches nothing "
        f"forever. Fix the name or declare the metric: {offenders}")


def test_slo_metric_lint_catches_typos_and_resolves_constants():
    """Self-test: a typo'd literal and a typo'd module constant both
    trip; canonical literals, module constants and prefix
    concatenations pass; a local variable is left to the runtime
    check."""
    bad = (
        "from sparkdl_tpu.core import health, telemetry\n"
        "from sparkdl_tpu.core.slo import SLORule\n"
        "SLORule('a', metric='sparkdl.executor.queue_wait_ss',\n"  # typo
        "        window_s=1.0, threshold=1.0)\n"
        "SLORule('b', metric=telemetry.M_QUEUE_WAIT_S,\n"          # ok
        "        window_s=1.0, threshold=1.0)\n"
        "SLORule('c', metric=telemetry.HEALTH_METRIC_PREFIX\n"     # ok
        "        + health.EXECUTOR_SHED,\n"
        "        window_s=1.0, threshold=1.0)\n"
        "SLORule('d', metric=telemetry.HEALTH_METRIC_PREFIX\n"     # typo'd
        "        + health.EXECUTOR_SHEDD,\n"                       # constant
        "        window_s=1.0, threshold=1.0)\n"
        "SLORule('e', metric=some_variable,\n"                     # dynamic
        "        window_s=1.0, threshold=1.0)\n"
        "SLORule('f', 'sparkdl.health.not_an_event',\n"            # bad
        "        1.0, 1.0)\n"                                      # mirror
    )
    flagged = _bad_slo_rule_metrics(ast.parse(bad))
    assert [line for line, _ in flagged] == [3, 10, 15]
    assert "queue_wait_ss" in flagged[0][1]
    assert "undeclared module constant" in flagged[1][1]
    assert "not_an_event" in flagged[2][1]
    # the shipped default rules resolve through exactly these paths
    assert "sparkdl.health.executor_shed" not in \
        _telemetry.CANONICAL_METRIC_NAMES
    assert "executor_shed" in _HEALTH_EVENT_VALUES
