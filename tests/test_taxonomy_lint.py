"""The six one-off AST lints (ISSUEs 2–7), now thin wrappers over the
shared analysis framework (ISSUE 8).

Each lint lives as a registered rule in ``sparkdl_tpu/analysis/lints.py``
— one engine, one suppression syntax (``# sparkdl: allow(<rule>):
<why>``), one catalog (docs/ANALYSIS.md). The package-wide tests here
invoke the analyzer per rule (so suppressions work exactly as in the
CLI); each self-test seeds the original violation shape through the
framework and asserts the registered rule still flags it — the
typo/self-test coverage the standalone lints had is preserved
verbatim. The full-catalog gate (every rule at once, plus the
concurrency pack) is ``tests/test_analysis.py``.
"""

import ast
import pathlib

from sparkdl_tpu import analysis
from sparkdl_tpu.analysis import framework, lints
from sparkdl_tpu.core import health as _health
from sparkdl_tpu.core import telemetry as _telemetry

ROOT = pathlib.Path(__file__).resolve().parent.parent / "sparkdl_tpu"


def _package_findings(rule_id):
    """Run ONE rule over the package through the framework (inline
    suppressions apply, the shipped empty baseline does not matter)."""
    return analysis.analyze(paths=[ROOT], rule_ids=[rule_id]).findings


def _seed(rule_id, source, rel="seed.py"):
    """Seed a violation through the framework; the registered rule must
    flag it (lines returned sorted)."""
    src = framework.SourceFile.from_source(source, rel=rel)
    res = analysis.analyze_sources([src], rule_ids=[rule_id])
    return sorted(f.line for f in res.findings)


# ---------------------------------------------------------------------------
# broad-retry (ISSUE 2)
# ---------------------------------------------------------------------------


def test_no_blind_broad_retry_loops():
    offenders = _package_findings("broad-retry")
    assert not offenders, (
        "broad except inside a loop without re-raise or "
        "core.resilience.classify — blind retry would replay FATAL "
        "errors. Route the handler through resilience.classify, or mark "
        "a deliberate non-retry swallow with "
        "'# sparkdl: allow(broad-retry): <reason>': "
        f"{[str(f) for f in offenders]}")


def test_lint_catches_the_old_blind_retry_shape():
    """Self-test: the pre-supervision `_run_partition` loop (retry every
    failure blindly) must trip the registered rule."""
    bad = (
        "def run(ops, batch):\n"
        "    for attempt in range(3):\n"
        "        try:\n"
        "            return ops(batch)\n"
        "        except Exception as e:\n"
        "            last = e\n"
    )
    assert _seed("broad-retry", bad) == [5]


# ---------------------------------------------------------------------------
# blocking-fetch-in-fit (ISSUE 3)
# ---------------------------------------------------------------------------


def test_trainer_step_loop_has_no_blocking_device_fetch():
    # vacuity guard: the rule only fires on files defining Trainer.fit,
    # so prove trainer.py still has one (with loops) before trusting a
    # clean package run
    tree = ast.parse((ROOT / "train" / "trainer.py").read_text())
    fits = [item for node in ast.walk(tree)
            if isinstance(node, ast.ClassDef) and node.name == "Trainer"
            for item in node.body
            if isinstance(item, ast.FunctionDef) and item.name == "fit"]
    assert fits, "Trainer.fit not found"
    assert any(isinstance(n, (ast.For, ast.While))
               for n in ast.walk(fits[0])), "Trainer.fit has no step loop?"
    offenders = _package_findings("blocking-fetch-in-fit")
    assert not offenders, (
        "blocking device fetch inside Trainer.fit's step loop — "
        "int()/float()/np.asarray/jax.device_get/block_until_ready "
        "there re-serialize the async input pipeline. Move the fetch "
        "into the designated sync helpers (sync/save_checkpoint): "
        f"{[str(f) for f in offenders]}")


def test_lint_catches_the_old_per_step_sync_shape():
    """Self-test: the pre-pipeline loop body (`step = int(state.step)`
    per step, plus a device_get checkpoint fetch) must trip the rule —
    while helper DEFINITIONS (pre-loop or even inside the loop) stay
    exempt: only their call-sites block."""
    bad = (
        "class Trainer:\n"
        "    def fit(self, state, batches):\n"
        "        def sync(st):\n"
        "            return int(st.step)\n"  # pre-loop helper: exempt
        "        for x, y in batches:\n"
        "            def fetch():\n"
        "                return int(state.step)\n"  # nested DEF: exempt
        "            state, m = step(state, x, y)\n"
        "            step_n = int(state.step)\n"  # line 9: violation
        "            ckpt.save(step_n, jax.device_get(state))\n"  # line 10
        "        return state\n"
    )
    assert _seed("blocking-fetch-in-fit", bad) == [9, 10]


# ---------------------------------------------------------------------------
# span-names (ISSUE 4)
# ---------------------------------------------------------------------------


def test_every_span_name_is_canonical():
    offenders = _package_findings("span-names")
    assert not offenders, (
        "span/phase name not declared in "
        "core.telemetry.CANONICAL_SPAN_NAMES — a typo'd name silently "
        "forks a timer and a trace track. Add the name to the catalog "
        f"(and docs/OBSERVABILITY.md) or fix the typo: "
        f"{[str(f) for f in offenders]}")


def test_span_name_lint_catches_typo_and_resolves_constants():
    """Self-test: a typo'd literal trips the rule; module-constant names
    resolve to their canonical strings and pass."""
    bad = (
        "from sparkdl_tpu.core import profiling, telemetry\n"
        "with profiling.annotate('sparkdl.train_stepp'):\n"  # typo
        "    pass\n"
        "with telemetry.span(telemetry.SPAN_FIT):\n"         # constant
        "    pass\n"
        "with profiling.annotate(profiling.STAGE_BATCH):\n"  # constant
        "    pass\n"
        "with telemetry.span(dynamic_name):\n"               # skipped
        "    pass\n"
    )
    assert _seed("span-names", bad) == [2]
    # the resolution helper still sees all three checkable names
    names = lints.span_names_in(ast.parse(bad))
    assert ("sparkdl.train_stepp", 2) in names
    assert ("sparkdl.fit", 4) in names
    assert ("sparkdl.stage_batch", 6) in names
    assert len(names) == 3  # the dynamic name is not checkable
    assert "sparkdl.train_stepp" not in _telemetry.CANONICAL_SPAN_NAMES


# ---------------------------------------------------------------------------
# executor-choke-point (ISSUE 5)
# ---------------------------------------------------------------------------


def test_featurize_route_enters_device_via_executor_choke_point():
    offenders = _package_findings("executor-choke-point")
    assert not offenders, (
        "direct apply_batch/jitted call on the engine featurize route — "
        "device entry must go through core.executor.execute (the "
        "coalescing choke point), or concurrent partitions silently "
        "regress to per-partition launches (docs/PERF.md "
        "'Cross-partition coalescing'): "
        f"{[str(f) for f in offenders]}")


def test_choke_point_lint_catches_direct_apply_batch():
    """Self-test: the pre-executor transformer shape (calling the model's
    apply_batch / jitted straight from the partition op) must trip —
    when the file lives on the guarded route (ml/)."""
    bad = (
        "def apply_partition(batch):\n"
        "    out = model.apply_batch(stacked, batch_size=64)\n"
        "    fn = model.jitted(mesh=mesh)\n"
        "    good = device_executor.execute(model, stacked)\n"
        "    return out\n"
    )
    assert _seed("executor-choke-point", bad, rel="ml/seed.py") == [2, 3]
    # the model layer and training path stay out of scope by path
    assert _seed("executor-choke-point", bad, rel="core/seed.py") == []


# ---------------------------------------------------------------------------
# health-constants (ISSUE 6)
# ---------------------------------------------------------------------------


def test_every_health_record_uses_a_declared_constant():
    offenders = _package_findings("health-constants")
    assert not offenders, (
        "health.record() call site not using a constant declared in "
        "core/health.py — a typo'd or ad-hoc event name silently forks "
        "a counter outside the docs catalog and the telemetry mirror. "
        f"Declare the event and reference it: "
        f"{[str(f) for f in offenders]}")


def test_health_record_lint_catches_typos_and_bare_strings():
    """Self-test: a bare string event, a typo'd constant, and a local
    variable all trip; a declared constant passes."""
    bad = (
        "from sparkdl_tpu.core import health\n"
        "health.record('task_retried', partition=1)\n"      # bare string
        "health.record(health.TASK_RETIRED)\n"              # typo'd name
        "health.record(evt, partition=1)\n"                 # dynamic name
        "health.record(health.TASK_RETRIED, partition=1)\n"  # ok
        "mon.record('whatever')\n"                          # not the hook
    )
    assert _seed("health-constants", bad) == [2, 3, 4]
    flagged = lints.bad_health_record_calls(ast.parse(bad))
    assert "TASK_RETIRED" in flagged[1][1]
    # the constants set is non-trivial and holds the canonical events
    assert "TASK_RETRIED" in lints.HEALTH_EVENT_CONSTANTS
    assert "BREAKER_OPEN" in lints.HEALTH_EVENT_CONSTANTS


# ---------------------------------------------------------------------------
# slo-metrics (ISSUE 7)
# ---------------------------------------------------------------------------


def test_every_slo_rule_metric_is_declared():
    # the rule is not vacuous: slo.py really constructs rules
    slo_tree = ast.parse((ROOT / "core" / "slo.py").read_text())
    assert any(isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
               and n.func.id == "SLORule" for n in ast.walk(slo_tree))
    offenders = _package_findings("slo-metrics")
    assert not offenders, (
        "SLO rule metric not declared in core.telemetry."
        "CANONICAL_METRIC_NAMES (or as a sparkdl.health.<event> mirror "
        "of a core/health.py constant) — a typo'd metric watches "
        f"nothing forever: {[str(f) for f in offenders]}")


def test_slo_metric_lint_catches_typos_and_resolves_constants():
    """Self-test: a typo'd literal and a typo'd module constant both
    trip; canonical literals, module constants and prefix
    concatenations pass; a local variable is left to the runtime
    check."""
    bad = (
        "from sparkdl_tpu.core import health, telemetry\n"
        "from sparkdl_tpu.core.slo import SLORule\n"
        "SLORule('a', metric='sparkdl.executor.queue_wait_ss',\n"  # typo
        "        window_s=1.0, threshold=1.0)\n"
        "SLORule('b', metric=telemetry.M_QUEUE_WAIT_S,\n"          # ok
        "        window_s=1.0, threshold=1.0)\n"
        "SLORule('c', metric=telemetry.HEALTH_METRIC_PREFIX\n"     # ok
        "        + health.EXECUTOR_SHED,\n"
        "        window_s=1.0, threshold=1.0)\n"
        "SLORule('d', metric=telemetry.HEALTH_METRIC_PREFIX\n"     # typo'd
        "        + health.EXECUTOR_SHEDD,\n"                       # constant
        "        window_s=1.0, threshold=1.0)\n"
        "SLORule('e', metric=some_variable,\n"                     # dynamic
        "        window_s=1.0, threshold=1.0)\n"
        "SLORule('f', 'sparkdl.health.not_an_event',\n"            # bad
        "        1.0, 1.0)\n"                                      # mirror
    )
    assert _seed("slo-metrics", bad) == [3, 10, 15]
    flagged = lints.bad_slo_rule_metrics(ast.parse(bad))
    assert "queue_wait_ss" in flagged[0][1]
    assert "undeclared module constant" in flagged[1][1]
    assert "not_an_event" in flagged[2][1]
    # the shipped default rules resolve through exactly these paths
    assert "sparkdl.health.executor_shed" not in \
        _telemetry.CANONICAL_METRIC_NAMES
    assert "executor_shed" in {
        getattr(_health, name) for name in lints.HEALTH_EVENT_CONSTANTS}


# ---------------------------------------------------------------------------
# tenant-tag (ISSUE 16)
# ---------------------------------------------------------------------------


def test_serving_plane_always_tags_executor_calls():
    # the rule is not vacuous: the serving plane really calls
    # executor.execute (the predict path and the shadow leg)
    server_tree = ast.parse(
        (ROOT / "serving" / "server.py").read_text())
    assert len(lints.untagged_execute_calls(server_tree)) == 0
    calls = [n for n in ast.walk(server_tree)
             if isinstance(n, ast.Call)
             and isinstance(n.func, ast.Attribute)
             and n.func.attr == "execute"]
    assert len(calls) >= 2, "serving plane stopped calling the executor?"
    offenders = _package_findings("tenant-tag")
    assert not offenders, (
        "serving-plane executor.execute() without a tenant= argument — "
        "the request burns the shared default lane's deficit-round-robin "
        "quota and vanishes from the per-tenant queue-wait series. "
        f"Thread the caller's tenant tag: {[str(f) for f in offenders]}")


def test_tenant_tag_lint_catches_untagged_serving_calls():
    """Self-test: an untagged serving-plane execute trips; an explicit
    tag — even ``tenant=None`` — passes, a ``**kwargs`` spread is not
    statically checkable and passes, and the batch route (ml/) stays
    out of scope by path."""
    bad = (
        "from sparkdl_tpu.core import executor\n"
        "def predict(model, batch, kw):\n"
        "    a = executor.execute(model, batch, batch_size=1)\n"  # bad
        "    b = execute(model, batch, batch_size=1)\n"           # bad
        "    c = executor.execute(model, batch, tenant='acme')\n"  # ok
        "    d = executor.execute(model, batch, tenant=None)\n"    # ok
        "    e = executor.execute(model, batch, **kw)\n"           # spread
        "    return a, b, c, d, e\n"
    )
    assert _seed("tenant-tag", bad, rel="serving/seed.py") == [3, 4]
    # the batch/featurize route resolves its tenant ambiently — out of
    # scope by path, same scoping mechanism as executor-choke-point
    assert _seed("tenant-tag", bad, rel="ml/seed.py") == []


def test_tenant_tag_suppression_works():
    bad = (
        "from sparkdl_tpu.core import executor\n"
        "def probe(model, batch):\n"
        "    return executor.execute(model, batch)"
        "  # sparkdl: allow(tenant-tag): synthetic warmup probe, "
        "not client traffic\n"
    )
    src = framework.SourceFile.from_source(bad, rel="serving/seed.py")
    res = analysis.analyze_sources([src], rule_ids=["tenant-tag"])
    assert not res.findings
    assert len(res.suppressed) == 1
