"""Generic Keras→jax ingestion: oracle equivalence per layer family.

Mirrors the reference's pattern of verifying graph conversion against the
framework it came from (SURVEY.md §4 oracle pattern).
"""

import numpy as np
import pytest

keras = pytest.importorskip("keras")
from keras import layers  # noqa: E402

from sparkdl_tpu.models.keras_ingest import keras_to_model_function  # noqa: E402


@pytest.fixture(scope="module")
def np_rng():
    return np.random.default_rng(0)


def _check(model, x, rtol=1e-4, atol=1e-4):
    mf = keras_to_model_function(model)
    got = np.asarray(mf(x))
    want = model.predict(x, verbose=0)
    np.testing.assert_allclose(got, want, rtol=rtol, atol=atol)
    return mf


def test_sequential_dense(np_rng):
    m = keras.Sequential([keras.Input((8,)),
                          layers.Dense(16, activation="relu"),
                          layers.Dropout(0.5),
                          layers.Dense(4, activation="softmax")])
    x = np_rng.normal(size=(5, 8)).astype(np.float32)
    mf = _check(m, x)
    assert mf.input_spec.shape == (None, 8)


def test_functional_dag_with_merge_and_bn(np_rng):
    inp = keras.Input((12, 12, 3))
    c1 = layers.Conv2D(6, 3, padding="same", activation="relu")(inp)
    c2 = layers.Conv2D(6, 1, padding="same")(inp)
    s = layers.Add()([c1, c2])
    b = layers.BatchNormalization()(s)
    p = layers.MaxPooling2D(2)(b)
    a = layers.AveragePooling2D(3, strides=2, padding="same")(p)
    out = layers.Dense(5)(layers.GlobalAveragePooling2D()(a))
    m = keras.Model(inp, out)
    # perturb weights incl. BN moving stats so identity stats can't hide bugs
    rng = np.random.default_rng(1)
    m.set_weights([w + rng.normal(scale=0.05, size=w.shape).astype(np.float32)
                   for w in m.get_weights()])
    x = np_rng.normal(size=(3, 12, 12, 3)).astype(np.float32)
    _check(m, x, rtol=1e-3)


def test_depthwise_separable_padding_relu6(np_rng):
    inp = keras.Input((10, 10, 4))
    r = layers.Rescaling(1 / 127.5, offset=-1)(inp)
    z = layers.ZeroPadding2D(((1, 0), (1, 0)))(r)
    d = layers.DepthwiseConv2D(3, strides=2)(z)
    d = layers.ReLU(max_value=6.0)(d)
    sp = layers.SeparableConv2D(6, 3, padding="same")(d)
    cc = layers.Concatenate()([sp, sp])
    m = keras.Model(inp, layers.GlobalMaxPooling2D()(cc))
    x = (np_rng.normal(size=(2, 10, 10, 4)) * 100).astype(np.float32)
    _check(m, x, rtol=1e-3)


def test_nested_model(np_rng):
    sub = keras.Sequential([keras.Input((8,)),
                            layers.Dense(8, activation="tanh")])
    inp = keras.Input((8,))
    m = keras.Model(inp, layers.Dense(2)(sub(inp)))
    x = np_rng.normal(size=(4, 8)).astype(np.float32)
    _check(m, x)


def test_keras_default_activations_match(np_rng):
    # keras leaky_relu default slope is 0.2 (jax's is 0.01); keras gelu is
    # exact (jax's default is tanh-approximate) — both must match keras
    x = np_rng.normal(size=(6, 5)).astype(np.float32) * 3
    for act in ("leaky_relu", "gelu", "selu", "softplus"):
        m = keras.Sequential([keras.Input((5,)),
                              layers.Dense(4, activation=act)])
        _check(m, x, rtol=1e-4, atol=1e-5)


def test_upsampling_interpolations(np_rng):
    x = np_rng.normal(size=(2, 4, 4, 3)).astype(np.float32)
    for interp in ("nearest", "bilinear"):
        m = keras.Sequential([
            keras.Input((4, 4, 3)),
            layers.UpSampling2D(2, interpolation=interp)])
        _check(m, x, rtol=1e-4, atol=1e-5)


def test_unsupported_layer_raises_at_ingestion():
    m = keras.Sequential([keras.Input((4, 8)), layers.LSTM(3)])
    with pytest.raises(ValueError, match="LSTM"):
        keras_to_model_function(m)


def test_single_input_multi_output_returns_dict(np_rng):
    inp = keras.Input((4,))
    m = keras.Model(inp, [layers.Dense(2, name="h1")(inp),
                          layers.Dense(3, name="h2")(inp)])
    mf = keras_to_model_function(m)
    x = np_rng.normal(size=(5, 4)).astype(np.float32)
    got = mf.apply_batch(x, batch_size=4)
    assert set(got) == {"h1", "h2"}
    w1, w2 = m.predict(x, verbose=0)
    np.testing.assert_allclose(got["h1"], w1, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got["h2"], w2, rtol=1e-4, atol=1e-5)


def test_channels_first_rejected_at_ingestion():
    m = keras.Sequential([keras.Input((3, 10, 10)),
                          layers.Conv2D(4, 3, data_format="channels_first")])
    with pytest.raises(ValueError, match="channels_last"):
        keras_to_model_function(m)


def test_bn_nonchannel_axis_rejected_at_ingestion():
    m = keras.Sequential([keras.Input((6, 6, 3)),
                          layers.BatchNormalization(axis=1),
                          layers.Flatten(), layers.Dense(2)])
    with pytest.raises(ValueError, match="BatchNormalization axis"):
        keras_to_model_function(m)


def test_trainable_mask_marks_bn_moving_stats(np_rng):
    m = keras.Sequential([keras.Input((4,)),
                          layers.Dense(3),
                          layers.BatchNormalization(),
                          layers.Dense(2, activation="softmax")])
    mf = keras_to_model_function(m)
    mask = mf.trainable_mask
    assert mask is not None
    bn_name = m.layers[1].name
    # gamma, beta trainable; moving_mean, moving_variance frozen
    assert mask[bn_name] == [True, True, False, False]
    dense_name = m.layers[0].name
    assert all(mask[dense_name])


def test_finetune_does_not_corrupt_bn_moving_stats(np_rng):
    import jax

    from sparkdl_tpu.train.trainer import Trainer

    m = keras.Sequential([keras.Input((4,)),
                          layers.Dense(8, activation="relu"),
                          layers.BatchNormalization(),
                          layers.Dense(3, activation="softmax")])
    mf = keras_to_model_function(m)
    bn_name = m.layers[1].name
    before = jax.device_get(mf.variables[bn_name])
    x = np_rng.normal(size=(32, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np_rng.integers(0, 3, size=32)]
    trainer, state = Trainer.from_model_function(
        mf, optimizer="adam", learning_rate=0.05)
    state = trainer.fit(state, [(x, y)], epochs=5)
    after = jax.device_get(state.params[bn_name])
    # moving stats (positions 2, 3) must be untouched; gamma/beta must train
    np.testing.assert_array_equal(after[2], before[2])
    np.testing.assert_array_equal(after[3], before[3])
    assert not np.allclose(after[0], before[0])


def test_shared_bn_with_positive_axis(np_rng):
    """A BN instance shared across two nodes with axis stored positively
    (legacy .h5 style) is the supported last-axis case — must ingest."""
    inp = keras.Input((6, 6, 3))
    bn = layers.BatchNormalization(axis=3)
    out = layers.Add()([bn(inp), bn(layers.Conv2D(3, 1)(inp))])
    m = keras.Model(inp, out)
    x = np_rng.normal(size=(2, 6, 6, 3)).astype(np.float32)
    _check(m, x)


def test_multi_input_multi_output_functional_model(rng):
    """2-input/2-output functional graph ingests to a dict-spec
    ModelFunction matching keras predict (oracle), and runs through the
    TPUTransformer inputMapping/outputMapping DataFrame path."""
    import keras
    from keras import layers

    a_in = keras.Input((4,), name="a")
    b_in = keras.Input((6,), name="b")
    ha = layers.Dense(5, activation="relu", name="da")(a_in)
    hb = layers.Dense(5, activation="relu", name="db")(b_in)
    merged = layers.Concatenate(name="cat")([ha, hb])
    out1 = layers.Dense(3, name="head1")(merged)
    out2 = layers.Dense(2, activation="softmax", name="head2")(merged)
    model = keras.Model([a_in, b_in], [out1, out2])

    mf = keras_to_model_function(model)
    assert isinstance(mf.input_spec, dict)
    assert set(mf.input_spec) == {"a", "b"}

    a = rng.normal(size=(7, 4)).astype(np.float32)
    b = rng.normal(size=(7, 6)).astype(np.float32)
    got = mf.apply_batch({"a": a, "b": b}, batch_size=4)
    want1, want2 = model.predict([a, b], verbose=0)
    np.testing.assert_allclose(got["head1"], want1, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got["head2"], want2, rtol=1e-4, atol=1e-5)

    # DataFrame path
    from sparkdl_tpu.engine.dataframe import DataFrame
    from sparkdl_tpu.ml import TPUTransformer

    df = DataFrame.fromColumns({"colA": a, "colB": b}, numPartitions=2)
    t = TPUTransformer(modelFunction=mf,
                       inputMapping={"colA": "a", "colB": "b"},
                       outputMapping={"head1": "o1", "head2": "o2"},
                       batchSize=4)
    rows = t.transform(df).collect()
    np.testing.assert_allclose(
        np.array([r["o1"] for r in rows], dtype=np.float32), want1,
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.array([r["o2"] for r in rows], dtype=np.float32), want2,
        rtol=1e-4, atol=1e-5)


def test_multi_io_rejected_at_single_io_surfaces(rng):
    """Multi-IO Keras models must fail EAGERLY with guidance at the
    single-column surfaces (KerasTransformer etc.), not deep in a trace."""
    from sparkdl_tpu.ml import KerasTransformer

    a_in = keras.Input((4,), name="a")
    b_in = keras.Input((4,), name="b")
    out = layers.Add()([a_in, b_in])
    m = keras.Model([a_in, b_in], out)
    t = KerasTransformer(inputCol="x", outputCol="y", model=m)
    with pytest.raises(ValueError, match="inputMapping"):
        t.loadKerasModelAsFunction()


def test_duplicate_output_names_rejected(rng):
    shared = layers.Dense(3, name="shared")
    a_in = keras.Input((4,), name="a")
    b_in = keras.Input((4,), name="b")
    m = keras.Model([a_in, b_in], [shared(a_in), shared(b_in)])
    with pytest.raises(ValueError, match="not unique"):
        keras_to_model_function(m)


def test_normalization_layer(rng):
    """keras preprocessing Normalization (EfficientNet/ConvNeXt stems):
    explicit mean/variance, both directions, oracle-exact."""
    mean, var = [1.0, 2.0, 3.0], [4.0, 1.0, 0.25]
    for invert in (False, True):
        m = keras.Sequential([
            keras.Input((3,)),
            layers.Normalization(mean=mean, variance=var, invert=invert)])
        mf = keras_to_model_function(m)
        x = rng.normal(size=(5, 3)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(mf.apply_fn(mf.variables, x)), np.asarray(m(x)),
            rtol=1e-6, atol=1e-6)


def test_layernorm_and_hard_silu(rng):
    """LayerNormalization + hard_silu (MobileNetV3's activation) ingest
    and match keras exactly."""
    m = keras.Sequential([
        keras.Input((6, 4)),
        layers.LayerNormalization(epsilon=1e-5),
        layers.Activation("hard_silu"),
        layers.Dense(2)])
    mf = keras_to_model_function(m)
    x = rng.normal(size=(3, 6, 4)).astype(np.float32) * 5
    np.testing.assert_allclose(
        np.asarray(mf.apply_fn(mf.variables, x)), np.asarray(m(x)),
        rtol=1e-5, atol=1e-5)


def test_layernorm_no_scale_center(rng):
    m = keras.Sequential([
        keras.Input((8,)),
        layers.LayerNormalization(center=False, scale=False)])
    mf = keras_to_model_function(m)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(mf.apply_fn(mf.variables, x)), np.asarray(m(x)),
        rtol=1e-5, atol=1e-6)


def test_normalization_bf16_compute(rng):
    """with_compute_dtype(bf16) over an EfficientNet-style stem
    (Rescaling -> Normalization -> Conv): two r4 bugs covered — baked
    Normalization constants must follow the activation dtype, and an
    EAGER numpy input must not flow numpy promotion rules (np-bf16 *
    python float -> f32) into dtype-strict convs."""
    import jax.numpy as jnp

    m = keras.Sequential([
        keras.Input((8, 8, 3)),
        layers.Rescaling(1 / 255.0),
        layers.Normalization(mean=[0.5, 0.4, 0.3], variance=[1., 2., 3.]),
        layers.Conv2D(4, 3, padding="same"),
        layers.GlobalAveragePooling2D()])
    mf = keras_to_model_function(m).with_compute_dtype(jnp.bfloat16)
    x = (rng.uniform(0, 255, size=(2, 8, 8, 3))).astype(np.float32)
    out = np.asarray(mf.apply_fn(mf.variables, x))   # EAGER numpy input
    want = np.asarray(m(x))
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, want, rtol=0.05, atol=0.02)
    jout = np.asarray(__import__("jax").jit(mf.apply_fn)(mf.variables, x))
    np.testing.assert_allclose(jout, out, rtol=0.02, atol=0.01)
