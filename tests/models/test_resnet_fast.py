"""ResNet fast path == Flax module (f32, CPU, 32x32 smallest-valid input).

The path is equality-tested but NOT registry-selected: it measured neutral
on TPU (see models/resnet_fast.py docstring) because XLA already handles
ResNet's uniform convs well. Kept as the generalization proof of the
BN-folding/branch-fusion technique.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkdl_tpu.models.resnet import ResNet50
from sparkdl_tpu.models.resnet_fast import resnet_fast_apply


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(0)
    x = rng.uniform(-1.0, 1.0, size=(2, 32, 32, 3)).astype(np.float32)
    mod = ResNet50(include_top=True, classes=1000)
    vs = jax.jit(mod.init)(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3), jnp.float32))
    return x, vs


def test_predict_matches_module(setup):
    x, vs = setup
    mod = ResNet50(include_top=True, classes=1000)
    want = np.asarray(mod.apply(vs, x, train=False))
    got = np.asarray(resnet_fast_apply(vs, x, include_top=True,
                                       compute_dtype=jnp.float32))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-6)


def test_featurize_matches_module(setup):
    x, vs = setup
    feat_vars = {"params": {k: v for k, v in vs["params"].items()
                            if k != "predictions"},
                 "batch_stats": vs["batch_stats"]}
    mod = ResNet50(include_top=False, pooling="avg")
    want = np.asarray(mod.apply(feat_vars, x, train=False))
    got = np.asarray(resnet_fast_apply(feat_vars, x, include_top=False,
                                       compute_dtype=jnp.float32))
    assert got.shape == want.shape == (2, 2048)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)
