"""Registry + featurizer/predictor tests (fast path: TestNet; shape checks
for the big families run through jax.eval_shape so no heavy compute)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkdl_tpu.core.model_function import TensorSpec
from sparkdl_tpu.models import (
    SUPPORTED_MODEL_NAMES, build_featurizer, build_predictor, get_model_spec,
    registry,
)


def test_supported_models_cover_reference_surface():
    # The reference registry (SURVEY.md §2.1 keras_applications.py) carried
    # InceptionV3, Xception, ResNet50, VGG16, VGG19; BASELINE.json adds
    # MobileNetV2. TestNet mirrors the Scala test resource.
    for required in ("InceptionV3", "Xception", "ResNet50", "VGG16", "VGG19",
                     "MobileNetV2", "TestNet"):
        assert required in SUPPORTED_MODEL_NAMES


def test_unknown_model_rejected():
    with pytest.raises(ValueError):
        get_model_spec("NopeNet")


def test_testnet_featurizer_end_to_end(rng):
    mf = build_featurizer("TestNet", seed=0)
    x = rng.uniform(0, 255, size=(3, 32, 32, 3)).astype(np.float32)
    feats = mf.apply_batch(x, batch_size=2)
    assert feats.shape == (3, 16)
    # deterministic across rebuilds with same seed
    mf2 = build_featurizer("TestNet", seed=0)
    np.testing.assert_allclose(feats, mf2.apply_batch(x, batch_size=2),
                               rtol=1e-6)


def test_testnet_predictor_probabilities(rng):
    mf = build_predictor("TestNet", seed=0)
    x = rng.uniform(0, 255, size=(2, 32, 32, 3)).astype(np.float32)
    probs = np.asarray(mf(x))
    assert probs.shape == (2, 10)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-5)


@pytest.mark.parametrize("name", ["InceptionV3", "ResNet50", "Xception",
                                  "VGG16", "VGG19", "MobileNetV2"])
def test_feature_dims_by_shape_inference(name):
    """Validate declared feature_dim without running the network."""
    spec = get_model_spec(name)
    kwargs = dict(spec.featurize_kwargs or {"include_top": False,
                                            "pooling": "avg"})
    module = spec.builder(**kwargs)
    h, w = spec.input_size
    x = jnp.zeros((1, h, w, 3), jnp.float32)
    var_shapes = jax.eval_shape(
        lambda: module.init(jax.random.PRNGKey(0), x))
    out = jax.eval_shape(
        lambda v: module.apply(v, x), var_shapes)
    assert out.shape == (1, spec.feature_dim)


def test_preprocess_modes():
    x = jnp.full((1, 2, 2, 3), 255.0)
    np.testing.assert_allclose(np.asarray(registry.preprocess_tf_mode(x)),
                               1.0, atol=1e-6)
    caffe = np.asarray(registry.preprocess_caffe_mode(x))
    # BGR swap + mean subtract
    np.testing.assert_allclose(
        caffe[0, 0, 0], 255.0 - np.asarray(registry._CAFFE_MEAN), atol=1e-4)


def test_featurizer_weights_roundtrip_msgpack(tmp_path, rng):
    mf = build_featurizer("TestNet", seed=0)
    p = tmp_path / "w.msgpack"
    mf.toMsgpack(str(p))
    mf2 = build_featurizer("TestNet", weights=str(p))
    x = rng.uniform(0, 255, size=(2, 32, 32, 3)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(mf(x)), np.asarray(mf2(x)),
                               rtol=1e-6)
