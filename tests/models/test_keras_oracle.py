"""Keras-oracle equivalence tests (SURVEY.md §4 oracle pattern).

For each named model family: build the keras.applications architecture with
random weights, convert to Flax via models.convert, run the SAME input
through both, and require matching outputs. This validates architecture
parity op-for-op AND converter correctness in one shot — the strongest
offline check available (no pretrained downloads in this environment).

These are the slowest tests in the suite (keras/TF CPU forward); inputs are
kept tiny (batch 2) and each family runs once.
"""

import numpy as np
import pytest

keras = pytest.importorskip("keras")

from sparkdl_tpu.models import registry  # noqa: E402
from sparkdl_tpu.models.convert import convert_keras_model  # noqa: E402
from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec  # noqa: E402

# (name, tolerance). BN-heavy deep nets accumulate fp32 reassociation
# differences; tolerances are per-family, asserted on softmax probabilities
# and on raw features.
FAMILIES = [
    ("InceptionV3", 2e-4),
    ("ResNet50", 2e-4),
    ("Xception", 2e-4),
    ("VGG16", 2e-4),
    ("VGG19", 2e-4),
    ("MobileNetV2", 2e-4),
]


def _run_pair(name, tol):
    spec = registry.get_model_spec(name)
    h, w = spec.input_size
    rng = np.random.default_rng(0)
    x = rng.uniform(-1.0, 1.0, size=(2, h, w, 3)).astype(np.float32)

    kmodel = registry.build_keras_reference(name)
    expected = np.asarray(kmodel(x))

    variables = convert_keras_model(name, kmodel)
    module = spec.builder(include_top=True, classes=spec.classes)
    mf = ModelFunction.fromFlax(module, variables,
                                TensorSpec((None, h, w, 3)), train=False)
    got = np.asarray(mf(x))

    assert got.shape == expected.shape
    np.testing.assert_allclose(got, expected, atol=tol, rtol=1e-3)


@pytest.mark.parametrize("name,tol", FAMILIES,
                         ids=[f[0] for f in FAMILIES])
def test_keras_oracle(name, tol):
    _run_pair(name, tol)


# -- r5: ingestion-backed named models, featurizer-role oracle ---------------

INGESTED_FAMILIES = [
    # (name, keras preprocess module attr or None for in-model scaling)
    ("ResNet50V2", "resnet_v2"),
    ("EfficientNetV2B0", None),
    ("ConvNeXtTiny", None),
    # r5 review: the r4-era families were oracle-run in a builder session
    # but never committed — pin them here so "every family oracle-tested"
    # is enforced by the suite, not claimed
    ("DenseNet121", "densenet"),
    ("EfficientNetB0", None),
    ("MobileNetV3Small", None),
    ("NASNetMobile", "nasnet"),
]


@pytest.mark.parametrize("name,pre_module", INGESTED_FAMILIES,
                         ids=[f[0] for f in INGESTED_FAMILIES])
def test_ingested_named_featurizer_oracle(name, pre_module):
    """The r5 ingestion-backed names: DeepImageFeaturizer's ModelFunction
    (device preprocess composed in front of the walker's program) must
    match the keras model's own forward after the family's documented
    preprocess_input — validating the registry's preprocess mode and
    feature_dim per name, not just the walker per layer."""
    import importlib

    spec = registry.get_model_spec(name)
    h, w = spec.input_size
    ctor = registry._resolve_keras_ctor(name)
    kmodel = ctor(weights=None, include_top=False, pooling="avg",
                  input_shape=(h, w, 3))
    mf = registry.build_featurizer(name, weights=kmodel)

    rng = np.random.default_rng(0)
    x = rng.uniform(0, 255, size=(2, h, w, 3)).astype(np.float32)
    got = np.asarray(mf.apply_fn(mf.variables, x))
    assert got.shape == (2, spec.feature_dim)

    if pre_module is not None:
        pre = importlib.import_module(
            f"keras.applications.{pre_module}").preprocess_input
        x_ref = pre(x.copy())
    else:
        x_ref = x  # family normalizes in-model (identity preprocess)
    want = np.asarray(kmodel(x_ref))
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=1e-3)
