"""Fused InceptionV3 fast path == Flax module apply (f32, CPU).

The fast path (models/inception_fast.py) folds BatchNorm into conv weights
and fuses the parallel 1x1 branch convs; per-channel math is unchanged, so
outputs must match the definitional module to float tolerance. Mirrors the
reference's oracle pattern (SURVEY.md §4): optimized pipeline == plain
framework forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkdl_tpu.models.inception import InceptionV3
from sparkdl_tpu.models.inception_fast import inception_v3_fast_apply


@pytest.fixture(scope="module")
def xin():
    rng = np.random.default_rng(0)
    return rng.uniform(-1.0, 1.0, size=(2, 299, 299, 3)).astype(np.float32)


def _init(module):
    return jax.jit(module.init)(jax.random.PRNGKey(0),
                                jnp.zeros((1, 299, 299, 3), jnp.float32))


def test_featurize_matches_module(xin):
    mod = InceptionV3(include_top=False, pooling="avg")
    vs = _init(mod)
    want = np.asarray(mod.apply(vs, xin, train=False))
    got = np.asarray(inception_v3_fast_apply(
        vs, xin, include_top=False, compute_dtype=jnp.float32))
    assert got.shape == want.shape == (2, 2048)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_predict_matches_module(xin):
    mod = InceptionV3(include_top=True, classes=1000)
    vs = _init(mod)
    want = np.asarray(mod.apply(vs, xin, train=False))
    got = np.asarray(inception_v3_fast_apply(
        vs, xin, include_top=True, compute_dtype=jnp.float32))
    assert got.shape == want.shape == (2, 1000)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-5)


def test_registry_featurizer_uses_fast_path_and_matches(xin):
    from sparkdl_tpu.models import registry

    fast = registry.build_featurizer("InceptionV3", weights="random")
    slow = registry.build_featurizer("InceptionV3", weights="random",
                                     fast=False)
    # the fast path must actually be selected, else this is slow == slow
    assert fast.fast_path and not slow.fast_path
    a = np.asarray(fast(xin))
    b = np.asarray(slow(xin))
    np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)
