"""Fused InceptionV3 fast path == Flax module apply (f32, CPU).

The fast path (models/inception_fast.py) folds BatchNorm into conv weights
and fuses the parallel 1x1 branch convs; per-channel math is unchanged, so
outputs must match the definitional module to float tolerance. Mirrors the
reference's oracle pattern (SURVEY.md §4): optimized pipeline == plain
framework forward.

Cost control (this is the suite's priciest model): ONE jitted init at
75x75 — InceptionV3's smallest valid input, which still exercises all 94
ConvBN units and every fusion group — and the featurize variables are
derived from the predict variables (drop the head) instead of a second
init. The registry wiring test reuses those variables so it never pays an
InceptionV3 init.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkdl_tpu.models.inception import InceptionV3
from sparkdl_tpu.models.inception_fast import inception_v3_fast_apply

_SIZE = 75  # smallest valid InceptionV3 input (stem+reductions stay >= 1)


@pytest.fixture(scope="module")
def xin():
    rng = np.random.default_rng(0)
    return rng.uniform(-1.0, 1.0, size=(2, _SIZE, _SIZE, 3)).astype(np.float32)


@pytest.fixture(scope="module")
def predict_vars():
    module = InceptionV3(include_top=True, classes=1000)
    return jax.jit(module.init)(jax.random.PRNGKey(0),
                                jnp.zeros((1, _SIZE, _SIZE, 3), jnp.float32))


@pytest.fixture(scope="module")
def featurize_vars(predict_vars):
    # the headless model's tree is the predict tree minus the head
    params = {k: v for k, v in predict_vars["params"].items()
              if k != "predictions"}
    return {"params": params, "batch_stats": predict_vars["batch_stats"]}


def test_featurize_matches_module(xin, featurize_vars):
    mod = InceptionV3(include_top=False, pooling="avg")
    want = np.asarray(mod.apply(featurize_vars, xin, train=False))
    got = np.asarray(inception_v3_fast_apply(
        featurize_vars, xin, include_top=False, compute_dtype=jnp.float32))
    assert got.shape == want.shape == (2, 2048)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_predict_matches_module(xin, predict_vars):
    mod = InceptionV3(include_top=True, classes=1000)
    want = np.asarray(mod.apply(predict_vars, xin, train=False))
    got = np.asarray(inception_v3_fast_apply(
        predict_vars, xin, include_top=True, compute_dtype=jnp.float32))
    assert got.shape == want.shape == (2, 1000)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-5)


def test_registry_selects_fast_path(featurize_vars, predict_vars):
    """The registry must actually WIRE the fast path (and honor fast=False);
    numeric parity of that path is covered above — the registry passes the
    same variables into the same inception_v3_fast_apply."""
    from sparkdl_tpu.models import registry

    fast = registry.build_featurizer("InceptionV3", weights=featurize_vars)
    slow = registry.build_featurizer("InceptionV3", weights=featurize_vars,
                                     fast=False)
    assert fast.fast_path and not slow.fast_path
    pred = registry.build_predictor("InceptionV3", weights=predict_vars)
    assert pred.fast_path
    # other zoo models have no fast path and must not claim one
    other = registry.build_featurizer("TestNet", weights="random")
    assert not other.fast_path
