"""Telemetry-tuned bucket ladder (ISSUE 12 tentpole, core/batching.py):
the bucket_size cap contract, cold-planner identity with the blind
power-of-two ladder, bounded retunes with hysteresis, persistence beside
the compilation cache, and the executor integration."""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from sparkdl_tpu.core import batching, executor, telemetry
from sparkdl_tpu.core.batching import BucketPlanner
from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.core.telemetry import Telemetry
from sparkdl_tpu.engine.dataframe import EngineConfig


@pytest.fixture(autouse=True)
def _fresh_planners():
    saved = EngineConfig.snapshot()
    batching.reset_planners()
    executor.reset()
    yield
    executor.reset()
    batching.reset_planners()
    EngineConfig.restore(saved)


# ---------------------------------------------------------------------------
# bucket_size cap contract (satellite: cap applied before rounding)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [1, 3, 7, 8, 9, 16, 33, 40, 63, 64])
@pytest.mark.parametrize("batch_size", [8, 40, 64, 100])
@pytest.mark.parametrize("multiple", [1, 4, 16])
def test_bucket_size_never_exceeds_rounded_cap(n, batch_size, multiple):
    if n > batch_size:
        pytest.skip("chunks never exceed batch_size on the chunked path")
    b = batching.bucket_size(n, batch_size, multiple)
    cap = -(-batch_size // multiple) * multiple  # roundup(batch_size)
    assert b >= n
    assert b % multiple == 0
    # THE regression: the old code capped at the raw batch_size BEFORE
    # rounding, so e.g. (n=40, batch_size=40, multiple=16) returned 48
    # only by luck of ordering while (n=33, batch_size=40, multiple=16)
    # could exceed the rounded cap; the contract is result <= roundup(cap)
    assert b <= cap


def test_bucket_size_known_values():
    assert batching.bucket_size(40, 40, 16) == 48
    assert batching.bucket_size(33, 40, 16) == 48
    assert batching.bucket_size(5, 64) == 8
    assert batching.bucket_size(64, 64) == 64
    # n above batch_size (public-helper use): bucket covers n
    assert batching.bucket_size(65, 64) == 65


# ---------------------------------------------------------------------------
# Cold planner == blind ladder
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("batch_size,multiple", [(64, 1), (40, 16),
                                                 (128, 8), (100, 1), (7, 1)])
def test_cold_planner_matches_pow2_ladder_exactly(batch_size, multiple):
    planner = BucketPlanner(batch_size, multiple)
    for n in range(1, batch_size + 1):
        assert planner.bucket_for(n) == batching.bucket_size(
            n, batch_size, multiple), n


def test_plan_observes_and_returns_bucket():
    planner = BucketPlanner(64)
    assert planner.plan(5) == 8
    assert planner.plan(64) == 64


# ---------------------------------------------------------------------------
# Retune: bounded, hysteresis-gated, waste-reducing
# ---------------------------------------------------------------------------


def _drive(planner, sizes, rounds):
    for _ in range(rounds):
        for n in sizes:
            planner.observe(n)


def test_skewed_stream_retunes_and_cuts_pad_rows():
    planner = BucketPlanner(64)
    _drive(planner, [17], rounds=2 * batching.PLANNER_UPDATE_EVERY)
    # pow2 padded 17 -> 32 (15 pad rows/launch); the tuned ladder has a
    # 17 rung, so the dominant launch pads zero
    assert planner.bucket_for(17) == 17
    # the cap rung survives every retune: any n <= batch_size is coverable
    assert planner.bucket_for(64) == 64
    assert planner.bucket_for(33) <= 64


def test_rung_count_stays_bounded_by_pow2_ladder_length():
    planner = BucketPlanner(64)
    max_rungs = len(batching._pow2_ladder(64, 1, 8))
    # adversarial: many distinct sizes, several retune rounds
    rng = np.random.default_rng(0)
    for _ in range(6):
        _drive(planner, rng.integers(1, 65, size=16).tolist(),
               rounds=batching.PLANNER_UPDATE_EVERY // 8)
    assert len(planner.ladder()) <= max_rungs


def test_pow2_aligned_stream_never_retunes():
    planner = BucketPlanner(64)
    before = planner.ladder()
    # sizes already exactly on pow2 rungs: zero pad rows, nothing to win
    _drive(planner, [8, 16, 32, 64],
           rounds=4 * batching.PLANNER_UPDATE_EVERY)
    assert planner.ladder() == before


def test_adoptions_capped_at_max_updates():
    planner = BucketPlanner(64)
    rng = np.random.default_rng(1)
    for round_i in range(batching.PLANNER_MAX_UPDATES * 3):
        # shift the distribution every round to invite a retune
        base = int(rng.integers(1, 60))
        _drive(planner, [base], rounds=batching.PLANNER_UPDATE_EVERY)
    assert planner._updates <= batching.PLANNER_MAX_UPDATES


def test_adoption_emits_telemetry():
    with Telemetry() as tel:
        planner = BucketPlanner(64)
        _drive(planner, [17], rounds=2 * batching.PLANNER_UPDATE_EVERY)
        snap = tel.metrics.snapshot()
    assert snap["counters"].get(telemetry.M_BUCKET_LADDER_UPDATE, 0) >= 1
    assert snap["gauges"][telemetry.M_PLANNER_WASTE] < 0.05


def test_mesh_multiple_respected_after_retune():
    planner = BucketPlanner(64, multiple=8)
    _drive(planner, [17, 19], rounds=2 * batching.PLANNER_UPDATE_EVERY)
    for rung in planner.ladder():
        assert rung % 8 == 0
    assert planner.bucket_for(17) >= 17


# ---------------------------------------------------------------------------
# iter_batches integration
# ---------------------------------------------------------------------------


def test_iter_batches_uses_planner_ladder():
    planner = BucketPlanner(64)
    _drive(planner, [17], rounds=2 * batching.PLANNER_UPDATE_EVERY)
    arr = np.ones((17, 3), np.float32)
    [(chunk, n_valid)] = list(batching.iter_batches(arr, 64,
                                                    planner=planner))
    assert n_valid == 17
    assert chunk.shape[0] == 17  # tuned rung, not the pow2 32
    # planner-less default unchanged
    [(chunk, _)] = list(batching.iter_batches(arr, 64))
    assert chunk.shape[0] == 32


# ---------------------------------------------------------------------------
# Registry, knob gating, persistence
# ---------------------------------------------------------------------------


def test_planner_for_returns_one_shared_instance():
    a = batching.planner_for("m", 64, 1)
    b = batching.planner_for("m", 64, 1)
    assert a is b
    assert batching.planner_for("m", 32, 1) is not a


def test_default_planner_honors_bucket_ladder_knob():
    EngineConfig.bucket_ladder = "pow2"
    assert batching.default_planner("m", 64, 1) is None
    EngineConfig.bucket_ladder = "tuned"
    assert batching.default_planner("m", 64, 1) is not None


def test_ladder_persists_beside_compile_cache(tmp_path, monkeypatch):
    from sparkdl_tpu import COMPILE_CACHE_DIR_ENV

    monkeypatch.setenv(COMPILE_CACHE_DIR_ENV, str(tmp_path))
    planner = batching.planner_for("persist_me", 64, 1)
    _drive(planner, [17], rounds=2 * batching.PLANNER_UPDATE_EVERY)
    tuned = planner.ladder()
    assert tuned != batching._pow2_ladder(64, 1, 8)
    path = batching.ladder_store_path()
    assert path is not None and os.path.exists(path)
    doc = json.load(open(path))
    assert doc["version"] == 1
    assert doc["ladders"]["persist_me|64|1"] == list(tuned)
    # a "warm process" (fresh registry) reloads the learned ladder
    batching.reset_planners()
    warm = batching.planner_for("persist_me", 64, 1)
    assert warm.ladder() == tuned


def test_no_cache_dir_means_no_persistence(monkeypatch):
    from sparkdl_tpu import COMPILE_CACHE_DIR_ENV

    monkeypatch.delenv(COMPILE_CACHE_DIR_ENV, raising=False)
    assert batching.ladder_store_path() is None
    planner = batching.planner_for("ephemeral", 64, 1)
    _drive(planner, [17], rounds=2 * batching.PLANNER_UPDATE_EVERY)
    # retune still works, it just isn't written anywhere


# ---------------------------------------------------------------------------
# Executor integration: values identical through retunes
# ---------------------------------------------------------------------------


def _model(name="planner_model"):
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(6, 3)).astype(np.float32))

    def apply_fn(vs, x):
        return jnp.tanh(x @ vs)

    return ModelFunction(apply_fn, w, TensorSpec((None, 6), "float32"),
                         name=name)


def test_tuned_ladder_execute_stays_value_identical():
    EngineConfig.bucket_ladder = "tuned"
    mf = _model()
    x = np.random.default_rng(2).normal(size=(17, 6)).astype(np.float32)
    expected = mf.apply_batch(x, batch_size=64)
    outs = []
    # enough solo executes to cross the retune threshold several times
    for _ in range(2 * batching.PLANNER_UPDATE_EVERY + 4):
        outs.append(executor.execute(mf, x, batch_size=64))
    planner = batching.planner_for(mf.name, 64, 1)
    assert planner.bucket_for(17) == 17  # the ladder did adapt
    for out in outs:
        np.testing.assert_array_equal(out, expected)
