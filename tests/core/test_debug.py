"""Debug hardening (SURVEY.md §5.2): NaN and shape sanitizer behavior."""

import numpy as np
import pytest

from sparkdl_tpu.core.debug import debug_mode


def test_debug_mode_catches_nan_loss():
    """A NaN produced inside a jitted train step raises at the producing op
    under debug_mode instead of silently poisoning the metrics."""
    import flax.linen as nn
    import jax

    from sparkdl_tpu.train import Trainer

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(2)(x)

    module = Net()
    variables = module.init(jax.random.PRNGKey(0), np.zeros((1, 3), np.float32))

    def nan_loss(outputs, labels):
        return jax.numpy.log(-jax.numpy.ones(())) + outputs.sum() * 0.0

    trainer, state = Trainer.from_flax(module, variables, loss=nan_loss,
                                       optimizer="sgd", learning_rate=0.1)
    x = np.ones((4, 3), np.float32)
    y = np.zeros((4, 2), np.float32)
    with debug_mode():
        with pytest.raises(FloatingPointError):
            trainer.fit(state, [(x, y)], epochs=1)
    # outside debug mode the same step completes (loss is NaN, not an error)
    state2 = trainer.fit(state, [(x, y)], epochs=1)
    assert int(state2.step) == 1


def test_debug_mode_restores_config():
    import jax

    before = jax.config.jax_debug_nans
    with debug_mode():
        assert jax.config.jax_debug_nans
    assert jax.config.jax_debug_nans == before


def test_binary_head_one_hot_labels_raise():
    """(N,2) one-hot labels into a 1-unit sigmoid head must raise, not
    silently broadcast (ADVICE r2)."""
    import jax.numpy as jnp

    from sparkdl_tpu.train.optimizers import accuracy_metric, make_loss

    loss = make_loss("binary_crossentropy")
    probs = jnp.full((4, 1), 0.9)
    onehot = jnp.eye(2)[jnp.array([1, 0, 1, 1])]
    with pytest.raises(ValueError, match="1-unit"):
        loss(probs, onehot)
    # accuracy with one-hot labels argmaxes to class ids (not the class-0
    # indicator, which would invert the metric)
    acc = accuracy_metric(probs, onehot)
    np.testing.assert_allclose(float(acc), 0.75)
