"""Periodic snapshot exporter lifecycle (ISSUE 7): cadence under a fake
clock, drop-safe final flush at scope exit, no thread leak after
Telemetry teardown, and a crashed tick that records a health event
instead of dying silently."""

import json
import os
import threading
import time

import pytest

from sparkdl_tpu.core import health, telemetry
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.telemetry import SnapshotExporter, Telemetry


class _FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _lines(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def test_snapshots_appear_at_cadence_under_fake_clock(tmp_path,
                                                      monkeypatch):
    """Ticks export exactly when the cadence clock says a snapshot is
    due — no early, no duplicate — and each line carries the sequence
    number, the windowed + cumulative views and the executor state."""
    clock = _FakeClock()
    monkeypatch.setattr(telemetry, "_monotonic", clock)
    with Telemetry("cadence", window_s=10.0, window_buckets=10) as tel:
        exp = SnapshotExporter(tel, interval_s=1.0, out_dir=str(tmp_path))
        telemetry.observe(telemetry.M_QUEUE_WAIT_S, 0.01)
        assert not exp.tick_if_due()      # t+0: not due yet
        clock.advance(0.5)
        assert not exp.tick_if_due()      # half an interval: still not
        clock.advance(0.6)
        assert exp.tick_if_due()          # t+1.1: first snapshot
        assert not exp.tick_if_due()      # immediately after: not due
        clock.advance(2.5)
        assert exp.tick_if_due()          # due again
        exp.close()                       # final flush (third line)
    lines = _lines(exp.snapshot_path)
    assert [line["seq"] for line in lines] == [1, 2, 3]
    assert lines[-1]["final"] is True
    assert lines[0]["uptime_s"] == pytest.approx(1.1)
    for line in lines:
        assert "windowed" in line and "cumulative" in line
        assert "executor" in line
        assert line["run_id"] == tel.run_id
    qw = telemetry.M_QUEUE_WAIT_S
    assert lines[0]["windowed"]["histograms"][qw]["count"] == 1
    assert lines[0]["cumulative"]["histograms"][qw]["count"] == 1


def test_exporter_thread_cadence_final_flush_and_no_leak(tmp_path):
    """The real daemon thread: snapshots accumulate at the configured
    interval, scope exit flushes one final snapshot, and no exporter
    thread survives Telemetry teardown."""
    with Telemetry("live", out_dir=str(tmp_path),
                   export_interval_s=0.05) as tel:
        exp = tel.exporter
        assert exp is not None
        assert exp._thread is not None and exp._thread.is_alive()
        assert exp._thread.daemon
        deadline = time.monotonic() + 10.0
        while exp.seq < 3 and time.monotonic() < deadline:
            telemetry.observe(telemetry.M_TASK_DURATION_S, 0.01)
            time.sleep(0.01)
        assert exp.seq >= 3
    # teardown: the thread is gone — nothing named like the exporter
    assert not any("sparkdl-telemetry-export" in t.name
                   for t in threading.enumerate())
    lines = _lines(exp.snapshot_path)
    assert lines[-1]["final"] is True
    assert [line["seq"] for line in lines] == \
        list(range(1, len(lines) + 1))
    # the Prometheus file landed atomically and is a valid exposition
    text = open(exp.prom_path).read()
    assert "# HELP" in text and "# TYPE" in text
    assert "sparkdl_task_duration_s_count" in text
    # the run report carries the timeline derived from the snapshots
    report = tel.report()
    assert report["timeline"]["snapshots"] == len(lines)
    assert report["timeline"]["entries"][-1]["final"] is True
    assert report["timeline"]["errors"] == 0


def test_long_interval_scope_still_flushes_final_snapshot(tmp_path):
    """A scope shorter than one export interval still writes its final
    state: the shutdown flush is drop-safe, not best-effort."""
    with Telemetry("short", out_dir=str(tmp_path),
                   export_interval_s=300.0) as tel:
        telemetry.count("sparkdl.health.executor_shed", 2)
    lines = _lines(tel.exporter.snapshot_path)
    assert len(lines) == 1
    assert lines[0]["seq"] == 1 and lines[0]["final"] is True
    shed = telemetry.HEALTH_METRIC_PREFIX + health.EXECUTOR_SHED
    assert lines[0]["cumulative"]["counters"][shed] == 2


def test_crashed_tick_records_health_event_and_survives(tmp_path,
                                                        monkeypatch):
    """A tick that raises records ONE telemetry_export_error health
    event (mirrored into the scope's counters) and the exporter keeps
    working afterwards — it never dies silently."""
    with HealthMonitor("crash") as mon:
        with Telemetry("boom", out_dir=str(tmp_path),
                       export_interval_s=300.0) as tel:
            exp = tel.exporter
            orig_export = exp._export

            def explode(final=False):
                raise RuntimeError("disk full")

            monkeypatch.setattr(exp, "_export", explode)
            exp.tick()                     # must not raise
            assert exp.errors == 1 and exp.seq == 0
            assert mon.count(health.TELEMETRY_EXPORT_ERROR) == 1
            monkeypatch.setattr(exp, "_export", orig_export)
            exp.tick()                     # healthy again
            assert exp.seq == 1 and exp.errors == 1
    report = tel.report()
    assert report["timeline"]["errors"] == 1
    assert report["metrics"]["counters"][
        telemetry.HEALTH_METRIC_PREFIX
        + health.TELEMETRY_EXPORT_ERROR] == 1
    # the final close flush still landed (seq 2: tick + final)
    assert report["timeline"]["snapshots"] == 2


def test_no_exporter_without_interval_and_validation(tmp_path,
                                                     monkeypatch):
    monkeypatch.delenv(telemetry.EXPORT_INTERVAL_ENV, raising=False)
    with Telemetry("quiet") as tel:
        assert tel.exporter is None
    assert tel.report()["timeline"] is None
    with pytest.raises(ValueError, match="export_interval_s"):
        Telemetry("bad", export_interval_s=0.0)
    with Telemetry("manual") as tel2:
        with pytest.raises(ValueError, match="export_interval_s"):
            SnapshotExporter(tel2, interval_s=-1.0,
                             out_dir=str(tmp_path))


def test_export_interval_env_opt_in(tmp_path, monkeypatch):
    monkeypatch.setenv(telemetry.TELEMETRY_DIR_ENV, str(tmp_path))
    monkeypatch.setenv(telemetry.EXPORT_INTERVAL_ENV, "0.05")
    with Telemetry("envjob") as tel:
        assert tel.export_interval_s == 0.05
        assert tel.exporter is not None
    assert len(_lines(tel.exporter.snapshot_path)) >= 1


def test_exporter_without_out_dir_keeps_timeline_only(monkeypatch):
    """No out_dir: no files, but ticks still feed the in-memory timeline
    (and the SLO watchdog) — the live plane works programmatically."""
    monkeypatch.delenv(telemetry.TELEMETRY_DIR_ENV, raising=False)
    with Telemetry("mem", out_dir=None,
                   export_interval_s=300.0) as tel:
        assert tel.exporter.snapshot_path is None
        assert tel.exporter.prom_path is None
        tel.exporter.tick()
    report = tel.report()
    assert report["timeline"]["snapshots"] == 2  # tick + final flush
    assert report["timeline"]["snapshot_path"] is None


def test_shared_run_id_scopes_write_disjoint_files(tmp_path):
    """ISSUE 15 satellite: two scopes sharing run_id AND out_dir (a
    cluster worker pins the coordinator's run id) must not clobber each
    other's artifacts — the worker's process_scope suffixes every file
    name while the coordinator keeps the bare historical names."""
    out = str(tmp_path)
    with Telemetry("coord", out_dir=out, run_id="shared",
                   export_interval_s=30.0) as coord:
        pass
    with Telemetry("worker", out_dir=out, run_id="shared",
                   export_interval_s=30.0, process_scope="w0") as worker:
        pass
    names = sorted(os.listdir(out))
    for stem in ("sparkdl_snapshots_shared{}.jsonl",
                 "sparkdl_metrics_shared{}.prom",
                 "sparkdl_trace_shared{}.json",
                 "sparkdl_run_report_shared{}.json"):
        assert stem.format("") in names          # coordinator: bare
        assert stem.format(".w0") in names       # worker: suffixed
    # each artifact is really its own scope's, not a lucky overwrite
    assert json.load(open(coord.report_path))["run"] == "coord"
    assert json.load(open(worker.report_path))["run"] == "worker"
    assert coord.report_path != worker.report_path
    assert coord.exporter.snapshot_path != worker.exporter.snapshot_path
    with open(worker.exporter.snapshot_path) as f:
        (line,) = [json.loads(l) for l in f]     # the final flush
    assert line["run_id"] == "shared" and line["final"] is True


def test_postmortem_bundle_dirs_leave_exporter_artifacts_undisturbed(
        tmp_path):
    """ISSUE 19 satellite: the flight recorder drops postmortem bundle
    DIRECTORIES (``postmortem_<run_id>_<seq>/``, staged as ``.tmp`` then
    renamed) into the SAME out_dir the exporter writes — mid-run. The
    bundle namespace must never collide with any scope's file naming
    (bare or process_scope-suffixed) and the dump must not perturb
    snapshot ``seq`` monotonicity or the final flush."""
    out = str(tmp_path)
    with Telemetry("coord", out_dir=out, run_id="shared",
                   export_interval_s=30.0) as tel:
        tel.exporter.tick()
        # the recorder's atomic-write idiom, landing between two ticks
        staging = os.path.join(out, "postmortem_shared_0001.tmp")
        os.mkdir(staging)
        with open(os.path.join(staging, "breach.json"), "w") as f:
            json.dump({"trigger": "slo_breach"}, f)
        os.rename(staging, os.path.join(out, "postmortem_shared_0001"))
        tel.exporter.tick()
    lines = _lines(tel.exporter.snapshot_path)
    seqs = [line["seq"] for line in lines]
    assert len(seqs) >= 3  # tick, tick, final flush
    assert seqs == sorted(set(seqs))  # strictly monotone past the dump
    assert lines[-1]["final"] is True

    # a worker scope sharing run_id AND out_dir (the cluster layout the
    # recorder runs under) still writes all its suffixed artifacts
    with Telemetry("worker", out_dir=out, run_id="shared",
                   export_interval_s=30.0, process_scope="w0") as worker:
        pass
    names = set(os.listdir(out))
    assert "postmortem_shared_0001" in names  # survived both closes
    assert not any(n.startswith("postmortem_")
                   for n in names - {"postmortem_shared_0001"})
    for scope in (tel, worker):
        for path in (scope.exporter.snapshot_path,
                     scope.exporter.prom_path, scope.report_path):
            assert os.path.isfile(path)  # files, never the bundle dir
