"""Low-precision featurize + donated inference buffers (ISSUE 12
tentpole): the with_dtype precision matrix (fp32 bit-identity escape
hatch, bf16/int8 tolerance contract), EngineConfig threading through the
executor choke point, and buffer donation semantics."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkdl_tpu.core import batching, executor
from sparkdl_tpu.core import model_function as mfn
from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.engine.dataframe import EngineConfig

# Documented tolerance contract (docs/PERF.md "Launch shaping &
# precision") for bounded heads (tanh/softmax outputs in [-1, 1]):
BF16_ATOL = 0.05
INT8_ATOL = 0.15


@pytest.fixture(autouse=True)
def _fresh():
    saved = EngineConfig.snapshot()
    batching.reset_planners()
    executor.reset()
    yield
    executor.reset()
    batching.reset_planners()
    EngineConfig.restore(saved)


def _model(name="prec_model"):
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(6, 3)).astype(np.float32))

    def apply_fn(vs, x):
        return jnp.tanh(x @ vs)

    return ModelFunction(apply_fn, w, TensorSpec((None, 6), "float32"),
                         name=name)


def _rows(n, seed=1):
    return np.random.default_rng(seed).normal(size=(n, 6)).astype(np.float32)


# ---------------------------------------------------------------------------
# with_dtype semantics
# ---------------------------------------------------------------------------


def test_float32_is_identity_escape_hatch():
    mf = _model()
    assert mf.with_dtype("float32") is mf


def test_with_dtype_rejects_unknown_precision():
    with pytest.raises(ValueError, match="precision"):
        _model().with_dtype("float16")


def test_with_dtype_memoized_per_precision():
    mf = _model()
    assert mf.with_dtype("bfloat16") is mf.with_dtype("bfloat16")
    assert mf.with_dtype("int8") is mf.with_dtype("int8")
    assert mf.with_dtype("bfloat16") is not mf.with_dtype("int8")


def test_bf16_within_tolerance_outputs_float32():
    mf = _model()
    x = _rows(32)
    base = mf.apply_batch(x, batch_size=16)
    out = mf.with_dtype("bfloat16").apply_batch(x, batch_size=16)
    assert out.dtype == np.float32  # cast back at the program edge
    np.testing.assert_allclose(out, base, atol=BF16_ATOL)


def test_int8_within_tolerance_outputs_float32():
    mf = _model()
    x = _rows(32)
    base = mf.apply_batch(x, batch_size=16)
    out = mf.with_dtype("int8").apply_batch(x, batch_size=16)
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, base, atol=INT8_ATOL)


def test_int8_quantizes_matrix_leaves_symmetric_per_channel():
    mf = _model().with_dtype("int8")
    leaf = mf.variables  # single weight matrix -> one q8 marker dict
    assert mfn._is_q8_leaf(leaf)
    q = np.asarray(leaf[mfn._Q8_WEIGHTS])
    scale = np.asarray(leaf[mfn._Q8_SCALE])
    assert q.dtype == np.int8
    assert scale.shape == (3,)  # per output channel (last axis)
    assert np.abs(q).max() <= 127
    # symmetric: dequantized max per channel reproduces the fp32 max
    w = np.asarray(_model().variables)
    np.testing.assert_allclose(np.abs(q * scale).max(axis=0),
                               np.abs(w).max(axis=0), rtol=0.02)


def test_precision_models_keep_float_source_for_persistence():
    mf = _model()
    assert mf.with_dtype("bfloat16").float_source is mf
    assert mf.with_dtype("int8").float_source is mf


def test_with_compute_dtype_handles_dict_inputs():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(4, 3)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(4, 3)).astype(np.float32))

    def apply_fn(vs, x):
        return jnp.tanh(x["a"] @ vs["w"] + x["b"] @ vs["v"])

    spec = {"a": TensorSpec((None, 4), "float32"),
            "b": TensorSpec((None, 4), "float32")}
    mf = ModelFunction(apply_fn, {"w": w, "v": v}, spec, name="dict_model")
    x = {"a": np.random.default_rng(1).normal(size=(8, 4))
         .astype(np.float32),
         "b": np.random.default_rng(2).normal(size=(8, 4))
         .astype(np.float32)}
    base = mf.apply_batch(x, batch_size=8)
    out = mf.with_dtype("bfloat16").apply_batch(x, batch_size=8)
    np.testing.assert_allclose(out, base, atol=BF16_ATOL)


# ---------------------------------------------------------------------------
# EngineConfig threading through the executor choke point
# ---------------------------------------------------------------------------


def test_fp32_knob_bit_identical_through_executor():
    EngineConfig.inference_precision = "float32"
    mf = _model()
    x = _rows(9)
    expected = mf.apply_batch(x, batch_size=16)
    np.testing.assert_array_equal(
        executor.execute(mf, x, batch_size=16), expected)


def test_bf16_knob_threads_through_executor():
    EngineConfig.inference_precision = "bfloat16"
    mf = _model()
    x = _rows(9)
    base = mf.apply_batch(x, batch_size=16)
    out = executor.execute(mf, x, batch_size=16)
    np.testing.assert_allclose(out, base, atol=BF16_ATOL)
    # the executor resolved the SAME memoized precision variant (shared
    # jit cache — no per-call recompile)
    assert mf.with_dtype("bfloat16") in mf._precision_cache.values()


def test_int8_knob_threads_through_executor():
    EngineConfig.inference_precision = "int8"
    mf = _model()
    x = _rows(9)
    base = mf.apply_batch(x, batch_size=16)
    np.testing.assert_allclose(executor.execute(mf, x, batch_size=16),
                               base, atol=INT8_ATOL)


def test_validation_accepts_the_full_knob_matrix():
    for precision in ("float32", "bfloat16", "int8"):
        for donate in (True, False):
            for ladder in ("tuned", "pow2"):
                EngineConfig.inference_precision = precision
                EngineConfig.inference_donate_buffers = donate
                EngineConfig.bucket_ladder = ladder
                EngineConfig.validate()


# ---------------------------------------------------------------------------
# Donated inference buffers
# ---------------------------------------------------------------------------


def test_donated_path_value_identical():
    EngineConfig.inference_precision = "float32"
    EngineConfig.inference_donate_buffers = True
    mf = _model()
    x = _rows(11)
    expected = mf.apply_batch(x, batch_size=16)  # non-donated reference
    np.testing.assert_array_equal(
        executor.execute(mf, x, batch_size=16), expected)
    # host numpy staging survives donation: x itself is untouched
    np.testing.assert_array_equal(x, _rows(11))


def test_donation_rejects_caller_reuse_of_device_buffer():
    # shape-preserving head: the output CAN alias the input, so XLA
    # actually consumes the donated buffer (a non-aliasable launch makes
    # donation a safe no-op instead — see test_donated_path_value_identical)
    def apply_fn(vs, x):
        return jnp.tanh(x * vs)

    mf = ModelFunction(apply_fn, jnp.float32(2.0),
                       TensorSpec((None, 6), "float32"), name="alias_model")
    x = _rows(16)
    expected = np.asarray(mf.jitted()(x))
    xd = jnp.asarray(x)
    out = np.asarray(mf.jitted(donate_batch=True)(xd))
    np.testing.assert_array_equal(out, expected)
    # the donated device buffer is consumed by the launch — reading it
    # afterwards is an error, not silently stale data
    with pytest.raises(RuntimeError):
        np.asarray(xd)


def test_donate_apply_batch_matches_non_donated():
    mf = _model()
    x = _rows(33)
    np.testing.assert_array_equal(
        mf.apply_batch(x, batch_size=16, donate=True),
        mf.apply_batch(x, batch_size=16))


def test_donate_off_knob_respected():
    EngineConfig.inference_donate_buffers = False
    mf = _model()
    x = _rows(5)
    out = executor.execute(mf, x, batch_size=16)
    np.testing.assert_array_equal(out, mf.apply_batch(x, batch_size=16))
    # only the non-donated jit variant was built
    assert (None, True) not in mf._jit_cache
