"""Durable job recovery: job identity, write-ahead journal, atomic spill,
resume semantics (docs/RESILIENCE.md "Durable recovery")."""

import json
import os

import pyarrow as pa
import pytest

from sparkdl_tpu.core import durability, health
from sparkdl_tpu.core.durability import PartitionJournal
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.engine import DataFrame, EngineConfig

_DEFAULTS = EngineConfig.snapshot()


@pytest.fixture(autouse=True)
def _restore_engine_config():
    yield
    for k, v in _DEFAULTS.items():
        setattr(EngineConfig, k, v)


def _batch(lo, hi):
    return pa.record_batch([pa.array(list(range(lo, hi)))], names=["x"])


def make_df(n=12, parts=4):
    return DataFrame.fromRows([{"x": i} for i in range(n)],
                              numPartitions=parts)


# -- job identity ------------------------------------------------------------

def test_job_id_stable_across_equal_plans():
    def build():
        df = make_df()
        return df.withColumn("y", lambda x: x + 1, ["x"], pa.int64())

    a, b = build(), build()
    assert (durability.job_id(a._partitions, a._schema, a._ops)
            == durability.job_id(b._partitions, b._schema, b._ops))


def test_job_id_sensitive_to_ops_data_and_captured_state():
    df = make_df()
    base = df.withColumn("y", lambda x: x + 1, ["x"], pa.int64())
    ids = {durability.job_id(f._partitions, f._schema, f._ops) for f in (
        base,
        df.select("x"),                                    # different op
        base.select("y"),                                  # extra op
        make_df(16, 4).withColumn(                          # different data
            "y", lambda x: x + 1, ["x"], pa.int64()),
    )}
    assert len(ids) == 4
    # captured closure state distinguishes same-qualname plans
    assert (durability.job_id(*[getattr(df.select("x"), a) for a in
                                ("_partitions", "_schema", "_ops")])
            != durability.job_id(*[getattr(df.select("x", "x"), a) for a in
                                   ("_partitions", "_schema", "_ops")]))


def test_maybe_journal_opt_in_only(tmp_path):
    df = make_df().select("x")
    assert EngineConfig.durable_dir is None
    assert durability.maybe_journal(df._partitions, df._schema,
                                    df._ops) is None
    EngineConfig.durable_dir = str(tmp_path)
    # no ops -> nothing to recover; stays off even when opted in
    plain = make_df()
    assert durability.maybe_journal(plain._partitions, plain._schema,
                                    plain._ops) is None
    assert durability.maybe_journal(df._partitions, df._schema,
                                    df._ops) is not None


# -- journal mechanics -------------------------------------------------------

def test_commit_load_roundtrip_bit_identical(tmp_path):
    j = PartitionJournal(str(tmp_path), "job-a", 2)
    b0, b1 = _batch(0, 5), _batch(5, 9)
    j.commit(0, b0)
    j.commit(1, b1, quarantined=True)

    j2 = PartitionJournal(str(tmp_path), "job-a", 2)
    assert j2.resume() == {0, 1}
    assert j2.load(0).equals(b0) and j2.load(1).equals(b1)
    recs = j2.records()
    assert [r["partition"] for r in recs] == [0, 1]
    assert [r["quarantined"] for r in recs] == [False, True]


def test_commit_idempotent_and_attempts_counted(tmp_path):
    j = PartitionJournal(str(tmp_path), "job-b", 1)
    j.note_attempt(0)
    j.note_attempt(0)
    j.commit(0, _batch(0, 3))
    j.commit(0, _batch(100, 103))  # hedge loser: no-op
    assert j.load(0).equals(_batch(0, 3))
    assert j.records()[0]["attempts"] == 2


def test_torn_journal_tail_discarded_never_trusted(tmp_path):
    j = PartitionJournal(str(tmp_path), "job-c", 2)
    j.commit(0, _batch(0, 4))
    j.commit(1, _batch(4, 8))
    path = os.path.join(str(tmp_path), "job-c", "journal.jsonl")
    lines = open(path).read().splitlines()
    # crash mid-append: last record torn
    with open(path, "w") as f:
        f.write(lines[0] + "\n" + lines[1][:len(lines[1]) // 2])
    with HealthMonitor() as mon:
        j2 = PartitionJournal(str(tmp_path), "job-c", 2)
        assert j2.resume() == {0}
    assert mon.events(health.DURABLE_JOURNAL_TORN)
    assert not j2.committed(1)


def test_tampered_record_body_fails_line_digest(tmp_path):
    j = PartitionJournal(str(tmp_path), "job-d", 1)
    j.commit(0, _batch(0, 4))
    path = os.path.join(str(tmp_path), "job-d", "journal.jsonl")
    obj = json.loads(open(path).read())
    obj["rec"]["attempts"] = 99  # bit-rot / tamper: crc no longer matches
    with open(path, "w") as f:
        f.write(json.dumps(obj) + "\n")
    j2 = PartitionJournal(str(tmp_path), "job-d", 1)
    assert j2.resume() == set()


def test_corrupt_spill_dropped_and_partition_recomputes(tmp_path):
    j = PartitionJournal(str(tmp_path), "job-e", 2)
    j.commit(0, _batch(0, 4))
    j.commit(1, _batch(4, 8))
    spill = os.path.join(str(tmp_path), "job-e", "part-00001.arrow")
    raw = bytearray(open(spill, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(spill, "wb") as f:
        f.write(raw)
    with HealthMonitor() as mon:
        j2 = PartitionJournal(str(tmp_path), "job-e", 2)
        assert j2.resume() == {0}  # bad spill discarded, not trusted
    assert mon.events(health.DURABLE_JOURNAL_TORN)
    # the discarded record is gone from the rewritten journal too
    j3 = PartitionJournal(str(tmp_path), "job-e", 2)
    assert j3.resume() == {0}


# -- engine integration ------------------------------------------------------

def test_durable_materialize_resumes_zero_recompute(tmp_path):
    EngineConfig.durable_dir = str(tmp_path)
    calls = []

    def build():
        def op(batch):
            calls.append(len(batch))
            return pa.compute.add(batch.column("x"), 1)
        return make_df().withColumnBatch("y", op, outputType=pa.int64())

    want = build().collect()
    n_first = len(calls)
    assert n_first == 4  # one compute per partition

    with HealthMonitor() as mon:
        got = build().collect()  # fresh frame, same plan -> same job id
    assert got == want
    assert len(calls) == n_first  # zero re-runs: all served from spill
    assert mon.events(health.DURABLE_RESUMED)
    assert len(mon.events(health.DURABLE_PARTITION_RESTORED)) == 4


def test_durable_stream_resumes_in_original_order(tmp_path):
    EngineConfig.durable_dir = str(tmp_path)
    calls = []

    def build():
        def op(batch):
            calls.append(len(batch))
            return pa.compute.add(batch.column("x"), 1)
        return make_df().withColumnBatch("y", op, outputType=pa.int64())

    want = [b for b in build().streamPartitions()]
    n_first = len(calls)
    got = [b for b in build().streamPartitions()]
    assert len(calls) == n_first
    assert len(got) == len(want) == 4
    for g, w in zip(got, want):
        assert g.equals(w)


def test_durable_partial_run_resumes_only_missing(tmp_path):
    EngineConfig.durable_dir = str(tmp_path)

    def build(calls):
        def op(batch):
            calls.append(batch.column("x")[0].as_py())
            return pa.compute.add(batch.column("x"), 1)
        return make_df().withColumnBatch("y", op, outputType=pa.int64())

    # simulate a crashed first run: commit partitions 0 and 2 by hand
    df = build([])
    job = durability.job_id(df._partitions, df._schema, df._ops)
    j = PartitionJournal(str(tmp_path), job, 4)
    ops = df._ops
    for i in (0, 2):
        out = df._partitions[i]
        for op in ops:
            out = op(out)
        j.commit(i, out)

    calls = []
    rows = build(calls).collect()
    assert sorted(calls) == [3, 9]  # only partitions 1 and 3 computed
    assert [r["y"] for r in rows] == [i + 1 for i in range(12)]


def test_durable_dir_unset_identical_behavior(tmp_path):
    calls = []

    def op(batch):
        calls.append(1)
        return pa.compute.add(batch.column("x"), 1)

    df = make_df().withColumnBatch("y", op, outputType=pa.int64())
    df.collect()
    df2 = make_df().withColumnBatch("y", op, outputType=pa.int64())
    df2.collect()
    assert len(calls) == 8  # no journal, no resume: both runs compute
    assert list(os.listdir(tmp_path)) == []


# -- run-id pinning ----------------------------------------------------------

def test_pinned_run_id_stable_across_processes(tmp_path):
    a = durability.pinned_run_id(str(tmp_path))
    b = durability.pinned_run_id(str(tmp_path))
    assert a == b and a.startswith("sparkdl-durable-")


def test_pinned_run_id_respects_existing_winner(tmp_path):
    with open(tmp_path / "run_id", "w") as f:
        f.write("winner-1234\n")
    assert durability.pinned_run_id(str(tmp_path)) == "winner-1234"
