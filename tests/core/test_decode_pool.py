"""Multi-process decode pool (ISSUE 9 tentpole): ordering, crash
respawn + classified retry, tolerant parity with the inline path, clean
shutdown, and the workers=0 inline default."""

import io
import os
import threading
import time

import numpy as np
import pytest
from PIL import Image

from sparkdl_tpu.core import decode_pool, health, resilience, telemetry
from sparkdl_tpu.core.decode_pool import DecodePool
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.resilience import Fault, FaultInjector
from sparkdl_tpu.core.telemetry import Telemetry
from sparkdl_tpu.engine.dataframe import EngineConfig
from sparkdl_tpu.image import imageIO


@pytest.fixture(autouse=True)
def _restore_engine_config_and_pool():
    saved = EngineConfig.snapshot()
    yield
    EngineConfig.restore(saved)
    decode_pool.shutdown()


def _jpeg(rng, h=16, w=16):
    buf = io.BytesIO()
    Image.fromarray(rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
                    ).save(buf, format="JPEG", quality=90)
    return buf.getvalue()


def _blobs(n=24, corrupt=(), none=()):
    rng = np.random.default_rng(0)
    out = []
    for i in range(n):
        if i in none:
            out.append(None)
        elif i in corrupt:
            out.append(b"definitely not an image")
        else:
            # sizes vary so per-blob decode times are unequal and chunks
            # finish out of order across workers
            out.append(_jpeg(rng, h=8 + 8 * (i % 7), w=8 + 4 * (i % 5)))
    return out


def test_order_preserved_under_unequal_decode_times():
    """Every output index must hold ITS blob's pixels even though blob
    sizes (and so decode times) vary and two workers race."""
    blobs = _blobs(40)
    inline = imageIO._decodeValidBlobs(blobs, (12, 12), 3)
    with DecodePool(workers=2) as pool:
        for _ in range(3):  # repeated fan-outs, same order every time
            got = pool.decode(blobs, target_size=(12, 12), channels=3)
            assert len(got) == len(blobs)
            for i, want in enumerate(inline):
                np.testing.assert_array_equal(got[i], want)


def test_flexible_decode_preserves_source_geometry():
    """No target size / channels (the readImages default-decoder
    contract): each blob keeps its own HxW, identical to the inline
    decoder."""
    blobs = _blobs(10)
    with DecodePool(workers=2) as pool:
        got = pool.decode(blobs)
    for blob, arr in zip(blobs, got):
        want = imageIO.decodePoolBlob(blob)
        np.testing.assert_array_equal(arr, want)
    # geometry genuinely varies (the test would be vacuous otherwise)
    assert len({a.shape for a in got}) > 1


def test_worker_crash_respawns_and_recovers():
    """One injected worker crash: the pool respawns the worker,
    re-dispatches exactly the lost chunk, returns the full correct
    result, and records one decode_pool_respawn health event."""
    blobs = _blobs(12)
    with DecodePool(workers=2) as pool:
        baseline = pool.decode(blobs, target_size=(8, 8), channels=3)
        with FaultInjector.seeded(0, decode_pool_worker_crash=1) as inj, \
                HealthMonitor() as mon:
            got = pool.decode(blobs, target_size=(8, 8), channels=3)
        assert inj.fired["decode_pool_worker_crash"] == 1
        assert mon.count(health.DECODE_POOL_RESPAWN) == 1
        assert pool.respawns == 1
        for a, b in zip(got, baseline):
            np.testing.assert_array_equal(a, b)
        # the pool healed: full worker complement alive, next call clean
        assert all(w.proc.is_alive() for w in pool._workers)
        got2 = pool.decode(blobs, target_size=(8, 8), channels=3)
        for a, b in zip(got2, baseline):
            np.testing.assert_array_equal(a, b)


def test_worker_crash_exhaustion_is_classified_retryable():
    """A persistently-crashing worker exhausts the chunk's resubmission
    budget and fails with DecodeWorkerLost — classified RETRYABLE, so
    the engine's task retry (not a blind loop) owns the replay. The
    pool itself stays usable afterwards."""
    blobs = _blobs(4)
    with DecodePool(workers=1) as pool:
        baseline = pool.decode(blobs, target_size=(8, 8), channels=3)
        with FaultInjector.seeded(
                0, decode_pool_worker_crash=Fault(times=-1)):
            with pytest.raises(resilience.DecodeWorkerLost) as ei:
                pool.decode(blobs, target_size=(8, 8), channels=3)
        assert resilience.classify(ei.value) == resilience.RETRYABLE
        # injector disarmed: the pool recovered and serves again
        got = pool.decode(blobs, target_size=(8, 8), channels=3)
        for a, b in zip(got, baseline):
            np.testing.assert_array_equal(a, b)


def test_worker_side_error_propagates_typed_like_inline():
    """An exception the INLINE decoder would raise (unsupported channel
    count) must re-raise at the submitting call site with its builtin
    type intact — classified FATAL, never silently degraded to null
    rows."""
    blobs = _blobs(4)
    with pytest.raises(ValueError):
        for b in blobs:  # the inline path raises on channels=2
            imageIO.decodePoolBlob(b, channels=2)
    with DecodePool(workers=1) as pool:
        with pytest.raises(ValueError) as ei:
            pool.decode(blobs, channels=2)
    assert resilience.classify(ei.value) == resilience.FATAL
    # and the pool stays healthy for the next (valid) call
    # — verified by close() not hanging (ctx manager above)


def test_tolerant_corrupt_blob_parity_pool_on_off():
    """decodeImageBytesBatch through the pool vs inline: identical rows
    (corrupt blobs degrade to the same Nones) and EQUAL decode_degraded
    health counters — exactly one event stream, owned by the submitting
    process."""
    blobs = _blobs(18, corrupt={3, 11}, none={7})
    EngineConfig.decode_workers = 2
    with HealthMonitor() as mon_on:
        on = imageIO.decodeImageBytesBatch(blobs, (10, 10))
    EngineConfig.decode_workers = 0
    with HealthMonitor() as mon_off:
        off = imageIO.decodeImageBytesBatch(blobs, (10, 10))
    assert mon_on.count(health.DECODE_DEGRADED) \
        == mon_off.count(health.DECODE_DEGRADED) == 2
    for i, (a, b) in enumerate(zip(on, off)):
        if b is None:
            assert a is None, i
        else:
            np.testing.assert_array_equal(a, b)
    assert on[3] is None and on[11] is None and on[7] is None


def test_injected_decode_error_parity_pool_on_off():
    """The decode_error fault fires in the SUBMITTING process on both
    paths: same degraded row, same single injected decode_degraded
    event."""
    blobs = _blobs(6)

    def run(workers):
        EngineConfig.decode_workers = workers
        with FaultInjector.seeded(0, decode_error=1) as inj, \
                HealthMonitor() as mon:
            out = imageIO.decodeImageBytesBatch(blobs, (8, 8))
        assert inj.fired["decode_error"] == 1
        return out, mon.count(health.DECODE_DEGRADED)

    on, degraded_on = run(2)
    decode_pool.shutdown()
    off, degraded_off = run(0)
    assert degraded_on == degraded_off == 1
    assert on[0] is None and off[0] is None
    for a, b in zip(on[1:], off[1:]):
        np.testing.assert_array_equal(a, b)


def test_close_midstream_leaks_no_processes_or_segments():
    """close() while decodes are in flight: the waiter fails with a
    RETRYABLE DecodeWorkerLost (never hangs), every worker process is
    joined, and no shared-memory segment survives."""
    before = set(os.listdir("/dev/shm")) if os.path.isdir("/dev/shm") \
        else set()
    blobs = _blobs(64, corrupt={5})
    pool = DecodePool(workers=2)
    errors = []
    done = threading.Event()

    def hammer():
        try:
            while not done.is_set():
                pool.decode(blobs, target_size=(32, 32), channels=3)
        except Exception as e:  # noqa: BLE001 - asserted below
            errors.append(e)
        finally:
            done.set()

    t = threading.Thread(target=hammer, name="test-decode-hammer")
    t.start()
    time.sleep(0.3)  # let decodes be genuinely in flight
    pool.close()
    done.set()
    t.join(timeout=20.0)
    assert not t.is_alive()
    if errors:  # the hammer was mid-call at close: must be classified
        assert isinstance(errors[0], resilience.DecodeWorkerLost)
        assert resilience.classify(errors[0]) == resilience.RETRYABLE
    assert all(not w.proc.is_alive() for w in pool._workers)
    assert pool._pending == {}
    pool.close()  # idempotent
    if os.path.isdir("/dev/shm"):
        leaked = {n for n in set(os.listdir("/dev/shm")) - before
                  if n.startswith("psm_")}
        assert not leaked, leaked


def test_workers_zero_is_inline_and_poolless():
    """The default keeps today's behavior bit-identically: no pool is
    ever constructed and the inline decoder serves the call."""
    assert EngineConfig.decode_workers == 0
    assert decode_pool.maybe_pool() is None
    blobs = _blobs(8, corrupt={2})
    out = imageIO.decodeImageBytesBatch(blobs, (8, 8))
    want = imageIO._decodeValidBlobs([b for b in blobs if b], (8, 8), 3)
    live = [a for i, a in enumerate(out) if blobs[i]]
    for a, b in zip(live, want):
        if b is None:
            assert a is None
        else:
            np.testing.assert_array_equal(a, b)
    assert decode_pool._pool is None


def test_maybe_pool_lifecycle_follows_the_knobs():
    """maybe_pool builds one process-wide pool per knob setting,
    rebuilds on reconfiguration, and validates the knobs."""
    EngineConfig.decode_workers = 1
    pool = decode_pool.maybe_pool()
    assert pool is not None and pool.workers == 1
    assert decode_pool.maybe_pool() is pool  # cached
    EngineConfig.decode_workers = 2
    EngineConfig.decode_pool_inflight = 3
    pool2 = decode_pool.maybe_pool()
    assert pool2 is not pool and pool.closed
    assert pool2.workers == 2 and pool2.inflight == 3
    decode_pool.shutdown()
    assert pool2.closed
    EngineConfig.decode_workers = -1
    with pytest.raises(ValueError, match="decode_workers"):
        decode_pool.maybe_pool()
    EngineConfig.decode_workers = 1
    EngineConfig.decode_pool_inflight = 0
    with pytest.raises(ValueError, match="decode_pool_inflight"):
        decode_pool.maybe_pool()


def test_read_images_pool_parity_and_telemetry(tmp_path):
    """The readImages ingest path end to end: pool on == pool off rows
    (including a corrupt file's null struct), equal health counters, and
    the pool's span + per-blob latency histogram + gauges land in the
    telemetry scope."""
    rng = np.random.default_rng(1)
    for i in range(9):
        Image.fromarray(rng.integers(0, 255, (12 + i, 14, 3),
                                     dtype=np.uint8)
                        ).save(tmp_path / f"img_{i}.png")
    (tmp_path / "bad.jpg").write_bytes(b"corrupt")

    with HealthMonitor() as mon_off:
        rows_off = imageIO.readImages(str(tmp_path), numPartition=3).collect()
    EngineConfig.decode_workers = 2
    with HealthMonitor() as mon_on, Telemetry("decode-pool-test") as tel:
        rows_on = imageIO.readImages(str(tmp_path), numPartition=3).collect()
    assert rows_on == rows_off
    assert mon_on.count(health.DECODE_DEGRADED) \
        == mon_off.count(health.DECODE_DEGRADED) == 1
    snap = tel.metrics.snapshot()
    assert snap["histograms"][telemetry.M_DECODE_POOL_DECODE_S]["count"] > 0
    assert telemetry.M_DECODE_POOL_DEPTH in snap["gauges"]
    assert telemetry.M_DECODE_POOL_BUSY in snap["gauges"]
    spans = tel.tracer.spans(telemetry.SPAN_DECODE_POOL)
    assert spans  # one fan-out span per pooled decode call
    # the span parents under the partition task that submitted it
    ids = {s["span_id"] for s in tel.tracer.spans()}
    assert all(s["parent_id"] in ids for s in spans)


def test_sweep_reclaims_dead_owner_segments_only():
    """A kill -9'd owner's run-scoped segments (name embeds the owner
    pid) are reclaimed by the next pool's startup sweep; a live owner's
    segments are untouched."""
    import subprocess
    import sys
    from multiprocessing import resource_tracker, shared_memory

    if not os.path.isdir(decode_pool._SHM_DIR):
        pytest.skip("no /dev/shm on this platform")
    # a pid that is certainly dead: a just-reaped child
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    dead_pid = proc.pid

    def make(owner_pid, seq):
        seg = shared_memory.SharedMemory(
            name=f"{decode_pool._SHM_PREFIX}_{owner_pid:x}_{owner_pid:x}"
                 f"_{seq:x}", create=True, size=64)
        # the test plays the worker's role: hand ownership to the shm
        # file itself so this process's tracker doesn't unlink/warn
        resource_tracker.unregister(seg._name, "shared_memory")
        seg.close()
        return seg.name

    dead_name = make(dead_pid, 1)
    live_name = make(os.getpid(), 2)
    try:
        with HealthMonitor() as mon:
            swept = decode_pool.sweep_orphaned_segments()
        assert swept >= 1
        listing = set(os.listdir(decode_pool._SHM_DIR))
        assert dead_name not in listing
        assert live_name in listing
        assert mon.events(health.DECODE_POOL_SHM_SWEPT)
    finally:
        try:
            os.unlink(os.path.join(decode_pool._SHM_DIR, live_name))
        except OSError:
            pass


def test_pool_startup_runs_orphan_sweep():
    """DecodePool() itself sweeps before spawning — the kill -9 resume
    path reclaims the dead run's segments with zero operator action."""
    import subprocess
    import sys
    from multiprocessing import resource_tracker, shared_memory

    if not os.path.isdir(decode_pool._SHM_DIR):
        pytest.skip("no /dev/shm on this platform")
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    seg = shared_memory.SharedMemory(
        name=f"{decode_pool._SHM_PREFIX}_{proc.pid:x}_{proc.pid:x}_9",
        create=True, size=64)
    resource_tracker.unregister(seg._name, "shared_memory")
    seg.close()
    pool = DecodePool(workers=1)
    try:
        assert seg.name not in set(os.listdir(decode_pool._SHM_DIR))
    finally:
        pool.close()


def test_decode_chunk_spans_adopt_under_the_pool_span():
    """ISSUE 15: with a telemetry scope active, every chunk a worker
    decodes comes back with a ``sparkdl.decode_chunk`` span measured
    IN the worker (origin pid preserved) and adopted under the
    coordinator's ``sparkdl.decode_pool`` span."""
    blobs = _blobs(12)
    with Telemetry("decode-trace") as tel, DecodePool(workers=2) as pool:
        got = pool.decode(blobs, target_size=(8, 8), channels=3)
    assert len(got) == len(blobs)
    (pool_span,) = tel.tracer.spans(telemetry.SPAN_DECODE_POOL)
    chunks = tel.tracer.spans(telemetry.SPAN_DECODE_CHUNK)
    assert chunks  # the fan-out produced at least one chunk
    worker_pids = set()
    for s in chunks:
        assert s["parent_id"] == pool_span["span_id"]
        assert s["trace_id"] == tel.run_id
        assert s["pid"] != os.getpid()    # measured in the worker
        assert s["process"] == f"decode-{s['pid']}"
        assert s["end_ns"] >= s["start_ns"]
        worker_pids.add(s["pid"])
    assert sum(s["attributes"]["blobs"] for s in chunks) == len(blobs)
    assert tel.tracer.summary()["remote_adopted"] == len(chunks)


def test_decode_without_scope_ships_no_spans():
    """Tracing off (no scope): the task tuple carries ctx=None, workers
    build no wire records, and a LATER scope sees nothing adopted —
    the off path stays observability-free end to end."""
    blobs = _blobs(6)
    with DecodePool(workers=1) as pool:
        pool.decode(blobs, target_size=(8, 8), channels=3)
        with Telemetry("later") as tel:
            pass
    assert tel.tracer.spans(telemetry.SPAN_DECODE_CHUNK) == []
    assert tel.tracer.summary()["remote_adopted"] == 0
