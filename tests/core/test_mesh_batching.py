"""Mesh construction + static batching tests (8 virtual CPU devices)."""

import jax
import numpy as np
import pytest

from sparkdl_tpu.core import MeshConfig, make_mesh, shard_batch
from sparkdl_tpu.core.batching import iter_batches, pad_batch, run_batched


def test_default_mesh_all_data():
    mesh = make_mesh()
    assert mesh.shape["data"] == 8
    assert mesh.shape["model"] == 1


def test_mesh_shapes():
    mesh = make_mesh(MeshConfig(data=2, model=4))
    assert mesh.shape["data"] == 2 and mesh.shape["model"] == 4
    mesh2 = make_mesh(MeshConfig(model=2))  # data absorbs -> 4
    assert mesh2.shape["data"] == 4


def test_mesh_invalid_shape_raises():
    with pytest.raises(ValueError):
        make_mesh(MeshConfig(data=3, model=3))
    with pytest.raises(ValueError):
        make_mesh(MeshConfig(model=5))


def test_shard_batch_places_on_data_axis():
    mesh = make_mesh()
    arr = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    sharded = shard_batch(mesh, arr)
    assert sharded.sharding.num_devices == 8
    np.testing.assert_array_equal(np.asarray(sharded), arr)


def test_pad_batch():
    arr = np.ones((3, 2))
    padded, n = pad_batch(arr, 5)
    assert padded.shape == (5, 2) and n == 3
    assert padded[3:].sum() == 0
    with pytest.raises(ValueError):
        pad_batch(np.ones((6, 2)), 5)


def test_iter_batches_shapes():
    chunks = list(iter_batches(np.arange(10).reshape(10, 1), 4))
    assert [c.shape for c, _ in chunks] == [(4, 1)] * 3
    assert [v for _, v in chunks] == [4, 4, 2]
    assert list(iter_batches(np.zeros((0, 1)), 4)) == []


def test_run_batched_concatenates():
    arr = np.arange(10, dtype=np.float32).reshape(10, 1)
    out = run_batched(lambda b: b * 2, arr, batch_size=3)
    np.testing.assert_array_equal(out, arr * 2)


def test_run_batched_empty():
    out = run_batched(lambda b: b, np.zeros((0, 4), np.float32), 3)
    assert out.shape[0] == 0


def test_run_batched_empty_template_memoized_per_fn_and_shape():
    """ISSUE 5 satellite: the empty-output template (a full jax.eval_shape
    trace) is computed once per (fn, element shape/dtype) — empty
    partitions in a quarantined stream must not pay repeated tracing."""
    traces = []

    def fn(b):
        traces.append(b.shape)
        return b * 2

    empty = np.zeros((0, 4), np.float32)
    out1 = run_batched(fn, empty, 8)
    out2 = run_batched(fn, empty, 8)
    assert out1.shape == out2.shape == (0, 4)
    assert len(traces) == 1  # the second empty call reused the template
    # batch_size does not change the element shape: still no new trace
    out3 = run_batched(fn, empty, 16)
    assert out3.shape == (0, 4) and len(traces) == 1
    # a different element shape (or dtype) is a different template
    run_batched(fn, np.zeros((0, 3), np.float32), 8)
    assert len(traces) == 2
    run_batched(fn, np.zeros((0, 4), np.int32), 8)
    assert len(traces) == 3
    # a different fn gets its own entry even at the same element shape
    other_traces = []

    def other(b):
        other_traces.append(b.shape)
        return b + 1

    out4 = run_batched(other, empty, 8)
    assert out4.shape == (0, 4)
    assert len(other_traces) == 1 and len(traces) == 3


def test_host_local_mesh_warns_when_discarding_model_axis(monkeypatch, caplog):
    """Substituting a data-only local mesh for a multi-host mesh with a
    non-trivial model axis must WARN: parameter sharding is silently lost
    otherwise and surfaces later as an inexplicable OOM (ADVICE r5)."""
    import logging

    from sparkdl_tpu.core import mesh as mesh_mod

    full = make_mesh(MeshConfig(data=4, model=2))
    monkeypatch.setattr(mesh_mod.jax, "process_count", lambda: 2)
    monkeypatch.setattr(mesh_mod.jax, "local_devices",
                        lambda: jax.devices()[:4])
    with caplog.at_level(logging.WARNING, logger="sparkdl_tpu.core.mesh"):
        local = mesh_mod.host_local_mesh(full)
    assert local.shape["data"] == 4 and local.shape["model"] == 1
    assert any("model" in r.message and "discard" in r.message
               for r in caplog.records)

    # a data-only mesh substitutes silently (nothing is lost)
    caplog.clear()
    data_only = make_mesh(MeshConfig(data=8))
    with caplog.at_level(logging.WARNING, logger="sparkdl_tpu.core.mesh"):
        local2 = mesh_mod.host_local_mesh(data_only)
    assert local2.shape["data"] == 4
    assert not caplog.records
