"""SLO watchdog (ISSUE 7): declarative rules over the sliding-window
metric plane — construction-time validation, breach/recovery pairing
with hold-down, windowed-not-cumulative verdicts, default rules."""

import json
import time

import pytest

from sparkdl_tpu.core import health, slo, telemetry
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.slo import SLORule, SLOWatchdog
from sparkdl_tpu.core.telemetry import Telemetry

_SHED = telemetry.HEALTH_METRIC_PREFIX + health.EXECUTOR_SHED


class _FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture
def clock(monkeypatch):
    c = _FakeClock()
    monkeypatch.setattr(telemetry, "_monotonic", c)
    return c


def _scope():
    return Telemetry("slo-test", window_s=10.0, window_buckets=10)


# -- rule validation ---------------------------------------------------------

def test_rule_validation_rejects_typos_and_bad_fields():
    good = dict(window_s=1.0, threshold=1.0)
    SLORule("ok", metric=telemetry.M_QUEUE_WAIT_S, **good)
    SLORule("ok2", metric=_SHED, stat="rate_per_s", **good)
    with pytest.raises(ValueError, match="not a declared name"):
        SLORule("typo", metric="sparkdl.executor.queue_wait_ss", **good)
    with pytest.raises(ValueError, match="not a declared name"):
        SLORule("typo2", metric="sparkdl.health.executor_shedd", **good)
    with pytest.raises(ValueError, match="comparator"):
        SLORule("c", metric=telemetry.M_QUEUE_WAIT_S, comparator="!=",
                **good)
    with pytest.raises(ValueError, match="stat"):
        SLORule("s", metric=telemetry.M_QUEUE_WAIT_S, stat="p42", **good)
    with pytest.raises(ValueError, match="window_s"):
        SLORule("w", metric=telemetry.M_QUEUE_WAIT_S, window_s=0.0,
                threshold=1.0)
    with pytest.raises(ValueError, match="for_s"):
        SLORule("f", metric=telemetry.M_QUEUE_WAIT_S, for_s=-1.0, **good)
    rule = SLORule("dup", metric=telemetry.M_QUEUE_WAIT_S, **good)
    with pytest.raises(ValueError, match="duplicate"):
        SLOWatchdog([rule, rule])


def test_rule_validation_rejects_stat_kind_mismatch():
    """A stat the metric's instrument kind can never produce must fail
    at construction — it would observe None forever and watch nothing."""
    good = dict(window_s=1.0, threshold=1.0)
    # p99 of a health mirror (always a counter): rejected
    with pytest.raises(ValueError, match="cannot be observed"):
        SLORule("shed_p99", metric=_SHED, stat="p99", **good)
    # counter stats on a histogram work (count/rate are merged views)
    SLORule("qw_rate", metric=telemetry.M_QUEUE_WAIT_S,
            stat="rate_per_s", **good)
    # gauge value on a histogram: rejected
    with pytest.raises(ValueError, match="cannot be observed"):
        SLORule("qw_value", metric=telemetry.M_QUEUE_WAIT_S,
                stat="value", **good)
    # histogram stats on a gauge: rejected
    with pytest.raises(ValueError, match="cannot be observed"):
        SLORule("depth_p99", metric=telemetry.M_EXECUTOR_QUEUE_DEPTH,
                stat="p99", **good)
    # every canonical metric has a declared kind (the map is total)
    assert set(telemetry.CANONICAL_METRIC_KINDS) == \
        set(telemetry.CANONICAL_METRIC_NAMES)


def test_scope_rejects_rule_window_past_ring_capacity(tmp_path):
    """A rule window the metric ring cannot answer fails at scope
    construction, not silently capped at the first tick — and a
    standalone watchdog over an undersized registry warns (once)
    instead of silently judging over less history."""
    wide = SLORule("qw", metric=telemetry.M_QUEUE_WAIT_S, window_s=300.0,
                   threshold=1.0, stat="p99")
    with pytest.raises(ValueError, match="ring capacity"):
        Telemetry("bad", out_dir=str(tmp_path), export_interval_s=1.0,
                  window_s=60.0, slo_rules=[wide])
    # standalone: evaluates over the capped window, with a warning
    with Telemetry("standalone", window_s=10.0, window_buckets=10) as tel:
        wd = SLOWatchdog([wide])
        out = wd.evaluate(tel.metrics)
        assert out["qw"]["breached"] is False
        assert "qw" in wd._capacity_warned
    # the shipped DEFAULTS adapt instead of refusing the scope: a small
    # ring re-parameterizes them to its capacity
    with Telemetry("small-ring", export_interval_s=300.0,
                   window_s=5.0, window_buckets=10) as tel2:
        assert [r.window_s for r in tel2.slo_watchdog.rules] == [5.0] * 3
        assert {r.name for r in tel2.slo_watchdog.rules} == \
            {r.name for r in slo.DEFAULT_RULES}


# -- breach / recovery pairing -----------------------------------------------

def test_breach_and_recovery_pair_exactly_once(clock):
    rule = SLORule("qw", metric=telemetry.M_QUEUE_WAIT_S, window_s=2.0,
                   threshold=0.1, stat="p99")
    with HealthMonitor() as mon, _scope() as tel:
        wd = SLOWatchdog([rule])
        # no data is never a breach: a quiet executor pages nobody
        assert wd.evaluate(tel.metrics)["qw"]["breached"] is False
        telemetry.observe(telemetry.M_QUEUE_WAIT_S, 5.0)
        out = wd.evaluate(tel.metrics)
        assert out["qw"]["breached"] is True
        assert out["qw"]["observed"] == pytest.approx(5.0)
        wd.evaluate(tel.metrics)   # still breached: no second event
        assert mon.count(health.SLO_BREACH) == 1
        clock.advance(30.0)        # the spike ages out of the window
        assert wd.evaluate(tel.metrics)["qw"]["breached"] is False
        wd.evaluate(tel.metrics)   # stays recovered: no second event
        assert wd.state()["qw"]["breached"] is False
    assert mon.count(health.SLO_BREACH) == 1
    assert mon.count(health.SLO_RECOVERED) == 1
    # the alert payload: rule name, observed value, threshold
    (breach,) = mon.events(health.SLO_BREACH)
    assert breach["rule"] == "qw"
    assert breach["observed"] == pytest.approx(5.0)
    assert breach["threshold"] == 0.1
    assert breach["metric"] == telemetry.M_QUEUE_WAIT_S
    (rec,) = mon.events(health.SLO_RECOVERED)
    assert rec["rule"] == "qw"
    # mirrored into the scope's counters at the health choke point
    assert tel.metrics.counter(
        telemetry.HEALTH_METRIC_PREFIX + health.SLO_BREACH).value == 1
    assert tel.metrics.counter(
        telemetry.HEALTH_METRIC_PREFIX + health.SLO_RECOVERED).value == 1


def test_hold_down_requires_continuous_breach(clock):
    rule = SLORule("shed", metric=_SHED, window_s=5.0, threshold=0.5,
                   comparator=">=", stat="rate_per_s", for_s=1.0)
    with HealthMonitor() as mon, _scope() as tel:
        wd = SLOWatchdog([rule])
        telemetry.count(_SHED, 10)
        wd.evaluate(tel.metrics)            # breaching, held 0 s
        assert mon.count(health.SLO_BREACH) == 0
        clock.advance(0.5)
        wd.evaluate(tel.metrics)            # held 0.5 s < for_s
        assert mon.count(health.SLO_BREACH) == 0
        clock.advance(0.6)
        wd.evaluate(tel.metrics)            # held 1.1 s >= for_s: fires
        assert mon.count(health.SLO_BREACH) == 1
    assert mon.count(health.SLO_RECOVERED) == 0  # never recovered in-scope


def test_transient_blip_shorter_than_hold_down_never_fires(clock):
    rule = SLORule("shed", metric=_SHED, window_s=2.0, threshold=0.5,
                   comparator=">=", stat="rate_per_s", for_s=5.0)
    with HealthMonitor() as mon, _scope() as tel:
        wd = SLOWatchdog([rule])
        telemetry.count(_SHED, 10)
        wd.evaluate(tel.metrics)            # breaching, pending
        clock.advance(3.0)                  # blip ages out before for_s
        wd.evaluate(tel.metrics)            # back in budget: pending reset
        telemetry.count(_SHED, 10)          # a second, separate blip
        wd.evaluate(tel.metrics)
        clock.advance(3.0)
        wd.evaluate(tel.metrics)
    # two blips, neither held for 5 s: no breach, and no recovery either
    assert mon.count(health.SLO_BREACH) == 0
    assert mon.count(health.SLO_RECOVERED) == 0


def test_floor_comparator_on_gauge_value(clock):
    """'<' rules state throughput floors: a gauge below target breaches,
    back above recovers."""
    rule = SLORule("ingest_floor", metric=telemetry.M_EXAMPLES_PER_SEC,
                   window_s=5.0, threshold=100.0, comparator="<",
                   stat="value")
    with HealthMonitor() as mon, _scope() as tel:
        wd = SLOWatchdog([rule])
        telemetry.gauge_set(telemetry.M_EXAMPLES_PER_SEC, 50.0)
        assert wd.evaluate(tel.metrics)["ingest_floor"]["breached"]
        telemetry.gauge_set(telemetry.M_EXAMPLES_PER_SEC, 500.0)
        assert not wd.evaluate(tel.metrics)["ingest_floor"]["breached"]
    assert mon.count(health.SLO_BREACH) == 1
    assert mon.count(health.SLO_RECOVERED) == 1


def test_windowed_not_cumulative_verdict(clock):
    """An old spike outside the rule window must NOT breach — the exact
    '10-minute-old p99 pollutes current' failure this plane removes."""
    rule = SLORule("qw", metric=telemetry.M_QUEUE_WAIT_S, window_s=2.0,
                   threshold=0.1, stat="p99")
    with HealthMonitor() as mon, _scope() as tel:
        telemetry.observe(telemetry.M_QUEUE_WAIT_S, 5.0)  # the spike
        clock.advance(60.0)                               # long ago now
        wd = SLOWatchdog([rule])
        out = wd.evaluate(tel.metrics)
        assert out["qw"]["observed"] is None
        assert out["qw"]["breached"] is False
        # while the cumulative view still reports the spike
        assert tel.metrics.snapshot()["histograms"][
            telemetry.M_QUEUE_WAIT_S]["p99"] == pytest.approx(5.0)
    assert mon.count(health.SLO_BREACH) == 0


# -- default rules -----------------------------------------------------------

def test_default_rules_cover_the_overload_story():
    by_name = {r.name: r for r in slo.DEFAULT_RULES}
    assert set(by_name) == {"executor_queue_wait_p99",
                            "executor_shed_rate",
                            "executor_breaker_open"}
    assert by_name["executor_queue_wait_p99"].metric == \
        telemetry.M_QUEUE_WAIT_S
    assert by_name["executor_shed_rate"].metric == _SHED
    assert by_name["executor_breaker_open"].metric == \
        telemetry.HEALTH_METRIC_PREFIX + health.BREAKER_OPEN
    # re-parameterized copies keep the same shape
    custom = slo.default_rules(window_s=1.5, for_s=0.25)
    assert {r.name for r in custom} == set(by_name)
    assert all(r.window_s == 1.5 and r.for_s == 0.25 for r in custom)


def test_breaker_open_default_rule_fires_on_trip(clock):
    rules = slo.default_rules(window_s=1.0)
    with HealthMonitor() as mon, _scope() as tel:
        wd = SLOWatchdog(rules)
        telemetry.count(telemetry.HEALTH_METRIC_PREFIX
                        + health.BREAKER_OPEN)
        out = wd.evaluate(tel.metrics)
        assert out["executor_breaker_open"]["breached"] is True
        clock.advance(10.0)
        assert not wd.evaluate(
            tel.metrics)["executor_breaker_open"]["breached"]
    assert mon.count(health.SLO_BREACH) == 1
    assert mon.count(health.SLO_RECOVERED) == 1
    assert mon.events(health.SLO_BREACH)[0]["rule"] == \
        "executor_breaker_open"


# -- scope integration -------------------------------------------------------

def test_scope_wires_watchdog_into_exporter_snapshots(tmp_path):
    rules = slo.default_rules(window_s=1.0)
    with Telemetry("wired", out_dir=str(tmp_path),
                   export_interval_s=0.02, window_s=2.0,
                   window_buckets=10, slo_rules=rules) as tel:
        assert tel.slo_watchdog is not None
        assert tel.slo_watchdog.rules == tuple(rules)
        deadline = time.monotonic() + 5.0
        while tel.exporter.seq < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
    with open(tel.exporter.snapshot_path) as f:
        lines = [json.loads(line) for line in f]
    assert len(lines) >= 2
    for line in lines:
        assert set(line["slo"]) == {r.name for r in rules}
        for verdict in line["slo"].values():
            assert verdict["breached"] is False  # quiet run: no paging


# -- tail exemplars (ISSUE 15) -----------------------------------------------

def test_breach_carries_exemplars_into_event_exporter_and_trace(tmp_path):
    """Acceptance: an induced queue-wait breach on an exemplar-armed
    scope attaches >=1 exemplar trace id to the slo_breach event, the
    exporter's snapshot line mirrors it under ``slo_exemplars``, and the
    span id resolves to a REAL span in the exported Chrome trace — a
    page links straight to the offending trace."""
    rules = [SLORule("qw", metric=telemetry.M_QUEUE_WAIT_S, window_s=5.0,
                     threshold=0.1, stat="p99")]
    with HealthMonitor("slo-ex") as mon, \
            Telemetry("slo-ex", out_dir=str(tmp_path),
                      export_interval_s=0.02, window_s=10.0,
                      window_buckets=10, exemplar_k=3,
                      slo_rules=rules) as tel:
        with telemetry.span(telemetry.SPAN_TASK, partition=7) as sp:
            ctx = sp.context
            telemetry.observe(telemetry.M_QUEUE_WAIT_S, 5.0,
                              exemplar=ctx)
        deadline = time.monotonic() + 5.0
        while (mon.count(health.SLO_BREACH) == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert mon.count(health.SLO_BREACH) == 1
    breach = mon.events(health.SLO_BREACH)[0]
    assert breach["rule"] == "qw"
    assert breach["exemplars"] == [
        {"value": 5.0, "trace_id": tel.run_id, "span_id": ctx.span_id}]
    # the live plane: the breaching snapshot line names the same trace
    with open(tel.exporter.snapshot_path) as f:
        lines = [json.loads(line) for line in f]
    carrying = [l for l in lines
                if (l["slo"]["qw"].get("exemplars")
                    and l["slo"]["qw"]["breached"])]
    assert carrying
    assert carrying[0]["slo"]["qw"]["exemplars"][0]["span_id"] == \
        ctx.span_id
    # ...and the run report's compact timeline mirrors it
    report = json.load(open(tel.report_path))
    timeline = [e for e in report["timeline"]["entries"]
                if e.get("slo_exemplars")]
    assert timeline
    assert timeline[0]["slo_breached"] == ["qw"]
    assert timeline[0]["slo_exemplars"]["qw"][0]["span_id"] == \
        ctx.span_id
    # and the id is not a dangling pointer: it resolves to an exported
    # span in the scope's own Chrome trace artifact
    trace = json.load(open(tel.trace_path))
    by_span_id = {e["args"]["span_id"]: e
                  for e in trace["traceEvents"] if e["ph"] == "X"}
    assert by_span_id[ctx.span_id]["name"] == telemetry.SPAN_TASK


def test_unbreached_rules_ship_no_exemplars(clock):
    """Exemplars ride ONLY breached verdicts: a healthy evaluation over
    an armed scope keeps the verdict shape exemplar-free."""
    rule = SLORule("qw", metric=telemetry.M_QUEUE_WAIT_S, window_s=2.0,
                   threshold=10.0, stat="p99")
    with HealthMonitor(), Telemetry("quiet", window_s=10.0,
                                    window_buckets=10,
                                    exemplar_k=2) as tel:
        with telemetry.span(telemetry.SPAN_TASK) as sp:
            telemetry.observe(telemetry.M_QUEUE_WAIT_S, 0.01,
                              exemplar=sp.context)
        wd = SLOWatchdog([rule])
        out = wd.evaluate(tel.metrics)
    assert out["qw"]["breached"] is False
    assert "exemplars" not in out["qw"]
