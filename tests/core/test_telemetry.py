"""Unified telemetry (ISSUE 4): span tracing with cross-thread parenting,
log-scale histograms, Chrome-trace export, run report, zero-cost no-op."""

import json
import logging
import os
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.core import health, profiling, resilience, telemetry
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.pipeline import DevicePrefetcher
from sparkdl_tpu.core.telemetry import (
    Histogram,
    MetricsRegistry,
    Telemetry,
)
from sparkdl_tpu.engine import DataFrame, EngineConfig


@pytest.fixture(autouse=True)
def _restore_engine_config():
    saved = {k: getattr(EngineConfig, k) for k in (
        "speculation", "speculation_quantile", "speculation_min_runtime_s",
        "max_task_retries", "max_workers")}
    yield
    for k, v in saved.items():
        setattr(EngineConfig, k, v)


def _by_id(spans):
    return {s["span_id"]: s for s in spans}


# -- zero-overhead no-op path ------------------------------------------------

def test_inactive_path_is_allocation_free_noop(monkeypatch):
    """No scope: span() returns the SHARED singleton (no allocation), the
    metric helpers are pure no-ops, and nothing is ever recorded —
    including the windowed plane (ISSUE 7): with telemetry inactive the
    record path never even reaches an instrument, and a ring-free
    instrument (the bare default) records without reading the clock."""
    assert telemetry.active() is None
    s1 = telemetry.span("sparkdl.task")
    s2 = telemetry.span("sparkdl.fit", anything=1)
    assert s1 is telemetry.NULL_SPAN and s2 is telemetry.NULL_SPAN
    with s1:
        assert telemetry.current_context() is None
    # metric helpers: no registry exists to record into, no error either
    telemetry.count("sparkdl.health.task_retried")
    telemetry.gauge_set(telemetry.M_PADDING_WASTE, 0.5)
    telemetry.observe(telemetry.M_STEP_TIME_S, 0.1)
    # unwindowed instruments never touch the window clock on the record
    # path — the windowed-metric feature costs the no-ring path nothing
    def clock_read_is_a_bug():
        raise AssertionError("ring-free record path read the window clock")

    monkeypatch.setattr(telemetry, "_monotonic", clock_read_is_a_bug)
    h = Histogram("h")
    h.observe(0.25)
    c = telemetry.Counter("c")
    c.inc()
    g = telemetry.Gauge("g")
    g.set(1.0)
    # their windowed views are inert, not wrong
    assert c.window_count(10.0) == 0
    assert g.window_values(10.0) is None
    w = h.window_snapshot(10.0)
    assert w["count"] == 0 and w["p50"] is None and w["p99"] is None
    monkeypatch.setattr(telemetry, "_monotonic", time.monotonic)
    # a scope opened AFTER the no-ops sees none of them
    with Telemetry("after") as tel:
        pass
    snap = tel.metrics.snapshot()
    assert snap["counters"] == {} and snap["histograms"] == {}
    assert [s["name"] for s in tel.tracer.spans()] == ["sparkdl.run"]
    # and its windowed snapshot is just as empty
    wsnap = tel.metrics.window_snapshot()
    assert wsnap["counters"] == {} and wsnap["histograms"] == {}


def test_annotate_without_scope_unchanged():
    """profiling.annotate still feeds phase timers with no scope active
    (the pre-telemetry contract)."""
    profiling.reset_phase_stats()
    with profiling.annotate("sparkdl.decode", rows=3):
        pass
    stats = profiling.phase_stats(reset=True)
    assert stats["sparkdl.decode"]["count"] == 1


# -- span model / parenting --------------------------------------------------

def test_nested_spans_parent_under_scope_root():
    with Telemetry("t") as tel:
        with telemetry.span("sparkdl.fit") as outer:
            with telemetry.span("sparkdl.train_step", step=1) as inner:
                assert telemetry.current_context() == inner.context
            assert telemetry.current_context() == outer.context
    spans = _by_id(tel.tracer.spans())
    root = next(s for s in spans.values() if s["name"] == "sparkdl.run")
    fit = next(s for s in spans.values() if s["name"] == "sparkdl.fit")
    step = next(s for s in spans.values()
                if s["name"] == "sparkdl.train_step")
    assert root["parent_id"] is None
    assert fit["parent_id"] == root["span_id"]
    assert step["parent_id"] == fit["span_id"]
    assert step["attributes"]["step"] == 1
    assert len({s["trace_id"] for s in spans.values()}) == 1


def test_span_records_error_attribute_on_exception():
    with Telemetry("t") as tel:
        with pytest.raises(ValueError):
            with telemetry.span("sparkdl.task_attempt", partition=0):
                raise ValueError("boom")
    (span,) = tel.tracer.spans("sparkdl.task_attempt")
    assert span["attributes"]["error"] == "ValueError"


def test_annotate_feeds_active_tracer_with_attributes():
    """Existing phase names become spans for free (the annotate hook)."""
    with Telemetry("t") as tel:
        with profiling.annotate("sparkdl.decode", rows=7):
            pass
    (span,) = tel.tracer.spans("sparkdl.decode")
    assert span["attributes"]["rows"] == 7


def test_cross_thread_handoff_attach_and_explicit_parent():
    with Telemetry("t") as tel:
        with telemetry.span("sparkdl.fit") as fit:
            ctx = telemetry.current_context()

            def staged_worker():
                telemetry.attach(ctx)
                with telemetry.span("sparkdl.stage_batch"):
                    pass

            def explicit_worker():
                with telemetry.span("sparkdl.device_sync", parent=ctx):
                    pass

            threads = [threading.Thread(target=staged_worker),
                       threading.Thread(target=explicit_worker)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
    spans = tel.tracer.spans()
    fit_rec = next(s for s in spans if s["name"] == "sparkdl.fit")
    for name in ("sparkdl.stage_batch", "sparkdl.device_sync"):
        child = next(s for s in spans if s["name"] == name)
        assert child["parent_id"] == fit_rec["span_id"]
        assert child["trace_id"] == fit_rec["trace_id"]
        assert child["thread_id"] != fit_rec["thread_id"]


def test_supervisor_pool_spans_parent_under_materialize():
    """Engine partition tasks run on pool threads; their spans must
    parent under the driver's materialize span in the one run trace."""
    with Telemetry("t") as tel:
        df = DataFrame.fromRows([{"x": i} for i in range(12)],
                                numPartitions=3)
        df.withColumn("y", lambda x: x + 1, ["x"]).collect()
    spans = tel.tracer.spans()
    by_id = _by_id(spans)
    mat = next(s for s in spans if s["name"] == "sparkdl.materialize")
    tasks = [s for s in spans if s["name"] == "sparkdl.task"]
    assert len(tasks) == 3
    driver_tid = mat["thread_id"]
    assert any(s["thread_id"] != driver_tid for s in tasks)
    for task in tasks:
        assert task["parent_id"] == mat["span_id"]
        assert task["trace_id"] == tel.run_id
    # each pool task ran (at least) one retry-loop attempt span under it
    for att in (s for s in spans if s["name"] == "sparkdl.task_attempt"):
        assert by_id[att["parent_id"]]["name"] == "sparkdl.task"


def test_retried_task_attempt_spans_share_the_task_trace():
    """A retried task's attempts are siblings under the same sparkdl.task
    span — one trace tells the whole retry story."""
    EngineConfig.max_task_retries = 2
    df = DataFrame.fromRows([{"x": i} for i in range(4)], numPartitions=1)
    failures = {"n": 1}
    lock = threading.Lock()

    def flaky(batch):
        with lock:
            if failures["n"]:
                failures["n"] -= 1
                raise resilience.TransferStall("transient")
        return batch

    with Telemetry("t") as tel:
        df.mapPartitions(flaky).collect()
    attempts = tel.tracer.spans("sparkdl.task_attempt")
    assert [a["attributes"]["attempt"] for a in attempts] == [0, 1]
    assert attempts[0]["attributes"]["error"] == "TransferStall"
    assert "error" not in attempts[1].get("attributes", {})
    parents = {a["parent_id"] for a in attempts}
    assert len(parents) == 1  # both under the SAME pool-thread task span
    assert len({a["trace_id"] for a in attempts}) == 1


def test_hedged_task_spans_share_the_task_trace():
    """A hedged straggler's duplicate attempt parents under the same
    context as the primary (pool_attempt 0 vs 1, one trace)."""
    EngineConfig.speculation = True
    EngineConfig.speculation_quantile = 0.5
    EngineConfig.speculation_min_runtime_s = 0.05
    EngineConfig.max_workers = 9
    df = DataFrame.fromRows([{"x": i} for i in range(12)], numPartitions=6)
    stalled = set()
    lock = threading.Lock()

    def slow_once(batch):
        key = batch.column(0)[0].as_py()
        with lock:
            again = key in stalled
            stalled.add(key)
        if key == 10 and not again:
            time.sleep(1.5)
        return batch

    with HealthMonitor() as mon, Telemetry("t") as tel:
        df.mapPartitions(slow_once).collect()
    assert mon.count(health.HEDGE_WON) == 1
    hedged_partition = mon.events(health.TASK_HEDGED)[0]["partition"]

    def hedged_spans():
        return [s for s in tel.tracer.spans("sparkdl.task")
                if s["attributes"]["partition"] == hedged_partition]

    # a clean run returns without waiting for the hedge LOSER (the
    # stalled primary) — its span lands when its sleep ends; wait it out
    deadline = time.monotonic() + 5.0
    while len(hedged_spans()) < 2 and time.monotonic() < deadline:
        time.sleep(0.05)
    task_spans = hedged_spans()
    assert sorted(s["attributes"]["pool_attempt"] for s in task_spans) \
        == [0, 1]
    assert len({s["parent_id"] for s in task_spans}) == 1
    assert len({s["trace_id"] for s in task_spans}) == 1
    # rows_out counts the WINNING attempt only — the hedge loser running
    # to completion must not double-count its partition's rows
    assert tel.metrics.counter(telemetry.M_ENGINE_ROWS_OUT).value == 12


def test_prefetcher_staging_thread_spans_parent_under_consumer():
    """DevicePrefetcher hands the consumer's context to its staging
    thread: spans opened by stage_fn parent under the consumer span."""
    def stage(item):
        with profiling.annotate("sparkdl.stage_batch", item=item):
            return item * 2

    with Telemetry("t") as tel:
        with telemetry.span("sparkdl.fit") as fit:
            with DevicePrefetcher(range(5), stage_fn=stage,
                                  depth=2) as staged:
                assert list(staged) == [0, 2, 4, 6, 8]
    stage_spans = tel.tracer.spans("sparkdl.stage_batch")
    assert len(stage_spans) == 5
    fit_rec = next(s for s in tel.tracer.spans()
                   if s["name"] == "sparkdl.fit")
    for s in stage_spans:
        assert s["parent_id"] == fit_rec["span_id"]
        assert s["thread_id"] != fit_rec["thread_id"]
        assert s["thread_name"].startswith("sparkdl-prefetch")


def test_span_ring_buffer_bounded_with_drop_count():
    with Telemetry("t", max_spans=4) as tel:
        for i in range(10):
            with telemetry.span("sparkdl.task", partition=i):
                pass
    assert len(tel.tracer.spans()) == 4
    # 10 task spans + the run root through a 4-slot ring
    assert tel.tracer.dropped == 7
    assert tel.tracer.summary()["spans_dropped"] == 7
    # the ring keeps the TAIL (most recent) spans
    kept = [s["attributes"].get("partition")
            for s in tel.tracer.spans("sparkdl.task")]
    assert kept == [7, 8, 9]


# -- metrics registry --------------------------------------------------------

def test_histogram_log_buckets_and_percentiles():
    h = Histogram("h", bounds=(1.0, 2.0, 4.0, 8.0))
    for v in (0.5, 1.0, 3.0, 5.0, 100.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 5
    assert snap["sum"] == pytest.approx(109.5)
    assert snap["min"] == 0.5 and snap["max"] == 100.0
    # bucket assignment uses Prometheus `le` semantics: value <= bound
    assert snap["buckets"] == {"1.0": 2, "4.0": 1, "8.0": 1, "+Inf": 1}


def test_histogram_percentile_within_bucket_error_bound():
    """Factor-2 buckets bound the relative error of the estimate: every
    percentile estimate lands within 2x of the true value."""
    h = Histogram("h")  # default log-scale seconds buckets
    values = [i / 100.0 for i in range(1, 101)]  # 0.01 .. 1.00
    for v in values:
        h.observe(v)
    for q, true in ((0.50, 0.50), (0.95, 0.95), (0.99, 0.99)):
        est = h.percentile(q)
        assert true / 2 <= est <= true * 2, (q, est)
    assert h.percentile(1.0) <= 1.0  # clamped to the observed max


def test_histogram_empty_and_degenerate():
    h = Histogram("h")
    assert h.percentile(0.5) is None
    h.observe(0.0)
    assert h.percentile(0.5) == 0.0  # clamped into [min, max]


def test_registry_get_or_create_and_snapshot():
    reg = MetricsRegistry()
    reg.counter("sparkdl.engine.rows_out").inc(5)
    reg.counter("sparkdl.engine.rows_out").inc(2)  # same instrument
    reg.gauge("sparkdl.batching.padding_waste").set(0.125)
    reg.histogram("sparkdl.task.duration_s").observe(0.25)
    snap = reg.snapshot()
    assert snap["counters"] == {"sparkdl.engine.rows_out": 7}
    assert snap["gauges"] == {"sparkdl.batching.padding_waste": 0.125}
    hist = snap["histograms"]["sparkdl.task.duration_s"]
    assert hist["count"] == 1 and hist["p50"] is not None
    json.dumps(snap)  # JSON-able end to end


def test_prometheus_text_exposition():
    reg = MetricsRegistry()
    reg.counter("sparkdl.engine.rows_out").inc(3)
    reg.gauge("sparkdl.train.examples_per_sec").set(120.5)
    h = reg.histogram("sparkdl.task.duration_s", bounds=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.prometheus_text()
    assert "# TYPE sparkdl_engine_rows_out counter" in text
    assert "sparkdl_engine_rows_out 3" in text
    assert "sparkdl_train_examples_per_sec 120.5" in text
    assert 'sparkdl_task_duration_s_bucket{le="0.1"} 1' in text
    assert 'sparkdl_task_duration_s_bucket{le="1.0"} 2' in text  # cumulative
    assert 'sparkdl_task_duration_s_bucket{le="+Inf"} 3' in text
    assert "sparkdl_task_duration_s_count 3" in text


# -- sliding-window metrics (ISSUE 7) ----------------------------------------

class _FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture
def fake_clock(monkeypatch):
    clock = _FakeClock()
    monkeypatch.setattr(telemetry, "_monotonic", clock)
    return clock


def test_windowed_counter_rotation_and_expiry(fake_clock):
    reg = MetricsRegistry(window_s=10.0, window_buckets=10)  # 1 s slots
    c = reg.counter("sparkdl.health.executor_shed")
    c.inc(3)
    fake_clock.advance(1.0)
    c.inc(2)
    assert c.window_count(10.0) == 5
    assert c.window_count(1.0) == 2   # only the current slot
    fake_clock.advance(8.0)           # first inc is 9 s old: still in
    assert c.window_count(10.0) == 5
    fake_clock.advance(1.0)           # 10 s: the first slot ages out
    assert c.window_count(10.0) == 2
    fake_clock.advance(1.0)           # 11 s: everything aged out
    assert c.window_count(10.0) == 0
    assert c.value == 5               # the cumulative view is untouched
    # a slot index reused after a full ring revolution is reset first —
    # no ghost counts from the previous epoch
    fake_clock.advance(100.0)
    c.inc(1)
    assert c.window_count(10.0) == 1
    assert c.value == 6


def test_windowed_gauge_envelope(fake_clock):
    reg = MetricsRegistry(window_s=10.0, window_buckets=10)
    g = reg.gauge("sparkdl.executor.queue_depth")
    g.set(5)
    g.set(2)                          # same slot: last=2, min=2, max=5
    fake_clock.advance(1.0)
    g.set(9)
    assert g.window_values(10.0) == {"last": 9.0, "min": 2.0, "max": 9.0}
    fake_clock.advance(20.0)          # window empty
    assert g.window_values(10.0) is None
    assert g.value == 9.0             # cumulative last-write survives


def test_windowed_histogram_percentiles_and_aging(fake_clock):
    reg = MetricsRegistry(window_s=10.0, window_buckets=10)
    h = reg.histogram("sparkdl.executor.queue_wait_s")
    for _ in range(50):
        h.observe(0.01)
    for _ in range(50):
        h.observe(0.5)
    w = h.window_snapshot(10.0)
    assert w["count"] == 100
    assert w["rate_per_s"] == pytest.approx(10.0)
    assert w["min"] == 0.01 and w["max"] == 0.5
    assert 0.01 / 2 <= w["p50"] <= 0.01 * 2    # factor-2 bucket bound
    assert 0.5 / 2 <= w["p99"] <= 0.5
    # the spike ages out of the window but stays in the cumulative view:
    # "current p99" stops being polluted by history (the ISSUE 7 motive)
    fake_clock.advance(30.0)
    w2 = h.window_snapshot(10.0)
    assert w2["count"] == 0 and w2["sum"] == 0.0
    assert w2["min"] is None and w2["max"] is None
    assert w2["p50"] is None and w2["p95"] is None and w2["p99"] is None
    cum = h.snapshot()
    assert cum["count"] == 100 and cum["p99"] is not None


def test_registry_window_snapshot_shape_defaults_and_clamp(fake_clock):
    reg = MetricsRegistry(window_s=10.0, window_buckets=10)
    reg.counter("sparkdl.health.executor_shed").inc(4)
    reg.gauge("sparkdl.executor.queue_depth").set(3)
    reg.histogram("sparkdl.executor.queue_wait_s").observe(0.2)
    snap = reg.window_snapshot()          # default: the full ring
    assert snap["window_s"] == 10.0
    assert snap["counters"]["sparkdl.health.executor_shed"] == \
        {"count": 4, "rate_per_s": 0.4}
    assert snap["gauges"]["sparkdl.executor.queue_depth"]["last"] == 3.0
    assert snap["histograms"]["sparkdl.executor.queue_wait_s"]["count"] == 1
    json.dumps(snap)                      # JSON-able end to end
    # a query past the ring capacity clamps to it (can't answer more)
    assert reg.window_snapshot(1e9)["window_s"] == 10.0
    # a non-positive window is a caller bug, not a division crash
    with pytest.raises(ValueError, match="window_s"):
        reg.window_snapshot(0.0)
    # a bare registry (no windows) answers with empty sections
    bare = MetricsRegistry()
    assert bare.window_snapshot() == {
        "window_s": None, "counters": {}, "gauges": {}, "histograms": {}}
    with pytest.raises(ValueError):
        MetricsRegistry(window_s=0.0)


def test_histogram_snapshot_empty_percentiles_are_null():
    """ISSUE 7 satellite: an empty histogram (and an all-zero-count
    window) reports null percentiles, never a bucket-midpoint guess."""
    h = Histogram("h")
    snap = h.snapshot()
    assert snap["count"] == 0
    assert snap["p50"] is None and snap["p95"] is None \
        and snap["p99"] is None
    assert snap["min"] is None and snap["max"] is None
    # percentiles and buckets come from ONE locked copy: an empty
    # histogram's snapshot stays internally consistent
    assert snap["buckets"] == {}
    json.dumps(snap)  # null, not NaN — JSON-able


# -- prometheus exposition conformance (ISSUE 7 satellite) -------------------

def test_prometheus_text_format_conformance():
    """Every family gets exactly one # HELP and one # TYPE line before
    its samples; every sample line parses; histogram buckets are
    cumulative and close with +Inf == count."""
    import re

    reg = MetricsRegistry()
    reg.counter("sparkdl.engine.rows_out").inc(3)
    reg.gauge("sparkdl.train.examples_per_sec").set(120.5)
    h = reg.histogram("sparkdl.task.duration_s", bounds=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    text = reg.prometheus_text()
    assert text.endswith("\n")
    name_re = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
    sample_re = re.compile(
        rf'^({name_re})(\{{le="[^"\n]*"\}})? (-?[0-9.e+-]+|NaN)$')
    help_re = re.compile(rf"^# HELP ({name_re}) .+$")
    type_re = re.compile(
        rf"^# TYPE ({name_re}) (counter|gauge|histogram)$")
    seen_help, seen_type = set(), set()
    for line in text.strip().splitlines():
        if line.startswith("# HELP"):
            m = help_re.match(line)
            assert m, line
            assert m.group(1) not in seen_help, f"duplicate HELP: {line}"
            seen_help.add(m.group(1))
        elif line.startswith("# TYPE"):
            m = type_re.match(line)
            assert m, line
            assert m.group(1) not in seen_type, f"duplicate TYPE: {line}"
            seen_type.add(m.group(1))
        else:
            m = sample_re.match(line)
            assert m, line
            base = m.group(1)
            family = re.sub(r"_(bucket|sum|count)$", "", base)
            # samples only after their family's HELP + TYPE
            assert base in seen_type or family in seen_type, line
            assert base in seen_help or family in seen_help, line
    assert seen_help == seen_type
    # histogram buckets: cumulative, closing +Inf equals the count
    assert 'sparkdl_task_duration_s_bucket{le="0.1"} 1' in text
    assert 'sparkdl_task_duration_s_bucket{le="1.0"} 2' in text
    assert 'sparkdl_task_duration_s_bucket{le="+Inf"} 3' in text
    assert "sparkdl_task_duration_s_count 3" in text


def test_prometheus_label_value_escaping():
    assert telemetry.escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    assert telemetry.escape_label_value("plain") == "plain"
    assert telemetry.escape_label_value(0.1) == "0.1"


# -- chrome trace export -----------------------------------------------------

def test_chrome_trace_roundtrips_with_monotonic_timestamps(tmp_path):
    def worker(ctx):
        with telemetry.span("sparkdl.stage_batch", parent=ctx):
            time.sleep(0.002)

    with Telemetry("t") as tel:
        with telemetry.span("sparkdl.fit") as fit:
            time.sleep(0.001)
            with telemetry.span("sparkdl.train_step"):
                time.sleep(0.002)
            t = threading.Thread(target=worker, args=(fit.context,))
            t.start()
            t.join()
    path = tmp_path / "trace.json"
    path.write_text(json.dumps(tel.tracer.chrome_trace()))
    doc = json.load(open(path))  # round-trips through json.load
    events = doc["traceEvents"]
    complete = {e["name"]: e for e in events if e["ph"] == "X"}
    assert {"sparkdl.run", "sparkdl.fit", "sparkdl.train_step",
            "sparkdl.stage_batch"} <= set(complete)
    for e in complete.values():
        assert e["ts"] >= 0 and e["dur"] >= 0
    # monotonic consistency: children start within their parent's window
    fit_e = complete["sparkdl.fit"]
    for child in ("sparkdl.train_step", "sparkdl.stage_batch"):
        c = complete[child]
        assert fit_e["ts"] <= c["ts"]
        assert c["ts"] + c["dur"] <= fit_e["ts"] + fit_e["dur"] + 1e-3
    # one track per thread: distinct tids + thread_name metadata
    tids = {e["tid"] for e in events if e["ph"] == "X"}
    assert len(tids) == 2
    meta = [e for e in events if e["ph"] == "M"]
    assert {e["tid"] for e in meta} == tids


# -- run report + health integration ----------------------------------------

def test_run_report_written_at_scope_exit(tmp_path):
    with HealthMonitor("hm") as mon:
        with Telemetry("job", out_dir=str(tmp_path)) as tel:
            health.record(health.TASK_RETRIED, partition=1)
            health.record(health.TASK_QUARANTINED, partition=2, error="x")
            with profiling.annotate("sparkdl.decode"):
                pass
            telemetry.observe(telemetry.M_STEP_TIME_S, 0.02)
    report = json.load(open(tel.report_path))
    assert report["run_id"] == tel.run_id
    # trace summary
    assert report["trace"]["spans_recorded"] >= 2
    assert "sparkdl.decode" in report["trace"]["by_name"]
    # metric snapshot mirrors the health counters exactly
    counters = report["metrics"]["counters"]
    assert counters["sparkdl.health.task_retried"] \
        == mon.count(health.TASK_RETRIED) == 1
    assert counters["sparkdl.health.task_quarantined"] \
        == mon.count(health.TASK_QUARANTINED) == 1
    # phase/overlap stats and the health report ride along
    assert "sparkdl.decode" in report["phases"]
    assert "overlap_ratio" in report["overlap"]
    assert report["health"]["counters"]["task_retried"] == 1
    # chrome trace artifact exists and loads
    trace = json.load(open(report["chrome_trace"]))
    assert any(e["name"] == "sparkdl.run" for e in trace["traceEvents"])


def test_no_files_written_without_out_dir(tmp_path, monkeypatch):
    monkeypatch.delenv(telemetry.TELEMETRY_DIR_ENV, raising=False)
    with Telemetry("quiet") as tel:
        pass
    assert tel.report_path is None


def test_env_var_opt_in(tmp_path, monkeypatch):
    monkeypatch.setenv(telemetry.TELEMETRY_DIR_ENV, str(tmp_path))
    with Telemetry("envjob") as tel:
        pass
    assert tel.report_path is not None
    assert json.load(open(tel.report_path))["run"] == "envjob"


def test_scope_root_span_carries_error_of_failed_run():
    with pytest.raises(ValueError):
        with Telemetry("failing") as tel:
            raise ValueError("boom")
    (root,) = tel.tracer.spans("sparkdl.run")
    assert root["attributes"]["error"] == "ValueError"


def test_scopes_nest_and_restore():
    with Telemetry("outer") as outer:
        assert telemetry.active() is outer
        with Telemetry("inner") as inner:
            assert telemetry.active() is inner
            telemetry.count("sparkdl.health.gang_restart")
        assert telemetry.active() is outer
    assert telemetry.active() is None
    assert inner.metrics.counter("sparkdl.health.gang_restart").value == 1
    assert outer.metrics.snapshot()["counters"] == {}


def test_log_records_stamped_with_run_and_trace_ids(caplog):
    logger = logging.getLogger("sparkdl_tpu.core.health")
    with caplog.at_level(logging.INFO, logger="sparkdl_tpu.core.health"):
        with Telemetry("stamp") as tel:
            logger.info("inside scope")
        logger.info("outside scope")
    inside = next(r for r in caplog.records if r.message == "inside scope")
    outside = next(r for r in caplog.records
                   if r.message == "outside scope")
    assert inside.run_id == tel.run_id
    assert inside.trace_id == tel.run_id
    assert not hasattr(outside, "run_id")
    # non-framework records stay untouched even inside a scope
    with Telemetry("stamp2"):
        other = logging.LogRecord("someapp", logging.INFO, __file__, 1,
                                  "x", (), None)
        assert not hasattr(other, "run_id")


# -- instrumentation: batching / trainer metrics -----------------------------

def test_run_batched_feeds_padding_and_bucket_metrics():
    import jax.numpy as jnp

    from sparkdl_tpu.core.batching import run_batched

    x = np.arange(40, dtype=np.float32).reshape(10, 4)
    with Telemetry("t") as tel:
        out = run_batched(lambda c: jnp.asarray(c) * 2, x, batch_size=8)
    np.testing.assert_allclose(np.asarray(out), x * 2)
    snap = tel.metrics.snapshot()
    # 10 rows in chunks of 8: [8 valid @ bucket 8, 2 valid @ bucket 8
    # (min_bucket)] -> 10 valid + 6 pad rows
    assert snap["counters"][telemetry.M_BATCH_ROWS] == 10
    assert snap["counters"][telemetry.M_BATCH_PAD_ROWS] == 6
    assert snap["gauges"][telemetry.M_PADDING_WASTE] \
        == pytest.approx(6 / 16)
    assert snap["histograms"][telemetry.M_BATCH_BUCKET_ROWS]["count"] == 2


def test_trainer_fit_emits_spans_and_step_metrics():
    import jax
    import flax.linen as nn

    from sparkdl_tpu.train.trainer import Trainer

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(2)(x)

    m = M()
    v = m.init(jax.random.PRNGKey(0), np.zeros((1, 3), np.float32))
    xs = np.random.default_rng(0).normal(size=(8, 3)).astype(np.float32)
    ys = np.zeros((8, 2), np.float32)
    batches = [(xs[i:i + 4], ys[i:i + 4]) for i in range(0, 8, 4)]
    trainer, state = Trainer.from_flax(m, v, loss="mse", optimizer="sgd",
                                       learning_rate=0.1)
    with Telemetry("fit") as tel:
        trainer.fit(state, batches, epochs=2, prefetch=2, sync_every=2)
    spans = tel.tracer.spans()
    by_id = _by_id(spans)
    fit = next(s for s in spans if s["name"] == "sparkdl.fit")
    assert fit["attributes"]["steps"] == 4
    epochs = [s for s in spans if s["name"] == "sparkdl.epoch"]
    assert [e["attributes"]["epoch"] for e in epochs] == [0, 1]
    for e in epochs:
        assert e["parent_id"] == fit["span_id"]
    # staging-thread spans parent under their epoch in the same trace
    driver_tid = fit["thread_id"]
    stage = [s for s in spans if s["name"] == "sparkdl.stage_batch"]
    assert len(stage) == 4
    for s in stage:
        assert by_id[s["parent_id"]]["name"] == "sparkdl.epoch"
        assert s["thread_id"] != driver_tid
        assert s["trace_id"] == tel.run_id
    steps = [s for s in spans if s["name"] == "sparkdl.train_step"]
    assert [s["attributes"]["step"] for s in steps] == [1, 2, 3, 4]
    # host step-interval histogram observed (never a device sync)
    snap = tel.metrics.snapshot()
    assert snap["histograms"][telemetry.M_STEP_TIME_S]["count"] == 3


# -- cross-process tracing + tail exemplars (ISSUE 15) ------------------------

def test_histogram_window_snapshot_at_ring_rotation_boundary(fake_clock):
    """The exact slot-rotation edge: a slot at window-age stays included
    (resolution = one slot span), one tick past it ages out, and a fresh
    observation REUSES its ring index after clearing the old exemplars —
    no ghosts from the previous revolution."""
    reg = MetricsRegistry(window_s=10.0, window_buckets=10,
                          exemplar_k=2)  # 1 s slots
    h = reg.histogram("sparkdl.executor.queue_wait_s")
    ctx_a = telemetry.SpanContext("t", 0xA)
    h.observe(0.4, exemplar=ctx_a)        # lands in slot epoch 1000
    fake_clock.advance(9.0)               # exact boundary: still inside
    w = h.window_snapshot(10.0)
    assert w["count"] == 1
    assert w["exemplars"] == [
        {"value": 0.4, "trace_id": "t", "span_id": 0xA}]
    fake_clock.advance(1.0)               # one slot past: aged out
    w = h.window_snapshot(10.0)
    assert w["count"] == 0
    assert w["exemplars"] == []           # armed: key present but empty
    # same ring index, new epoch: rotation resets counts AND exemplars
    ctx_b = telemetry.SpanContext("t", 0xB)
    h.observe(0.2, exemplar=ctx_b)
    w = h.window_snapshot(10.0)
    assert w["count"] == 1
    assert w["exemplars"] == [
        {"value": 0.2, "trace_id": "t", "span_id": 0xB}]


def test_exemplar_reservoir_keeps_topk_by_value(fake_clock):
    """k=2 reservoir: the smallest kept exemplar is evicted by a larger
    newcomer, a sub-minimum value is rejected, and the snapshot lists
    survivors descending."""
    reg = MetricsRegistry(window_s=10.0, window_buckets=10, exemplar_k=2)
    h = reg.histogram("sparkdl.executor.queue_wait_s")
    for value, span_id in ((1.0, 0xA), (3.0, 0xB), (2.0, 0xC)):
        h.observe(value, exemplar=telemetry.SpanContext("t", span_id))
    w = h.window_snapshot(10.0)
    assert w["exemplars"] == [
        {"value": 3.0, "trace_id": "t", "span_id": 0xB},
        {"value": 2.0, "trace_id": "t", "span_id": 0xC}]  # 0xA evicted
    h.observe(0.5, exemplar=telemetry.SpanContext("t", 0xD))
    assert h.window_snapshot(10.0)["exemplars"] == [
        {"value": 3.0, "trace_id": "t", "span_id": 0xB},
        {"value": 2.0, "trace_id": "t", "span_id": 0xC}]  # 0xD rejected
    # an exemplar-less observation still counts, just isn't kept
    h.observe(9.0)
    w = h.window_snapshot(10.0)
    assert w["count"] == 5 and w["max"] == 9.0
    assert w["exemplars"][0]["span_id"] == 0xB


def test_exemplars_off_keeps_window_snapshot_shape(fake_clock):
    """Unarmed (the default): passing an exemplar is inert and the
    snapshot has NO ``exemplars`` key — the pre-ISSUE-15 shape exactly."""
    reg = MetricsRegistry(window_s=10.0, window_buckets=10)
    h = reg.histogram("sparkdl.executor.queue_wait_s")
    h.observe(0.3, exemplar=telemetry.SpanContext("t", 1))
    w = h.window_snapshot(10.0)
    assert w["count"] == 1
    assert "exemplars" not in w


def test_export_ring_rebases_remaps_and_accounts_truncation():
    tr = telemetry.Tracer(trace_id="run-x")
    root = tr.span(telemetry.SPAN_RUN, parent=telemetry.ROOT)
    root.__enter__()                      # stays open, like a live scope
    t_lo = time.perf_counter_ns()
    for i in range(6):
        with tr.span(telemetry.SPAN_TASK, parent=root.context,
                     partition=i):
            pass
    t_hi = time.perf_counter_ns()
    ring = tr.export_ring(clock_offset_ns=1_000_000, process="w0",
                          parent_remap={root.context.span_id: 0xC0DE},
                          limit=4)
    assert ring["clock_offset_ns"] == 1_000_000
    assert ring["dropped"] == 2           # truncation is never silent
    assert len(ring["spans"]) == 4
    # the most recent spans are the ones kept (traces want the tail)
    assert [s["attributes"]["partition"] for s in ring["spans"]] == \
        [2, 3, 4, 5]
    for s in ring["spans"]:
        assert s["pid"] == os.getpid()
        assert s["process"] == "w0"
        assert s["parent_id"] == 0xC0DE   # re-parented off the open root
        # rebased to ABSOLUTE parent-clock time: local clock + offset
        assert t_lo + 1_000_000 <= s["start_ns"] <= s["end_ns"] \
            <= t_hi + 1_000_000
    # the exporter's own ring is untouched by building the shipped view
    assert len(tr.spans(telemetry.SPAN_TASK)) == 6


def test_adopt_remote_spans_rebases_and_rejects_noncanonical():
    worker = telemetry.Tracer(trace_id="run-x")
    for _ in range(3):
        with worker.span(telemetry.SPAN_CLUSTER_TASK, parent=None):
            pass
    ring = worker.export_ring(process="w1")
    bad = dict(ring["spans"][0], name="sparkdl.decode_chunkk")
    coord = telemetry.Tracer(trace_id="run-x")
    adopted, rejected = coord.adopt_remote_spans(ring["spans"] + [bad])
    assert (adopted, rejected) == (3, 1)
    got = coord.spans(telemetry.SPAN_CLUSTER_TASK)
    assert len(got) == 3
    for s in got:
        assert s["process"] == "w1"       # keeps its origin labeling
        assert s["end_ns"] >= s["start_ns"]
    summ = coord.summary()
    assert summ["remote_adopted"] == 3
    assert summ["remote_rejected"] == 1
    assert summ["spans_recorded"] == 3    # the bad record never landed


def test_record_remote_allocates_ids_and_rejects_noncanonical():
    tr = telemetry.Tracer(trace_id="run-x")
    parent = telemetry.SpanContext("run-x", 0x77)
    t0 = time.perf_counter_ns()
    assert tr.record_remote(telemetry.SPAN_DECODE_CHUNK, parent,
                            t0, t0 + 5_000_000, pid=12345,
                            process="decode-12345", blobs=3) is True
    (s,) = tr.spans(telemetry.SPAN_DECODE_CHUNK)
    assert s["parent_id"] == 0x77 and s["trace_id"] == "run-x"
    assert s["pid"] == 12345 and s["process"] == "decode-12345"
    assert s["thread_id"] == 0 and s["thread_name"] == "decode-12345"
    assert s["attributes"] == {"blobs": 3}
    assert s["span_id"] != 0x77           # allocated HERE, pid-salted
    assert s["span_id"] >> 40 == os.getpid()
    # non-canonical: rejected + counted, never raised (runtime path)
    assert tr.record_remote("sparkdl.decode_chunkk", parent,
                            t0, t0, pid=1) is False
    assert tr.summary()["remote_rejected"] == 1


def test_remote_span_wire_record_requires_canonical_name():
    rec = telemetry.remote_span(telemetry.SPAN_DECODE_CHUNK,
                                100, 200, pid=7, blobs=2)
    assert rec == {"name": telemetry.SPAN_DECODE_CHUNK,
                   "start_ns": 100, "end_ns": 200, "pid": 7,
                   "attributes": {"blobs": 2}}
    assert telemetry.remote_span(telemetry.SPAN_DECODE_CHUNK, 1, 2
                                 )["pid"] == os.getpid()
    with pytest.raises(ValueError, match="canonical"):
        telemetry.remote_span("sparkdl.decode_chunkk", 0, 1)


def test_clock_handshake_over_a_pipe():
    import multiprocessing as mp

    parent, child = mp.get_context("spawn").Pipe()
    try:
        def _answer():
            tag, t0 = parent.recv()
            assert tag == "clock"
            assert isinstance(t0, int)
            parent.send(time.perf_counter_ns())

        t = threading.Thread(target=_answer)
        t.start()
        offset = telemetry.clock_handshake(child)
        t.join()
        # same process, same CLOCK_MONOTONIC: the estimated offset is
        # bounded by the pipe round-trip (generous CI slack)
        assert abs(offset) < 100_000_000
    finally:
        parent.close()
        child.close()
    # a dead peer (or one that never answers) degrades to 0, not a hang
    a, b = mp.get_context("spawn").Pipe()
    a.close()
    assert telemetry.clock_handshake(b, timeout_s=0.1) == 0
    b.close()


def test_chrome_trace_process_groups_only_after_remote_merge():
    tr = telemetry.Tracer(trace_id="run-x")
    with tr.span(telemetry.SPAN_TASK):
        pass
    # purely local: NO process_name metadata — the pre-merge shape
    events = tr.chrome_trace()["traceEvents"]
    assert not any(e["name"] == "process_name" for e in events)
    tr.record_remote(telemetry.SPAN_DECODE_CHUNK,
                     telemetry.SpanContext("run-x", 1), 0, 10,
                     pid=424242, process="decode-424242")
    events = tr.chrome_trace()["traceEvents"]
    groups = {e["pid"]: e["args"]["name"] for e in events
              if e["ph"] == "M" and e["name"] == "process_name"}
    assert groups == {os.getpid(): "coordinator",
                      424242: "decode-424242"}
