"""ModelFunction tests, incl. the ingestion format-matrix (SURVEY.md §4):
one tiny model exported every way, identical results through each ctor."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkdl_tpu.core import (
    MeshConfig, ModelFunction, TensorSpec, make_mesh,
)


class TinyNet(nn.Module):
    features: int = 5

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(8)(x)
        x = nn.relu(x)
        return nn.Dense(self.features)(x)


@pytest.fixture(scope="module")
def tiny():
    module = TinyNet()
    spec = TensorSpec((None, 3))
    variables = module.init(jax.random.PRNGKey(0),
                            jnp.zeros(spec.with_batch(1)))
    mf = ModelFunction.fromFlax(module, variables, spec)
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (7, 3)))
    expected = np.asarray(module.apply(variables, x))
    return module, spec, variables, mf, x, expected


def test_from_flax_matches_direct_apply(tiny):
    _, _, _, mf, x, expected = tiny
    np.testing.assert_allclose(np.asarray(mf(x)), expected, rtol=1e-6)


def test_apply_batch_pads_and_unpads(tiny):
    _, _, _, mf, x, expected = tiny
    out = mf.apply_batch(x, batch_size=4)  # 7 rows -> chunks 4 + 3(padded)
    assert out.shape == expected.shape
    np.testing.assert_allclose(out, expected, rtol=1e-5)


def test_format_matrix_equivalence(tiny, tmp_path):
    """The TFInputGraph ctor-matrix test: every ingestion route agrees."""
    module, spec, variables, mf, x, expected = tiny

    routes = {}
    # fromFunction
    routes["function"] = ModelFunction.fromFunction(
        lambda vs, a: module.apply(vs, a), variables, spec)
    # fromMsgpack
    mp = tmp_path / "weights.msgpack"
    mf.toMsgpack(str(mp))
    routes["msgpack"] = ModelFunction.fromMsgpack(str(mp), module, spec)
    # fromOrbax
    od = tmp_path / "orbax_ckpt"
    mf.toOrbax(str(od))
    routes["orbax"] = ModelFunction.fromOrbax(str(od), module, spec)
    # fromJaxExport (symbolic batch dim)
    blob = mf.toJaxExport()
    routes["export"] = ModelFunction.fromJaxExport(blob)
    # fromJaxExport via file, fixed batch
    ep = tmp_path / "model.stablehlo"
    mf.toJaxExport(str(ep), batch_size=7)
    routes["export_file"] = ModelFunction.fromJaxExport(str(ep))

    for name, route in routes.items():
        out = np.asarray(route(x))
        np.testing.assert_allclose(out, expected, rtol=1e-5,
                                   err_msg=f"route {name} diverged")


def test_export_symbolic_batch_runs_any_size(tiny):
    _, _, _, mf, _, _ = tiny
    exported = ModelFunction.fromJaxExport(mf.toJaxExport())
    assert exported.input_spec.shape[0] is None
    for n in (1, 5, 16):
        out = exported(np.zeros((n, 3), np.float32))
        assert np.asarray(out).shape == (n, 5)


def test_composition_fuses(tiny):
    _, _, _, mf, x, expected = tiny
    composed = (mf.with_preprocess(lambda a: a * 2.0)
                  .with_postprocess(lambda y: y + 1.0))
    out = np.asarray(composed(x / 2.0))
    np.testing.assert_allclose(out, expected + 1.0, rtol=1e-5)


def test_flattened(tiny):
    module, spec, variables, mf, x, _ = tiny
    out = mf.flattened()(x)
    assert out.ndim == 2


def test_mesh_sharded_apply(tiny):
    _, _, _, mf, x, expected = tiny
    mesh = make_mesh(MeshConfig(data=8))
    out = mf.apply_batch(x, batch_size=8, mesh=mesh)
    np.testing.assert_allclose(out, expected, rtol=1e-5)


def test_jit_cache_reused(tiny):
    _, _, _, mf, x, _ = tiny
    f1 = mf.jitted()
    f2 = mf.jitted()
    assert f1 is f2


def test_first_launch_records_compile_span_once_per_shape():
    """ISSUE 5 satellite: the first dispatch of each new input shape is
    wrapped in a `sparkdl.compile` span (bucket-ladder compile storms are
    visible in the run report); repeat dispatches at a seen shape are not."""
    from sparkdl_tpu.core import telemetry
    from sparkdl_tpu.core.telemetry import Telemetry

    mf = ModelFunction(lambda vs, x: x * vs, jnp.asarray(2.0),
                       TensorSpec((None, 3)), name="compile_span")
    with Telemetry() as tel:
        mf.apply_batch(np.ones((4, 3), np.float32), batch_size=8)
        mf.apply_batch(np.ones((4, 3), np.float32), batch_size=8)
        mf.apply_batch(np.ones((12, 3), np.float32), batch_size=8)
    compiles = tel.tracer.spans(telemetry.SPAN_COMPILE)
    # bucket 8 compiles once (second call is a repeat); the 12-row call
    # adds buckets 8 (seen) + the tail bucket only if it differs — with
    # batch_size 8 the chunks are 8 and a 4-row tail at bucket 8, both
    # seen, so exactly ONE compile span total
    assert len(compiles) == 1
    assert compiles[0]["attributes"]["model"] == "compile_span"


def test_compile_cache_env_configures_jax(tmp_path, monkeypatch):
    """ISSUE 5 satellite: SPARKDL_COMPILE_CACHE_DIR wires jax's persistent
    compilation cache at package init."""
    import sparkdl_tpu

    prev = jax.config.jax_compilation_cache_dir
    try:
        monkeypatch.delenv(sparkdl_tpu.COMPILE_CACHE_DIR_ENV, raising=False)
        assert sparkdl_tpu._configure_compile_cache() is False  # unset: no-op
        target = str(tmp_path / "xla_cache")
        monkeypatch.setenv(sparkdl_tpu.COMPILE_CACHE_DIR_ENV, target)
        assert sparkdl_tpu._configure_compile_cache() is True
        assert jax.config.jax_compilation_cache_dir == target
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
