"""ModelFunction tests, incl. the ingestion format-matrix (SURVEY.md §4):
one tiny model exported every way, identical results through each ctor."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkdl_tpu.core import (
    MeshConfig, ModelFunction, TensorSpec, make_mesh,
)


class TinyNet(nn.Module):
    features: int = 5

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(8)(x)
        x = nn.relu(x)
        return nn.Dense(self.features)(x)


@pytest.fixture(scope="module")
def tiny():
    module = TinyNet()
    spec = TensorSpec((None, 3))
    variables = module.init(jax.random.PRNGKey(0),
                            jnp.zeros(spec.with_batch(1)))
    mf = ModelFunction.fromFlax(module, variables, spec)
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (7, 3)))
    expected = np.asarray(module.apply(variables, x))
    return module, spec, variables, mf, x, expected


def test_from_flax_matches_direct_apply(tiny):
    _, _, _, mf, x, expected = tiny
    np.testing.assert_allclose(np.asarray(mf(x)), expected, rtol=1e-6)


def test_apply_batch_pads_and_unpads(tiny):
    _, _, _, mf, x, expected = tiny
    out = mf.apply_batch(x, batch_size=4)  # 7 rows -> chunks 4 + 3(padded)
    assert out.shape == expected.shape
    np.testing.assert_allclose(out, expected, rtol=1e-5)


def test_format_matrix_equivalence(tiny, tmp_path):
    """The TFInputGraph ctor-matrix test: every ingestion route agrees."""
    module, spec, variables, mf, x, expected = tiny

    routes = {}
    # fromFunction
    routes["function"] = ModelFunction.fromFunction(
        lambda vs, a: module.apply(vs, a), variables, spec)
    # fromMsgpack
    mp = tmp_path / "weights.msgpack"
    mf.toMsgpack(str(mp))
    routes["msgpack"] = ModelFunction.fromMsgpack(str(mp), module, spec)
    # fromOrbax
    od = tmp_path / "orbax_ckpt"
    mf.toOrbax(str(od))
    routes["orbax"] = ModelFunction.fromOrbax(str(od), module, spec)
    # fromJaxExport (symbolic batch dim)
    blob = mf.toJaxExport()
    routes["export"] = ModelFunction.fromJaxExport(blob)
    # fromJaxExport via file, fixed batch
    ep = tmp_path / "model.stablehlo"
    mf.toJaxExport(str(ep), batch_size=7)
    routes["export_file"] = ModelFunction.fromJaxExport(str(ep))

    for name, route in routes.items():
        out = np.asarray(route(x))
        np.testing.assert_allclose(out, expected, rtol=1e-5,
                                   err_msg=f"route {name} diverged")


def test_export_symbolic_batch_runs_any_size(tiny):
    _, _, _, mf, _, _ = tiny
    exported = ModelFunction.fromJaxExport(mf.toJaxExport())
    assert exported.input_spec.shape[0] is None
    for n in (1, 5, 16):
        out = exported(np.zeros((n, 3), np.float32))
        assert np.asarray(out).shape == (n, 5)


def test_composition_fuses(tiny):
    _, _, _, mf, x, expected = tiny
    composed = (mf.with_preprocess(lambda a: a * 2.0)
                  .with_postprocess(lambda y: y + 1.0))
    out = np.asarray(composed(x / 2.0))
    np.testing.assert_allclose(out, expected + 1.0, rtol=1e-5)


def test_flattened(tiny):
    module, spec, variables, mf, x, _ = tiny
    out = mf.flattened()(x)
    assert out.ndim == 2


def test_mesh_sharded_apply(tiny):
    _, _, _, mf, x, expected = tiny
    mesh = make_mesh(MeshConfig(data=8))
    out = mf.apply_batch(x, batch_size=8, mesh=mesh)
    np.testing.assert_allclose(out, expected, rtol=1e-5)


def test_jit_cache_reused(tiny):
    _, _, _, mf, x, _ = tiny
    f1 = mf.jitted()
    f2 = mf.jitted()
    assert f1 is f2
