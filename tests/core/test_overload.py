"""Overload protection for the device execution service (ISSUE 6
tentpole, core/executor.py): admission control (block vs shed),
deadline-aware shedding, priority lanes, the per-model circuit breaker,
read-time EngineConfig validation, and shutdown/reset idempotency."""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkdl_tpu.core import executor, health, resilience, telemetry
from sparkdl_tpu.core.executor import (
    ExecutorCircuitOpen,
    ExecutorOverloaded,
    ExecutorShutdown,
    deadline_scope,
    task_scope,
)
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.core.resilience import Deadline, RetryPolicy
from sparkdl_tpu.core.telemetry import Telemetry
from sparkdl_tpu.engine.dataframe import EngineConfig
from sparkdl_tpu.engine.supervisor import run_partition_task

_ELEMENT = (6,)
_FEATURES = 3


@pytest.fixture(autouse=True)
def _fresh_executor_and_config():
    """Each test gets its own service instance and a full EngineConfig
    snapshot/restore (every public knob, so new overload knobs are
    covered without listing them)."""
    saved = EngineConfig.snapshot()
    executor.reset()
    yield
    executor.reset()
    EngineConfig.restore(saved)


def _model(name="overload_model", sleep_s=0.0, fail_flag=None):
    """Row-wise model; ``sleep_s`` injects host time at execution (via
    pure_callback) so a launch can be held in flight deterministically;
    ``fail_flag`` (a mutable [bool]) makes execution fail FATALLY while
    set — and heal when cleared — without recompiling."""
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(_ELEMENT[0], _FEATURES))
                    .astype(np.float32))

    def apply_fn(vs, x):
        if sleep_s or fail_flag is not None:
            def host_hook(a):
                if sleep_s:
                    time.sleep(sleep_s)
                if fail_flag is not None and fail_flag[0]:
                    raise ValueError(
                        "INVALID_ARGUMENT: deliberate terminal failure")
                return a
            x = jax.pure_callback(
                host_hook, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return jnp.tanh(x @ vs)

    return ModelFunction(apply_fn, w, TensorSpec((None,) + _ELEMENT,
                                                 "float32"), name=name)


def _rows(n, seed=1):
    return np.random.default_rng(seed).normal(
        size=(n,) + _ELEMENT).astype(np.float32)


def _record_apply_threads(mf):
    """Instrument apply_batch to record which thread ran it (and with
    which input object), returning (log, original_apply)."""
    log = []
    orig = mf.apply_batch

    def recording(tree, *args, **kwargs):
        log.append((threading.current_thread().name, id(tree)))
        return orig(tree, *args, **kwargs)

    mf.apply_batch = recording
    return log, orig


# ---------------------------------------------------------------------------
# Admission control: shed mode
# ---------------------------------------------------------------------------


def test_shed_mode_fails_fast_and_accounts_exactly():
    """Over the queue bound in shed mode: the overflow request raises
    ExecutorOverloaded (classified RETRYABLE) without queueing; every
    shed is one EXECUTOR_SHED health event, and the shed-rate and
    queue-depth gauges are live."""
    mf = _model(sleep_s=0.3)
    EngineConfig.coalesce_window_ms = 30_000.0  # park queued requests
    EngineConfig.executor_max_queued_requests = 1
    EngineConfig.executor_overload_mode = "shed"
    outcome = {}

    def busy():
        outcome["busy"] = executor.execute(mf, _rows(2, seed=0),
                                           batch_size=32)

    def queued(name):
        try:
            outcome[name] = executor.execute(mf, _rows(3, seed=1),
                                             batch_size=32)
        except BaseException as e:  # noqa: BLE001 - asserted below
            outcome[name + "_error"] = e

    with HealthMonitor() as mon, Telemetry() as tel:
        t_busy = threading.Thread(target=busy)
        t_busy.start()
        time.sleep(0.1)  # inline launch in flight
        t_a = threading.Thread(target=queued, args=("a",))
        t_a.start()
        time.sleep(0.05)  # a queued; queue is now at the bound
        t_b = threading.Thread(target=queued, args=("b",))
        t_b.start()
        t_b.join(timeout=5.0)
        assert not t_b.is_alive()
        # b was shed immediately — a is still parked in the window
        err = outcome.get("b_error")
        assert isinstance(err, ExecutorOverloaded)
        assert resilience.classify(err) == resilience.RETRYABLE
        executor.shutdown()  # release a from the parked window
        t_a.join(timeout=5.0)
        t_busy.join(timeout=5.0)
    assert isinstance(outcome.get("a_error"), ExecutorShutdown)
    assert mon.count(health.EXECUTOR_SHED) == 1
    snap = tel.metrics.snapshot()
    assert snap["counters"]["sparkdl.health." + health.EXECUTOR_SHED] == 1
    # 1 shed of 3 submits seen by bounded admission (busy inline + a + b)
    assert snap["gauges"][telemetry.M_EXECUTOR_SHED_RATE] == \
        pytest.approx(1 / 3)
    assert telemetry.M_EXECUTOR_QUEUE_DEPTH in snap["gauges"]


def test_queued_rows_bound_sheds_but_empty_queue_always_admits():
    mf = _model(sleep_s=0.25)
    EngineConfig.coalesce_window_ms = 30_000.0
    EngineConfig.executor_max_queued_rows = 4
    EngineConfig.executor_overload_mode = "shed"
    outcome = {}

    def run(name, n, seed):
        try:
            outcome[name] = executor.execute(mf, _rows(n, seed=seed),
                                             batch_size=32)
        except BaseException as e:  # noqa: BLE001 - asserted below
            outcome[name + "_error"] = e

    t_busy = threading.Thread(target=run, args=("busy", 2, 0))
    t_busy.start()
    time.sleep(0.08)
    # 6 rows > the 4-row bound, but the queue is EMPTY: always admitted
    # (a bound smaller than one request must not wedge)
    t_big = threading.Thread(target=run, args=("big", 6, 1))
    t_big.start()
    time.sleep(0.05)
    # now 6 rows are queued: any further queued rows exceed the bound
    t_over = threading.Thread(target=run, args=("over", 2, 2))
    t_over.start()
    t_over.join(timeout=5.0)
    assert isinstance(outcome.get("over_error"), ExecutorOverloaded)
    executor.shutdown()
    t_big.join(timeout=5.0)
    t_busy.join(timeout=5.0)
    assert isinstance(outcome.get("big_error"), ExecutorShutdown)


# ---------------------------------------------------------------------------
# Admission control: block (backpressure) mode
# ---------------------------------------------------------------------------


def test_block_mode_waits_for_room_and_completes():
    """Default overload mode: a submit over the bound BLOCKS until the
    coalescer drains the queue, then completes normally — backpressure,
    not failure."""
    mf = _model(sleep_s=0.1)
    EngineConfig.coalesce_window_ms = 50.0
    EngineConfig.executor_max_queued_requests = 1
    assert EngineConfig.executor_overload_mode == "block"  # the default
    inputs = [_rows(3, seed=i) for i in range(4)]
    expected = [mf.apply_batch(x, batch_size=32) for x in inputs]
    results = [None] * 4
    errors = [None] * 4
    barrier = threading.Barrier(4)

    def work(i):
        try:
            barrier.wait()
            results[i] = executor.execute(mf, inputs[i], batch_size=32)
        except BaseException as e:  # noqa: BLE001 - asserted below
            errors[i] = e

    threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20.0)
    assert not any(t.is_alive() for t in threads)
    assert errors == [None] * 4
    for got, want in zip(results, expected):
        np.testing.assert_array_equal(got, want)


def test_block_mode_backpressure_is_bounded_by_the_deadline():
    mf = _model(sleep_s=0.4)
    EngineConfig.coalesce_window_ms = 30_000.0  # nothing drains
    EngineConfig.executor_max_queued_requests = 1
    outcome = {}

    def run(name, seed, deadline=None):
        try:
            outcome[name] = executor.execute(mf, _rows(2, seed=seed),
                                             batch_size=32,
                                             deadline=deadline)
        except BaseException as e:  # noqa: BLE001 - asserted below
            outcome[name + "_error"] = e

    with HealthMonitor() as mon:
        t_busy = threading.Thread(target=run, args=("busy", 0))
        t_busy.start()
        time.sleep(0.1)
        t_a = threading.Thread(target=run, args=("a", 1))
        t_a.start()
        time.sleep(0.05)  # queue full; b must block...
        t0 = time.monotonic()
        t_b = threading.Thread(target=run, args=("b", 2, Deadline(0.25)))
        t_b.start()
        t_b.join(timeout=5.0)
        waited = time.monotonic() - t0
        assert not t_b.is_alive()
        err = outcome.get("b_error")
        assert isinstance(err, resilience.DeadlineExceeded)
        assert 0.15 < waited < 2.0  # blocked ~the deadline, not forever
        executor.shutdown()
        t_a.join(timeout=5.0)
        t_busy.join(timeout=5.0)
    assert mon.count(health.EXECUTOR_DEADLINE_SHED) == 1


# ---------------------------------------------------------------------------
# Deadline propagation: drop expired requests before paying for a launch
# ---------------------------------------------------------------------------


def test_expired_request_is_dropped_at_drain_time_without_a_launch():
    mf = _model(sleep_s=0.2)
    EngineConfig.coalesce_window_ms = 400.0
    apply_log, orig_apply = _record_apply_threads(mf)
    outcome = {}

    def busy():
        outcome["busy"] = executor.execute(mf, _rows(2, seed=0),
                                           batch_size=32)

    def doomed():
        t0 = time.monotonic()
        try:
            # expires while queued (the window is 400 ms, the budget 80)
            outcome["doomed"] = executor.execute(
                mf, _rows(3, seed=1), batch_size=32,
                deadline=Deadline(0.08))
        except BaseException as e:  # noqa: BLE001 - asserted below
            outcome["doomed_error"] = e
        outcome["doomed_s"] = time.monotonic() - t0

    with HealthMonitor() as mon:
        t_busy = threading.Thread(target=busy)
        t_busy.start()
        time.sleep(0.05)  # inline launch in flight
        t_d = threading.Thread(target=doomed)
        t_d.start()
        t_d.join(timeout=5.0)
        t_busy.join(timeout=5.0)
    err = outcome.get("doomed_error")
    assert isinstance(err, resilience.DeadlineExceeded)
    # and PROMPTLY: the queued deadline caps the coalescer's window wait,
    # so the caller fails at ~its 80 ms budget, not after the 400 ms
    # window (margin for CI scheduling jitter)
    assert outcome["doomed_s"] < 0.3, outcome["doomed_s"]
    assert mon.count(health.EXECUTOR_DEADLINE_SHED) == 1
    # the doomed request never paid for a launch: apply_batch ran only
    # for the busy inline request
    assert len(apply_log) == 1
    np.testing.assert_array_equal(outcome["busy"],
                                  orig_apply(_rows(2, seed=0),
                                             batch_size=32))


def test_already_expired_deadline_is_rejected_before_queueing():
    mf = _model(sleep_s=0.1)
    EngineConfig.coalesce_window_ms = 100.0
    dead = Deadline(0.0)
    time.sleep(0.01)
    # force the queued path (not inline) by keeping the state busy
    t_busy = threading.Thread(
        target=lambda: executor.execute(mf, _rows(2, seed=0),
                                        batch_size=32))
    t_busy.start()
    time.sleep(0.04)
    with HealthMonitor() as mon:
        with pytest.raises(resilience.DeadlineExceeded):
            executor.execute(mf, _rows(3, seed=1), batch_size=32,
                             deadline=dead)
    t_busy.join(timeout=5.0)
    assert mon.count(health.EXECUTOR_DEADLINE_SHED) == 1


def test_run_partition_task_threads_its_deadline_into_the_executor():
    """The supervisor's per-task Deadline rides into executor calls
    ambiently (deadline_scope), and Deadline(None) is NOT threaded —
    the unloaded hot path stays free of expiry checks."""
    seen = {}

    def op(batch):
        seen["deadline"] = executor.current_deadline()
        return batch

    fast = RetryPolicy(max_retries=0, base_delay_s=0.0, jitter=0.0)
    run_partition_task(0, "x", [op], policy=fast, deadline_s=5.0)
    assert seen["deadline"] is not None
    assert seen["deadline"].timeout_s == 5.0
    assert seen["deadline"].remaining() <= 5.0
    run_partition_task(0, "x", [op], policy=fast, deadline_s=None)
    assert seen["deadline"] is None
    assert executor.current_deadline() is None  # scope restored


# ---------------------------------------------------------------------------
# Priority lanes
# ---------------------------------------------------------------------------


def test_interactive_lane_drains_before_earlier_bulk_requests():
    """Three requests queue behind a busy launch: interactive arrives
    LAST but is drained into the first coalesced launch; the overflowing
    request (bulk, by lane order) replays alone in the next round. Had
    the drain been FIFO, the two bulk requests would have coalesced and
    the interactive one would have replayed."""
    mf = _model(sleep_s=0.25)
    EngineConfig.coalesce_window_ms = 400.0
    # cap 7: the window does NOT fill at the two bulk requests (6 rows),
    # so the late interactive arrival is present at drain time — and the
    # drain then fits exactly two of the three 3-row requests
    EngineConfig.coalesce_max_rows = 7
    apply_log, orig_apply = _record_apply_threads(mf)
    inputs = {"bulk1": _rows(3, seed=1), "bulk2": _rows(3, seed=2),
              "inter": _rows(3, seed=3)}
    expected = {k: orig_apply(v, batch_size=32)
                for k, v in inputs.items()}
    outcome = {}
    errors = []

    def run(name, priority):
        try:
            outcome[name] = executor.execute(mf, inputs[name],
                                             batch_size=32,
                                             priority=priority)
        except BaseException as e:  # noqa: BLE001
            errors.append((name, e))

    t_busy = threading.Thread(
        target=lambda: executor.execute(mf, _rows(2, seed=0),
                                        batch_size=32),
        name="requester-busy")
    t_busy.start()
    time.sleep(0.08)  # inline launch in flight
    threads = []
    for name, prio, delay in (("bulk1", "bulk", 0.0),
                              ("bulk2", "bulk", 0.04),
                              ("inter", "interactive", 0.08)):
        time.sleep(delay and 0.04)
        t = threading.Thread(target=run, args=(name, prio),
                             name=f"requester-{name}")
        t.start()
        threads.append(t)
    for t in threads + [t_busy]:
        t.join(timeout=10.0)
    assert not errors, errors
    for name, want in expected.items():
        np.testing.assert_array_equal(outcome[name], want)
    # interactive + bulk1 went up in the coalesced launch; only the busy
    # inline request and the displaced-to-next-round bulk2 ran through
    # apply_batch on their own threads
    replay_threads = {name for name, _ in apply_log}
    assert replay_threads == {"requester-busy", "requester-bulk2"}


def test_shed_mode_interactive_displaces_newest_queued_bulk():
    mf = _model(sleep_s=0.3)
    EngineConfig.coalesce_window_ms = 30_000.0
    EngineConfig.executor_max_queued_requests = 1
    EngineConfig.executor_overload_mode = "shed"
    outcome = {}

    def run(name, priority, seed):
        try:
            outcome[name] = executor.execute(mf, _rows(3, seed=seed),
                                             batch_size=32,
                                             priority=priority)
        except BaseException as e:  # noqa: BLE001 - asserted below
            outcome[name + "_error"] = e

    with HealthMonitor() as mon:
        t_busy = threading.Thread(target=run, args=("busy", "bulk", 0))
        t_busy.start()
        time.sleep(0.1)
        t_bulk = threading.Thread(target=run, args=("bulk", "bulk", 1))
        t_bulk.start()
        time.sleep(0.05)  # bulk queued; queue at the bound
        t_inter = threading.Thread(target=run,
                                   args=("inter", "interactive", 2))
        t_inter.start()
        # the bulk request is displaced IMMEDIATELY (not at drain time)
        t_bulk.join(timeout=5.0)
        assert not t_bulk.is_alive()
        err = outcome.get("bulk_error")
        assert isinstance(err, ExecutorOverloaded)
        assert "displaced" in str(err)
        executor.shutdown()  # release the interactive request (parked)
        t_inter.join(timeout=5.0)
        t_busy.join(timeout=5.0)
    # the interactive request took the queue slot (it was parked in the
    # 30s window until shutdown, proving it was queued, not shed)
    assert isinstance(outcome.get("inter_error"), ExecutorShutdown)
    sheds = mon.events(health.EXECUTOR_SHED)
    assert len(sheds) == 1 and sheds[0]["reason"] == "displaced"


# ---------------------------------------------------------------------------
# Per-model circuit breaker
# ---------------------------------------------------------------------------


def test_breaker_trips_fails_fast_probes_and_recovers():
    fail = [True]
    mf = _model(name="breaker_model", fail_flag=fail)
    EngineConfig.executor_breaker_threshold = 2
    EngineConfig.executor_breaker_window_s = 30.0
    EngineConfig.executor_breaker_cooldown_s = 0.15
    x = _rows(3, seed=1)
    calls = []
    orig = mf.apply_batch

    def counting(tree, *args, **kwargs):
        calls.append(1)
        return orig(tree, *args, **kwargs)

    mf.apply_batch = counting
    with HealthMonitor() as mon:
        # two terminal (FATAL) launch failures within the window trip it
        for _ in range(2):
            with pytest.raises(Exception) as ei:
                executor.execute(mf, x, batch_size=32)
            assert resilience.classify(ei.value) == resilience.FATAL
        assert mon.count(health.BREAKER_OPEN) == 1
        assert len(calls) == 2
        # open: fail fast WITHOUT touching the model or the queue
        with pytest.raises(ExecutorCircuitOpen) as ei:
            executor.execute(mf, x, batch_size=32)
        assert resilience.classify(ei.value) == resilience.RETRYABLE
        assert len(calls) == 2  # the fast-fail never reached the model
        # model heals; after the cooldown one half-open probe goes
        # through and recovery reopens traffic
        fail[0] = False
        time.sleep(0.2)
        out = executor.execute(mf, x, batch_size=32)
        np.testing.assert_array_equal(out, orig(x, batch_size=32))
        assert mon.count(health.BREAKER_PROBE) == 1
        assert mon.count(health.BREAKER_CLOSED) == 1
        # traffic flows again, no fast-fails
        np.testing.assert_array_equal(
            executor.execute(mf, x, batch_size=32),
            orig(x, batch_size=32))
    assert mon.count(health.BREAKER_OPEN) == 1


def test_breaker_failed_probe_reopens():
    fail = [True]
    mf = _model(name="breaker_reopen", fail_flag=fail)
    EngineConfig.executor_breaker_threshold = 1
    EngineConfig.executor_breaker_cooldown_s = 0.1
    x = _rows(2, seed=1)
    with HealthMonitor() as mon:
        with pytest.raises(Exception):
            executor.execute(mf, x, batch_size=32)
        assert mon.count(health.BREAKER_OPEN) == 1
        time.sleep(0.15)
        # the probe itself fails: breaker re-opens (probe=True trip)
        with pytest.raises(Exception) as ei:
            executor.execute(mf, x, batch_size=32)
        assert not isinstance(ei.value, ExecutorCircuitOpen)
        assert mon.count(health.BREAKER_PROBE) == 1
        assert mon.count(health.BREAKER_OPEN) == 2
        # and fails fast again while re-opened
        with pytest.raises(ExecutorCircuitOpen):
            executor.execute(mf, x, batch_size=32)
    assert mon.count(health.BREAKER_CLOSED) == 0


def test_probe_dying_in_queue_releases_the_probe_slot():
    """Regression: a half-open probe that EXPIRES in the queue — it never
    reached the device — must return the breaker to
    half-open-with-no-probe so the NEXT arrival probes, instead of
    wedging every future submit on 'probe in flight' forever."""
    def hooked(vs, x):
        def host_hook(a):
            if a[0, 0] >= 900.0:
                time.sleep(0.8)        # a launch held in flight
            if a[0, 0] <= -900.0:
                raise ValueError("INVALID_ARGUMENT: poisoned input")
            return a
        x = jax.pure_callback(host_hook,
                              jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(size=(_ELEMENT[0], _FEATURES))
                        .astype(np.float32))
        return jnp.tanh(x @ w)

    mf = ModelFunction(hooked, jnp.zeros(()),
                       TensorSpec((None,) + _ELEMENT, "float32"),
                       name="probe_wedge")
    EngineConfig.executor_breaker_threshold = 1
    EngineConfig.executor_breaker_cooldown_s = 0.05
    EngineConfig.coalesce_window_ms = 150.0
    ok = _rows(2, seed=1)
    bad = ok.copy()
    bad[0, 0] = -999.0
    slow = ok.copy()
    slow[0, 0] = 999.0
    with HealthMonitor() as mon:
        with pytest.raises(Exception) as ei:
            executor.execute(mf, bad, batch_size=32)  # inline FATAL: trip
        assert resilience.classify(ei.value) == resilience.FATAL
        assert mon.count(health.BREAKER_OPEN) == 1
        time.sleep(0.1)  # past the cooldown
        # hold a launch in flight WITHOUT consuming the probe slot (the
        # breaker knobs are per-submit snapshots: this submit opts out)
        EngineConfig.executor_breaker_threshold = 0
        busy = threading.Thread(target=lambda: executor.execute(
            mf, slow, batch_size=32))
        busy.start()
        time.sleep(0.1)  # the inline launch is in flight
        EngineConfig.executor_breaker_threshold = 1
        # probe #1: admitted half-open, QUEUED behind the busy launch,
        # and expires in the queue before the window drains
        with pytest.raises(resilience.DeadlineExceeded):
            executor.execute(mf, ok, batch_size=32,
                             deadline=Deadline(0.03))
        assert mon.count(health.BREAKER_PROBE) == 1
        # the slot was released: the next arrival is probe #2 (it would
        # raise ExecutorCircuitOpen 'probe in flight' if wedged), and its
        # success closes the breaker
        out = executor.execute(mf, ok, batch_size=32)
        np.testing.assert_array_equal(out, mf.apply_batch(ok,
                                                          batch_size=32))
        busy.join(timeout=5.0)
        assert not busy.is_alive()
    assert mon.count(health.BREAKER_PROBE) == 2
    assert mon.count(health.BREAKER_CLOSED) == 1
    assert mon.count(health.EXECUTOR_DEADLINE_SHED) == 1


def test_stale_nonprobe_outcome_does_not_decide_half_open_probe():
    """Regression: a pre-trip launch resolving DURING half-open must not
    close or reopen the breaker — 'exactly one probe; ITS outcome
    decides'. A stale failure only joins the rolling window."""
    mf = _model(name="stale_halfopen")
    EngineConfig.executor_breaker_threshold = 1
    executor.execute(mf, _rows(2), batch_size=16)  # prime the fn state
    svc = executor.service()
    state = next(iter(svc._states.values()))
    with state.cond:
        state.breaker_state = "half_open"
        state.breaker_probe_inflight = True
    with HealthMonitor() as mon:
        svc._breaker_note(state, None)  # stale success: ignored
        assert state.breaker_state == "half_open"
        assert state.breaker_probe_inflight
        svc._breaker_note(state, RuntimeError("stale launch failure"))
        assert state.breaker_state == "half_open"
        assert state.breaker_probe_inflight
        svc._breaker_note(state, None, is_probe=True)  # the probe decides
        assert state.breaker_state == "closed"
        assert not state.breaker_probe_inflight
    assert mon.count(health.BREAKER_CLOSED) == 1
    assert mon.count(health.BREAKER_OPEN) == 0


def test_hedge_dedup_adopts_the_latest_deadline():
    """Regression: a hedge deduping onto its sibling's QUEUED request
    must not inherit the primary's nearly-expired deadline — the shared
    request lives as long as the latest waiter's budget, so the hedge
    can still rescue a straggling primary instead of dying with it."""
    mf = _model(sleep_s=0.15)
    EngineConfig.coalesce_window_ms = 300.0
    token = ("hedged-task", 7)
    x = _rows(3, seed=1)
    outcome = {}

    def busy():
        outcome["busy"] = executor.execute(mf, _rows(2, seed=0),
                                           batch_size=32)

    def primary():
        with task_scope(token):
            try:
                outcome["primary"] = executor.execute(
                    mf, x, batch_size=32, deadline=Deadline(0.08))
            except BaseException as e:  # noqa: BLE001 - asserted below
                outcome["primary_error"] = e

    def hedge():
        with task_scope(token):
            outcome["hedge"] = executor.execute(
                mf, x, batch_size=32, deadline=Deadline(10.0))

    t_busy = threading.Thread(target=busy)
    t_busy.start()
    time.sleep(0.05)  # inline launch in flight -> primary queues
    t_p = threading.Thread(target=primary)
    t_p.start()
    time.sleep(0.02)  # primary queued; hedge dedups onto it
    t_h = threading.Thread(target=hedge)
    t_h.start()
    for t in (t_busy, t_p, t_h):
        t.join(timeout=10.0)
        assert not t.is_alive()
    # the shared request survived past the primary's 80 ms budget and
    # delivered to BOTH waiters (without the deadline merge, the drain
    # at ~300 ms would have dropped it and failed both)
    expected = mf.apply_batch(x, batch_size=32)
    np.testing.assert_array_equal(outcome["hedge"], expected)
    assert "primary_error" not in outcome
    np.testing.assert_array_equal(outcome["primary"], expected)


def test_invalid_priority_raises_instead_of_hanging():
    """Regression: a typo'd lane on a direct execute()/submit() call must
    raise immediately — queued into an undrained lane it would park the
    caller forever."""
    mf = _model()
    with pytest.raises(ValueError, match="priority"):
        executor.execute(mf, _rows(2), batch_size=16,
                         priority="INTERACTIVE")


def test_breaker_disabled_by_default_never_records_events():
    fail = [True]
    mf = _model(name="no_breaker", fail_flag=fail)
    x = _rows(2, seed=1)
    assert EngineConfig.executor_breaker_threshold == 0
    with HealthMonitor() as mon:
        for _ in range(3):
            with pytest.raises(Exception) as ei:
                executor.execute(mf, x, batch_size=32)
            assert not isinstance(ei.value, ExecutorCircuitOpen)
    assert mon.count(health.BREAKER_OPEN) == 0


# ---------------------------------------------------------------------------
# Shutdown / reset idempotency and submit races (satellite)
# ---------------------------------------------------------------------------


def test_double_shutdown_and_double_reset_are_noops():
    mf = _model()
    executor.execute(mf, _rows(2), batch_size=16)  # prime a state
    executor.shutdown()
    executor.shutdown()  # idempotent: no error, no hang
    svc = executor.reset()
    assert executor.service() is svc
    svc2 = executor.reset()  # reset over a fresh service is fine too
    assert executor.service() is svc2
    # and the new service works
    np.testing.assert_array_equal(
        executor.execute(mf, _rows(2), batch_size=16),
        mf.apply_batch(_rows(2), batch_size=16))


def test_shutdown_racing_concurrent_submits_never_hangs_or_leaks():
    """Submitters hammer the service while it is shut down mid-flight:
    every submit either returns a correct result or raises
    ExecutorShutdown — never a hang, never a leaked future, and a
    post-shutdown submit on the SAME service always raises."""
    mf = _model(sleep_s=0.02)
    EngineConfig.coalesce_window_ms = 20.0
    x = _rows(3, seed=1)
    expected = mf.apply_batch(x, batch_size=32)
    bad = []
    done = []

    def submitter():
        while True:
            try:
                out = executor.execute(mf, x, batch_size=32)
                np.testing.assert_array_equal(out, expected)
            except ExecutorShutdown:
                done.append(1)
                return
            except BaseException as e:  # noqa: BLE001 - asserted below
                bad.append(e)
                return

    threads = [threading.Thread(target=submitter) for _ in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.15)
    executor.shutdown()
    executor.shutdown()  # racing double-shutdown stays a no-op
    for t in threads:
        t.join(timeout=10.0)
    assert not any(t.is_alive() for t in threads)
    assert not bad, bad
    assert len(done) == 6
    with pytest.raises(ExecutorShutdown):
        executor.service().submit(mf, x, len(x), 32, None, 1,
                                  resilience.DEFAULT_INFERENCE_POLICY,
                                  None, 32, 0)


# ---------------------------------------------------------------------------
# EngineConfig read-time validation (satellite)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("knob,value", [
    ("max_task_retries", -1),
    ("task_retry_delay_s", -0.5),
    ("task_timeout_s", -3.0),
    ("task_timeout_s", 0.0),
    ("speculation_quantile", 1.5),
    ("speculation_quantile", -0.1),
    ("speculation_multiplier", 0.0),
    ("speculation_min_runtime_s", -1.0),
    ("quarantine_max_fatal", 0),
    ("coalesce_window_ms", -5.0),
    ("coalesce_max_rows", 0),
    ("executor_max_queued_requests", 0),
    ("executor_max_queued_requests", -2),
    ("executor_max_queued_rows", 0),
    ("executor_overload_mode", "drop"),
    ("executor_default_priority", "realtime"),
    ("executor_breaker_threshold", -1),
    ("executor_breaker_window_s", 0.0),
    ("executor_breaker_cooldown_s", -1.0),
    ("inference_precision", "float16"),
    ("inference_precision", "fp32"),
    ("inference_precision", None),
    ("inference_donate_buffers", "yes"),
    ("inference_donate_buffers", 1),
    ("bucket_ladder", "adaptive"),
    ("bucket_ladder", None),
    ("cluster_workers", -1),
    ("cluster_inflight_partitions", 0),
    ("cluster_inflight_partitions", -3),
    ("max_workers", 0),
])
def test_engine_config_validation_rejects(knob, value):
    setattr(EngineConfig, knob, value)
    with pytest.raises(ValueError, match=knob):
        EngineConfig.validate()


def test_bad_knobs_fail_at_the_read_site_not_downstream():
    mf = _model()
    EngineConfig.executor_max_queued_requests = 0
    with pytest.raises(ValueError, match="executor_max_queued_requests"):
        executor.execute(mf, _rows(2), batch_size=16)
    EngineConfig.executor_max_queued_requests = None
    EngineConfig.task_timeout_s = -1.0
    from sparkdl_tpu.engine.dataframe import DataFrame

    df = DataFrame.fromRows([{"x": i} for i in range(4)], numPartitions=2)
    with pytest.raises(ValueError, match="task_timeout_s"):
        df.mapPartitions(lambda b: b).collect()


def test_defaults_validate_cleanly_and_stay_unbounded():
    EngineConfig.validate()  # the shipped defaults are always legal
    assert EngineConfig.executor_max_queued_requests is None
    assert EngineConfig.executor_max_queued_rows is None
    assert EngineConfig.executor_overload_mode == "block"
    assert EngineConfig.executor_default_priority == "bulk"
    assert EngineConfig.executor_breaker_threshold == 0


# ---------------------------------------------------------------------------
# Transformer priority param plumbing
# ---------------------------------------------------------------------------


def test_transformer_priority_param_validates_and_rides_to_execute(
        monkeypatch):
    import pyarrow as pa

    from sparkdl_tpu.core import executor as device_executor
    from sparkdl_tpu.engine.dataframe import DataFrame
    from sparkdl_tpu.ml.tensor_transformer import TPUTransformer

    with pytest.raises(TypeError, match="priority"):
        TPUTransformer(inputCol="x", outputCol="y", priority="realtime")

    mf = _model()
    t = TPUTransformer(inputCol="x", outputCol="y", modelFunction=mf,
                       batchSize=16, priority="interactive")
    assert t.getPriority() == "interactive"
    seen = []
    orig_execute = device_executor.execute

    def spying_execute(*args, **kwargs):
        seen.append(kwargs.get("priority"))
        return orig_execute(*args, **kwargs)

    monkeypatch.setattr(device_executor, "execute", spying_execute)
    df = DataFrame.fromColumns(
        {"x": _rows(5).reshape(5, -1)}, numPartitions=2)
    out = t.transform(df).collect()
    assert len(out) == 5
    assert seen and all(p == "interactive" for p in seen)
    # unset: the transformer defers to EngineConfig's default lane
    t2 = TPUTransformer(inputCol="x", outputCol="y", modelFunction=mf,
                        batchSize=16)
    assert t2.getPriority() is None
