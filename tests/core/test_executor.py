"""Device execution service tests (ISSUE 5 tentpole, core/executor.py):
cross-partition dynamic batch coalescing — bit-identical order-preserving
results, the solo inline fast path, hedge dedup, per-request failure
isolation, and shutdown that never leaks a future."""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkdl_tpu.core import executor, health, resilience, telemetry
from sparkdl_tpu.core.executor import ExecutorShutdown, task_scope
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.core.resilience import Fault, FaultInjector
from sparkdl_tpu.core.telemetry import Telemetry
from sparkdl_tpu.engine.dataframe import EngineConfig

_ELEMENT = (6,)
_FEATURES = 3


@pytest.fixture(autouse=True)
def _fresh_executor():
    """Each test gets its own service instance and pristine knobs
    (EngineConfig is process-wide class state; the snapshot covers every
    public knob, so the ISSUE 6 overload knobs — and future ones — are
    restored without listing them)."""
    saved = EngineConfig.snapshot()
    executor.reset()
    yield
    executor.reset()
    EngineConfig.restore(saved)


def _model(name="exec_model", sleep_s=0.0):
    """Row-wise model; ``sleep_s`` injects host time at EXECUTION (via
    pure_callback), so tests can hold a launch in flight deterministically
    without fighting the scheduler."""
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(_ELEMENT[0], _FEATURES))
                    .astype(np.float32))

    def apply_fn(vs, x):
        if sleep_s:
            def slow_identity(a):
                time.sleep(sleep_s)
                return a
            x = jax.pure_callback(
                slow_identity,
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return jnp.tanh(x @ vs)

    return ModelFunction(apply_fn, w, TensorSpec((None,) + _ELEMENT,
                                                 "float32"), name=name)


def _rows(n, seed=1):
    return np.random.default_rng(seed).normal(
        size=(n,) + _ELEMENT).astype(np.float32)


# ---------------------------------------------------------------------------
# Routing and the inline fast path
# ---------------------------------------------------------------------------


def test_solo_request_takes_inline_path_and_matches_apply_batch():
    mf = _model()
    x = _rows(5)
    expected = mf.apply_batch(x, batch_size=16)
    with Telemetry() as tel:
        out = executor.execute(mf, x, batch_size=16)
    np.testing.assert_array_equal(out, expected)
    # no coalescer launch happened: the coalesce histograms stayed empty
    hists = tel.metrics.snapshot()["histograms"]
    assert telemetry.M_COALESCE_REQUESTS not in hists
    assert telemetry.M_QUEUE_WAIT_S not in hists


def test_coalesce_off_and_oversize_and_empty_bypass_the_service():
    mf = _model()
    EngineConfig.coalesce = False
    x = _rows(4)
    np.testing.assert_array_equal(executor.execute(mf, x, batch_size=16),
                                  mf.apply_batch(x, batch_size=16))
    EngineConfig.coalesce = True
    big = _rows(40)  # > batch_size: the chunked path, never queued
    np.testing.assert_array_equal(executor.execute(mf, big, batch_size=16),
                                  mf.apply_batch(big, batch_size=16))
    empty = _rows(0)
    out = executor.execute(mf, empty, batch_size=16)
    assert out.shape == (0, _FEATURES)


def test_coalesce_max_rows_caps_one_launch():
    EngineConfig.coalesce_max_rows = 4
    mf = _model()
    x = _rows(6)  # > cap: bypasses the queue, still correct
    np.testing.assert_array_equal(executor.execute(mf, x, batch_size=16),
                                  mf.apply_batch(x, batch_size=16))


# ---------------------------------------------------------------------------
# Coalescing: bit-identical, order-preserving, observable
# ---------------------------------------------------------------------------


def _run_concurrent(mf, inputs, batch_size=32, tokens=None):
    """Submit every input from its own thread (barrier start); returns
    the per-thread results in input order."""
    results = [None] * len(inputs)
    errors = [None] * len(inputs)
    barrier = threading.Barrier(len(inputs))

    def work(i):
        try:
            barrier.wait()
            if tokens and tokens[i] is not None:
                with task_scope(tokens[i]):
                    results[i] = executor.execute(mf, inputs[i],
                                                  batch_size=batch_size)
            else:
                results[i] = executor.execute(mf, inputs[i],
                                              batch_size=batch_size)
        except BaseException as e:  # noqa: BLE001 - asserted by caller
            errors[i] = e

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(len(inputs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results, errors


def test_concurrent_requests_coalesce_bit_identical_per_requester():
    mf = _model(sleep_s=0.05)  # holds the inline launch in flight
    EngineConfig.coalesce_window_ms = 150.0
    inputs = [_rows(3, seed=i) for i in range(6)]
    expected = [mf.apply_batch(x, batch_size=32) for x in inputs]
    with Telemetry() as tel:
        results, errors = _run_concurrent(mf, inputs)
    assert errors == [None] * 6
    for got, want in zip(results, expected):
        np.testing.assert_array_equal(got, want)
    hists = tel.metrics.snapshot()["histograms"]
    coalesced = hists[telemetry.M_COALESCE_REQUESTS]
    # at least one multi-request launch happened (5 queued behind the
    # inline request coalesce within the window)
    assert coalesced["max"] >= 2
    assert hists[telemetry.M_COALESCE_ROWS]["count"] >= 1
    assert hists[telemetry.M_QUEUE_WAIT_S]["count"] >= 2


def test_multi_input_dict_models_coalesce():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(4, 2)).astype(np.float32))
    mf = ModelFunction(
        lambda vs, x: {"out": jnp.tanh(x["a"] @ vs) + x["b"]},
        w,
        {"a": TensorSpec((None, 4), "float32"),
         "b": TensorSpec((None, 2), "float32")},
        name="dict_model")
    mf_slow = ModelFunction(mf.apply_fn, mf.variables, mf.input_spec,
                            name="dict_model")
    inputs = [{"a": rng.normal(size=(3, 4)).astype(np.float32),
               "b": rng.normal(size=(3, 2)).astype(np.float32)}
              for _ in range(4)]
    expected = [mf.apply_batch(x, batch_size=16) for x in inputs]
    EngineConfig.coalesce_window_ms = 100.0
    results, errors = _run_concurrent(mf_slow, inputs, batch_size=16)
    assert errors == [None] * 4
    for got, want in zip(results, expected):
        np.testing.assert_array_equal(got["out"], want["out"])


def test_hedged_duplicate_dedups_before_coalescing():
    """Two attempts of the SAME task (shared token) submitting while a
    sibling holds the device: the duplicate shares the first attempt's
    pending request — its rows launch exactly once."""
    mf = _model(sleep_s=0.15)
    EngineConfig.coalesce_window_ms = 250.0
    x_busy = _rows(2, seed=0)
    x_task = _rows(3, seed=1)
    expected = mf.apply_batch(x_task, batch_size=32)
    token = ("task", 1234, 7)
    with Telemetry() as tel:
        # occupy the key so the tokened submissions queue (inline holds
        # the device for sleep_s)
        results = {}
        errors = []

        def busy():
            results["busy"] = executor.execute(mf, x_busy, batch_size=32)

        def attempt(name):
            try:
                with task_scope(token):
                    results[name] = executor.execute(mf, x_task,
                                                     batch_size=32)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        t_busy = threading.Thread(target=busy)
        t_busy.start()
        time.sleep(0.05)  # inline launch now in flight
        t_a = threading.Thread(target=attempt, args=("primary",))
        t_b = threading.Thread(target=attempt, args=("hedge",))
        t_a.start()
        time.sleep(0.02)  # primary queued mid-window
        t_b.start()
        for t in (t_busy, t_a, t_b):
            t.join()
    assert not errors
    np.testing.assert_array_equal(results["primary"], expected)
    np.testing.assert_array_equal(results["hedge"], expected)
    snap = tel.metrics.snapshot()
    assert snap["counters"][telemetry.M_COALESCE_DEDUP] == 1
    # the task's rows were launched once, not twice: every coalesced
    # launch's row total sums to busy-is-inline + one copy of the task
    rows_hist = snap["histograms"].get(telemetry.M_COALESCE_ROWS)
    assert rows_hist is not None and rows_hist["sum"] == len(x_task)


# ---------------------------------------------------------------------------
# Failure semantics
# ---------------------------------------------------------------------------


def test_oom_on_coalesced_launch_splits_per_request_bit_identical():
    mf = _model(sleep_s=0.05)
    EngineConfig.coalesce_window_ms = 150.0
    inputs = [_rows(3, seed=i) for i in range(5)]
    expected = [mf.apply_batch(x, batch_size=32) for x in inputs]
    # fires only on a multi-request launch: a solo request's valid rows
    # never reach 6
    inj = FaultInjector.seeded(
        0, device_oom=Fault(times=1, when=lambda c: c.get("valid", 0) >= 6))
    with inj, HealthMonitor() as mon:
        results, errors = _run_concurrent(mf, inputs)
    assert errors == [None] * 5
    assert inj.fired["device_oom"] == 1
    for got, want in zip(results, expected):
        np.testing.assert_array_equal(got, want)
    assert mon.count(health.OOM_RECHUNK) == 1


def test_fatal_failure_poisons_only_its_own_request():
    """A FATAL error on the coalesced launch splits per-request: the
    poisoned request raises its own error, siblings complete."""
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(_ELEMENT[0], _FEATURES))
                    .astype(np.float32))

    def apply_fn(vs, x):
        def check(a):
            time.sleep(0.05)
            if np.any(np.isnan(a)):
                # INVALID_ARGUMENT marker: classifies FATAL even through
                # the XlaRuntimeError wrapper jit re-raises callbacks in
                raise ValueError("INVALID_ARGUMENT: deliberate poison row")
            return a
        x = jax.pure_callback(check, jax.ShapeDtypeStruct(x.shape, x.dtype),
                              x)
        return jnp.tanh(x @ vs)

    mf = ModelFunction(apply_fn, w, TensorSpec((None,) + _ELEMENT,
                                               "float32"), name="poison")
    EngineConfig.coalesce_window_ms = 150.0
    inputs = [_rows(3, seed=i) for i in range(4)]
    poisoned = inputs[2].copy()
    poisoned[1, 0] = np.nan
    inputs[2] = poisoned
    results, errors = _run_concurrent(mf, inputs)
    clean = [i for i in range(4) if i != 2]
    # the poisoned request failed alone...
    assert isinstance(errors[2], Exception)
    assert resilience.classify(errors[2]) == resilience.FATAL
    # ...and every sibling completed with its own rows
    for i in clean:
        assert errors[i] is None, errors[i]
        np.testing.assert_array_equal(
            results[i], mf.apply_batch(inputs[i], batch_size=32))


def test_transient_failure_records_retry_and_replays_per_request():
    """A transient on the super-batch records CHUNK_RETRY (parity with
    the chunk path) and hands every request back to its own thread for
    replay — the retry backoff never sleeps on the coalescer thread, so
    queued siblings keep draining."""
    mf = _model(sleep_s=0.05)
    EngineConfig.coalesce_window_ms = 150.0
    inputs = [_rows(3, seed=i) for i in range(4)]
    expected = [mf.apply_batch(x, batch_size=32) for x in inputs]
    inj = FaultInjector.seeded(
        0, transfer_stall=Fault(times=1,
                                when=lambda c: c.get("valid", 0) >= 6))
    with inj, HealthMonitor() as mon:
        results, errors = _run_concurrent(mf, inputs)
    assert errors == [None] * 4
    assert inj.fired["transfer_stall"] == 1
    for got, want in zip(results, expected):
        np.testing.assert_array_equal(got, want)
    assert mon.count(health.CHUNK_RETRY) == 1


# ---------------------------------------------------------------------------
# Shutdown: no leaked futures (the kill-midwindow contract)
# ---------------------------------------------------------------------------


def test_shutdown_midwindow_every_request_completes_or_raises():
    mf = _model(sleep_s=0.4)
    EngineConfig.coalesce_window_ms = 30_000.0  # park the queued request
    x_busy = _rows(2, seed=0)
    x_queued = _rows(3, seed=1)
    outcome = {}

    def busy():
        outcome["busy"] = executor.execute(mf, x_busy, batch_size=32)

    def queued():
        try:
            outcome["queued"] = executor.execute(mf, x_queued,
                                                 batch_size=32)
        except BaseException as e:  # noqa: BLE001 - asserted below
            outcome["queued_error"] = e

    t_busy = threading.Thread(target=busy)
    t_busy.start()
    time.sleep(0.1)  # inline launch in flight
    t_q = threading.Thread(target=queued)
    t_q.start()
    time.sleep(0.1)  # queued mid-window (the window is 30 s)
    executor.shutdown()
    t_q.join(timeout=5.0)
    t_busy.join(timeout=5.0)
    assert not t_q.is_alive() and not t_busy.is_alive()
    # the in-flight inline request completed; the parked one raised — no
    # future was leaked
    np.testing.assert_array_equal(outcome["busy"],
                                  mf.apply_batch(x_busy, batch_size=32))
    assert isinstance(outcome.get("queued_error"), ExecutorShutdown)
    assert "queued" not in outcome


def test_submit_after_shutdown_raises():
    mf = _model(sleep_s=0.2)
    EngineConfig.coalesce_window_ms = 100.0
    # prime a state so the submit below takes the queued path, then close
    x = _rows(2)
    results, errors = _run_concurrent(mf, [x, _rows(2, seed=3)])
    assert errors == [None, None]
    executor.shutdown()
    with pytest.raises(ExecutorShutdown):
        executor.service().submit(mf, x, len(x), 32, None, 1,
                                  resilience.DEFAULT_INFERENCE_POLICY,
                                  None, 32, 0)


# ---------------------------------------------------------------------------
# Post-review hardening (ISSUE 5): dedup identity, per-request policy,
# fetch-time failure isolation
# ---------------------------------------------------------------------------


def test_task_token_sequence_prevents_cross_call_dedup():
    """The dedup identity is (task token, call sequence): a task whose op
    chain enters the device twice must not dedup call N onto call M; a
    fresh attempt (hedge) restarts the sequence so its call N matches the
    primary's call N."""
    from sparkdl_tpu.core.executor import current_task_token

    assert current_task_token() is None
    with task_scope(("t", 1)):
        assert current_task_token() == ("t", 1, 0)
        assert current_task_token() == ("t", 1, 1)  # second device call
        with task_scope(("t", 2)):  # nested scope: its own sequence
            assert current_task_token() == ("t", 2, 0)
        assert current_task_token() == ("t", 1, 2)  # outer resumes
    with task_scope(("t", 1)):  # a hedge attempt restarts at 0
        assert current_task_token() == ("t", 1, 0)
    assert current_task_token() is None


def test_hedge_reexecutes_independently_once_sibling_is_in_flight():
    """Dedup only shares PRE-launch (queued) requests: a hedge arriving
    while its primary's launch is already in flight (here: the inline
    path) re-runs the pure ops independently — that is what lets
    speculation win past a launch stalled on the device."""
    mf = _model(sleep_s=0.2)
    EngineConfig.coalesce_window_ms = 100.0
    x = _rows(3, seed=4)
    expected = mf.apply_batch(x, batch_size=32)
    token = ("task", 99, 0)
    results = {}
    errors = []

    def attempt(name):
        try:
            with task_scope(token):
                results[name] = executor.execute(mf, x, batch_size=32)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    with Telemetry() as tel:
        t_primary = threading.Thread(target=attempt, args=("primary",))
        t_primary.start()
        time.sleep(0.08)  # primary's inline launch now in flight
        t_hedge = threading.Thread(target=attempt, args=("hedge",))
        t_hedge.start()
        t_primary.join()
        t_hedge.join()
    assert not errors
    np.testing.assert_array_equal(results["primary"], expected)
    np.testing.assert_array_equal(results["hedge"], expected)
    snap = tel.metrics.snapshot()
    # no sharing happened — the hedge ran its own (queued, solo) launch
    assert snap["counters"].get(telemetry.M_COALESCE_DEDUP, 0) == 0
    assert snap["histograms"][telemetry.M_COALESCE_ROWS]["sum"] == len(x)


def test_mixed_shape_window_launches_per_shape_group():
    """One jitted fn can serve several input shapes; a drained window
    holding different element shapes must not concat them into one
    launch — each shape group launches (and succeeds) separately."""
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(3,)).astype(np.float32))

    def apply_fn(vs, x):
        def slow(a):
            time.sleep(0.05)
            return a
        x = jax.pure_callback(slow, jax.ShapeDtypeStruct(x.shape, x.dtype),
                              x)
        return jnp.tanh(x.reshape((x.shape[0], -1)).sum(axis=1,
                                                        keepdims=True) * vs)

    mf = ModelFunction(apply_fn, w, TensorSpec((None, None), "float32"),
                       name="anyshape")
    EngineConfig.coalesce_window_ms = 150.0
    # two element widths against the same model: (N, 4) and (N, 7)
    inputs = ([rng.normal(size=(3, 4)).astype(np.float32)
               for _ in range(3)]
              + [rng.normal(size=(3, 7)).astype(np.float32)
                 for _ in range(3)])
    expected = [mf.apply_batch(x, batch_size=32) for x in inputs]
    results, errors = _run_concurrent(mf, inputs)
    assert errors == [None] * 6, errors
    for got, want in zip(results, expected):
        np.testing.assert_array_equal(got, want)


def test_caller_retry_policy_honored_when_queued():
    """A caller's retry_policy rides the request into the coalescer: with
    max_retries=0 a transient failure on the super-batch is NOT retried —
    it splits to per-request sub-launches immediately (which then also
    run under the caller's policy)."""
    mf = _model(sleep_s=0.05)
    EngineConfig.coalesce_window_ms = 150.0
    no_retry = resilience.RetryPolicy(max_retries=0)
    inputs = [_rows(3, seed=i) for i in range(4)]
    expected = [mf.apply_batch(x, batch_size=32) for x in inputs]
    inj = FaultInjector.seeded(
        0, transfer_stall=Fault(times=1,
                                when=lambda c: c.get("valid", 0) >= 6))
    results = [None] * 4
    errors = [None] * 4
    barrier = threading.Barrier(4)

    def work(i):
        try:
            barrier.wait()
            results[i] = executor.execute(mf, inputs[i], batch_size=32,
                                          retry_policy=no_retry)
        except BaseException as e:  # noqa: BLE001
            errors[i] = e

    with inj, HealthMonitor() as mon:
        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert errors == [None] * 4
    assert inj.fired["transfer_stall"] == 1
    for got, want in zip(results, expected):
        np.testing.assert_array_equal(got, want)
    # max_retries=0: the transient was never retried, so no CHUNK_RETRY —
    # the window split straight to per-request sub-launches
    assert mon.count(health.CHUNK_RETRY) == 0


def test_fetch_time_failure_replays_the_request_alone():
    """Async dispatch can surface a real device failure only at the
    requester's fetch: _await classifies it and re-runs THIS request
    alone through apply_batch (OOM recorded, siblings unaffected)."""
    mf = _model()
    x = _rows(3, seed=5)
    svc = executor.service()
    fn = mf.jitted(mesh=None)
    state = svc._state(fn, mf, 32, None, 1)

    class _LateBoom:
        """Stands in for a device array whose execution failed: the
        error surfaces at np.asarray, not at dispatch."""

        def __array__(self, *a, **k):
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory while "
                               "executing the coalesced launch")

    req = executor._Request(mf.stage_inputs(x), 3, None,
                            resilience.DEFAULT_INFERENCE_POLICY)
    req.future.set_result(_LateBoom())
    with HealthMonitor() as mon:
        out = svc._await(state, req, time.monotonic())
    np.testing.assert_array_equal(out, mf.apply_batch(x, batch_size=32))
    assert mon.count(health.OOM_RECHUNK) == 1


def test_reset_call_sequence_realigns_retry_attempts():
    """run_partition_task's classified retries re-run the op chain from
    the top inside ONE task_scope: reset_call_sequence restarts the
    device-call numbering so a retried attempt's call N dedups against a
    hedge's call N, never call M."""
    from sparkdl_tpu.core.executor import (current_task_token,
                                           reset_call_sequence)

    reset_call_sequence()  # outside any scope: a no-op
    assert current_task_token() is None
    with task_scope(("t", 3)):
        assert current_task_token() == ("t", 3, 0)
        assert current_task_token() == ("t", 3, 1)
        reset_call_sequence()  # next retry-loop attempt
        assert current_task_token() == ("t", 3, 0)
    assert current_task_token() is None


def test_solo_drained_window_replays_on_the_requester_thread():
    """A drained group of one (and every member of a terminal failure
    split) is handed BACK via the replay sentinel: apply_batch runs on
    the requester's own thread, never the coalescer's — the coalescer
    stays free to drain queued siblings instead of serializing device
    fetches and retry backoffs behind one request."""
    mf = _model(sleep_s=0.2)
    EngineConfig.coalesce_window_ms = 30.0
    apply_threads = []
    orig_apply = mf.apply_batch

    def recording_apply(*args, **kwargs):
        apply_threads.append(threading.current_thread().name)
        return orig_apply(*args, **kwargs)

    mf.apply_batch = recording_apply
    x_busy = _rows(2, seed=0)
    x_queued = _rows(3, seed=1)
    outcome = {}

    def busy():
        outcome["busy"] = executor.execute(mf, x_busy, batch_size=32)

    def queued():
        outcome["queued"] = executor.execute(mf, x_queued, batch_size=32)

    t_busy = threading.Thread(target=busy, name="requester-busy")
    t_busy.start()
    time.sleep(0.05)  # inline launch in flight
    t_q = threading.Thread(target=queued, name="requester-queued")
    t_q.start()  # queues; the 30 ms window drains it as a group of one
    t_busy.join()
    t_q.join()
    np.testing.assert_array_equal(
        outcome["busy"], orig_apply(x_busy, batch_size=32))
    np.testing.assert_array_equal(
        outcome["queued"], orig_apply(x_queued, batch_size=32))
    assert set(apply_threads) == {"requester-busy", "requester-queued"}
    assert not any(n.startswith("sparkdl-exec") for n in apply_threads)
