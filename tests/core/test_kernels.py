"""Fused Pallas kernel plane (core/kernels.py, ISSUE 20).

Covers the accept-if-faster machinery end to end on CPU: verdict
persistence (round-trip, corrupt/stale discard, backend partitioning),
the numeric contract of every fused kernel against its XLA twin
(interpreter mode), route gating across all three
``EngineConfig.pallas_kernels`` modes, the CPU autotune path (clean
rejections, byte-identical program), and the subprocess pin that the
``"off"`` mode never even imports this module.
"""

import json
import os
import subprocess
import sys

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkdl_tpu import COMPILE_CACHE_DIR_ENV
from sparkdl_tpu.core import kernels
from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.engine.dataframe import EngineConfig
from sparkdl_tpu.models.layers import ConvBN, SeparableConvBN

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _kernel_stack(monkeypatch):
    """Engine knobs + verdict map + INTERPRET flag isolation. The cache
    dir env is cleared so verdicts stay in-process unless a test opts
    into persistence with its own tmp_path."""
    saved = EngineConfig.snapshot()
    saved_interpret = kernels.INTERPRET
    monkeypatch.delenv(COMPILE_CACHE_DIR_ENV, raising=False)
    kernels.reset()
    yield
    kernels.INTERPRET = saved_interpret
    kernels.reset()
    EngineConfig.restore(saved)


def _site():
    return kernels.Site("pw1x1", "unit", (2, 4, 4, 8, 8), "float32")


def _inject(site, adopted):
    """Drop a settled verdict into the in-memory map (what a completed
    shootout would leave behind) without running device work."""
    with kernels._verdict_lock:
        kernels._verdicts[kernels._site_key(site)] = {
            "adopted": adopted, "reason": "injected"}


# ---------------------------------------------------------------------------
# Verdict store: round-trip, corruption, version skew, partitioning
# ---------------------------------------------------------------------------


def test_verdict_store_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv(COMPILE_CACHE_DIR_ENV, str(tmp_path))
    site = _site()
    kernels._persist_verdict(kernels._site_key(site),
                             {"adopted": True, "reason": "unit"})
    kernels.reset()  # wipe in-memory: the next lookup must hit the file
    got = kernels.verdict_for(site)
    assert got is not None and got["adopted"] is True
    doc = json.loads(
        (tmp_path / kernels._VERDICT_STORE_BASENAME).read_text())
    assert doc["version"] == kernels.VERDICT_STORE_VERSION
    assert kernels._site_key(site) in doc["verdicts"]


def test_verdict_store_merges_entries(tmp_path, monkeypatch):
    monkeypatch.setenv(COMPILE_CACHE_DIR_ENV, str(tmp_path))
    s1 = _site()
    s2 = kernels.Site("sep2d", "unit", (2, 6, 6, 8, 8), "float32")
    kernels._persist_verdict(kernels._site_key(s1),
                             {"adopted": False, "reason": "slow"})
    kernels._persist_verdict(kernels._site_key(s2),
                             {"adopted": True, "reason": "fast"})
    kernels.reset()
    assert kernels.verdict_for(s1)["adopted"] is False
    assert kernels.verdict_for(s2)["adopted"] is True


def test_verdict_store_corrupt_file_discarded(tmp_path, monkeypatch):
    monkeypatch.setenv(COMPILE_CACHE_DIR_ENV, str(tmp_path))
    path = tmp_path / kernels._VERDICT_STORE_BASENAME
    path.write_text("{definitely not json")
    kernels.reset()
    assert kernels.verdict_for(_site()) is None
    # a later persist rewrites a valid store over the wreckage
    kernels._persist_verdict(kernels._site_key(_site()),
                             {"adopted": False, "reason": "fresh"})
    kernels.reset()
    assert kernels.verdict_for(_site())["adopted"] is False
    assert json.loads(path.read_text())["version"] \
        == kernels.VERDICT_STORE_VERSION


def test_verdict_store_stale_version_discarded(tmp_path, monkeypatch):
    monkeypatch.setenv(COMPILE_CACHE_DIR_ENV, str(tmp_path))
    key = kernels._site_key(_site())
    (tmp_path / kernels._VERDICT_STORE_BASENAME).write_text(json.dumps(
        {"version": kernels.VERDICT_STORE_VERSION + 1,
         "verdicts": {key: {"adopted": True, "reason": "old format"}}}))
    kernels.reset()
    assert kernels.verdict_for(_site()) is None


def test_verdict_store_malformed_entries_discarded(tmp_path, monkeypatch):
    monkeypatch.setenv(COMPILE_CACHE_DIR_ENV, str(tmp_path))
    good, bad = _site(), kernels.Site("pw1x1", "bad", (1, 4, 4, 8, 8),
                                      "float32")
    (tmp_path / kernels._VERDICT_STORE_BASENAME).write_text(json.dumps(
        {"version": kernels.VERDICT_STORE_VERSION,
         "verdicts": {
             kernels._site_key(good): {"adopted": True, "reason": "ok"},
             kernels._site_key(bad): {"adopted": "yes"},  # not a bool
         }}))
    kernels.reset()
    assert kernels.verdict_for(good)["adopted"] is True
    assert kernels.verdict_for(bad) is None


def test_verdicts_stay_in_process_without_cache_dir(tmp_path):
    assert kernels.verdict_store_path() is None
    kernels._persist_verdict(kernels._site_key(_site()),
                             {"adopted": True, "reason": "unpersisted"})
    kernels.reset()
    assert kernels.verdict_for(_site()) is None
    assert list(tmp_path.iterdir()) == []


def test_backend_tag_partitions_verdicts(tmp_path, monkeypatch):
    """Interpreter verdicts must never answer for real hardware (and
    vice versa): the backend is part of the site key."""
    monkeypatch.setenv(COMPILE_CACHE_DIR_ENV, str(tmp_path))
    site = _site()
    kernels._persist_verdict(kernels._site_key(site),
                             {"adopted": True, "reason": "hw"})
    kernels.reset()
    assert kernels.verdict_for(site)["adopted"] is True
    kernels.INTERPRET = True
    assert kernels.verdict_for(site) is None


# ---------------------------------------------------------------------------
# Numeric contract: every fused kernel vs its XLA twin (interpreter mode)
# ---------------------------------------------------------------------------

_MATRIX = [
    kernels.Site("sep2d", "matrix", (2, 6, 6, 8, 8), "float32"),
    kernels.Site("sep2d", "matrix", (2, 6, 6, 8, 8), "bfloat16"),
    kernels.Site("pw1x1", "matrix", (2, 4, 4, 8, 16), "float32"),
    kernels.Site("pw1x1", "matrix", (2, 4, 4, 8, 16), "bfloat16"),
    kernels.Site("pw1x1_relu", "matrix", (2, 4, 4, 8, 16), "float32"),
    kernels.Site("pw1x1_relu", "matrix", (2, 4, 4, 8, 16), "bfloat16"),
]


@pytest.mark.parametrize("site", _MATRIX,
                         ids=lambda s: f"{s.kernel}-{s.dtype}")
def test_fused_kernel_matches_xla_twin(site):
    """The shootout's own candidate pair at O(1)-magnitude operands:
    bf16 must sit inside the adoption contract (BF16_TOLERANCE); fp32
    within float roundoff of the twin (the folded BN affine reorders
    ops, so bit-exactness is not expected — which is exactly why fp32
    candidates are auto-rejected by the exactness gate)."""
    kernels.INTERPRET = True
    pallas_fn, xla_fn, x = kernels._build_shootout(site)
    y_p = np.asarray(jnp.asarray(pallas_fn(x), jnp.float32))
    y_x = np.asarray(jnp.asarray(xla_fn(x), jnp.float32))
    assert y_p.shape == y_x.shape
    err = float(np.max(np.abs(y_p - y_x)))
    if site.dtype == "bfloat16":
        assert err <= kernels.BF16_TOLERANCE, err
    else:
        assert err <= 1e-5, err


@pytest.mark.parametrize("out_dtype,atol", [("float32", 1e-3),
                                            ("bfloat16", 2.0)])
def test_preproc_kernel_matches_resize(out_dtype, atol):
    """Fused cast+resize vs the jax.image.resize twin. Outputs live on
    the uint8 [0, 255] scale, so the bound is one bf16 ulp at 255 (2.0)
    rather than the O(1) BF16_TOLERANCE — the audition gate judges
    preproc bf16 sites against 0.05 and therefore rejects them, which
    is the conservative-by-design outcome."""
    kernels.INTERPRET = True
    site = kernels.Site("preproc", "matrix", (1, 8, 10, 3, 5, 6),
                        f"uint8->{out_dtype}")
    pallas_fn, xla_fn, x = kernels._build_shootout(site)
    y_p = np.asarray(jnp.asarray(pallas_fn(x), jnp.float32))
    ref = np.asarray(kernels.xla_preproc(x, (5, 6), "float32"))
    assert y_p.shape == ref.shape
    assert float(np.max(np.abs(y_p - ref))) <= atol


# ---------------------------------------------------------------------------
# Route gating: off / autotune / force
# ---------------------------------------------------------------------------


def _pw_operands(rng):
    x = jnp.asarray(rng.normal(size=(2, 4, 4, 8)).astype(np.float32))
    k4 = jnp.asarray((rng.normal(size=(1, 1, 8, 8)) * 0.3)
                     .astype(np.float32))
    gamma = jnp.asarray(
        (np.abs(rng.normal(size=8)) + 0.5).astype(np.float32))
    beta = jnp.asarray((rng.normal(size=8) * 0.1).astype(np.float32))
    mean = jnp.asarray((rng.normal(size=8) * 0.1).astype(np.float32))
    var = jnp.asarray(
        (np.abs(rng.normal(size=8)) + 1.0).astype(np.float32))
    return x, k4, gamma, beta, mean, var


def test_route_returns_none_without_adopted_verdict(rng):
    EngineConfig.pallas_kernels = "autotune"
    x, k4, gamma, beta, mean, var = _pw_operands(rng)
    assert kernels.route_pw1x1(x, k4, gamma, beta, mean, var, 1e-3,
                               relu=True, family="unit") is None


def test_route_honors_injected_verdicts(rng):
    EngineConfig.pallas_kernels = "autotune"
    kernels.INTERPRET = True
    x, k4, gamma, beta, mean, var = _pw_operands(rng)
    site = kernels.Site("pw1x1_relu", "unit", (2, 4, 4, 8, 8), "float32")
    _inject(site, adopted=False)
    assert kernels.route_pw1x1(x, k4, gamma, beta, mean, var, 1e-3,
                               relu=True, family="unit") is None
    _inject(site, adopted=True)
    routed = kernels.route_pw1x1(x, k4, gamma, beta, mean, var, 1e-3,
                                 relu=True, family="unit")
    assert routed is not None
    twin = kernels.xla_pw1x1(x, k4, gamma, beta, mean, var, 1e-3,
                             relu=True)
    np.testing.assert_allclose(np.asarray(routed), np.asarray(twin),
                               atol=1e-5)


def test_force_mode_routes_under_jit(rng):
    EngineConfig.pallas_kernels = "force"
    kernels.INTERPRET = True
    x, k4, gamma, beta, mean, var = _pw_operands(rng)
    routed = jax.jit(lambda a: kernels.route_pw1x1(
        a, k4, gamma, beta, mean, var, 1e-3, relu=True,
        family="unit"))(x)
    assert routed is not None
    twin = kernels.xla_pw1x1(x, k4, gamma, beta, mean, var, 1e-3,
                             relu=True)
    np.testing.assert_allclose(np.asarray(routed), np.asarray(twin),
                               atol=1e-5)


def test_force_mode_routes_sep2d(rng):
    EngineConfig.pallas_kernels = "force"
    kernels.INTERPRET = True
    x = jnp.asarray(rng.normal(size=(2, 6, 6, 8)).astype(np.float32))
    dw4 = jnp.asarray((rng.normal(size=(3, 3, 1, 8)) * 0.2)
                      .astype(np.float32))
    pw4 = jnp.asarray((rng.normal(size=(1, 1, 8, 8)) * 0.35)
                      .astype(np.float32))
    gamma = jnp.asarray(
        (np.abs(rng.normal(size=8)) + 0.5).astype(np.float32))
    beta = jnp.asarray((rng.normal(size=8) * 0.1).astype(np.float32))
    mean = jnp.asarray((rng.normal(size=8) * 0.1).astype(np.float32))
    var = jnp.asarray(
        (np.abs(rng.normal(size=8)) + 1.0).astype(np.float32))
    routed = kernels.route_sep2d(x, dw4, pw4, gamma, beta, mean, var,
                                 1e-3, family="unit")
    assert routed is not None
    twin = kernels.xla_sep2d(x, dw4, pw4, gamma, beta, mean, var, 1e-3)
    np.testing.assert_allclose(np.asarray(routed), np.asarray(twin),
                               atol=1e-5)


def test_route_preproc_force(rng):
    EngineConfig.pallas_kernels = "force"
    kernels.INTERPRET = True
    x = jnp.asarray(rng.integers(0, 256, size=(1, 8, 10, 3))
                    .astype(np.uint8))
    routed = kernels.route_preproc(x, (5, 6), "float32", family="unit")
    assert routed is not None
    twin = kernels.xla_preproc(x, (5, 6), "float32")
    np.testing.assert_allclose(np.asarray(routed), np.asarray(twin),
                               atol=1e-3)


def test_infeasible_site_never_routes(rng):
    """A site past the VMEM budget must fall back even under force."""
    EngineConfig.pallas_kernels = "force"
    kernels.INTERPRET = True
    x = jnp.asarray(rng.normal(size=(1, 2, 2, 4)).astype(np.float32))
    dw4 = jnp.zeros((3, 3, 1, 4), np.float32)
    pw4 = jnp.zeros((1, 1, 4, 4), np.float32)
    ones = jnp.ones((4,), np.float32)
    # h=2 < 3: sep2d geometry infeasible
    assert kernels.route_sep2d(x, dw4, pw4, ones, ones, ones, ones,
                               1e-3, family="unit") is None


# ---------------------------------------------------------------------------
# Autotune on CPU: clean rejections, byte-identical routed program
# ---------------------------------------------------------------------------


class _Tiny(nn.Module):
    """Smallest model that routes: one fused-family 1×1 ConvBN."""

    @nn.compact
    def __call__(self, x, train: bool = False):
        return ConvBN(8, (1, 1), act=True, kernel_family="tiny")(x, train)


def _tiny_model(rng):
    m = _Tiny()
    vs = m.init(jax.random.PRNGKey(0), np.zeros((1, 4, 4, 3), np.float32))
    x = rng.normal(size=(2, 4, 4, 3)).astype(np.float32)
    return m, vs, x


def test_cpu_autotune_rejects_cleanly_and_stays_byte_identical(rng):
    m, vs, x = _tiny_model(rng)
    EngineConfig.pallas_kernels = "off"
    y_off = np.asarray(jax.jit(lambda a: m.apply(vs, a))(x))

    EngineConfig.pallas_kernels = "autotune"  # INTERPRET stays False:
    # CPU has no Mosaic lowering, so every audition must reject cleanly
    kernels.ensure_autotuned(lambda a: m.apply(vs, a), x, model="tiny")
    snap = kernels.verdicts_snapshot()
    assert snap, "expected at least one audited site"
    assert all(v["adopted"] is False for v in snap.values())
    assert all("Mosaic" in v["reason"] for v in snap.values()), snap

    y_auto = np.asarray(jax.jit(lambda a: m.apply(vs, a))(x))
    assert y_auto.dtype == y_off.dtype
    np.testing.assert_array_equal(y_auto, y_off)


def test_ensure_autotuned_noop_outside_autotune_mode(rng):
    m, vs, x = _tiny_model(rng)
    for mode in ("off", "force"):
        EngineConfig.pallas_kernels = mode
        kernels.ensure_autotuned(lambda a: m.apply(vs, a), x)
        assert kernels.verdicts_snapshot() == {}


def test_model_function_first_launch_settles_verdicts(rng):
    """The production hook: ModelFunction's first-launch-of-a-shape
    path runs the site collection + shootouts before the real trace."""
    m, vs, x = _tiny_model(rng)
    EngineConfig.pallas_kernels = "autotune"
    mf = ModelFunction.fromFlax(m, vs, TensorSpec((None, 4, 4, 3),
                                                  "float32"),
                                name="tiny", train=False)
    out = mf.apply_batch(x, batch_size=2)
    assert np.asarray(out).shape == (2, 4, 4, 8)
    snap = kernels.verdicts_snapshot()
    assert snap and all(v["adopted"] is False for v in snap.values())


def test_convbn_force_interpret_matches_flax(rng):
    """Force + interpreter: the ConvBN structural opt-in actually swaps
    in the fused body, and its numerics sit on the Flax result."""
    m, vs, x = _tiny_model(rng)
    EngineConfig.pallas_kernels = "off"
    y_flax = np.asarray(m.apply(vs, x))
    EngineConfig.pallas_kernels = "force"
    kernels.INTERPRET = True
    y_fused = np.asarray(m.apply(vs, x))
    np.testing.assert_allclose(y_fused, y_flax, atol=1e-5)


def test_separable_convbn_force_interpret_matches_flax(rng):
    class _Sep(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            return SeparableConvBN(8, kernel_family="tiny")(x, train)

    m = _Sep()
    vs = m.init(jax.random.PRNGKey(0), np.zeros((1, 6, 6, 4), np.float32))
    x = rng.normal(size=(2, 6, 6, 4)).astype(np.float32)
    EngineConfig.pallas_kernels = "off"
    y_flax = np.asarray(m.apply(vs, x))
    EngineConfig.pallas_kernels = "force"
    kernels.INTERPRET = True
    y_fused = np.asarray(m.apply(vs, x))
    np.testing.assert_allclose(y_fused, y_flax, atol=1e-5)


def test_engine_config_rejects_unknown_kernel_mode():
    EngineConfig.pallas_kernels = "banana"
    with pytest.raises(ValueError, match="pallas_kernels"):
        EngineConfig.validate()


# ---------------------------------------------------------------------------
# Off mode: the module is never even imported
# ---------------------------------------------------------------------------


def test_off_mode_never_imports_kernels_module():
    """Subprocess pin: with pallas_kernels="off", building AND applying
    a fused-family model must leave core.kernels out of sys.modules —
    "off" means zero import cost and a byte-identical program, not a
    dormant registry."""
    script = r"""
import sys
from sparkdl_tpu.engine.dataframe import EngineConfig
EngineConfig.pallas_kernels = "off"
import numpy as np
import jax
from sparkdl_tpu.models.layers import ConvBN
m = ConvBN(4, (1, 1), kernel_family="pin")
vs = m.init(jax.random.PRNGKey(0), np.zeros((1, 4, 4, 3), np.float32))
y = m.apply(vs, np.ones((2, 4, 4, 3), np.float32))
assert y.shape == (2, 4, 4, 4), y.shape
assert "sparkdl_tpu.core.kernels" not in sys.modules, \
    "off mode imported the kernel registry"
print("CLEAN")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_REPO)
    env.pop(COMPILE_CACHE_DIR_ENV, None)
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr
    assert "CLEAN" in proc.stdout
