"""DevicePrefetcher: ordering, bounded depth, error propagation, clean
shutdown, counters, and genuine cross-thread staging overlap (ISSUE 3).
"""

import threading
import time

import pytest

from sparkdl_tpu.core import health, pipeline, profiling
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.core.pipeline import DevicePrefetcher


def _prefetch_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("sparkdl-prefetch")]


def _wait_no_prefetch_threads(timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not _prefetch_threads():
            return True
        time.sleep(0.01)
    return not _prefetch_threads()


def test_order_preserved_and_stage_fn_applied():
    with DevicePrefetcher(range(50), stage_fn=lambda i: i * 2,
                          depth=3) as pf:
        assert list(pf) == [i * 2 for i in range(50)]
    assert pf.stats.staged == 50
    assert pf.stats.consumed == 50
    assert _wait_no_prefetch_threads()


def test_depth_zero_is_inline_no_thread():
    before = _prefetch_threads()
    pf = DevicePrefetcher(range(10), stage_fn=lambda i: i + 1, depth=0)
    assert _prefetch_threads() == before  # no staging thread created
    assert list(pf) == list(range(1, 11))
    assert pf.stats.staged == 10


def test_inline_staging_counts_as_host_wait():
    """The serial (depth=0) path is 100% starvation: its whole pull+stage
    time feeds HOST_WAIT, so overlap_ratio reports ~0 for a serial run —
    not a phantom 'fully hidden' 1.0."""
    profiling.reset_phase_stats()

    def slow_stage(i):
        time.sleep(0.01)
        with profiling.annotate("sparkdl.stage"):
            time.sleep(0.005)
        return i

    with DevicePrefetcher(range(4), stage_fn=slow_stage, depth=0) as pf:
        assert list(pf) == [0, 1, 2, 3]
    assert pf.stats.stalls == 4
    assert pf.stats.stall_s >= 0.04
    stats = profiling.overlap_stats()
    assert stats["host_wait_s"] >= stats["host_etl_s"] > 0
    assert stats["overlap_ratio"] == 0.0


def test_producer_bounded_by_depth():
    """The staging thread runs at most depth staged-and-queued items plus
    the one it holds while blocked on put — never the whole stream."""
    produced = []

    def source():
        for i in range(100):
            produced.append(i)
            yield i

    pf = DevicePrefetcher(source(), depth=2)
    try:
        deadline = time.monotonic() + 5.0
        while len(produced) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.15)  # give an unbounded producer time to run away
        assert 3 <= len(produced) <= 4  # depth(2) queued + ≤2 in hand/flight
        assert next(pf) == 0  # stream still delivers, in order
    finally:
        pf.close()
    assert _wait_no_prefetch_threads()


def test_error_propagates_with_thread_joined():
    class Boom(RuntimeError):
        pass

    def source():
        yield 1
        yield 2
        raise Boom("decode failed mid-stream")

    pf = DevicePrefetcher(source(), depth=2, name="err")
    assert next(pf) == 1
    assert next(pf) == 2
    with pytest.raises(Boom, match="decode failed"):
        next(pf)
    # fully drained: thread joined, iteration stays terminated
    assert _wait_no_prefetch_threads()
    with pytest.raises(StopIteration):
        next(pf)


def test_stage_fn_error_propagates():
    def bad_stage(i):
        if i == 3:
            raise ValueError("bad batch 3")
        return i

    with DevicePrefetcher(range(10), stage_fn=bad_stage, depth=1) as pf:
        got = [next(pf), next(pf), next(pf)]
        with pytest.raises(ValueError, match="bad batch 3"):
            for item in pf:
                got.append(item)
    assert got == [0, 1, 2]
    assert _wait_no_prefetch_threads()


def test_close_midstream_wakes_blocked_producer():
    staged = []

    def source():
        for i in range(1000):
            staged.append(i)
            yield i

    pf = DevicePrefetcher(source(), depth=1)
    assert next(pf) == 0
    pf.close()  # producer is blocked on a full queue right now
    assert _wait_no_prefetch_threads()
    assert len(staged) < 1000  # source was NOT exhausted after close
    with pytest.raises(StopIteration):
        next(pf)
    pf.close()  # idempotent


def test_finish_and_close_are_mutually_idempotent():
    """The lifecycle check-and-set is atomic (PR 8 concurrency-analyzer
    fix): whichever of close()/end-of-stream _finish wins, the loser is
    a no-op — never an AttributeError on a nulled _thread, never a
    double prefetch_report."""
    with HealthMonitor("finish-close") as mon:
        pf = DevicePrefetcher(range(3), depth=2, report_health=True)
        list(pf)      # exhausts the stream -> _finish() ran
        pf.close()    # racing/late close: no-op
        pf._finish()  # and the reverse order: no-op too
        assert pf._thread is None
        assert mon.count(health.PREFETCH_REPORT) == 1

    with HealthMonitor("close-finish") as mon:
        pf = DevicePrefetcher(range(1000), depth=2, report_health=True)
        next(pf)
        closers = [threading.Thread(target=pf.close,
                                    name=f"closer-{i}")
                   for i in range(8)]
        [t.start() for t in closers]
        [t.join() for t in closers]
        pf._finish()  # consumer losing the race to a closer: no-op
        assert _wait_no_prefetch_threads()
        assert mon.count(health.PREFETCH_REPORT) == 1


def test_stall_counters_feed_host_wait_phase():
    profiling.reset_phase_stats()

    def slow_source():
        for i in range(3):
            time.sleep(0.03)
            yield i

    with DevicePrefetcher(slow_source(), depth=2, name="slow") as pf:
        assert list(pf) == [0, 1, 2]
    assert pf.stats.stalls >= 1  # consumer outran the slow host at least once
    assert pf.stats.stall_s > 0
    stats = profiling.phase_stats()
    assert profiling.HOST_WAIT in stats
    assert stats[profiling.HOST_WAIT]["total_s"] == pytest.approx(
        pf.stats.stall_s, rel=0.01)


def test_overlap_stats_ratio_bounds():
    profiling.reset_phase_stats()
    profiling.add_phase_time("sparkdl.decode", 2.0)
    profiling.add_phase_time(profiling.HOST_WAIT, 0.5)
    stats = profiling.overlap_stats()
    assert stats["host_etl_s"] == pytest.approx(2.0)
    assert stats["host_wait_s"] == pytest.approx(0.5)
    assert stats["overlap_ratio"] == pytest.approx(0.75)
    profiling.reset_phase_stats()
    assert profiling.overlap_stats()["overlap_ratio"] == 1.0


def test_health_report_recorded_per_stream():
    with HealthMonitor("pf") as mon:
        with DevicePrefetcher(range(5), depth=2, name="telemetry",
                              report_health=True) as pf:
            assert len(list(pf)) == 5
    events = mon.events(health.PREFETCH_REPORT)
    assert len(events) == 1
    assert events[0]["name"] == "telemetry"
    assert events[0]["staged"] == 5
    assert events[0]["consumed"] == 5


def test_health_report_off_by_default():
    """Per-chunk streams (run_batched) must NOT emit one event each —
    thousands of them would evict later quarantine/retry entries from
    HealthMonitor's bounded event log."""
    with HealthMonitor("quiet") as mon:
        with DevicePrefetcher(range(5), depth=2) as pf:
            assert len(list(pf)) == 5
    assert mon.events(health.PREFETCH_REPORT) == []
    assert pf.stats.consumed == 5  # stats still tracked


def test_staging_runs_concurrently_with_consumer():
    """Genuine overlap: the staging thread produces item k+1 WHILE the
    consumer holds item k un-returned — proven by event ordering, not
    timing."""
    main = threading.get_ident()
    producer_threads = []
    second_staged = threading.Event()

    def source():
        for i in range(4):
            producer_threads.append(threading.get_ident())
            yield i
            if i == 1:
                second_staged.set()

    with DevicePrefetcher(source(), depth=2) as pf:
        first = next(pf)  # consumer now "works on" item 0...
        # ...while the producer keeps staging ahead on its own thread
        assert second_staged.wait(timeout=5.0)
        assert first == 0
        assert list(pf) == [1, 2, 3]
    assert all(t != main for t in producer_threads)
    assert pf.stats.ready_hits >= 1  # at least one item was staged ahead


@pytest.mark.slow
def test_stress_many_streams_no_thread_leak():
    """Stress: hundreds of short-lived streams (the per-epoch / per-
    partition usage pattern) leave no threads behind, including streams
    abandoned mid-flight and streams that error."""
    for i in range(200):
        mode = i % 3
        if mode == 0:
            with DevicePrefetcher(range(20), depth=2) as pf:
                assert len(list(pf)) == 20
        elif mode == 1:
            pf = DevicePrefetcher(iter(range(50)), depth=3)
            next(pf)
            pf.close()  # abandoned mid-flight
        else:
            def bad():
                yield 1
                raise RuntimeError("x")

            pf = DevicePrefetcher(bad(), depth=1)
            next(pf)
            with pytest.raises(RuntimeError):
                next(pf)
    assert _wait_no_prefetch_threads(timeout=10.0)
