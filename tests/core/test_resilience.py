"""Resilience kernel: classifier, retry policy, deadlines, fault injection,
and the OOM bucket-halving inference fallback (docs/RESILIENCE.md)."""

import threading

import numpy as np
import pytest

import jax.numpy as jnp

from sparkdl_tpu.core import batching
from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.core.resilience import (
    FATAL,
    OOM,
    RETRYABLE,
    Deadline,
    DeadlineExceeded,
    DeviceOOM,
    DrainTimeout,
    Fault,
    FaultInjector,
    Preemption,
    RetryPolicy,
    TransferStall,
    WorkerDraining,
    classify,
)


# -- classifier --------------------------------------------------------------

@pytest.mark.parametrize("err,kind", [
    (ValueError("shape mismatch"), FATAL),
    (TypeError("dtype"), FATAL),
    (KeyError("col"), FATAL),
    (DeadlineExceeded("too slow"), FATAL),
    (RuntimeError("INVALID_ARGUMENT: bad program"), FATAL),
    (DeviceOOM(), OOM),
    (RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating"), OOM),
    (RuntimeError("Resource exhausted: HBM"), OOM),
    (Preemption(), RETRYABLE),
    (TransferStall(), RETRYABLE),
    # the elastic-capacity drain classes: both transient by design —
    # a drained-away dispatch re-routes to a live worker; a torn-down
    # drain takes the ordinary lost-worker re-dispatch path
    (WorkerDraining("all candidates draining"), RETRYABLE),
    (DrainTimeout("exceeded the 60s drain grace"), RETRYABLE),
    (RuntimeError("UNAVAILABLE: socket closed"), RETRYABLE),
    (RuntimeError("something unprecedented"), RETRYABLE),  # gang default
    (OSError("connection reset"), RETRYABLE),
    # transient infra markers override a fatal wrapper type
    (ValueError("UNAVAILABLE: socket closed mid-collective"), RETRYABLE),
    # "OOM" matches as a word, not a substring
    (RuntimeError("OOM while allocating 2.1GiB"), OOM),
    # allocator prose matches case-insensitively
    (RuntimeError("Out of memory while trying to allocate 8589934592 "
                  "bytes"), OOM),
    (RuntimeError("BLOOM shard failed to load"), RETRYABLE),
    (ValueError("BLOOM config invalid"), FATAL),
])
def test_classify(err, kind):
    assert classify(err) == kind


# -- RetryPolicy -------------------------------------------------------------

def test_retry_policy_deterministic_and_exponential():
    a, b = RetryPolicy(seed=7), RetryPolicy(seed=7)
    delays = [a.delay(i) for i in (1, 2, 3, 4)]
    assert delays == [b.delay(i) for i in (1, 2, 3, 4)]  # deterministic
    # exponential growth dominates jitter (jitter ≤ 50%, growth = 2x)
    assert delays[1] > delays[0] and delays[3] > delays[1]
    # different seeds give different jitter
    assert RetryPolicy(seed=8).delay(1) != a.delay(1)
    # no-jitter policy is exact
    p = RetryPolicy(base_delay_s=1.0, multiplier=2.0, jitter=0.0,
                    max_delay_s=5.0)
    assert [p.delay(i) for i in (1, 2, 3, 4)] == [1.0, 2.0, 4.0, 5.0]
    with pytest.raises(ValueError):
        p.delay(0)


def test_retry_policy_backoff_and_jitter_bounds():
    """Every delay lands in [ideal, ideal * (1 + jitter)] where ideal is
    the capped exponential — jitter only ever ADDS (never shortens a
    backoff below the schedule), and the cap bounds the worst case at
    max_delay_s * (1 + jitter)."""
    for seed in (0, 1, 7, 1234):
        p = RetryPolicy(base_delay_s=0.25, multiplier=3.0, jitter=0.4,
                        max_delay_s=2.0, seed=seed)
        for attempt in range(1, 9):
            ideal = min(0.25 * 3.0 ** (attempt - 1), 2.0)
            d = p.delay(attempt)
            assert ideal <= d <= ideal * 1.4 + 1e-12, (seed, attempt, d)
    with pytest.raises(ValueError):
        RetryPolicy().delay(-1)


def test_retry_policy_execute_retries_drain_classes():
    """WorkerDraining / DrainTimeout behave as transients end to end: a
    fake clock proves the retry loop consumed the classified-RETRYABLE
    path (backoff slept) rather than re-raising."""
    slept = []
    calls = []

    def raced():
        calls.append(1)
        if len(calls) == 1:
            raise WorkerDraining("routed to a draining worker")
        if len(calls) == 2:
            raise DrainTimeout("drain grace exceeded")
        return "ok"

    policy = RetryPolicy(max_retries=3, base_delay_s=0.5, jitter=0.0)
    assert policy.execute(raced, sleep=slept.append) == "ok"
    assert len(calls) == 3
    assert slept == [0.5, 1.0]  # one backoff per transient, no jitter


def test_retry_policy_execute_retries_transient_only():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransferStall()
        return "ok"

    policy = RetryPolicy(max_retries=3, base_delay_s=0.0)
    assert policy.execute(flaky, sleep=lambda d: None) == "ok"
    assert len(calls) == 3

    calls.clear()

    def fatal():
        calls.append(1)
        raise ValueError("bad shape")

    with pytest.raises(ValueError):
        policy.execute(fatal, sleep=lambda d: None)
    assert len(calls) == 1  # never retried

    calls.clear()

    def oom():
        calls.append(1)
        raise DeviceOOM()

    with pytest.raises(DeviceOOM):  # OOM needs a smaller batch, not a retry
        policy.execute(oom, sleep=lambda d: None)
    assert len(calls) == 1


def test_retry_policy_execute_exhaustion_raises_last_error():
    def always():
        raise Preemption()

    with pytest.raises(Preemption):
        RetryPolicy(max_retries=2, base_delay_s=0.0).execute(
            always, sleep=lambda d: None)


def test_retry_policy_execute_respects_deadline():
    clock = [0.0]

    def always():
        clock[0] += 10.0
        raise TransferStall()

    deadline = Deadline(15.0, clock=lambda: clock[0])
    with pytest.raises(DeadlineExceeded):
        RetryPolicy(max_retries=10, base_delay_s=0.0).execute(
            always, deadline=deadline, sleep=lambda d: None)


# -- Deadline ----------------------------------------------------------------

def test_deadline():
    clock = [0.0]
    d = Deadline(5.0, clock=lambda: clock[0])
    assert d.remaining() == 5.0 and not d.expired()
    d.check()
    clock[0] = 6.0
    assert d.expired()
    with pytest.raises(DeadlineExceeded, match="deadline"):
        d.check("thing")
    assert Deadline(None).remaining() == float("inf")


# -- FaultInjector -----------------------------------------------------------

def test_injector_unknown_point_rejected():
    with pytest.raises(ValueError, match="Unknown injection point"):
        FaultInjector.seeded(0, not_a_point=1)


def test_injector_fires_n_times_then_disarms():
    from sparkdl_tpu.core import resilience

    with FaultInjector.seeded(0, device_oom=2) as inj:
        for _ in range(2):
            with pytest.raises(DeviceOOM):
                resilience.inject("device_oom")
        resilience.inject("device_oom")  # disarmed: no raise
        assert inj.fired["device_oom"] == 2
    resilience.inject("device_oom")  # deactivated: no-op


def test_injector_when_predicate_and_after():
    from sparkdl_tpu.core import resilience

    with FaultInjector.seeded(
            0, preemption=Fault(when=lambda ctx: ctx.get("step") == 3)):
        resilience.inject("preemption", step=1)
        resilience.inject("preemption", step=2)
        with pytest.raises(Preemption):
            resilience.inject("preemption", step=3)
    with FaultInjector.seeded(0, transfer_stall=Fault(after=2)) as inj:
        resilience.inject("transfer_stall")
        resilience.inject("transfer_stall")
        with pytest.raises(TransferStall):
            resilience.inject("transfer_stall")
        assert inj.fired["transfer_stall"] == 1


def test_injector_nested_activation_restores_previous():
    from sparkdl_tpu.core import resilience

    with FaultInjector.seeded(0, device_oom=5):
        with FaultInjector.seeded(0, preemption=5):
            resilience.inject("device_oom")  # inner masks outer: no raise
            assert resilience.active_injector().faults.keys() == {"preemption"}
        with pytest.raises(DeviceOOM):
            resilience.inject("device_oom")
    assert resilience.active_injector() is None


def test_injector_visible_from_worker_threads():
    """Process-wide by design: engine partition ops run on pool threads
    where a ContextVar scope entered on the driver would be invisible."""
    from sparkdl_tpu.core import resilience

    hit = []

    def worker():
        try:
            resilience.inject("device_oom")
        except DeviceOOM:
            hit.append(True)

    with FaultInjector.seeded(0, device_oom=1):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert hit == [True]


# -- run_batched: retry + OOM re-chunking ------------------------------------

FAST = RetryPolicy(max_retries=2, base_delay_s=0.0)


def test_run_batched_oom_rechunks_at_halved_bucket_identical_output():
    calls = []

    def fn(chunk):
        calls.append(chunk.shape[0])
        return chunk * 2.0

    arr = np.arange(40, dtype=np.float32).reshape(20, 2)
    baseline = batching.run_batched(fn, arr, batch_size=8)
    calls.clear()
    with FaultInjector.seeded(
            0, device_oom=Fault(times=-1,
                                when=lambda ctx: ctx["rows"] >= 8)) as inj:
        out = batching.run_batched(fn, arr, batch_size=8, retry_policy=FAST)
    np.testing.assert_array_equal(out, baseline)  # values AND row order
    assert inj.fired["device_oom"] >= 2
    assert calls and max(calls) <= 4  # every dispatch ran at ≤ half bucket


def test_run_batched_transient_error_retries_same_chunk():
    calls = []

    def fn(chunk):
        calls.append(chunk.shape[0])
        return chunk + 1

    arr = np.arange(10, dtype=np.float32).reshape(10, 1)
    with FaultInjector.seeded(0, transfer_stall=1) as inj:
        out = batching.run_batched(fn, arr, batch_size=4, retry_policy=FAST)
    np.testing.assert_array_equal(out, arr + 1)
    assert inj.fired["transfer_stall"] == 1


def test_run_batched_fatal_error_propagates_unretried():
    calls = []

    def fn(chunk):
        calls.append(1)
        raise ValueError("bad dtype in program")

    with pytest.raises(ValueError, match="bad dtype"):
        batching.run_batched(fn, np.zeros((4, 1), np.float32), 4,
                             retry_policy=FAST)
    assert len(calls) == 1


def test_run_batched_oom_at_minimal_bucket_exhausts_and_raises():
    with FaultInjector.seeded(0, device_oom=Fault(times=-1)):
        with pytest.raises(DeviceOOM):
            # multiple=4 forbids halving below 4; bucket starts at 4
            batching.run_batched(lambda c: c, np.zeros((4, 1), np.float32),
                                 4, multiple=4, retry_policy=FAST)


# -- apply_batch: the acceptance-criteria path -------------------------------

def _linear_model():
    w = jnp.arange(6.0).reshape(3, 2)
    return ModelFunction.fromFunction(lambda vs, x: x @ vs, w,
                                      TensorSpec((None, 3)))


def test_apply_batch_injected_oom_halves_bucket_and_is_bit_identical():
    """Acceptance: under injected device_oom at the initial bucket size,
    apply_batch retries at a halved bucket and returns results identical
    (same values, same row order) to an uninjected run."""
    mf = _linear_model()
    rng = np.random.default_rng(42)
    arr = rng.normal(size=(50, 3)).astype(np.float32)
    baseline = mf.apply_batch(arr, batch_size=16)
    with FaultInjector.seeded(0, device_oom=1) as inj:
        out = mf.apply_batch(arr, batch_size=16)
    assert inj.fired["device_oom"] == 1
    assert np.array_equal(np.asarray(baseline), np.asarray(out))


def test_apply_batch_fatal_error_not_retried():
    calls = []

    def bad(vs, x):
        calls.append(1)
        raise ValueError("deliberate shape error")

    mf = ModelFunction.fromFunction(bad, None, TensorSpec((None, 3)))
    with pytest.raises(ValueError, match="deliberate"):
        mf.apply_batch(np.zeros((4, 3), np.float32), batch_size=4)
    assert len(calls) == 1


def test_apply_batch_outer_oom_fallback_halves_batch_size():
    """An OOM surfacing outside per-chunk dispatch (e.g. at the deferred
    fetch) re-runs the whole call at a halved batch_size."""
    mf = _linear_model()
    arr = np.arange(24, dtype=np.float32).reshape(8, 3)
    baseline = mf.apply_batch(arr, batch_size=8)

    seen = []
    original = batching.run_batched

    def oom_once(fn, tree, batch_size, **kw):
        seen.append(batch_size)
        if len(seen) == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: while fetching outputs")
        return original(fn, tree, batch_size, **kw)

    import sparkdl_tpu.core.model_function as mfmod

    orig = mfmod.batching.run_batched
    mfmod.batching.run_batched = oom_once
    try:
        out = mf.apply_batch(arr, batch_size=8)
    finally:
        mfmod.batching.run_batched = orig
    assert seen == [8, 4]
    np.testing.assert_array_equal(np.asarray(out), np.asarray(baseline))
