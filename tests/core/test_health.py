"""HealthMonitor: counters, bounded events, process-wide scoping, report."""

import logging
import threading

import pytest

from sparkdl_tpu.core import health
from sparkdl_tpu.core.health import HealthMonitor


def test_record_counts_and_events():
    mon = HealthMonitor("t")
    mon.record("task_retried", partition=3, kind="retryable")
    mon.record("task_retried", partition=5, kind="retryable")
    mon.record("oom_rechunk", bucket=16, half=8)
    assert mon.count("task_retried") == 2
    assert mon.count("oom_rechunk") == 1
    assert mon.count("nothing") == 0
    assert mon.counters() == {"task_retried": 2, "oom_rechunk": 1}
    evs = mon.events("task_retried")
    assert [e["partition"] for e in evs] == [3, 5]
    assert len(mon.events()) == 3


def test_record_n_batches_counter():
    mon = HealthMonitor()
    mon.record("decode_degraded", n=4, stage="structs")
    assert mon.count("decode_degraded") == 4
    assert mon.events("decode_degraded")[0]["n"] == 4


def test_event_log_bounded_counter_unbounded():
    mon = HealthMonitor(max_events=3)
    for i in range(10):
        mon.record("e", i=i)
    assert mon.count("e") == 10
    assert len(mon.events()) == 3
    rep = mon.report()
    assert rep["events_recorded"] == 3 and rep["events_dropped"] == 7


def test_dropped_events_counted_per_event_and_queryable():
    """Overflow is never silent (ISSUE 4 satellite): the total AND the
    per-event-name breakdown surface in report(), plus an accessor."""
    mon = HealthMonitor(max_events=2)
    mon.record("task_retried", partition=0)
    mon.record("task_retried", partition=1)
    assert mon.dropped_events() == 0
    for i in range(3):
        mon.record("task_retried", partition=2 + i)
    mon.record("oom_rechunk", bucket=8)
    assert mon.dropped_events() == 4
    rep = mon.report()
    assert rep["events_dropped"] == 4
    assert rep["events_dropped_by_event"] == {"task_retried": 3,
                                              "oom_rechunk": 1}
    # counters stay exact regardless of log overflow
    assert mon.count("task_retried") == 5
    assert mon.count("oom_rechunk") == 1


def test_module_record_requires_active_monitor():
    health.record("task_started")  # no monitor: no-op, no error
    assert health.active_monitor() is None
    with HealthMonitor("outer") as outer:
        health.record("task_started")
        with HealthMonitor("inner") as inner:
            health.record("task_started")
            assert health.active_monitor() is inner
        health.record("task_started")
        assert health.active_monitor() is outer
    assert health.active_monitor() is None
    assert outer.count("task_started") == 2
    assert inner.count("task_started") == 1


def test_record_visible_from_worker_threads():
    """Process-wide by design (the FaultInjector rationale): engine
    partition tasks record from pool threads."""
    with HealthMonitor() as mon:
        threads = [threading.Thread(
            target=lambda: [health.record("tick") for _ in range(100)])
            for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert mon.count("tick") == 400


def test_report_and_quarantine_registry():
    mon = HealthMonitor("chaos-run")
    mon.record(health.TASK_QUARANTINED, partition=2, error="boom")
    mon.record(health.TASK_RETRIED, partition=0)
    rep = mon.report()
    assert rep["run"] == "chaos-run"
    assert rep["counters"] == {"task_quarantined": 1, "task_retried": 1}
    assert rep["quarantined"] == [
        {"event": "task_quarantined", "partition": 2, "error": "boom"}]
    assert mon.quarantined()[0]["partition"] == 2


def test_log_report_once_at_job_end(caplog):
    with caplog.at_level(logging.INFO, logger="sparkdl_tpu.core.health"):
        with HealthMonitor("r1"):
            health.record("gang_restart")
        # deactivation IS the job-end hook: one report, cumulative
        health.log_report()  # inactive: no-op
        with HealthMonitor("empty"):
            pass  # nothing recorded: no report noise
    msgs = [r.message for r in caplog.records]
    assert any("'r1'" in m and "gang_restart=1" in m for m in msgs)
    assert len([m for m in msgs if "health report" in m]) == 1
