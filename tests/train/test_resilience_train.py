"""Gang-restart resilience: classified restarts, checkpoint-resumed
preemption recovery, corrupt-checkpoint fallback (docs/RESILIENCE.md)."""

import logging

import numpy as np
import pytest
import jax

import flax.linen as nn

from sparkdl_tpu.core.resilience import Fault, FaultInjector, RetryPolicy
from sparkdl_tpu.train import CheckpointManager, TPURunner, Trainer


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        return jax.nn.softmax(nn.Dense(3)(nn.relu(nn.Dense(8)(x))), axis=-1)


def _data(n=32, d=4):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return [(x[i:i + 8], y[i:i + 8]) for i in range(0, n, 8)]


@pytest.fixture
def module_and_vars():
    module = MLP()
    variables = module.init(jax.random.PRNGKey(0),
                            np.zeros((1, 4), np.float32))
    return module, variables


def test_injected_preemption_resumes_from_latest_checkpoint(
        tmp_path, module_and_vars):
    """Acceptance: TPURunner(max_restarts≥1) with an injected mid-training
    preemption resumes from the latest checkpoint step — the executed-step
    trace shows no redone steps (checkpoint_every=1 ⇒ zero lost work)."""
    module, variables = module_and_vars
    batches = _data()
    steps_run, attempts = [], []

    def train_fn(mesh=None):
        attempts.append(1)
        trainer, state = Trainer.from_flax(module, variables,
                                           optimizer="sgd",
                                           learning_rate=0.1, mesh=mesh)
        ckpt = CheckpointManager(str(tmp_path / "gang"))
        state = trainer.fit(state, batches, epochs=2, checkpoint=ckpt,
                            checkpoint_every=1,
                            on_step=steps_run.append)
        ckpt.wait_until_finished()
        ckpt.close()
        return int(state.step)

    with FaultInjector.seeded(
            0, preemption=Fault(when=lambda ctx: ctx["step"] == 3)) as inj:
        final = TPURunner(np=2, max_restarts=2).run(train_fn)
    assert final == 8
    assert inj.fired["preemption"] == 1
    assert len(attempts) == 2  # one preemption, one successful restart
    # the restart resumed AT the checkpoint: every step executed once
    assert steps_run == [1, 2, 3, 4, 5, 6, 7, 8]


def test_preemption_with_sparse_checkpoints_redoes_at_most_interval(
        tmp_path, module_and_vars):
    """checkpoint_every=2 + preemption at step 3: the restart resumes from
    step 2, so only step 3 is recomputed — bounded by the interval."""
    module, variables = module_and_vars
    batches = _data()
    steps_run = []

    def train_fn(mesh=None):
        trainer, state = Trainer.from_flax(module, variables,
                                           optimizer="sgd",
                                           learning_rate=0.1, mesh=mesh)
        ckpt = CheckpointManager(str(tmp_path / "gang2"))
        state = trainer.fit(state, batches, epochs=2, checkpoint=ckpt,
                            checkpoint_every=2,
                            on_step=steps_run.append)
        ckpt.wait_until_finished()
        ckpt.close()
        return int(state.step)

    with FaultInjector.seeded(
            0, preemption=Fault(when=lambda ctx: ctx["step"] == 3)):
        final = TPURunner(np=2, max_restarts=1).run(train_fn)
    assert final == 8
    assert steps_run == [1, 2, 3, 3, 4, 5, 6, 7, 8]  # exactly one redo


def test_fatal_error_raises_without_restart():
    """Acceptance: a fatal ValueError from the train fn is raised
    unwrapped, with zero restart attempts."""
    attempts = []

    def train_fn(mesh=None):
        attempts.append(1)
        raise ValueError("label shape (8, 4) does not match logits (8, 3)")

    with pytest.raises(ValueError, match="label shape"):
        TPURunner(np=2, max_restarts=3).run(train_fn)
    assert len(attempts) == 1


def test_runner_backoff_uses_policy_delays(monkeypatch):
    from sparkdl_tpu.core import health
    from sparkdl_tpu.core.health import HealthMonitor

    slept = []
    monkeypatch.setattr("sparkdl_tpu.train.runner.time.sleep", slept.append)
    policy = RetryPolicy(max_retries=2, base_delay_s=1.0, jitter=0.0)

    def always_fail(mesh=None):
        raise RuntimeError("worker lost")

    with HealthMonitor() as mon:
        with pytest.raises(RuntimeError, match="after 3 attempts"):
            TPURunner(np=2, max_restarts=2,
                      retry_policy=policy).run(always_fail)
    assert slept == [1.0, 2.0]  # exponential, not fixed
    # the health report distinguishes restarted-and-died from recovered
    assert mon.count(health.GANG_RESTART) == 2
    assert mon.count(health.GANG_FAILED) == 1


def test_runner_oom_gang_failure_not_restarted():
    """A same-shape replay reproduces an OOM and the runner has no
    batch-shrink response — surface it unretried, like FATAL."""
    from sparkdl_tpu.core import health
    from sparkdl_tpu.core.health import HealthMonitor
    from sparkdl_tpu.core.resilience import DeviceOOM

    attempts = []

    def oom_fn(mesh=None):
        attempts.append(1)
        raise DeviceOOM()

    with HealthMonitor() as mon:
        with pytest.raises(DeviceOOM):
            TPURunner(np=2, max_restarts=3).run(oom_fn)
    assert len(attempts) == 1
    assert mon.count(health.GANG_FATAL) == 1
    assert mon.count(health.GANG_RESTART) == 0


# -- checkpoint corruption ---------------------------------------------------

def _fit_with_checkpoints(tmp_path, module_and_vars, name="ck",
                          injector_ctx=None):
    module, variables = module_and_vars
    trainer, state = Trainer.from_flax(module, variables, optimizer="sgd",
                                       learning_rate=0.1)
    ckpt = CheckpointManager(str(tmp_path / name))
    state = trainer.fit(state, _data(), epochs=1, checkpoint=ckpt,
                        checkpoint_every=1)
    ckpt.wait_until_finished()
    return ckpt, jax.device_get(state)


def test_corrupt_latest_checkpoint_falls_back_with_warning(
        tmp_path, module_and_vars, caplog):
    """Acceptance: a truncated latest checkpoint restores from the
    previous retained step, warning names the skipped step."""
    ckpt, state = _fit_with_checkpoints(tmp_path, module_and_vars)
    assert ckpt.all_steps() == [2, 3, 4]
    ckpt._truncate_step(4)
    with caplog.at_level(logging.WARNING, logger="sparkdl_tpu.train.checkpoint"):
        restored = ckpt.restore(state)
    assert int(restored.step) == 3
    assert any("step 4" in r.message and "falling back" in r.message
               for r in caplog.records)
    ckpt.close()


def test_checkpoint_truncate_injection_point(tmp_path, module_and_vars,
                                             caplog):
    """The checkpoint_truncate fault corrupts a COMMITTED save; restore
    degrades to the previous step instead of raising."""
    module, variables = module_and_vars
    trainer, state = Trainer.from_flax(module, variables, optimizer="sgd",
                                       learning_rate=0.1)
    ckpt = CheckpointManager(str(tmp_path / "inj"))
    host = jax.device_get(state)
    ckpt.save(1, host, synchronous=True)
    with FaultInjector.seeded(0, checkpoint_truncate=1) as inj:
        ckpt.save(2, host, synchronous=True)
    assert inj.fired["checkpoint_truncate"] == 1
    with caplog.at_level(logging.WARNING):
        restored = ckpt.restore(host)
    assert int(restored.step) == int(host.step)  # step-1 copy restored
    assert any("falling back to step 1" in r.message
               for r in caplog.records)
    ckpt.close()


def test_save_over_existing_step_overwrites(tmp_path, module_and_vars):
    """Re-saving a step that already exists on disk (gang restart replay,
    or replay past a corrupt copy) must actually overwrite — Orbax would
    otherwise silently skip it (should_save() false) and a corrupt latest
    step would live forever."""
    ckpt, state = _fit_with_checkpoints(tmp_path, module_and_vars,
                                        name="overwrite")
    latest = ckpt.latest_step()
    ckpt._truncate_step(latest)
    ckpt.close()
    # a restarted gang opens a FRESH manager over the same directory
    ckpt2 = CheckpointManager(str(tmp_path / "overwrite"))
    with pytest.raises(Exception):
        ckpt2.restore(state, step=latest)  # corrupt: direct restore fails
    ckpt2.save(latest, state, synchronous=True)  # recomputed replay re-saves
    restored = ckpt2.restore(state, step=latest)  # now restores cleanly
    assert int(restored.step) == int(state.step)
    ckpt2.close()


def test_all_checkpoints_corrupt_raises(tmp_path, module_and_vars):
    ckpt, state = _fit_with_checkpoints(tmp_path, module_and_vars,
                                        name="allbad")
    for step in ckpt.all_steps():
        ckpt._truncate_step(step)
    with pytest.raises(Exception):
        ckpt.restore(state)
    ckpt.close()


def test_explicit_step_restore_does_not_fall_back(tmp_path, module_and_vars):
    ckpt, state = _fit_with_checkpoints(tmp_path, module_and_vars,
                                        name="explicit")
    ckpt._truncate_step(4)
    with pytest.raises(Exception):
        ckpt.restore(state, step=4)
    ckpt.close()


def test_resume_after_preemption_matches_uninterrupted_run(
        tmp_path, module_and_vars):
    """End-to-end determinism: preempted+resumed training produces the
    same final params as an uninterrupted run (exact replay of the batch
    stream from the checkpointed step)."""
    module, variables = module_and_vars
    batches = _data()

    def run(ckpt_dir, inject):
        def train_fn(mesh=None):
            trainer, state = Trainer.from_flax(module, variables,
                                               optimizer="sgd",
                                               learning_rate=0.1, mesh=mesh)
            ckpt = CheckpointManager(ckpt_dir)
            state = trainer.fit(state, batches, epochs=1, checkpoint=ckpt,
                                checkpoint_every=1)
            ckpt.wait_until_finished()
            ckpt.close()
            return jax.device_get(state)

        if inject:
            with FaultInjector.seeded(
                    0, preemption=Fault(when=lambda c: c["step"] == 2)):
                return TPURunner(np=2, max_restarts=1).run(train_fn)
        return TPURunner(np=2).run(train_fn)

    plain = run(str(tmp_path / "plain"), inject=False)
    resumed = run(str(tmp_path / "preempted"), inject=True)
    for a, b in zip(jax.tree.leaves(plain.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
