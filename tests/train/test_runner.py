"""TPURunner: mesh provisioning, restart-from-checkpoint gang semantics,
fault injection (SURVEY.md §3.5, §5.3)."""

import numpy as np
import pytest
import jax

import flax.linen as nn

from sparkdl_tpu.train import CheckpointManager, TPURunner, Trainer


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        return jax.nn.softmax(nn.Dense(3)(nn.relu(nn.Dense(8)(x))), axis=-1)


def _data(n=32, d=4):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return [(x[i:i + 8], y[i:i + 8]) for i in range(0, n, 8)]


def test_runner_passes_mesh_and_uses_np_devices():
    seen = {}

    def main(mesh=None):
        seen["mesh"] = mesh
        return "done"

    assert TPURunner(np=4).run(main) == "done"
    assert seen["mesh"].shape["data"] == 4


def test_runner_np_too_large_rejected():
    with pytest.raises(ValueError, match="devices"):
        TPURunner(np=1024).run(lambda mesh=None: None)


def test_runner_restarts_and_resumes_from_checkpoint(tmp_path):
    """Kill the gang at step 2 on attempt 1; the restart must resume from
    the checkpoint and finish all 8 steps."""
    batches = _data()
    module = MLP()
    variables = module.init(jax.random.PRNGKey(0),
                            np.zeros((1, 4), np.float32))
    attempts = []

    def train_fn(mesh=None):
        attempt = len(attempts)
        attempts.append(attempt)
        trainer, state = Trainer.from_flax(module, variables, optimizer="sgd",
                                           learning_rate=0.1, mesh=mesh)
        ckpt = CheckpointManager(str(tmp_path / "gang"))

        def fault(step):
            if attempt == 0 and step == 2:
                raise RuntimeError("injected worker loss")

        state = trainer.fit(state, batches, epochs=2, checkpoint=ckpt,
                            checkpoint_every=1, on_step=fault)
        ckpt.wait_until_finished()
        ckpt.close()
        return int(state.step)

    final = TPURunner(np=2, max_restarts=2).run(train_fn)
    assert final == 8
    assert len(attempts) == 2  # one failure, one successful restart


def test_runner_exhausted_restarts_raise():
    def always_fail(mesh=None):
        raise RuntimeError("broken")

    with pytest.raises(RuntimeError, match="after 2 attempts"):
        TPURunner(np=2, max_restarts=1).run(always_fail)
