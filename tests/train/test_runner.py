"""TPURunner: mesh provisioning, restart-from-checkpoint gang semantics,
fault injection (SURVEY.md §3.5, §5.3)."""

import os

import numpy as np
import pytest
import jax

import flax.linen as nn

from sparkdl_tpu.train import CheckpointManager, TPURunner, Trainer


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        return jax.nn.softmax(nn.Dense(3)(nn.relu(nn.Dense(8)(x))), axis=-1)


def _data(n=32, d=4):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return [(x[i:i + 8], y[i:i + 8]) for i in range(0, n, 8)]


# Abort signatures of jax's experimental gloo CPU-collectives transport
# dying in its own TCP pair layer (e.g. "op.preamble.length <= op.nbytes"
# → SIGABRT). Environmental raciness of the test transport, not framework
# logic — real TPU/GPU gangs never ride gloo.
_GLOO_ABORT_MARKERS = (b"gloo::EnforceNotMet", b"gloo/transport/tcp")


def _run_gang(worker: str, args, timeout: float = 240.0,
              num_processes: int = 2, gloo_retries: int = 2) -> None:
    """Launch the multi-process jax.distributed gang and assert every
    process exits 0. A gang that dies with a gloo transport abort is
    relaunched (fresh coordinator port) up to ``gloo_retries`` times —
    bounded triage for the CPU test transport's raciness; any other
    failure (framework bugs included) asserts immediately."""
    import socket
    import subprocess
    import sys

    for attempt in range(gloo_retries + 1):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = []
        for pid in range(num_processes):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)  # worker sets its own device count
            env.update({
                "SPARKDL_COORDINATOR": f"127.0.0.1:{port}",
                "SPARKDL_NUM_PROCESSES": str(num_processes),
                "SPARKDL_PROCESS_ID": str(pid),
            })
            procs.append(subprocess.Popen(
                [sys.executable, worker] + [str(a) for a in args], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
        if (attempt < gloo_retries
                and any(p.returncode != 0 for p in procs)
                and any(m in out for m in _GLOO_ABORT_MARKERS
                        for out in outs)):
            continue
        for p, out in zip(procs, outs):
            assert p.returncode == 0, out.decode(errors="replace")[-3000:]
        return


def test_runner_passes_mesh_and_uses_np_devices():
    seen = {}

    def main(mesh=None):
        seen["mesh"] = mesh
        return "done"

    assert TPURunner(np=4).run(main) == "done"
    assert seen["mesh"].shape["data"] == 4


def test_runner_np_too_large_rejected():
    with pytest.raises(ValueError, match="devices"):
        TPURunner(np=1024).run(lambda mesh=None: None)


def test_runner_restarts_and_resumes_from_checkpoint(tmp_path):
    """Kill the gang at step 2 on attempt 1; the restart must resume from
    the checkpoint and finish all 8 steps."""
    batches = _data()
    module = MLP()
    variables = module.init(jax.random.PRNGKey(0),
                            np.zeros((1, 4), np.float32))
    attempts = []

    def train_fn(mesh=None):
        attempt = len(attempts)
        attempts.append(attempt)
        trainer, state = Trainer.from_flax(module, variables, optimizer="sgd",
                                           learning_rate=0.1, mesh=mesh)
        ckpt = CheckpointManager(str(tmp_path / "gang"))

        def fault(step):
            if attempt == 0 and step == 2:
                raise RuntimeError("injected worker loss")

        state = trainer.fit(state, batches, epochs=2, checkpoint=ckpt,
                            checkpoint_every=1, on_step=fault)
        ckpt.wait_until_finished()
        ckpt.close()
        return int(state.step)

    final = TPURunner(np=2, max_restarts=2).run(train_fn)
    assert final == 8
    assert len(attempts) == 2  # one failure, one successful restart


def test_runner_exhausted_restarts_raise():
    def always_fail(mesh=None):
        raise RuntimeError("broken")

    with pytest.raises(RuntimeError, match="after 2 attempts"):
        TPURunner(np=2, max_restarts=1).run(always_fail)


def test_two_process_distributed_training_matches_single(tmp_path):
    """2-process jax.distributed on CPU (SURVEY.md §5.8, §3.5): each
    process feeds its local half of every global batch; the trained params
    must equal a single-process run over the same global batches."""
    import sys

    import jax

    from sparkdl_tpu.core.mesh import MeshConfig, make_mesh

    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    _run_gang(worker, [tmp_path])

    got = np.load(tmp_path / "multihost_params.npy")

    # single-process reference over the SAME global batches (8 local devices)
    sys.path.insert(0, os.path.dirname(worker))
    try:
        import _multihost_worker as w
    finally:
        sys.path.pop(0)
    mesh = make_mesh(MeshConfig(data=8))
    trainer, state = w.build_trainer(mesh)
    state = trainer.fit(state, w.global_batches(), epochs=1)
    want = np.concatenate([np.ravel(leaf) for leaf in jax.tree.leaves(
        jax.device_get(state.params))])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_two_process_estimator_fit_matches_single(tmp_path):
    """Multi-host `.fit(df)` through the PUBLIC ML API (VERDICT r3 #4):
    each process decodes only its round-robin partition share, emits local
    batches, and the fitted params equal a single-process streaming fit of
    the same DataFrame (partition sizes == local batch, shuffle=False, so
    the global batch sequence is identical)."""
    import json
    import sys

    import jax

    keras = pytest.importorskip("keras")
    from keras import layers
    from PIL import Image

    from sparkdl_tpu.core.mesh import MeshConfig, make_mesh

    # deterministic data: 4 partitions x 8 rows of trivially-labeled PNGs
    rng = np.random.default_rng(0)
    rows = []
    for i in range(32):
        label = i % 2
        arr = rng.integers(0, 40, size=(8, 8, 3), dtype=np.uint8)
        arr[..., label] += 180
        p = tmp_path / f"img_{i:02d}.png"
        Image.fromarray(arr).save(p)
        rows.append({"uri": str(p), "label": label})
    model_file = str(tmp_path / "model.keras")
    keras.Sequential([
        keras.Input((8, 8, 3)), layers.Rescaling(1 / 255.0),
        layers.Flatten(), layers.Dense(2, activation="softmax"),
    ]).save(model_file)
    with open(tmp_path / "manifest.json", "w") as f:
        json.dump({"rows": rows, "model_file": model_file}, f)

    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_estimator_worker.py")
    _run_gang(worker, [tmp_path, tmp_path], timeout=420)
    got = np.load(tmp_path / "multihost_estimator_params.npy")
    with open(tmp_path / "multihost_estimator_history.json") as f:
        got_history = json.load(f)

    # single-process reference: same estimator, same DataFrame, 8 local
    # devices (this pytest process), streaming fit
    sys.path.insert(0, os.path.dirname(worker))
    try:
        import _multihost_estimator_worker as w
    finally:
        sys.path.pop(0)
    mesh = make_mesh(MeshConfig(data=8))
    est, df = w.build_estimator(str(tmp_path), mesh)
    model = est.fit(df)
    want = w.flat_params(model)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # collected (streaming=False) path: per-host batch slicing must equal
    # the single-process collected fit (r5)
    got_collected = np.load(tmp_path / "multihost_collected_params.npy")
    want_collected = w.flat_params(w.collected_fit(est, df))
    np.testing.assert_allclose(got_collected, want_collected,
                               rtol=1e-5, atol=1e-6)
    # epoch-end validation under multi-host (VERDICT r4 #7): history equals
    # the single-process fit's
    want_history = model.history["epochs"]
    assert len(got_history) == len(want_history) == 2
    for g, s in zip(got_history, want_history):
        assert g["epoch"] == s["epoch"]
        for key in ("val_loss", "val_accuracy"):
            assert key in g and key in s
            np.testing.assert_allclose(g[key], s[key], rtol=1e-5,
                                       atol=1e-6)


def test_two_process_transform_matches_single(tmp_path):
    """Multi-host DP INFERENCE through the public ML API (VERDICT r4 #1):
    each process featurizes only its round-robin partition share (asserted
    inside the worker), gatherProcesses reassembles the full frame in
    original order, and the gathered features equal a single-process
    transform of the same DataFrame."""
    import sys

    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_transform_worker.py")
    _run_gang(worker, [tmp_path])
    got = np.load(tmp_path / "multihost_transform_features.npy")

    # single-process reference: same frame, same featurizer (processShard
    # is a no-op at process_count == 1)
    sys.path.insert(0, os.path.dirname(worker))
    try:
        import _multihost_transform_worker as w
    finally:
        sys.path.pop(0)
    out = w.build_featurizer().transform(w.build_frame()).collect()
    want = w.features_matrix(out)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
