"""Worker process for the 2-process jax.distributed CPU test.

Each of the two processes owns 4 virtual CPU devices (8 global), joins the
process group via the SPARKDL_* env triple (train/runner.py), builds the
global data mesh, and feeds its LOCAL half of every deterministic global
batch through Trainer.fit — the per-host input feeding of SURVEY.md §5.8.
Process 0 writes the final params for comparison against a single-process
run of the same global batches.

Usage: python _multihost_worker.py <out_dir>
(env: SPARKDL_COORDINATOR/NUM_PROCESSES/PROCESS_ID set by the test)
"""

import os
import sys

if __name__ == "__main__":
    # Worker-only env: MUST precede the first jax import. Guarded so that
    # importing this module from the pytest process (for build_trainer /
    # global_batches) does not mutate its env or jax config.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=4")

import jax  # noqa: E402

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from sparkdl_tpu.core.mesh import MeshConfig, make_mesh  # noqa: E402
from sparkdl_tpu.models import registry  # noqa: E402
from sparkdl_tpu.train import Trainer  # noqa: E402
from sparkdl_tpu.train.runner import maybe_initialize_distributed  # noqa: E402

GLOBAL_BATCH = 16
STEPS = 3


def global_batches():
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, size=(STEPS * GLOBAL_BATCH, 32, 32, 3)
                    ).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[
        rng.integers(0, 10, size=STEPS * GLOBAL_BATCH)]
    return [(x[s * GLOBAL_BATCH:(s + 1) * GLOBAL_BATCH],
             y[s * GLOBAL_BATCH:(s + 1) * GLOBAL_BATCH])
            for s in range(STEPS)]


def build_trainer(mesh):
    spec = registry.get_model_spec("TestNet")
    module = spec.builder(include_top=True, classes=spec.classes)
    variables = module.init(jax.random.PRNGKey(0),
                            np.zeros((1, 32, 32, 3), np.float32))
    return Trainer.from_flax(module, variables,
                             loss="categorical_crossentropy",
                             optimizer="sgd", learning_rate=0.05, mesh=mesh)


def main(out_dir: str) -> None:
    assert maybe_initialize_distributed(), "SPARKDL_* env triple not set"
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())
    mesh = make_mesh(MeshConfig(data=8))
    pid = jax.process_index()
    per = GLOBAL_BATCH // 2
    local = [(x[pid * per:(pid + 1) * per], y[pid * per:(pid + 1) * per])
             for x, y in global_batches()]
    trainer, state = build_trainer(mesh)
    state = trainer.fit(state, local, epochs=1)
    assert int(state.step) == STEPS
    params = jax.device_get(state.params)
    if pid == 0:
        flat = np.concatenate([np.ravel(leaf)
                               for leaf in jax.tree.leaves(params)])
        np.save(os.path.join(out_dir, "multihost_params.npy"), flat)


if __name__ == "__main__":
    main(sys.argv[1])
