"""Worker for the 2-process multi-host `KerasImageFileEstimator.fit(df)`
test (VERDICT r3 #4 / SURVEY.md §2.5, §3.5).

Each of two processes owns 4 virtual CPU devices (8 global), joins the
process group via the SPARKDL_* env triple, and calls the PUBLIC ML API:
``estimator.fit(image_dataframe)``. The estimator's streaming path must
shard partitions per-process (each host decodes only its round-robin
share), emit local batches, and let Trainer assemble the global arrays —
process 0 writes the fitted params for comparison with a single-process
fit of the same DataFrame.

Usage: python _multihost_estimator_worker.py <data_dir> <out_dir>
(data_dir holds manifest.json {rows, model_file} written by the test)
"""

import json
import os
import sys

if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=4")

import jax  # noqa: E402

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from sparkdl_tpu.core.mesh import MeshConfig, make_mesh  # noqa: E402
from sparkdl_tpu.engine.dataframe import DataFrame  # noqa: E402
from sparkdl_tpu.ml import KerasImageFileEstimator  # noqa: E402
from sparkdl_tpu.train.runner import maybe_initialize_distributed  # noqa: E402

# Four partitions of 8 rows, global batch 16, shuffle=False: the global
# batch sequence ([p0;p1], [p2;p3]) is identical between the 2-process
# run (host0 streams p0,p2 / host1 p1,p3, each contributing local halves)
# and a single-process streaming fit — so params must match exactly.
NUM_PARTITIONS = 4
GLOBAL_BATCH = 16


def build_estimator(data_dir: str, mesh) -> "KerasImageFileEstimator":
    with open(os.path.join(data_dir, "manifest.json")) as f:
        manifest = json.load(f)
    df = DataFrame.fromRows(manifest["rows"],
                            numPartitions=NUM_PARTITIONS)
    # deterministic epoch-end validation set (VERDICT r4 #7): identical
    # arrays on every process; history must equal the single-process fit's
    vrng = np.random.default_rng(3)
    vx = vrng.integers(0, 255, size=(6, 8, 8, 3)).astype(np.float32)
    vy = np.eye(2, dtype=np.float32)[vrng.integers(0, 2, 6)]
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFile=manifest["model_file"], kerasOptimizer="sgd",
        kerasLoss="categorical_crossentropy", mesh=mesh,
        kerasFitParams={"epochs": 2, "batch_size": GLOBAL_BATCH,
                        "shuffle": False, "streaming": True,
                        "learning_rate": 0.05,
                        "validation_data": (vx, vy)})
    return est, df


def flat_params(model) -> np.ndarray:
    params = jax.device_get(model.getModelFunction().variables)
    return np.concatenate([np.ravel(leaf)
                           for leaf in jax.tree.leaves(params)])


def collected_fit(est, df):
    """The collected (streaming=False) path under multi-host: each host
    must slice its share of every global batch (r5)."""
    est = est.copy()
    fp = est.getKerasFitParams()
    fp["streaming"] = False
    fp["shuffle"] = False
    est.setKerasFitParams(fp)
    return est.fit(df)


def main(data_dir: str, out_dir: str) -> None:
    assert maybe_initialize_distributed(), "SPARKDL_* env triple not set"
    assert jax.process_count() == 2, jax.process_count()
    mesh = make_mesh(MeshConfig(data=8))
    est, df = build_estimator(data_dir, mesh)
    model = est.fit(df)
    collected = collected_fit(est, df)
    if jax.process_index() == 0:
        np.save(os.path.join(out_dir, "multihost_estimator_params.npy"),
                flat_params(model))
        np.save(os.path.join(out_dir, "multihost_collected_params.npy"),
                flat_params(collected))
        with open(os.path.join(out_dir,
                               "multihost_estimator_history.json"), "w") as f:
            json.dump(model.history["epochs"], f)


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2])
