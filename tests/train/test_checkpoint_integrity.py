"""Checkpoint crash consistency: per-file checksum manifests (silent
bit-rot detection) and monotonic fencing tokens (zombie-writer refusal)
— docs/RESILIENCE.md "Durable recovery"."""

import json
import logging
import os

import numpy as np
import pytest
import jax

import flax.linen as nn

from sparkdl_tpu.core import health, resilience
from sparkdl_tpu.core.health import HealthMonitor
from sparkdl_tpu.train import CheckpointManager, Trainer


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        return jax.nn.softmax(nn.Dense(3)(nn.relu(nn.Dense(8)(x))), axis=-1)


@pytest.fixture
def host_state():
    module = MLP()
    variables = module.init(jax.random.PRNGKey(0),
                            np.zeros((1, 4), np.float32))
    _trainer, state = Trainer.from_flax(module, variables, optimizer="sgd",
                                        learning_rate=0.1)
    return jax.device_get(state)


def _bit_flip_one_data_file(directory, step):
    """Flip one byte mid-file in the step's largest non-manifest file —
    size unchanged, so only a checksum can tell."""
    step_dir = os.path.join(directory, str(step))
    candidates = []
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            if name == "sparkdl.sums.json":
                continue
            path = os.path.join(root, name)
            candidates.append((os.path.getsize(path), path))
    size, path = max(candidates)
    raw = bytearray(open(path, "rb").read())
    raw[size // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(raw)
    return path


def test_sync_save_writes_manifest_inside_step_dir(tmp_path, host_state):
    ckpt = CheckpointManager(str(tmp_path / "ck"))
    ckpt.save(1, host_state, synchronous=True)
    manifest = tmp_path / "ck" / "1" / "sparkdl.sums.json"
    assert manifest.exists()
    data = json.loads(manifest.read_text())
    assert data["step"] == 1 and data["files"]
    # orbax's own root dir contents are untouched: the manifest rides
    # retention for free by living inside the step
    ckpt.close()


def test_bit_flip_rejected_by_checksum_explicit_step(tmp_path, host_state):
    ckpt = CheckpointManager(str(tmp_path / "ck"))
    ckpt.save(1, host_state, synchronous=True)
    _bit_flip_one_data_file(ckpt.directory, 1)
    with HealthMonitor() as mon:
        with pytest.raises(IOError, match="checksum verification"):
            ckpt.restore(host_state, step=1)
    assert mon.events(health.CHECKPOINT_CHECKSUM_REJECTED)
    ckpt.close()


def test_bit_flip_falls_back_to_previous_step(tmp_path, host_state, caplog):
    ckpt = CheckpointManager(str(tmp_path / "ck"))
    ckpt.save(1, host_state, synchronous=True)
    ckpt.save(2, host_state, synchronous=True)
    _bit_flip_one_data_file(ckpt.directory, 2)
    with caplog.at_level(logging.WARNING,
                         logger="sparkdl_tpu.train.checkpoint"):
        restored = ckpt.restore(host_state)
    assert int(restored.step) == int(host_state.step)
    assert any("step 2" in r.message and "falling back" in r.message
               for r in caplog.records)
    ckpt.close()


def test_manifestless_step_restores_without_verification(tmp_path,
                                                         host_state):
    """Legacy steps (or ones whose manifest a crash shredded) restore on
    Orbax's own error handling — the manifest extends detection, it is
    not a gate."""
    ckpt = CheckpointManager(str(tmp_path / "ck"))
    ckpt.save(1, host_state, synchronous=True)
    os.unlink(os.path.join(ckpt.directory, "1", "sparkdl.sums.json"))
    restored = ckpt.restore(host_state, step=1)
    assert int(restored.step) == int(host_state.step)
    ckpt.close()


def test_stale_incarnation_save_refused(tmp_path, host_state):
    """A zombie writer from a superseded gang attempt must not clobber
    its successor's checkpoints: the newer incarnation fences it off."""
    old = CheckpointManager(str(tmp_path / "ck"))
    old.save(1, host_state, synchronous=True)
    new = CheckpointManager(str(tmp_path / "ck"))  # supersedes `old`
    with HealthMonitor() as mon:
        with pytest.raises(resilience.StaleCheckpointWriter) as ei:
            old.save(2, host_state, synchronous=True)
    assert mon.events(health.CHECKPOINT_FENCED)
    # FATAL by taxonomy: every retry of a fenced save would be refused too
    assert resilience.classify(ei.value) == resilience.FATAL
    # the live incarnation keeps saving normally
    new.save(2, host_state, synchronous=True)
    assert new.all_steps() == [1, 2]
    new.close()
    old.close()


def test_fence_token_is_monotonic_per_directory(tmp_path, host_state):
    a = CheckpointManager(str(tmp_path / "ck"))
    b = CheckpointManager(str(tmp_path / "ck"))
    c = CheckpointManager(str(tmp_path / "ck"))
    assert a._incarnation < b._incarnation < c._incarnation
    fence = json.loads((tmp_path / "ck.fence.json").read_text())
    assert fence["incarnation"] == c._incarnation
    for m in (a, b, c):
        m.close()
    # a manager on a DIFFERENT directory is unaffected
    other = CheckpointManager(str(tmp_path / "other"))
    assert other._incarnation == 1
    other.close()
