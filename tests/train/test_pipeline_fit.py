"""Pipelined Trainer.fit (ISSUE 3): bit-identical to the serial loop,
exact resume under deferred sync, genuine staging/compute overlap, and
failure paths (staging errors propagate, no leaked threads, checkpoints
flushed)."""

import threading
import time

import numpy as np
import pytest

import jax
import flax.linen as nn

from sparkdl_tpu.train import CheckpointManager, MetricsLogger, Trainer


class TinyMLP(nn.Module):
    classes: int = 3

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Dense(16)(x)
        x = nn.relu(x)
        x = nn.Dense(self.classes)(x)
        return jax.nn.softmax(x, axis=-1)


def _toy_data(n=64, d=6, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, classes)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


def _batches(x, y, bs):
    return [(x[i:i + bs], y[i:i + bs]) for i in range(0, len(x) - bs + 1, bs)]


def _make(seed=0):
    x, y = _toy_data()
    module = TinyMLP()
    variables = module.init(jax.random.PRNGKey(seed), x[:1])
    trainer, state = Trainer.from_flax(module, variables, optimizer="sgd",
                                       learning_rate=0.1)
    return trainer, state, _batches(x, y, 16)


def _leaves(tree):
    return [np.asarray(a) for a in jax.tree.leaves(jax.device_get(tree))]


def test_pipelined_fit_bit_identical_to_serial_loop():
    """Acceptance: the pipelined fit (prefetch + deferred sync) produces
    a final state BIT-IDENTICAL to a hand-rolled serial reference loop —
    params AND opt_state."""
    trainer, state_p, batches = _make()
    fitted = trainer.fit(state_p, batches, epochs=3, sync_every=3,
                         prefetch=2)

    # serial reference: same init, same jitted step, one blocking step at
    # a time (the pre-pipeline behavior)
    _, state_s, _ = _make()
    import jax.numpy as jnp

    step = trainer.make_train_step()
    for _ in range(3):
        for x, y in batches:
            state_s, _ = step(state_s, jnp.asarray(x), jnp.asarray(y))
            _ = int(state_s.step)  # per-step barrier

    assert int(fitted.step) == int(state_s.step) == 12
    for a, b in zip(_leaves(fitted.params), _leaves(state_s.params)):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(_leaves(fitted.opt_state), _leaves(state_s.opt_state)):
        np.testing.assert_array_equal(a, b)


def test_pipelined_fit_matches_serial_fit_settings():
    """prefetch=0 / sync_every=1 (the serial configuration) and the
    pipelined defaults agree bitwise — the knobs change scheduling only."""
    trainer, s1, batches = _make()
    f1 = trainer.fit(s1, batches, epochs=2, sync_every=1, prefetch=0)
    _, s2, _ = _make()
    f2 = trainer.fit(s2, batches, epochs=2, sync_every=7, prefetch=3)
    for a, b in zip(_leaves(f1.params), _leaves(f2.params)):
        np.testing.assert_array_equal(a, b)


def test_exact_resume_under_deferred_sync(tmp_path):
    """Acceptance: resume lands on the precise next batch with NO per-step
    sync (no on_step hook) — a partial fit's checkpoint continued to the
    full epoch count matches the uninterrupted fit bitwise."""
    trainer, ref_state, batches = _make()
    ref = trainer.fit(ref_state, batches, epochs=2, sync_every=3, prefetch=2)

    _, s_a, _ = _make()
    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    partial = trainer.fit(s_a, batches, epochs=1, checkpoint=ckpt,
                          checkpoint_every=3, sync_every=3, prefetch=2)
    assert int(partial.step) == 4
    assert ckpt.latest_step() == 4
    _, s_b, _ = _make()  # scratch-shaped state; fit restores + replays
    resumed = trainer.fit(s_b, batches, epochs=2, checkpoint=ckpt,
                          sync_every=3, prefetch=2)
    ckpt.close()
    assert int(resumed.step) == 8
    for a, b in zip(_leaves(ref.params), _leaves(resumed.params)):
        np.testing.assert_array_equal(a, b)


def test_staging_overlaps_training_steps():
    """Acceptance: staging-thread work observed while a step is in
    flight. Event-ordered (no timing): the source stages batch k+1 only
    after the main loop has DISPATCHED step k without syncing — possible
    only if staging runs on a separate thread concurrently with the
    un-awaited device work."""
    trainer, state, batches = _make()
    main = threading.get_ident()
    dispatched = threading.Event()
    overlapped = threading.Event()
    source_threads = []

    class Stream:
        def __iter__(self):
            for i, pair in enumerate(batches):
                source_threads.append(threading.get_ident())
                if i >= 1:
                    # step i-1 was dispatched and NOT synced (sync_every
                    # exceeds the batch count, no on_step, no checkpoint)
                    if dispatched.wait(timeout=10.0):
                        overlapped.set()
                yield pair

    class Logger(MetricsLogger):
        def log_step(self, step, metrics, examples=None, defer=False):
            dispatched.set()
            return super().log_step(step, metrics, examples=examples,
                                    defer=defer)

    logger = Logger(sinks=[lambda r: None])
    fitted = trainer.fit(state, Stream(), epochs=1, metrics_logger=logger,
                         sync_every=100, prefetch=2)
    assert overlapped.is_set()
    assert all(t != main for t in source_threads)  # staged off-thread
    assert int(fitted.step) == len(batches)
    # deferred metrics all materialized at the epoch-boundary sync
    assert [r["step"] for r in logger.history] == [1, 2, 3, 4]
    assert all(isinstance(r["loss"], float) for r in logger.history)


def test_serial_fallback_stages_on_main_thread():
    trainer, state, batches = _make()
    main = threading.get_ident()
    source_threads = []

    class Stream:
        def __iter__(self):
            for pair in batches:
                source_threads.append(threading.get_ident())
                yield pair

    trainer.fit(state, Stream(), epochs=1, prefetch=0)
    assert all(t == main for t in source_threads)


def test_stream_error_propagates_and_flushes(tmp_path):
    """Acceptance: an exception raised mid-stream by the staging thread
    propagates to the fit caller with the prefetcher fully drained (no
    leaked thread, no swallowed error) and pending checkpoints flushed."""

    class DecodeBoom(RuntimeError):
        pass

    trainer, state, batches = _make()
    ckpt = CheckpointManager(str(tmp_path / "ckpt"))

    class Stream:
        def __iter__(self):
            yield batches[0]
            yield batches[1]
            raise DecodeBoom("partition 2 unreadable")

    with pytest.raises(DecodeBoom, match="partition 2 unreadable"):
        trainer.fit(state, Stream(), epochs=1, checkpoint=ckpt,
                    checkpoint_every=1, sync_every=100, prefetch=2)
    # both completed steps were checkpointed and the async writes flushed
    assert ckpt.latest_step() == 2
    ckpt.close()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if not [t for t in threading.enumerate()
                if t.name.startswith("sparkdl-prefetch")]:
            break
        time.sleep(0.01)
    assert not [t for t in threading.enumerate()
                if t.name.startswith("sparkdl-prefetch")]


def test_deferred_metrics_rate_is_window_averaged():
    trainer, state, batches = _make()
    logger = MetricsLogger(sinks=[lambda r: None])
    trainer.fit(state, batches, epochs=2, metrics_logger=logger,
                sync_every=4, prefetch=2)
    assert len(logger.history) == 8
    # first flush window has no prior timestamp → no rate; later ones do
    assert any("examples_per_sec" in r for r in logger.history[4:])


def test_preemption_abort_with_deferred_sync_resumes_exact(tmp_path):
    """The chaos e2e runs with per-step syncs (on_step + checkpoint_every=1
    force them); this covers the genuinely-deferred case: preemption fires
    at a step with NO sync due (not checkpoint-due, not sync_every-due,
    no on_step), so the abort unwinds with un-flushed deferred metrics and
    un-awaited in-flight steps — pending checkpoint writes must flush and
    the checkpoint-resumed continuation must match the uninterrupted fit
    bitwise."""
    from sparkdl_tpu.core.resilience import (Fault, FaultInjector,
                                             InjectedFault)

    trainer, ref_state, batches = _make()
    ref = trainer.fit(ref_state, batches, epochs=2, sync_every=8, prefetch=2)

    _, s_a, _ = _make()
    ckpt = CheckpointManager(str(tmp_path / "c"))
    logger = MetricsLogger(sinks=[lambda r: None])
    with FaultInjector.seeded(
            0, preemption=Fault(when=lambda c: c["step"] == 3)) as inj:
        with pytest.raises(InjectedFault):
            trainer.fit(s_a, batches, epochs=2, checkpoint=ckpt,
                        checkpoint_every=2, sync_every=8, prefetch=2,
                        metrics_logger=logger)
    assert inj.fired["preemption"] == 1
    assert ckpt.latest_step() == 2  # step 3 was not checkpoint-due
    # abort-path flush materialized the deferred records for steps 1-3
    assert [r["step"] for r in logger.history] == [1, 2, 3]

    _, s_b, _ = _make()
    resumed = trainer.fit(s_b, batches, epochs=2, checkpoint=ckpt,
                          sync_every=8, prefetch=2)
    ckpt.close()
    assert int(resumed.step) == 8
    for a, b in zip(_leaves(ref.params), _leaves(resumed.params)):
        np.testing.assert_array_equal(a, b)


def test_on_step_sees_completed_host_steps():
    """on_step keeps its per-step contract (the fault-injection hook):
    called once per step, in order, after the step's sync."""
    trainer, state, batches = _make()
    seen = []
    trainer.fit(state, batches, epochs=2, on_step=seen.append,
                sync_every=50, prefetch=2)
    assert seen == list(range(1, 9))


@pytest.mark.slow
def test_pipelined_fit_stress_epoch_churn(tmp_path):
    """Stress: many epochs over a tiny stream — per-epoch prefetcher
    creation/teardown stays leak-free and the host/device step counters
    stay in lockstep throughout (the sync() consistency check runs every
    epoch boundary)."""
    trainer, state, batches = _make()
    fitted = trainer.fit(state, batches, epochs=40, sync_every=5,
                         prefetch=2)
    assert int(fitted.step) == 40 * len(batches)
    assert not [t for t in threading.enumerate()
                if t.name.startswith("sparkdl-prefetch")]
