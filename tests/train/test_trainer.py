"""Trainer: loss descent, mesh DP equivalence, checkpoint/resume exactness.

The distributed assertions run on the 8-device CPU mesh (conftest), per
SURVEY.md §4's rebuild test plan.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import flax.linen as nn

from sparkdl_tpu.core.mesh import MeshConfig, make_mesh
from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.train import CheckpointManager, MetricsLogger, Trainer
from sparkdl_tpu.train.optimizers import make_loss, make_optimizer


class TinyMLP(nn.Module):
    classes: int = 3

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Dense(16)(x)
        x = nn.relu(x)
        x = nn.Dense(self.classes)(x)
        return jax.nn.softmax(x, axis=-1)


class TinyBN(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Dense(8)(x)
        x = nn.BatchNorm(use_running_average=not train)(x)
        return jax.nn.softmax(nn.Dense(2)(x), axis=-1)


def _toy_data(n=64, d=6, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, classes)).astype(np.float32)
    y = np.argmax(x @ w, axis=1)
    y1h = np.eye(classes, dtype=np.float32)[y]
    return x, y, y1h


def _batches(x, y, bs):
    return [(x[i:i + bs], y[i:i + bs]) for i in range(0, len(x) - bs + 1, bs)]


def test_loss_descends():
    x, _, y1h = _toy_data()
    module = TinyMLP()
    variables = module.init(jax.random.PRNGKey(0), x[:1])
    trainer, state = Trainer.from_flax(module, variables, optimizer="sgd",
                                       learning_rate=0.5)
    logger = MetricsLogger(sinks=[lambda r: None])
    state = trainer.fit(state, _batches(x, y1h, 16), epochs=10,
                        metrics_logger=logger)
    losses = [r["loss"] for r in logger.history]
    assert losses[-1] < losses[0] * 0.7
    assert int(state.step) == 4 * 10


def test_batch_stats_update():
    x, _, _ = _toy_data(classes=2)
    y = np.eye(2, dtype=np.float32)[np.zeros(len(x), dtype=int)]
    module = TinyBN()
    variables = module.init(jax.random.PRNGKey(0), x[:1])
    before = jax.device_get(variables["batch_stats"])
    trainer, state = Trainer.from_flax(module, variables)
    assert trainer.has_model_state
    state = trainer.fit(state, _batches(x, y, 16), epochs=1)
    after = jax.device_get(state.model_state["batch_stats"])
    # moving stats must have moved
    leaves_b = jax.tree.leaves(before)
    leaves_a = jax.tree.leaves(after)
    assert any(not np.allclose(a, b) for a, b in zip(leaves_a, leaves_b))


def test_mesh_dp_matches_single_device():
    """The load-bearing DP correctness test: same data, same init → the
    8-way data-parallel step produces the same params as single-device."""
    x, _, y1h = _toy_data(n=32)
    module = TinyMLP()
    variables = module.init(jax.random.PRNGKey(0), x[:1])

    trainer1, state1 = Trainer.from_flax(module, variables, optimizer="sgd",
                                         learning_rate=0.1)
    state1 = trainer1.fit(state1, _batches(x, y1h, 16), epochs=2)

    mesh = make_mesh(MeshConfig(data=8))
    trainer8, state8 = Trainer.from_flax(module, variables, optimizer="sgd",
                                         learning_rate=0.1, mesh=mesh)
    state8 = trainer8.fit(state8, _batches(x, y1h, 16), epochs=2)

    p1 = jax.device_get(state1.params)
    p8 = jax.device_get(state8.params)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p8)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_checkpoint_resume_exact(tmp_path):
    """Interrupted training resumed from checkpoint must land on exactly
    the same params as uninterrupted training (gang-restart semantics)."""
    x, _, y1h = _toy_data(n=64)
    module = TinyMLP()
    variables = module.init(jax.random.PRNGKey(0), x[:1])
    batches = _batches(x, y1h, 16)  # 4 steps/epoch

    # uninterrupted: 2 epochs = 8 steps
    trainer, ref_state = Trainer.from_flax(module, variables, optimizer="sgd",
                                           learning_rate=0.1)
    ref_state = trainer.fit(ref_state, batches, epochs=2)

    # interrupted at step 5 (mid epoch 2), checkpoint every step
    ckpt_dir = str(tmp_path / "ckpt")
    trainer2, state2 = Trainer.from_flax(module, variables, optimizer="sgd",
                                         learning_rate=0.1)
    ckpt = CheckpointManager(ckpt_dir)

    class Boom(RuntimeError):
        pass

    def bomb(step):
        if step == 5:
            raise Boom()

    with pytest.raises(Boom):
        trainer2.fit(state2, batches, epochs=2, checkpoint=ckpt,
                     checkpoint_every=1, on_step=bomb)
    ckpt.wait_until_finished()
    assert ckpt.latest_step() == 5

    # restart from scratch-shaped state; fit resumes at step 5
    _, fresh = Trainer.from_flax(module, variables, optimizer="sgd",
                                 learning_rate=0.1)
    resumed = trainer2.fit(fresh, batches, epochs=2, checkpoint=ckpt)
    assert int(resumed.step) == 8
    for a, b in zip(jax.tree.leaves(jax.device_get(ref_state.params)),
                    jax.tree.leaves(jax.device_get(resumed.params))):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    ckpt.close()


def test_model_function_training():
    # training an ingested-style plain ModelFunction (stateless path)
    x, _, y1h = _toy_data()
    w = np.zeros((6, 3), dtype=np.float32)
    mf = ModelFunction.fromFunction(
        lambda vs, x: jax.nn.softmax(x @ vs["w"], axis=-1), {"w": w},
        TensorSpec((None, 6)))
    trainer, state = Trainer.from_model_function(mf, optimizer="sgd",
                                                 learning_rate=1.0)
    logger = MetricsLogger(sinks=[lambda r: None])
    state = trainer.fit(state, _batches(x, y1h, 32), epochs=20,
                        metrics_logger=logger)
    assert logger.history[-1]["accuracy"] > 0.8


def test_make_optimizer_and_loss_validation():
    with pytest.raises(ValueError, match="optimizer"):
        make_optimizer("not_an_opt")
    with pytest.raises(ValueError, match="loss"):
        make_loss("not_a_loss")
    # logits variant differs from probability variant
    logits = jnp.array([[2.0, -1.0]])
    labels = jnp.array([[1.0, 0.0]])
    l_probs = make_loss("categorical_crossentropy")(
        jax.nn.softmax(logits), labels)
    l_logits = make_loss("categorical_crossentropy", from_logits=True)(
        logits, labels)
    np.testing.assert_allclose(float(l_probs), float(l_logits), rtol=1e-5)


def test_binary_crossentropy_rank_alignment():
    """(N,) labels vs (N,1) sigmoid head must NOT broadcast to (N,N)
    (ADVICE r1: silently wrong loss 0.89 vs correct 0.18)."""
    probs = jnp.array([[0.9], [0.2], [0.8], [0.7]])
    labels = jnp.array([1.0, 0.0, 1.0, 1.0])
    loss = make_loss("binary_crossentropy")(probs, labels)
    want = -np.mean([np.log(0.9), np.log(0.8), np.log(0.8), np.log(0.7)])
    np.testing.assert_allclose(float(loss), want, rtol=1e-5)
    # logits form aligns too
    logits = jnp.log(probs / (1 - probs))
    loss_l = make_loss("binary_crossentropy", from_logits=True)(logits, labels)
    np.testing.assert_allclose(float(loss_l), want, rtol=1e-5)


def test_accuracy_metric_binary_head():
    from sparkdl_tpu.train.optimizers import accuracy_metric

    probs = jnp.array([[0.9], [0.2], [0.8], [0.4]])
    labels = jnp.array([1.0, 0.0, 0.0, 1.0])
    np.testing.assert_allclose(float(accuracy_metric(probs, labels)), 0.5)
    # rank-2 labels too
    np.testing.assert_allclose(
        float(accuracy_metric(probs, labels[:, None])), 0.5)


def test_binary_head_training_learns():
    """End-to-end: Dense(1, sigmoid) head + (N,) labels trains correctly."""
    rng = np.random.default_rng(3)
    x = rng.normal(size=(128, 4)).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)

    class BinaryHead(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            return jax.nn.sigmoid(nn.Dense(1)(x))

    module = BinaryHead()
    variables = module.init(jax.random.PRNGKey(0), x[:1])
    trainer, state = Trainer.from_flax(
        module, variables, loss="binary_crossentropy", optimizer="sgd",
        learning_rate=1.0)
    logger = MetricsLogger(sinks=[lambda r: None])
    state = trainer.fit(state, _batches(x, y, 32), epochs=15,
                        metrics_logger=logger)
    assert logger.history[-1]["loss"] < logger.history[0]["loss"] * 0.5
    assert logger.history[-1]["accuracy"] > 0.9


def test_mixed_precision_trains_close_to_full_precision(rng):
    """bf16 compute / f32 master params: learns the same separable problem
    and keeps params/opt state in float32."""
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.softmax(nn.Dense(2)(x))

    module = Net()
    x = rng.normal(size=(32, 8)).astype(np.float32)
    w_true = rng.normal(size=(8,)).astype(np.float32)
    labels = (x @ w_true > 0).astype(np.int64)
    y = np.eye(2, dtype=np.float32)[labels]
    variables = module.init(jax.random.PRNGKey(0), x[:1])

    trainer, state = Trainer.from_flax(
        module, variables, loss="categorical_crossentropy",
        optimizer="sgd", learning_rate=0.5, compute_dtype="bfloat16")
    state = trainer.fit(state, [(x, y)] * 30, epochs=1)
    # master params stayed f32
    assert all(leaf.dtype == np.float32
               for leaf in jax.tree.leaves(jax.device_get(state.params)))
    eval_step = trainer.make_eval_step()
    preds = np.asarray(eval_step(state, x)).argmax(axis=-1)
    assert (preds == labels).mean() >= 0.9


def test_mixed_precision_batch_stats_stay_f32(rng):
    import flax.linen as nn

    class BNNet(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Dense(4)(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            return nn.softmax(nn.Dense(2)(x))

    module = BNNet()
    x = rng.normal(size=(16, 8)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, size=16)]
    variables = module.init(jax.random.PRNGKey(0), x[:1])
    init_stats = jax.tree.leaves(jax.device_get(
        {k: v for k, v in variables.items() if k == "batch_stats"}))
    trainer, state = Trainer.from_flax(
        module, variables, optimizer="sgd", learning_rate=0.1,
        compute_dtype="bfloat16")
    state = trainer.fit(state, [(x, y)], epochs=2)
    new_stats = jax.tree.leaves(jax.device_get(state.model_state))
    for leaf in new_stats:
        assert leaf.dtype == np.float32, leaf.dtype
    # the moving averages must actually MOVE: bf16 stats would stall on
    # small momentum increments (the update stays f32 by design)
    assert any(not np.allclose(a, b) for a, b in zip(init_stats, new_stats))


def test_step_cache_shared_across_fits_and_lrs():
    """from_model_function fits share ONE compiled step per
    (loss, opt, mesh, dtype) — and the injected-lr design means different
    learning rates reuse it while still applying their own lr."""
    import numpy as np

    from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
    from sparkdl_tpu.train import Trainer

    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    module = nn.Dense(1)
    variables = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 4)))
    mf = ModelFunction(lambda vs, x: module.apply(vs, x),
                       variables, TensorSpec((None, 4), "float32"),
                       name="lin")
    x = np.ones((8, 4), np.float32)
    y = np.zeros((8, 1), np.float32)

    def fitted_params(lr):
        trainer, state = Trainer.from_model_function(
            mf, loss="mse", optimizer="sgd", learning_rate=lr)
        state = trainer.fit(state, [(x, y)], epochs=1)
        return jax.device_get(state.params)

    p_small = fitted_params(1e-4)
    cache = mf._train_step_cache
    assert len(cache) == 1
    p_large = fitted_params(0.5)
    assert len(cache) == 1  # second fit reused the compiled step...
    small_step = np.abs(variables["params"]["kernel"]
                        - p_small["params"]["kernel"]).max()
    large_step = np.abs(variables["params"]["kernel"]
                        - p_large["params"]["kernel"]).max()
    assert large_step > 100 * small_step  # ...but applied ITS lr
