"""Worker for the 2-process multi-host ``DeepImageFeaturizer.transform(df)``
test (VERDICT r4 #1 / SURVEY.md §2.4 row 1, §3.1 — the flagship featurize
path the reference scaled horizontally).

Each of two processes owns 4 virtual CPU devices, joins the process group
via the SPARKDL_* env triple, and calls the PUBLIC ML API:
``featurizer.transform(df)``. The transformer must shard the frame
per-process (each host decodes + featurizes only its round-robin partition
share — asserted via the local shard's row count), and
``gatherProcesses()`` must reassemble the FULL output in original row
order on every host; process 0 writes the gathered features for
comparison with a single-process transform of the same DataFrame.

Usage: python _multihost_transform_worker.py <out_dir>
"""

import os
import sys

if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=4")

import jax  # noqa: E402

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from sparkdl_tpu.engine.dataframe import DataFrame, EngineConfig  # noqa: E402
from sparkdl_tpu.image import imageIO  # noqa: E402
from sparkdl_tpu.ml import DeepImageFeaturizer  # noqa: E402
from sparkdl_tpu.train.runner import maybe_initialize_distributed  # noqa: E402

if __name__ == "__main__":
    # the pytest conftest pins fp32/pow2 so references stay bit-comparable;
    # gang subprocesses never import that conftest, so mirror the pin here
    # (the parent's single-process reference is computed under it)
    EngineConfig.inference_precision = "float32"
    EngineConfig.bucket_ladder = "pow2"

NUM_ROWS = 16
NUM_PARTITIONS = 4


def build_frame() -> "DataFrame":
    """Deterministic image-struct frame, identical on every process."""
    import pyarrow as pa

    rng = np.random.default_rng(7)
    rows = []
    for i in range(NUM_ROWS):
        arr = rng.integers(0, 255, size=(32, 32, 3), dtype=np.uint8)
        rows.append({"image": imageIO.imageArrayToStruct(arr, origin=str(i)),
                     "idx": i})
    schema = pa.schema([pa.field("image", imageIO.imageSchema),
                        pa.field("idx", pa.int64())])
    return DataFrame.fromRows(rows, schema=schema,
                              numPartitions=NUM_PARTITIONS)


def build_featurizer() -> "DeepImageFeaturizer":
    # TestNet: seeded Flax init — identical weights on every process
    return DeepImageFeaturizer(inputCol="image", outputCol="features",
                               modelName="TestNet", batchSize=8)


def features_matrix(collected) -> np.ndarray:
    return np.stack([np.asarray(r["features"], np.float32)
                     for r in collected])


def main(out_dir: str) -> None:
    assert maybe_initialize_distributed(), "SPARKDL_* env triple not set"
    assert jax.process_count() == 2, jax.process_count()
    df = build_frame()
    out = build_featurizer().transform(df)
    # the transform output is this host's shard: half the partitions
    local = out.collect()
    assert len(local) == NUM_ROWS // 2, (jax.process_index(), len(local))
    # local shard holds exactly the round-robin partition share
    want_idx = []
    per_part = NUM_ROWS // NUM_PARTITIONS
    for p in range(jax.process_index(), NUM_PARTITIONS, 2):
        want_idx.extend(range(p * per_part, (p + 1) * per_part))
    assert [r["idx"] for r in local] == want_idx, (jax.process_index(),
                                                  [r["idx"] for r in local])
    # opt-in gather: every host reassembles the FULL frame in original order
    full = out.gatherProcesses().collect()
    assert [r["idx"] for r in full] == list(range(NUM_ROWS))
    if jax.process_index() == 0:
        np.save(os.path.join(out_dir, "multihost_transform_features.npy"),
                features_matrix(full))


if __name__ == "__main__":
    main(sys.argv[1])
