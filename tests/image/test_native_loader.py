"""Native C++ loader tests (skipped when the toolchain can't build it)."""

from io import BytesIO

import numpy as np
import pytest
from PIL import Image

from sparkdl_tpu.native import loader

pytestmark = pytest.mark.skipif(not loader.available(),
                                reason="native loader not built")


def _png_bytes(arr):
    buf = BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()


def _jpeg_bytes(arr, quality=95):
    buf = BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


def test_png_lossless_roundtrip(rng):
    arr = rng.integers(0, 255, (57, 43, 3), dtype=np.uint8)
    out = loader.decode(_png_bytes(arr))
    np.testing.assert_array_equal(out, arr)


def test_jpeg_matches_pil(rng):
    arr = rng.integers(0, 255, (64, 48, 3), dtype=np.uint8)
    data = _jpeg_bytes(arr)
    out = loader.decode(data)
    pil = np.asarray(Image.open(BytesIO(data)))
    # libjpeg decode should be bit-identical (same library under PIL)
    assert int(np.abs(out.astype(int) - pil.astype(int)).max()) <= 1


def test_grayscale_png(rng):
    arr = rng.integers(0, 255, (20, 20), dtype=np.uint8)
    out = loader.decode(_png_bytes(arr))
    assert out.shape == (20, 20, 1)
    np.testing.assert_array_equal(out[:, :, 0], arr)


def test_rgba_png(rng):
    arr = rng.integers(0, 255, (10, 12, 4), dtype=np.uint8)
    out = loader.decode(_png_bytes(arr))
    assert out.shape == (10, 12, 4)
    np.testing.assert_array_equal(out, arr)


def test_resize_target(rng):
    arr = rng.integers(0, 255, (100, 80, 3), dtype=np.uint8)
    out = loader.decode(_png_bytes(arr), target_size=(32, 32))
    assert out.shape == (32, 32, 3)


def test_jpeg_dct_scaling_path(rng):
    # Target much smaller than source -> exercises scale_denom shortcut.
    arr = rng.integers(0, 255, (512, 512, 3), dtype=np.uint8)
    out = loader.decode(_jpeg_bytes(arr), target_size=(64, 64))
    assert out.shape == (64, 64, 3)


def test_corrupt_returns_none():
    assert loader.decode(b"not an image") is None


def test_batch_decode(rng):
    blobs = [
        _jpeg_bytes(rng.integers(0, 255, (40 + i, 30, 3), dtype=np.uint8))
        for i in range(5)
    ]
    out = loader.decode_batch(blobs, (24, 24))
    assert out.shape == (5, 24, 24, 3) and out.dtype == np.uint8


def test_batch_decode_with_failure_returns_none(rng):
    blobs = [_png_bytes(rng.integers(0, 255, (8, 8, 3), dtype=np.uint8)),
             b"garbage"]
    assert loader.decode_batch(blobs, (8, 8)) is None


def test_batch_grayscale_promoted_to_rgb(rng):
    gray = rng.integers(0, 255, (16, 16), dtype=np.uint8)
    out = loader.decode_batch([_png_bytes(gray)], (16, 16))
    assert out.shape == (1, 16, 16, 3)
    np.testing.assert_array_equal(out[0, :, :, 0], out[0, :, :, 1])


def test_grayscale_png_with_trns_probe_matches_decode(rng):
    # Regression: probe undercounted channels for gray+tRNS -> heap overflow.
    arr = rng.integers(0, 255, (16, 16), dtype=np.uint8)
    buf = BytesIO()
    Image.fromarray(arr, mode="L").save(buf, format="PNG", transparency=128)
    out = loader.decode(buf.getvalue())
    assert out is not None and out.shape == (16, 16, 2)
