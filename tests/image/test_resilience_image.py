"""Data-plane degradation: corrupt/undecodable rows become nulls, the
partition completes, and drops are surfaced (docs/RESILIENCE.md)."""

import numpy as np

from sparkdl_tpu.core.resilience import Fault, FaultInjector
from sparkdl_tpu.image import imageIO


def _struct(rng, h=8, w=8):
    return imageIO.imageArrayToStruct(
        rng.integers(0, 255, (h, w, 3), dtype=np.uint8))


def test_tolerant_staging_drops_corrupt_rows_keeps_order(rng):
    structs = [_struct(rng) for _ in range(6)]
    structs[1] = dict(structs[1], data=structs[1]["data"][:7])  # truncated
    structs[4] = dict(structs[4], mode=99)  # unknown OpenCV code
    batch, kept, dropped = imageIO.imageStructsToBatchArrayTolerant(
        structs, dtype=None)
    assert kept == [0, 2, 3, 5] and dropped == 2
    for j, i in enumerate(kept):
        np.testing.assert_array_equal(
            batch[j], imageIO.imageStructToArray(structs[i]))


def test_tolerant_staging_all_corrupt_returns_empty(rng):
    structs = [dict(_struct(rng), mode=99) for _ in range(3)]
    batch, kept, dropped = imageIO.imageStructsToBatchArrayTolerant(
        structs, target_size=(8, 8))
    assert kept == [] and dropped == 3
    assert batch.shape == (0, 8, 8, 3)


def test_tolerant_staging_matches_strict_on_clean_input(rng):
    structs = [_struct(rng) for _ in range(4)]
    strict = imageIO.imageStructsToBatchArray(structs, dtype="float32")
    tolerant, kept, dropped = imageIO.imageStructsToBatchArrayTolerant(
        structs, dtype="float32")
    assert dropped == 0 and kept == [0, 1, 2, 3]
    np.testing.assert_array_equal(strict, tolerant)


def test_decode_error_injection_in_decode_bytes(tmp_path, rng):
    from PIL import Image

    p = tmp_path / "img.png"
    Image.fromarray(
        rng.integers(0, 255, (8, 8, 3), dtype=np.uint8)).save(p)
    data = p.read_bytes()
    assert imageIO.decodeImageBytes(data) is not None
    with FaultInjector.seeded(0, decode_error=1) as inj:
        assert imageIO.decodeImageBytes(data) is None
        assert imageIO.decodeImageBytes(data) is not None  # disarmed
    assert inj.fired["decode_error"] == 1


def test_decode_error_injection_in_batch_decode(tmp_path, rng):
    from PIL import Image

    blobs = []
    for i in range(4):
        p = tmp_path / f"b{i}.png"
        Image.fromarray(
            rng.integers(0, 255, (8, 8, 3), dtype=np.uint8)).save(p)
        blobs.append(p.read_bytes())
    with FaultInjector.seeded(0, decode_error=Fault(after=1, times=1)):
        out = imageIO.decodeImageBytesBatch(blobs, (8, 8))
    assert [o is None for o in out] == [False, True, False, False]


def test_read_images_with_injected_decode_error(tiny_image_dir):
    """readImages degrades injected-undecodable files to null structs —
    the partition (and the read) completes."""
    baseline = imageIO.readImages(str(tiny_image_dir)).collect()
    n_ok = sum(r["image"] is not None for r in baseline)
    assert n_ok >= 2
    with FaultInjector.seeded(0, decode_error=1):
        rows = imageIO.readImages(str(tiny_image_dir)).collect()
    assert len(rows) == len(baseline)
    assert sum(r["image"] is not None for r in rows) == n_ok - 1
